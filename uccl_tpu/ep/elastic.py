"""Elastic hybrid device/host buffers (XLA memory-kind offload).

The reference's DeepEPv2 runtime backs its EP windows with *host* memory when
device memory is short or GPUDirect is absent (ElasticBuffer,
experimental/lite/lite-ep/csrc/elastic/buffer.hpp: ``uccl_use_host_window``,
host workspace mapped into the device; lite-ep/README.md:35 "elastic hybrid
GPU/CPU buffers"). The TPU-native analog is XLA's memory-space annotation:
an array lives in ``device`` (HBM) or ``pinned_host`` memory of the same
TPU, moved by ``jax.device_put`` (async, DMA-backed on TPU).

Two facilities:

* :class:`ElasticBuffer` — a named tensor store with an HBM budget: arrays
  placed on device while the budget holds, spilled to pinned host memory
  beyond it; ``get`` stages host-resident arrays back on demand.
* :class:`ElasticKVCache` — the serving-side application: a blockwise KV
  cache whose hot tail lives in HBM and whose cold prefix is offloaded to
  host memory, letting decode contexts grow past the HBM budget. Feeds the
  same attention contract as ``models.inference`` (see
  ``decode_step_elastic`` there).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import SingleDeviceSharding


def _nbytes(arr) -> int:
    return int(np.prod(arr.shape)) * arr.dtype.itemsize


def _memory_shardings(device) -> Tuple[SingleDeviceSharding, SingleDeviceSharding, bool]:
    """(device_sharding, host_sharding, has_host) for one device. Backends
    without a pinned_host memory space degrade to device-only placement —
    the elastic API keeps working, spills just stay in HBM. The device-side
    kind is probed rather than assumed: some CPU backends expose only
    ``unpinned_host`` and reject the literal ``"device"`` kind."""
    kinds = {m.kind for m in device.addressable_memories()}
    dev_kind = (
        "device" if "device" in kinds else device.default_memory().kind
    )
    device_s = SingleDeviceSharding(device, memory_kind=dev_kind)
    if "pinned_host" in kinds:
        return device_s, SingleDeviceSharding(device, memory_kind="pinned_host"), True
    return device_s, device_s, False


class ElasticBuffer:
    """Named tensor store with an HBM budget and pinned-host spill.

    put() places an array in device memory while ``device_bytes`` stays
    under the budget, else in pinned host memory. get() always returns a
    device-resident array (host-resident entries are staged per call and
    NOT promoted — the store's placement is the durable state, a get is a
    read). pin=True forces device placement regardless of budget (the
    analog of the reference's always-device workspace).
    """

    def __init__(self, hbm_budget_bytes: int, device=None):
        self.device = device if device is not None else jax.devices()[0]
        self.budget = int(hbm_budget_bytes)
        self._device_s, self._host_s, self.has_host = _memory_shardings(
            self.device
        )
        self._store: Dict[str, jax.Array] = {}
        self._on_device: Dict[str, bool] = {}

    @property
    def device_bytes(self) -> int:
        return sum(
            _nbytes(a) for n, a in self._store.items() if self._on_device[n]
        )

    @property
    def host_bytes(self) -> int:
        return sum(
            _nbytes(a) for n, a in self._store.items() if not self._on_device[n]
        )

    def put(self, name: str, arr: jax.Array, *, pin: bool = False) -> None:
        if name in self._store:
            self.delete(name)
        fits = self.device_bytes + _nbytes(arr) <= self.budget
        on_dev = pin or fits or not self.has_host
        sharding = self._device_s if on_dev else self._host_s
        self._store[name] = jax.device_put(arr, sharding)
        self._on_device[name] = on_dev

    def get(self, name: str) -> jax.Array:
        arr = self._store[name]
        if self._on_device[name]:
            return arr
        return jax.device_put(arr, self._device_s)

    def placement(self, name: str) -> str:
        return "device" if self._on_device[name] else "host"

    def offload(self, name: str) -> None:
        """Explicitly demote an entry to host memory (frees its HBM)."""
        if self._on_device[name] and self.has_host:
            self._store[name] = jax.device_put(self._store[name], self._host_s)
            self._on_device[name] = False

    def delete(self, name: str) -> None:
        self._store.pop(name, None)
        self._on_device.pop(name, None)

    def names(self) -> List[str]:
        return list(self._store)


class ElasticKVCache:
    """Blockwise KV cache: hot blocks in HBM, cold blocks in host memory.

    Token layout mirrors ``models.inference.KVCache`` per block:
    k/v blocks are ``[L, B, block_tokens, Hkv, D]``. The cache holds
    ``hot_blocks`` most-recent full blocks on device; older full blocks are
    offloaded to pinned host memory as they age out. A partial "current"
    block accumulates decode-time tokens on device.

    ``kv()`` returns the full (K, V, length) context on device — cold
    blocks are staged back per call (async ``device_put``s overlap on TPU),
    which is the streaming cost elasticity pays for contexts beyond HBM.
    """

    def __init__(
        self,
        n_layers: int,
        batch: int,
        n_kv_heads: int,
        head_dim: int,
        *,
        block_tokens: int = 128,
        hot_blocks: int = 4,
        dtype=jnp.float32,
        device=None,
    ):
        self.shape = (n_layers, batch, block_tokens, n_kv_heads, head_dim)
        self.block_tokens = block_tokens
        self.hot_blocks = max(1, int(hot_blocks))
        self.dtype = dtype
        self.device = device if device is not None else jax.devices()[0]
        self._device_s, self._host_s, self.has_host = _memory_shardings(
            self.device
        )
        self._cold: List[Tuple[jax.Array, jax.Array]] = []
        self._hot: List[Tuple[jax.Array, jax.Array]] = []
        self._cur_k = jnp.zeros(self.shape, dtype)
        self._cur_v = jnp.zeros(self.shape, dtype)
        self._cur_fill = 0

    @property
    def length(self) -> int:
        return (
            (len(self._cold) + len(self._hot)) * self.block_tokens
            + self._cur_fill
        )

    @property
    def cold_blocks(self) -> int:
        return len(self._cold)

    def device_committed_bytes(self) -> int:
        """HBM durably held by the cache (hot ring + current block); cold
        blocks live in host memory and only transit HBM inside kv()."""
        per_block = 2 * int(np.prod(self.shape)) * jnp.dtype(self.dtype).itemsize
        return (len(self._hot) + 1) * per_block

    def _seal_current(self) -> None:
        self._hot.append(
            (
                jax.device_put(self._cur_k, self._device_s),
                jax.device_put(self._cur_v, self._device_s),
            )
        )
        self._cur_k = jnp.zeros(self.shape, self.dtype)
        self._cur_v = jnp.zeros(self.shape, self.dtype)
        self._cur_fill = 0
        while len(self._hot) > self.hot_blocks:
            k, v = self._hot.pop(0)
            self._cold.append(
                (
                    jax.device_put(k, self._host_s),
                    jax.device_put(v, self._host_s),
                )
            )

    def append_tokens(self, k_new: jax.Array, v_new: jax.Array) -> None:
        """k/v_new: [L, B, S_new, Hkv, D] — append S_new tokens (prefill
        chunks or single decode tokens)."""
        s_new = k_new.shape[2]
        off = 0
        while off < s_new:
            room = self.block_tokens - self._cur_fill
            take = min(room, s_new - off)
            sl = (slice(None), slice(None), slice(off, off + take))
            self._cur_k = jax.lax.dynamic_update_slice(
                self._cur_k,
                k_new[sl].astype(self.dtype),
                (0, 0, self._cur_fill, 0, 0),
            )
            self._cur_v = jax.lax.dynamic_update_slice(
                self._cur_v,
                v_new[sl].astype(self.dtype),
                (0, 0, self._cur_fill, 0, 0),
            )
            self._cur_fill += take
            off += take
            if self._cur_fill == self.block_tokens:
                self._seal_current()

    def kv(self) -> Tuple[jax.Array, jax.Array, int]:
        """Full context on device: (K, V, length), K/V
        [L, B, n_blocks*block_tokens, Hkv, D] (tail beyond `length` is
        zero padding from the partial block)."""
        staged_k, staged_v = [], []
        for k, v in self._cold:  # issue all stagings first: async overlap
            staged_k.append(jax.device_put(k, self._device_s))
            staged_v.append(jax.device_put(v, self._device_s))
        for k, v in self._hot:
            staged_k.append(k)
            staged_v.append(v)
        staged_k.append(self._cur_k)
        staged_v.append(self._cur_v)
        return (
            jnp.concatenate(staged_k, axis=2),
            jnp.concatenate(staged_v, axis=2),
            self.length,
        )

    @staticmethod
    def from_cache(cache, *, block_tokens=128, hot_blocks=4, device=None):
        """Blockify a ``models.inference.KVCache`` produced by prefill (the
        disaggregation hand-off: prefill ships a dense cache, the decode
        worker re-homes it elastically)."""
        n_layers, batch, _, hkv, d = cache.k.shape
        length = int(cache.length)
        ekv = ElasticKVCache(
            n_layers, batch, hkv, d,
            block_tokens=block_tokens, hot_blocks=hot_blocks,
            dtype=cache.k.dtype, device=device,
        )
        ekv.append_tokens(cache.k[:, :, :length], cache.v[:, :, :length])
        return ekv


def admit_warm_spare(buf: ElasticBuffer, weights, *, prefix: str = "",
                     pin: bool = False) -> int:
    """Warm-spare admission: import a model's weights into an elastic
    store — the spin-up path of an elastic resize (a spare joining the
    fleet mid-run stages its params here before taking traffic).

    ``weights`` is a fetched weight-push snapshot
    (:class:`uccl_tpu.p2p.weight_push.WeightSnapshot` — the versioned
    fleet distribution path, whose wire bytes were already counted at
    fetch time) or a plain ``{name: array}`` mapping / param pytree. A
    raw tree is the legacy local-copy path: its bytes land on
    ``p2p_bytes_total{verb="weight_push"}`` here so a spare admitted off
    an untracked host copy is visible on the SAME fleet byte series as a
    wire-fetched one — never silent. Returns the bytes imported; entries
    are named ``prefix + dotted-path``."""
    from uccl_tpu import obs
    from uccl_tpu.p2p import weight_push as _wp

    if isinstance(weights, _wp.WeightSnapshot):
        pairs = list(weights.flat().items())
        version = weights.version
    else:
        pairs = [(k, np.asarray(v))
                 for k, v in _wp.flatten_tree(weights)]
        version = None
        obs.counter("p2p_bytes_total").inc(
            sum(int(a.nbytes) for _, a in pairs), verb="weight_push")
    total = 0
    for key, arr in pairs:
        buf.put(prefix + key, jnp.asarray(arr), pin=pin)
        total += int(arr.nbytes)
    obs.instant("warm_spare_admit", track="wire", entries=len(pairs),
                bytes=total, version=version)
    return total


def admit_warm_replica(router, prototype_backend, *, weights=None,
                       engine_kw: Optional[Dict] = None):
    """Elastic UP-scale: build a warm-spare serving replica off
    ``prototype_backend`` (sharing its compiled-program caches — N
    replicas cost one warmup, the ``serving.replicate_backend`` rule),
    optionally serving a pushed weight snapshot
    (:class:`~uccl_tpu.p2p.weight_push.WeightSnapshot` — its wire bytes
    were counted at fetch), and :meth:`~uccl_tpu.serving.Router.attach`
    it to the live router mid-run. The twin of ``Router.detach`` (the
    graceful down-scale): together they are the fleet-resize primitive
    the load-following control loop actuates. Returns the new
    ``ServingEngine`` (its stable replica id is on the router's
    ``attach`` instant)."""
    from uccl_tpu.serving.engine import (
        ServingEngine, _reweight_backend, replicate_backend,
    )

    backend = replicate_backend(prototype_backend, 2)[1]
    if weights is not None:
        backend = _reweight_backend(backend, weights)
    eng = ServingEngine(backend, **(engine_kw or {}))
    router.attach(eng)
    return eng
