"""Expert-parallel engine: MoE dispatch/combine over the mesh.

The analog of the reference's ``ep/`` pillar (DeepEP-compatible dispatch/combine
all-to-all, SURVEY.md §2.3). The reference replaces NVIDIA IBGDA with a GPU→CPU
command ring + CPU proxy posting RDMA (ep/include/uccl_ibgda.cuh:27,
ep/src/proxy.cpp:701); on TPU the fabric is compiler-driven, so dispatch/combine
lower to capacity-bucketed ``lax.all_to_all`` exchanges over the EP mesh axes —
the GShard-lineage formulation that keeps every shape static and every matmul on
the MXU — with optional fp8 payload packing on the wire (the analog of
internode_ll.cu's fp8+scales message format).

Surfaces:
* :mod:`uccl_tpu.ep.ops`    — per-shard routing/dispatch/combine for shard_map code.
* :mod:`uccl_tpu.ep.ll`     — packed low-latency path: ragged wire + grouped
  GEMMs over receive counts (the DeepEP LL contract, internode_ll.cu analog).
* :mod:`uccl_tpu.ep.pallas_a2a` — device-initiated all-to-all: the member-major
  exchange as ONE Pallas kernel issuing inter-chip remote DMAs (write-once
  per-source slots, credit-granted flow control) — selected via
  ``Buffer(..., wire="pallas")`` for both the normal and LL row formats;
  ``n_chunks=N`` chunk-pipelines it (double-buffered per-chunk kernels, so
  the MoE layer overlaps expert GEMMs with dispatch/combine DMAs).
* :class:`uccl_tpu.ep.Buffer` — DeepEP-shaped host API (dispatch / combine /
  low_latency_dispatch / low_latency_combine / get_dispatch_layout), including
  the overlap half of the contract: :class:`uccl_tpu.ep.EventOverlap`
  dataflow events (previous_event / async_finish), two-phase receive hooks
  (return_recv_hook), and :class:`uccl_tpu.ep.Config` tuning hints.
"""

from uccl_tpu.ep import ll, ops, pallas_a2a
from uccl_tpu.ep.buffer import Buffer, Config, EventOverlap, LowLatencyHandle
from uccl_tpu.ep.cross_pod import CrossPodMoE
from uccl_tpu.ep.elastic import ElasticBuffer, ElasticKVCache
from uccl_tpu.ep.engram import EngramTable, mesh_fetch

__all__ = [
    "ops",
    "ll",
    "pallas_a2a",
    "Buffer",
    "Config",
    "EventOverlap",
    "LowLatencyHandle",
    "CrossPodMoE",
    "ElasticBuffer",
    "ElasticKVCache",
    "EngramTable",
    "mesh_fetch",
]
