"""Device-initiated EP all-to-all: a Pallas remote-DMA kernel on the ICI.

EP dispatch/combine was the last pillar still riding XLA-scheduled
``lax`` collectives while the reference's whole EP story is *device-initiated*
transfer (ep/src/internode_ll.cu packs per-expert token messages and RDMAs
them via the IBGDA-replacement proxy, ep/src/proxy.cpp:701). This module is
the EP analog of what :mod:`uccl_tpu.collective.pallas_ccl` did for the ring
collectives: the all-to-all that moves routed token rows is issued as
``pltpu.make_async_remote_copy`` inter-chip DMAs from inside ONE kernel — no
per-step XLA dispatch, payload resident in VMEM, both ICI directions of the
axis carrying traffic concurrently.

Schedule (the all-to-all generalization of the ring kernels' design):

* Member ``r`` holds a send buffer of ``W`` destination chunks and a recv
  buffer of ``W`` source slots. Chunk ``r`` short-circuits locally; the
  remaining ``W-1`` exchanges run in ``S = ceil((W-1)/2)`` steps — at step
  ``s`` member ``r`` DMAs chunk ``r+s`` forward and chunk ``r-s`` backward
  (counter-rotating streams, the torus form of the reference's multipath
  chunk spraying, transport.cc:2186).
* **Write-once slots**: the sender addresses the destination's slot by its
  own rank, so every recv slot is written exactly once — data can never be
  clobbered, and the arrival semaphore for a slot carries exactly that
  source's payload count.
* **Full-peer entry barrier**: unlike a ring (where neighbor liveness bounds
  skew transitively), the first all-to-all DMA may target ANY peer's buffer,
  so kernel entry synchronizes with every member of the axis.
* **Credit-granted slot rotation** (generalized from ``pallas_ccl``): each
  stream rotates 2 semaphore parities. With only data dependencies, a peer
  could run ahead and alias a parity slot two steps early; so after consuming
  its step-``s`` arrival, a member grants an explicit credit
  (``semaphore_signal``) to the peer that targets it at step ``s+2``, and
  senders wait for a credit from step 3 on (two parities start free).
  Signals and waits are balanced per stream, so every semaphore drains.

The per-source arrival counts (how many routed rows each source actually
sent) ride the same counts exchange both lax wire paths already use
(:func:`uccl_tpu.ep.ops.counts_exchange` — a [W, E_local] int32 side channel
that is launch-latency-only next to the payload); the payload slots
themselves are fixed-size per pair, which is exactly the dense-chunk LL wire
layout (:mod:`uccl_tpu.ep.ll` ``wire="dense"``) and the sorted path's
capacity layout.

Combine-side note: the *wire* (the reverse all-to-all of expert outputs) is
device-initiated here; the weighted per-token reduction applies immediately
on the received buffer in the same jit (a [T, K]-row gather + weighted sum —
XLA fuses it into the kernel's consumer). The gather itself stays outside
the kernel by design: Mosaic has no dynamic vector gather, and the reduction
is arithmetic XLA already fuses well — the pillar gap was who issues the
DMAs, not who multiplies the weights.

Chunk pipelining (``n_chunks > 1``): the capacity/slot axis splits into
independent per-chunk kernels rotating 2-parity ``collective_id`` pairs
(:func:`uccl_tpu.collective.dma.chunk_collective_id`) so two chunk kernels
can be in flight at once — the double buffering that lets a consumer (the
chunk-pipelined MoE layer, :func:`uccl_tpu.ep.ops.moe_ffn`) hide dispatch
chunk c+1 and combine chunk c-1 under the expert GEMM of chunk c. Identical
numerics to the unchunked exchange; the 2-deep VMEM residency is charged up
front (``dma.chunk_budget``).

Fallback: payloads over the VMEM budget (or the interpreter's single-core
ceiling), chunk pipelines over the 2x double-buffer budget, worlds of 1,
and meshes the legacy discharge interpreter cannot address fall back to the
unchunked kernel and ultimately ``lax.all_to_all`` with identical
semantics — the ``wire="pallas"`` surface is transparent either way.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from uccl_tpu.collective import dma as _dma


def _lax_fallback(x: jax.Array, axis) -> jax.Array:
    return lax.all_to_all(x, axis, split_axis=0, concat_axis=0, tiled=True)


def _a2a_kernel(axis, n: int, faithful: bool):
    """Build the kernel body for an n-member all-to-all over ``axis``.

    ``faithful`` is static: under the legacy discharge interpreter (jax
    0.4.x) remote semaphore signals are unimplemented, but every DMA
    discharges into a synchronous cross-device gather — the barrier and
    credits it elides are subsumed by that global ordering."""
    s_fwd = (n - 1 + 1) // 2  # fwd stream steps: dsts r+1 .. r+S
    s_bwd = (n - 1) // 2  # bwd stream steps: dsts r-1 .. r-S'

    def stream_step(x_ref, out_ref, send_sem, recv_sem, ack_sem, r, s, h,
                    d, last):
        """One direction's DMA at step s: d=+1 fwd / -1 bwd; ``last`` is the
        stream's static step count (credit window arithmetic)."""
        dst = lax.rem(r + d * s + s * n, n)
        if faithful:

            @pl.when(s >= 3)
            def _():  # credit: my step-(s-2) parity slot drained downstream
                pltpu.semaphore_wait(ack_sem.at[h], 1)

        sl = lax.rem(s, 2)
        rdma = pltpu.make_async_remote_copy(
            src_ref=x_ref.at[dst],
            # write-once: my rows land in the destination's slot ``r`` —
            # the sender's rank IS the per-source slot index
            dst_ref=out_ref.at[r],
            send_sem=send_sem.at[h, sl],
            recv_sem=recv_sem.at[h, sl],
            **_dma.remote_kwargs(axis, dst, faithful),
        )
        rdma.start()
        return rdma

    def stream_finish(ack_sem, rdma, r, s, h, d, last):
        rdma.wait_recv()  # slot (r - d*s) arrived
        if faithful:

            @pl.when(s <= last - 2)
            def _():  # grant the peer that targets me at step s+2
                pltpu.semaphore_signal(
                    ack_sem.at[h], inc=1,
                    **_dma.remote_kwargs(
                        axis, lax.rem(r - d * (s + 2) + (s + 2) * n, n),
                        faithful,
                    ),
                )

    def kernel(x_ref, out_ref, send_sem, recv_sem, ack_sem):
        r = lax.axis_index(axis)
        if faithful:
            _dma.all_barrier(axis, n)
        out_ref[r] = x_ref[r]  # local chunk short-circuits

        def step(s, _):
            descs = []
            for h, (d, last) in enumerate(((1, s_fwd), (-1, s_bwd))):
                descs.append(
                    stream_step(x_ref, out_ref, send_sem, recv_sem,
                                ack_sem, r, s, h, d, last)
                )
            for h, (d, last) in enumerate(((1, s_fwd), (-1, s_bwd))):
                stream_finish(ack_sem, descs[h], r, s, h, d, last)
            for rdma in descs:
                rdma.wait_send()
            return 0

        lax.fori_loop(1, s_bwd + 1, step, 0)
        if s_fwd > s_bwd:  # even n: the antipodal chunk, fwd stream only
            # traced like the loop counter, so pl.when sees the same types
            s = jnp.int32(s_fwd)
            rdma = stream_step(x_ref, out_ref, send_sem, recv_sem, ack_sem,
                               r, s, 0, 1, s_fwd)
            stream_finish(ack_sem, rdma, r, s, 0, 1, s_fwd)
            rdma.wait_send()

    return kernel


def _all_to_all_chunked(x, axis, n: int, interpret: bool,
                        collective_id: int, n_chunks: int, chunk_axis: int):
    """Split ``chunk_axis`` into ``n_chunks`` independent per-chunk kernels.

    The slot axis is padded to a multiple of ``n_chunks`` with empty rows
    (``dma.pad_capacity`` — the shared rounding rule — so routing/drop
    semantics are untouched by the chunking) and each chunk rides its own
    Pallas all-to-all with a 2-parity rotated ``collective_id``: chunk c and
    chunk c+1 never share barrier/credit semaphores, so two chunk kernels
    can be in flight at once — the double buffering that lets a consumer's
    compute for chunk c hide under the wire of chunk c+1. The budget gate
    charges that 2-deep footprint (2 resident send+recv pairs); over budget
    (or unchunkable shapes) returns None and the caller falls back to the
    unchunked wire."""
    if x.ndim <= chunk_axis:
        return None
    size = x.shape[chunk_axis]
    if size == 0:
        return None
    n_chunks = min(n_chunks, size)
    if n_chunks <= 1:
        return None
    padded = _dma.pad_capacity(size, n_chunks)
    cs = padded // n_chunks
    chunk_elems_per_peer = x.size // size * cs // n
    if not _dma.chunk_budget(n, chunk_elems_per_peer, x.dtype.itemsize,
                             "ep_all_to_all_chunked", interpret):
        return None
    if padded != size:
        pad = [(0, 0)] * x.ndim
        pad[chunk_axis] = (0, padded - size)
        x = jnp.pad(x, pad)
    outs = []
    for c in range(n_chunks):
        sl = [slice(None)] * x.ndim
        sl[chunk_axis] = slice(c * cs, (c + 1) * cs)
        # launch-granularity credit: chunk c waits on chunk c-2 (its id
        # parity twin), so at most two chunk kernels are ever in flight
        xc = _dma.tie_chunk(x[tuple(sl)],
                            outs[c - 2] if c >= 2 else None)
        outs.append(
            all_to_all(
                xc, axis, interpret=interpret,
                collective_id=_dma.chunk_collective_id(collective_id, c),
            )
        )
    out = jnp.concatenate(outs, axis=chunk_axis)
    if padded != size:
        sl = [slice(None)] * x.ndim
        sl[chunk_axis] = slice(0, size)
        out = out[tuple(sl)]
    return out


def all_to_all(
    x: jax.Array,
    axis,
    *,
    interpret=None,
    collective_id=None,
    n_chunks: int = 1,
    chunk_axis: int = 1,
) -> jax.Array:
    """Per-shard ``[W, ...] -> [W, ...]`` all-to-all as ONE Pallas kernel.

    Chunk ``d`` of my buffer lands in slot *my-rank* of member ``d``'s
    output — the exact contract of ``lax.all_to_all(x, axis, 0, 0,
    tiled=True)``, which is also the fallback lowering when the payload
    exceeds the VMEM budget. Use inside ``shard_map`` over the EP axis.

    ``n_chunks > 1`` splits ``chunk_axis`` (a trailing axis — the
    capacity/slot axis of the EP layouts; never 0, the member axis) into
    that many independent per-chunk kernels on 2-parity rotated collective
    ids, so a consumer can overlap chunk c's compute with chunk c±1's wire
    (see :func:`_all_to_all_chunked`). Identical numerics to the unchunked
    exchange; falls back to it when the 2x double-buffer footprint exceeds
    the budget or the shape cannot chunk."""
    n = lax.axis_size(axis)
    if n == 1:
        return x
    if x.shape[0] != n:
        raise ValueError(
            f"all_to_all leading dim {x.shape[0]} != axis size {n}"
        )
    if collective_id is None:
        collective_id = _dma.CID_A2A  # the generic lane ({6,7} when chunked)
    interpret = _dma.resolve_interpret(interpret)
    if (
        isinstance(axis, (tuple, list))
        and len(axis) > 1
        and not _dma.faithful_sync(interpret)
    ):
        # the legacy discharge interpreter addresses peers by flat LOGICAL
        # id along ONE named axis; a tuple EP axis (e.g. flagship's
        # ("dp", "cp")) is unaddressable there — same transparent downgrade
        # Buffer._pallas_wire_ok applies at the verb level
        _dma.record_fallback("ep_all_to_all", "tuple_axis_mesh",
                             detail=tuple(axis))
        return _lax_fallback(x, axis)
    if n_chunks > 1:
        if chunk_axis == 0:
            raise ValueError("chunk_axis 0 is the member axis; chunk a "
                             "trailing (slot) axis instead")
        out = _all_to_all_chunked(x, axis, n, interpret, collective_id,
                                  n_chunks, chunk_axis)
        if out is not None:
            return out
    view, k, m = _dma.pad_chunks(x.reshape(-1), n)  # [n, m//128, 128]
    # both the send and recv buffers are VMEM-resident for the kernel's
    # lifetime, so the budget is charged for the padded pair
    if not _dma.check_budget(2 * n * m * x.dtype.itemsize, "ep_all_to_all",
                             interpret):
        return _lax_fallback(x, axis)
    rows = m // _dma.LANES
    faithful = _dma.faithful_sync(interpret)

    buf = pl.pallas_call(
        _a2a_kernel(axis, n, faithful),
        out_shape=jax.ShapeDtypeStruct((n, rows, _dma.LANES), x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((2, 2)),  # send, per stream x parity
            pltpu.SemaphoreType.DMA((2, 2)),  # recv
            pltpu.SemaphoreType.REGULAR((2,)),  # ack credits, per stream
        ],
        compiler_params=_dma.compiler_params(collective_id),
        interpret=_dma.interp(interpret),
    )(view)
    out = buf.reshape(n, m)[:, :k]
    return out.reshape(x.shape)


# ---------------------------------------------------------------------------
# Scheduled (contention-aware) wire: one Pallas kernel per Birkhoff round
#
# The unscheduled kernel above ships every (src, dst) pair on two fixed
# counter-rotating streams; under skewed routing the hottest link serializes
# while cold links idle. The scheduled wire drives the SAME one-sided
# write-once DMAs in a different ORDER: the host scheduler
# (uccl_tpu.ep.a2a_sched.wire_schedule) decomposes the traffic matrix into
# contention-free full-permutation rounds (heaviest flows first), and each
# round runs as its own small kernel — every member sends exactly one chunk
# and receives exactly one chunk per round, so no ICI port ever carries two
# transfers at once. Exactness is structural: the same per-pair capacity
# chunks cross the wire exactly once each (shadow duplicates are never read
# back), merely reordered, so the assembled result is bit-identical to the
# unscheduled kernel and to lax.all_to_all.
#
# Rounds must be FULL permutations (self-loops allowed — a self-DMA is a
# local copy): under the legacy discharge interpreter a remote DMA lowers to
# a rendezvous collective over ALL mesh members, so a member predicated out
# of a round would deadlock the rendezvous; on real hardware full rounds
# also keep the entry barrier and semaphore accounting uniform.


def _sched_round_kernel(axis, n: int, faithful: bool):
    """One permutation round: member ``r`` DMAs its chunk for ``pi[r]`` into
    that member's single round-output slot. Write-once per kernel (every
    member receives exactly one chunk), so no credit protocol is needed —
    cross-round airborne discipline is the launch-level 2-id rotation +
    tie_chunk, exactly like the chunk pipeline."""

    def kernel(pi_ref, x_ref, out_ref, send_sem, recv_sem):
        r = lax.axis_index(axis)
        if faithful:
            _dma.all_barrier(axis, n)
        dst = pi_ref[r]
        rdma = pltpu.make_async_remote_copy(
            src_ref=x_ref.at[dst],
            dst_ref=out_ref,
            send_sem=send_sem,
            recv_sem=recv_sem,
            **_dma.remote_kwargs(axis, dst, faithful),
        )
        rdma.start()
        rdma.wait_send()
        rdma.wait_recv()

    return kernel


def _run_rounds(view, axis, n: int, perms, interpret, base_cid: int,
                launch_seq: list):
    """Launch one round kernel per permutation over ``view`` ([n, rows,
    LANES]). ``launch_seq`` is the GLOBAL launch list shared across chunks:
    kernel i ties to kernel i-2's output and takes id parity i&1, so the
    whole scheduled exchange is one linear sequence with at most two
    kernels airborne — the invariant that makes the {base, base+1} id
    rotation sound across chunk AND round boundaries."""
    rows = view.shape[1]
    faithful = _dma.faithful_sync(interpret)
    kern = _sched_round_kernel(axis, n, faithful)
    outs = []
    for pi in perms:
        i = len(launch_seq)
        v = _dma.tie_chunk(view, launch_seq[i - 2] if i >= 2 else None)
        out = pl.pallas_call(
            kern,
            out_shape=jax.ShapeDtypeStruct((rows, _dma.LANES), view.dtype),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.SemaphoreType.DMA(()),  # send
                pltpu.SemaphoreType.DMA(()),  # recv
            ],
            compiler_params=_dma.compiler_params(
                _dma.chunk_collective_id(base_cid, i)
            ),
            interpret=_dma.interp(interpret),
        )(jnp.asarray(pi, jnp.int32), v)
        launch_seq.append(out)
        outs.append(out)
    return outs


def _assemble_rounds(view, round_outs, k_mat, axis, n: int):
    """Gather each source's slot from its designated round and overwrite
    the diagonal with the local chunk. ``k_mat`` is the static [W, W]
    designated-round matrix; on member ``r`` the needed column is
    ``k_mat[:, r]`` — a dynamic slice of a constant by the traced rank."""
    r = lax.axis_index(axis)
    stacked = jnp.stack(round_outs)  # [R, rows, LANES]
    col = lax.dynamic_index_in_dim(
        jnp.asarray(k_mat, jnp.int32), r, axis=1, keepdims=False
    )  # [n]: designated round per source
    gathered = jnp.take(stacked, col, axis=0)  # [n, rows, LANES]
    local = lax.dynamic_index_in_dim(view, r, axis=0, keepdims=False)
    return lax.dynamic_update_index_in_dim(gathered, local, r, axis=0)


def _normalize_schedule(schedule, n: int):
    """Accept (rounds, K) from a2a_sched.wire_schedule (Round objects or
    raw permutation tuples) and return (perm tuples, K) validated against
    the axis size."""
    rounds, k_mat = schedule
    perms = []
    for rnd in rounds:
        perm = tuple(getattr(rnd, "perm", rnd))
        if sorted(perm) != list(range(n)):
            raise ValueError(
                f"scheduled a2a round {perm} is not a permutation of "
                f"range({n})"
            )
        perms.append(perm)
    import numpy as _np

    k_arr = _np.asarray(k_mat, _np.int32)
    if k_arr.shape != (n, n):
        raise ValueError(
            f"designated-round matrix is {k_arr.shape}, want {(n, n)}"
        )
    if perms and (k_arr.max() >= len(perms) or k_arr.min() < 0):
        raise ValueError("designated-round matrix indexes a missing round")
    for s in range(n):
        for d in range(n):
            if s != d and perms and perms[k_arr[s, d]][s] != d:
                raise ValueError(
                    f"round {k_arr[s, d]} does not carry pair ({s}, {d})"
                )
    return perms, k_arr


def _scheduled_chunked(x, axis, n: int, perms, k_mat, interpret,
                       collective_id: int, n_chunks: int, chunk_axis: int):
    """Chunk-pipelined scheduled exchange: the capacity axis splits exactly
    like :func:`_all_to_all_chunked`, each chunk runs the full round
    schedule, and ALL (chunk, round) kernels share one global launch
    sequence (see :func:`_run_rounds`) so two are airborne at most. Returns
    None past the double-buffer budget (caller falls back unchunked)."""
    if x.ndim <= chunk_axis:
        return None
    size = x.shape[chunk_axis]
    if size == 0:
        return None
    n_chunks = min(n_chunks, size)
    if n_chunks <= 1:
        return None
    padded = _dma.pad_capacity(size, n_chunks)
    cs = padded // n_chunks
    chunk_elems_per_peer = x.size // size * cs // n
    if not _dma.chunk_budget(n, chunk_elems_per_peer, x.dtype.itemsize,
                             "ep_a2a_sched_chunked", interpret):
        return None
    if padded != size:
        pad = [(0, 0)] * x.ndim
        pad[chunk_axis] = (0, padded - size)
        x = jnp.pad(x, pad)
    launch_seq: list = []
    outs = []
    for c in range(n_chunks):
        sl = [slice(None)] * x.ndim
        sl[chunk_axis] = slice(c * cs, (c + 1) * cs)
        xc = x[tuple(sl)]
        cshape = xc.shape
        view, kc, mc = _dma.pad_chunks(xc.reshape(-1), n)
        round_outs = _run_rounds(view, axis, n, perms, interpret,
                                 collective_id, launch_seq)
        buf = _assemble_rounds(view, round_outs, k_mat, axis, n)
        outs.append(buf.reshape(n, mc)[:, :kc].reshape(cshape))
    out = jnp.concatenate(outs, axis=chunk_axis)
    if padded != size:
        sl = [slice(None)] * x.ndim
        sl[chunk_axis] = slice(0, size)
        out = out[tuple(sl)]
    return out


def scheduled_all_to_all(
    x: jax.Array,
    axis,
    schedule,
    *,
    interpret=None,
    collective_id=None,
    n_chunks: int = 1,
    chunk_axis: int = 1,
) -> jax.Array:
    """Per-shard ``[W, ...] -> [W, ...]`` all-to-all driven one contention-
    free permutation round at a time.

    ``schedule`` is the host-built ``(rounds, K)`` pair from
    :func:`uccl_tpu.ep.a2a_sched.wire_schedule`: load-ordered full
    permutations plus the designated-round matrix. Same tiled contract —
    and bit-identical output — as :func:`all_to_all` and
    ``lax.all_to_all``: the rounds are a pure reordering of the same
    write-once per-pair DMAs, reassembled by designated round. Composes
    with ``n_chunks`` pipelining exactly like the unscheduled wire (one
    global launch sequence keeps at most two kernels airborne on the
    rotated {22, 23} id pair). Falls back to the unscheduled kernel — and
    transitively to lax — past the VMEM budget or on meshes the kernel
    cannot address."""
    n = lax.axis_size(axis)
    if n == 1:
        return x
    if x.shape[0] != n:
        raise ValueError(
            f"all_to_all leading dim {x.shape[0]} != axis size {n}"
        )
    interpret = _dma.resolve_interpret(interpret)
    if (
        isinstance(axis, (tuple, list))
        and len(axis) > 1
        and not _dma.faithful_sync(interpret)
    ):
        _dma.record_fallback("ep_a2a_sched", "tuple_axis_mesh",
                             detail=tuple(axis))
        return _lax_fallback(x, axis)
    perms, k_mat = _normalize_schedule(schedule, n)
    if not perms:  # empty schedule: nothing crosses the wire at n > 1
        raise ValueError("scheduled a2a needs at least one round at n > 1")
    if collective_id is None:
        collective_id = _dma.CID_SCHED
    if n_chunks > 1:
        if chunk_axis == 0:
            raise ValueError("chunk_axis 0 is the member axis; chunk a "
                             "trailing (slot) axis instead")
        out = _scheduled_chunked(x, axis, n, perms, k_mat, interpret,
                                 collective_id, n_chunks, chunk_axis)
        if out is not None:
            return out
    view, k, m = _dma.pad_chunks(x.reshape(-1), n)  # [n, m//128, 128]
    # resident per round kernel: the [n, ...] send view + one round slot,
    # two kernels airborne (the global tie_chunk sequence)
    if not _dma.check_budget(2 * (n + 1) * m * x.dtype.itemsize,
                             "ep_a2a_sched", interpret):
        return all_to_all(x, axis, interpret=interpret)
    launch_seq: list = []
    round_outs = _run_rounds(view, axis, n, perms, interpret, collective_id,
                             launch_seq)
    buf = _assemble_rounds(view, round_outs, k_mat, axis, n)
    out = buf.reshape(n, m)[:, :k]
    return out.reshape(x.shape)
