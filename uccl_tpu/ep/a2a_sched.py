"""Contention-aware scheduling of the EP all-to-all traffic matrix.

The EP dispatch/combine wire ships every (src, dst) pair simultaneously on
fixed counter-rotating streams (ep/pallas_a2a.py): under skewed expert
routing — the common case in real MoE traffic — the hottest link serializes
while cold links idle. FAST (PAPERS.md: "An Efficient Scheduler for
All-to-All GPU Communication") recovers that bandwidth by decomposing the
traffic matrix into load-ordered contention-free permutation rounds: each
round every member sends to at most one peer and receives from at most one
peer, so no ICI port carries two transfers at once, and the heaviest flows
go first so stragglers overlap the tail instead of gating it.

This module is the HOST side of that design: pure-numpy schedule
construction over a [W, W] traffic matrix, consumed by the device driver
(:func:`ep.pallas_a2a.scheduled_all_to_all`) which runs one Birkhoff round
per kernel on rotated collective ids. Nothing here traces — the matrix must
be host-available (benches/serving derive it from routing counts via
:func:`traffic_from_topk`; inside a jit the counts are traced, so callers
pass the matrix through the ``a2a_sched`` knob instead).

Vocabulary: a *round* is a partial permutation ``perm[W]`` (``perm[s]`` =
destination of member ``s``'s transfer this round, ``-1`` = idle). The
greedy heaviest-first first-fit below is the classic Birkhoff-von-Neumann
style decomposition relaxed to partial matchings: every nonzero
off-diagonal entry lands in exactly one round, no round has a source or
destination conflict, and the round count is bounded by the greedy
edge-coloring bound ``2·Δ − 1`` (Δ = max in/out degree of the nonzero
pattern) — each edge (s, d) conflicts with at most Δ−1 other edges at s
plus Δ−1 at d, so first-fit always finds a free round among the first
``2Δ − 1``.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

from uccl_tpu.obs import counters as _obsc

# get-or-create: the scheduled-a2a observability pair (OBSERVABILITY.md).
ROUNDS_TOTAL = _obsc.counter(
    "ep_a2a_rounds_total",
    "permutation rounds driven by the scheduled EP all-to-all, by algo "
    "(sched = contention-free Birkhoff rounds, streams = the fixed "
    "counter-rotating wire counted as its W-1 implicit rounds)",
)
SKEW_GAUGE = _obsc.gauge(
    "ep_a2a_skew",
    "hottest-port/mean-port load of the last EP traffic matrix the a2a "
    "planner saw (1.0 = uniform; the sched/streams crossover input)",
)


@dataclasses.dataclass(frozen=True)
class Round:
    """One contention-free permutation round of the decomposition.

    ``perm[s]`` is the destination member of source ``s`` (``-1`` = idle
    this round); ``load`` is the round's total traffic (sum of the matrix
    entries it carries) — the heaviest-first sort key.
    """

    perm: Tuple[int, ...]
    load: float

    @property
    def n_edges(self) -> int:
        return sum(1 for d in self.perm if d >= 0)

    def inverse(self) -> Tuple[int, ...]:
        """``inv[d]`` = source sending to member ``d`` this round (-1 = none)."""
        inv = [-1] * len(self.perm)
        for s, d in enumerate(self.perm):
            if d >= 0:
                inv[d] = s
        return tuple(inv)


def _as_matrix(matrix) -> np.ndarray:
    m = np.asarray(matrix, np.float64)
    if m.ndim != 2 or m.shape[0] != m.shape[1]:
        raise ValueError(f"traffic matrix must be square, got {m.shape}")
    if (m < 0).any():
        raise ValueError("traffic matrix entries must be non-negative")
    return m


def skew(matrix) -> float:
    """Hottest-port / mean-port load of the OFF-DIAGONAL traffic — the
    planner's contention feature. Row s = bytes member s's send port ships,
    column d = bytes member d's receive port absorbs; the fixed streams
    serialize behind whichever port is hottest (real MoE skew is usually a
    hot COLUMN — everyone routing to the members that own the popular
    experts), while the mean row is what a perfectly balanced schedule
    would pay. Uniform (and all-zero) matrices score 1.0. Symmetric under
    transposition, so dispatch and its transposed combine matrix see the
    same value."""
    m = _as_matrix(matrix).copy()
    np.fill_diagonal(m, 0.0)
    rows = m.sum(axis=1)
    mean = rows.mean()
    if mean <= 0.0:
        return 1.0
    return float(max(rows.max(), m.sum(axis=0).max()) / mean)


def max_degree(matrix) -> int:
    """Max nonzero in/out degree of the off-diagonal pattern (the Δ of the
    ``2Δ − 1`` greedy round bound)."""
    m = _as_matrix(matrix).copy()
    np.fill_diagonal(m, 0.0)
    nz = m > 0.0
    if not nz.any():
        return 0
    return int(max(nz.sum(axis=1).max(), nz.sum(axis=0).max()))


def decompose(matrix) -> List[Round]:
    """Greedy heaviest-first Birkhoff-style decomposition into partial
    permutation rounds.

    Edges (off-diagonal nonzero entries) are processed by descending weight
    (ties broken by (src, dst) for determinism) and first-fit assigned to
    the earliest round where both the source's send port and the
    destination's receive port are free. The result is returned sorted by
    round load, heaviest first. Properties (tested host-only in
    tests/test_a2a_sched.py):

    * each round is a partial permutation — no port contention;
    * every nonzero off-diagonal entry is carried by exactly one round, so
      the per-edge sum over rounds reproduces the matrix exactly;
    * ``len(rounds) ≤ max(1, 2·max_degree(matrix) − 1)``;
    * round loads are non-increasing (heaviest-first ordering).

    The diagonal (local traffic) never crosses the wire and is ignored.
    A zero matrix decomposes to no rounds.
    """
    m = _as_matrix(matrix)
    w = m.shape[0]
    edges = [
        (float(m[s, d]), s, d)
        for s in range(w)
        for d in range(w)
        if s != d and m[s, d] > 0.0
    ]
    edges.sort(key=lambda e: (-e[0], e[1], e[2]))

    perms: List[List[int]] = []
    loads: List[float] = []
    src_used: List[set] = []
    dst_used: List[set] = []
    for wgt, s, d in edges:
        for i in range(len(perms)):
            if s not in src_used[i] and d not in dst_used[i]:
                break
        else:
            i = len(perms)
            perms.append([-1] * w)
            loads.append(0.0)
            src_used.append(set())
            dst_used.append(set())
        perms[i][s] = d
        loads[i] += wgt
        src_used[i].add(s)
        dst_used[i].add(d)

    rounds = [Round(tuple(p), l) for p, l in zip(perms, loads)]
    rounds.sort(key=lambda r: -r.load)
    return rounds


def full_rounds(world: int) -> List[Round]:
    """The unscheduled wire's implicit schedule as rounds: W−1 full rotation
    permutations (round s sends s+1 hops forward) — what the fixed streams
    ship when every pair talks. Used to complete a partial decomposition to
    total coverage (:func:`complete_rounds`) and as the streams-side round
    count on :data:`ROUNDS_TOTAL`."""
    return [
        Round(tuple((s + h) % world for s in range(world)), 0.0)
        for h in range(1, world)
    ]


def wire_schedule(matrix, world: int) -> Tuple[List[Round], np.ndarray]:
    """The device driver's schedule: full-permutation rounds + the
    designated-round matrix.

    The fixed-capacity EP wire ships ALL W·(W−1) off-diagonal slots
    (zero-count pairs carry empty capacity rows), so the device schedule
    must cover the complete bipartite pattern regardless of which matrix
    entries were nonzero — and under the interpret-mode substrate a remote
    DMA is a rendezvous collective over ALL mesh members, so every round
    must keep every member participating: rounds are FULL permutations,
    never partial (a member with nothing useful to send this round ships a
    shadow edge — a self-loop is a cheap local copy, a duplicate pair is
    dead bandwidth on a port that was idle anyway). Construction:

    1. :func:`decompose` the matrix (heaviest-first partial matchings);
    2. first-fit the uncovered zero-load off-diagonal pairs into the
       existing rounds' free ports (new trailing rounds only when full) —
       after this every off-diagonal pair has exactly ONE designated round,
       recorded in ``K[s, d]``;
    3. pad each round's remaining holes to a full permutation with shadow
       edges (self-loops first, then a rotation of the leftover ports) —
       shadow receptions are never read back: assembly gathers each slot
       from its designated round via ``K`` and overwrites the diagonal with
       the local chunk.

    Returns ``(rounds, K)``: ``rounds[i].perm`` is a total permutation of
    ``range(world)``; ``K`` is int32 [W, W] with ``K[s, d]`` = the round
    carrying pair (s, d) for s != d (diagonal entries are 0 and unused).
    The heavy prefix — and therefore the heaviest-first ordering — is
    preserved by steps 2-3 (they only touch free ports).
    """
    m = _as_matrix(matrix)
    if m.shape[0] != world:
        raise ValueError(
            f"traffic matrix is {m.shape[0]}x{m.shape[0]}, world is {world}"
        )
    base = decompose(m)
    perms = [list(r.perm) for r in base]
    loads = [r.load for r in base]
    k_mat = np.zeros((world, world), np.int32)
    covered = set()
    for i, r in enumerate(base):
        for s, d in enumerate(r.perm):
            if d >= 0:
                covered.add((s, d))
                k_mat[s, d] = i
    src_used = [set(s for s, d in enumerate(p) if d >= 0) for p in perms]
    dst_used = [set(d for d in p if d >= 0) for p in perms]
    missing = [
        (s, d) for s in range(world) for d in range(world)
        if s != d and (s, d) not in covered
    ]
    # hop-ordered fill packs the zero-load pairs into rotation-shaped
    # rounds (an empty matrix completes to exactly the W-1 rotations the
    # fixed streams would drive, not a ragged lexicographic packing)
    missing.sort(key=lambda sd: ((sd[1] - sd[0]) % world, sd[0]))
    for s, d in missing:
        for i in range(len(perms)):
            if s not in src_used[i] and d not in dst_used[i]:
                break
        else:
            i = len(perms)
            perms.append([-1] * world)
            loads.append(0.0)
            src_used.append(set())
            dst_used.append(set())
        perms[i][s] = d
        src_used[i].add(s)
        dst_used[i].add(d)
        k_mat[s, d] = i
    # pad holes to total permutations with shadow edges (not recorded in K)
    for i, p in enumerate(perms):
        free_src = [s for s in range(world) if s not in src_used[i]]
        free_dst = [d for d in range(world) if d not in dst_used[i]]
        self_loops = sorted(set(free_src) & set(free_dst))
        for s in self_loops:
            p[s] = s
            free_src.remove(s)
            free_dst.remove(s)
        # leftover ports are disjoint after self-loop extraction, so any
        # pairing is a valid (duplicate-pair) shadow edge
        for s, d in zip(free_src, free_dst):
            p[s] = d
    return [Round(tuple(p), l) for p, l in zip(perms, loads)], k_mat


def traffic_from_topk(topk_idx, num_experts: int, capacity: int,
                      world: int) -> np.ndarray:
    """Host-side [W, W] traffic matrix from per-member top-k routing.

    ``topk_idx``: [W, T, K] expert ids per source member. Mirrors the
    sorted-path drop semantics exactly (ops.sorted_from_topk): per (member,
    expert) demand is clipped at ``capacity`` — ``kept = min(count, C)`` —
    and expert ``e`` lives on member ``e // (E // W)``. Entry [s, d] is the
    number of routed rows member ``s`` sends to member ``d``'s experts
    (the diagonal counts local rows; :func:`decompose` ignores it).
    """
    idx = np.asarray(topk_idx)
    if idx.ndim != 3 or idx.shape[0] != world:
        raise ValueError(f"topk_idx must be [world, T, K], got {idx.shape}")
    if num_experts % world:
        raise ValueError(f"num_experts {num_experts} not divisible by world {world}")
    e_local = num_experts // world
    traffic = np.zeros((world, world), np.int64)
    for s in range(world):
        counts = np.bincount(idx[s].reshape(-1), minlength=num_experts)
        kept = np.minimum(counts[:num_experts], capacity)
        traffic[s] = kept.reshape(world, e_local).sum(axis=1)
    return traffic


def zipf_topk(rng: np.random.Generator, world: int, tokens: int, k: int,
              num_experts: int, alpha: float) -> np.ndarray:
    """Synthetic skewed routing for benches/tests: [W, T, K] expert ids with
    Zipf(alpha) expert popularity (alpha=0 → uniform). Every member draws
    from the same popularity law, so hot experts concentrate traffic on
    their owner members — the skewed-column pattern the scheduler exists
    for."""
    ranks = np.arange(1, num_experts + 1, dtype=np.float64)
    p = ranks ** (-float(alpha))
    p /= p.sum()
    return rng.choice(
        num_experts, size=(world, tokens, k), p=p
    ).astype(np.int32)


def record_decision(algo: str, world: int, n_rounds: Optional[int] = None,
                    matrix=None) -> None:
    """Land one a2a scheduling decision on the obs pair: the skew the
    planner saw (gauge) and the round count the chosen algo will drive
    (counter; the fixed streams count their W−1 implicit rotation rounds).
    The planner's algo choice itself goes on collective_plan_total via
    plan.CollectivePlanner.plan_ep_a2a — this records the schedule shape.
    """
    if matrix is not None:
        SKEW_GAUGE.set(skew(matrix))
    if n_rounds is None:
        n_rounds = max(0, world - 1)
    if n_rounds > 0:
        ROUNDS_TOTAL.inc(n_rounds, algo=algo)
