"""Engram-style remote-memory row fetch (reference: lite-ep's experimental
"0 SM Engram" primitive — deep_ep.ElasticBuffer.engram_write/engram_fetch,
tests/elastic/test_engram.py; csrc/kernels/elastic/engram.hpp).

The reference's shape: every rank owns a contiguous shard of a global row
table ``[world * entries, hidden]``; ``engram_fetch(indices)`` gathers rows
by GLOBAL index from the owning ranks' memory over RDMA with zero SM cost,
returning a hook to overlap the fetch. The TPU-native re-design has the
same two deployment shapes as the rest of the EP pillar:

* **on-mesh** (:func:`mesh_fetch`): the table is sharded over a mesh axis
  and the fetch is a sharded ``take`` — XLA emits the gather collectives
  over ICI, which on TPU is the compiler-driven analog of the zero-SM
  claim (no hand-written kernel occupies compute either way).
* **cross-host** (:class:`EngramTable`): each host registers its shard as
  an advertised window on the transfer engine; ``fetch`` groups the
  requested global indices by owner and issues ONE batched one-sided
  ``readv`` per owner (vectorized descriptors: one ring pass, one proxy
  wake — engine.h readv), reassembling rows into their requested order.
  ``fetch_async`` returns a ``wait()`` hook so the caller overlaps the
  remote reads with local work — the reference's hook contract.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple

import numpy as np


def mesh_fetch(table, indices):
    """Sharded global-row gather on the mesh. ``table``: a jax array
    (optionally sharded on dim 0), ``indices``: [T] global row ids.
    Returns [T, hidden]; XLA plans the cross-shard movement."""
    import jax.numpy as jnp

    return jnp.take(table, indices, axis=0)


class EngramTable:
    """One rank's view of a cross-host row table over the transfer engine.

    ``local_rows`` ([entries, hidden], c-contiguous) is registered and
    advertised once; :meth:`link` wires the per-peer connections and swaps
    window descriptors (symmetric send-then-recv, like the channel probe
    handshake). Global row ``g`` lives on rank ``g // entries`` at local
    offset ``g % entries``.
    """

    def __init__(self, ep, local_rows: np.ndarray, rank: int, world: int):
        if not local_rows.flags["C_CONTIGUOUS"]:
            raise ValueError("local_rows must be C-contiguous")
        self.ep = ep
        self.rank = rank
        self.world = world
        self.rows = local_rows
        self.entries, self.hidden = local_rows.shape
        self.row_bytes = int(local_rows.strides[0])
        self._mr = ep.reg(local_rows)
        self._fifo = ep.advertise(self._mr)
        self._conns: Dict[int, int] = {}
        self._peer_fifos: Dict[int, bytes] = {}

    def link(self, peers: Dict[int, int]) -> None:
        """peers: {rank: conn_id} for every OTHER rank. Exchanges window
        descriptors so both directions can fetch."""
        from uccl_tpu.p2p.channel import FifoItem  # noqa: F401 (doc link)

        self._conns = dict(peers)
        for r, conn in sorted(peers.items()):
            self.ep.send(conn, b"EG" + self._fifo)
        for r, conn in sorted(peers.items()):
            msg = self.ep.recv(conn, timeout_ms=30000)
            if not msg.startswith(b"EG"):
                raise IOError(f"engram link broken with rank {r}: {msg[:8]!r}")
            self._peer_fifos[r] = msg[2:]

    def _plan(self, indices: np.ndarray):
        owners = indices // self.entries
        offsets = indices % self.entries
        if (owners >= self.world).any() or (indices < 0).any():
            raise ValueError("global index out of range")
        return owners, offsets

    def fetch_async(self, indices) -> Tuple[np.ndarray, Callable[[], np.ndarray]]:
        """Start fetching rows by global index; returns ``(out, wait)``
        where ``wait()`` blocks until ``out`` ([T, hidden], requested
        order) is fully populated — the reference's hook contract, for
        overlapping remote reads with local compute."""
        from uccl_tpu.p2p.channel import FifoItem

        idx = np.asarray(indices, np.int64).reshape(-1)
        owners, offsets = self._plan(idx)
        out = np.empty((idx.size, self.hidden), self.rows.dtype)
        pending: List[Tuple[int, int]] = []  # (conn, xid) batches
        for r in np.unique(owners):
            rows_here = np.nonzero(owners == r)[0]
            if r == self.rank:
                out[rows_here] = self.rows[offsets[rows_here]]
                continue
            item = FifoItem.unpack(self._peer_fifos[int(r)])
            dsts = [out[i] for i in rows_here]
            fifos = [
                item.slice(int(offsets[i]) * self.row_bytes, self.row_bytes
                           ).pack()
                for i in rows_here
            ]
            conn = self._conns[int(r)]
            for x in self.ep.readv_async(conn, dsts, fifos):
                pending.append((conn, x))

        def wait(timeout_ms: int = 30000) -> np.ndarray:
            failed = [
                x for _, x in pending if not self.ep.wait(x, timeout_ms)
            ]
            if failed:
                raise IOError(
                    f"engram fetch: {len(failed)}/{len(pending)} rows failed"
                )
            return out

        return out, wait

    def fetch(self, indices) -> np.ndarray:
        """Blocking fetch: rows [T, hidden] in requested order."""
        _, wait = self.fetch_async(indices)
        return wait()

    def close(self) -> None:
        self.ep.dereg(self._mr)
