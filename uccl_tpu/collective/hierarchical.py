"""Hierarchical cross-pod collectives: ICI inside the mesh, DCN between pods.

The reference's multi-NIC/multi-engine split re-expressed for TPU scale-out
(SURVEY.md §7 step 4): within a pod, collectives ride ICI via the mesh
(Communicator); between pods — where the host owns the wire — the transfer
engine moves the data. The canonical hierarchical allreduce:

  1. reduce_scatter over the local mesh axis (ICI) — each host ends with a
     reduced shard,
  2. allreduce of that shard across pods over DCN (ring over Channels),
  3. all_gather back over ICI.

``DcnGroup`` is the cross-pod communicator: N processes, rank i connected to
its ring neighbors through multipath Channels, bootstrap via the OOB store.
Works between any processes with TCP reach — the same code path drives
pod-to-pod DCN on real deployments and localhost process pairs in tests.
"""

from __future__ import annotations

import threading
from typing import List, Optional

import numpy as np

from uccl_tpu.p2p.channel import Channel
from uccl_tpu.p2p.endpoint import Endpoint
from uccl_tpu.parallel.distributed import Session, exchange_json
from uccl_tpu.utils.logging import get_logger

_log = get_logger("COLL")


def _local_ip() -> str:
    """Address peers should dial: UCCL_TPU_HOST_IP env, else the hostname's
    address, else loopback (single-host default)."""
    import os
    import socket

    ip = os.environ.get("UCCL_TPU_HOST_IP")
    if ip:
        return ip
    try:
        ip = socket.gethostbyname(socket.gethostname())
        if ip and not ip.startswith("127."):
            return ip
    except OSError:
        pass
    return "127.0.0.1"


class DcnGroup:
    """Cross-process collective group over the DCN transfer engine.

    Bootstraps a bidirectional ring: every rank connects a Channel to its
    next neighbor and accepts one from its previous neighbor (addresses via
    the session's OOB store). ``tag`` must be unique per group per session
    (ranks must create groups in the same order).
    """

    def __init__(self, sess: Session, n_paths: int = 2, tag: str = "0"):
        self.rank = sess.rank
        self.world = sess.world
        self.ep = Endpoint(n_engines=max(2, n_paths))
        addrs = exchange_json(
            sess,
            f"dcn_group/{tag}/addr",
            {"ip": _local_ip(), "port": self.ep.port},
        )
        self._next: Optional[Channel] = None
        self._prev: Optional[Channel] = None
        self._ring_mr: Optional[int] = None
        self._ring_recv: Optional[np.ndarray] = None
        self._peer_fifo: Optional[bytes] = None
        if self.world > 1:
            nxt = addrs[(self.rank + 1) % self.world]
            acc = {}
            t = threading.Thread(
                target=lambda: acc.setdefault("c", Channel.accept(self.ep, 30000))
            )
            t.start()
            self._next = Channel.connect(self.ep, nxt["ip"], nxt["port"], n_paths)
            # Channel.accept makes ~2*n_paths blocking calls of 30s each;
            # join must outlast the worst case or we misreport failure.
            t.join(timeout=30 * (2 * n_paths + 1))
            self._prev = acc.get("c")
            if self._prev is None:
                raise ConnectionError("ring bootstrap failed: no inbound channel")

    def close(self):
        self.ep.close()

    # ------------------------------------------------------------------
    def _setup_ring_buf(self, nbytes: int, dtype) -> np.ndarray:
        """(Re)advertise the hop landing buffer: one byte-window serves every
        hop of every collective (no per-hop registrations to leak); it only
        regrows — and re-exchanges descriptors — when a larger payload
        arrives, which happens in lockstep on all ranks (SPMD collectives)."""
        if self._ring_recv is None or self._ring_recv.nbytes < nbytes:
            if self._ring_mr is not None:
                self.ep.dereg(self._ring_mr)
            self._ring_recv = np.empty(max(nbytes, 1), np.uint8)
            self._ring_mr = self.ep.reg(self._ring_recv)
            fifo = self.ep.advertise(self._ring_mr)
            self._prev.send(b"FIFO" + fifo)
            msg = self._next.recv(timeout_ms=30000)
            if not msg.startswith(b"FIFO"):
                raise IOError(f"ring fifo exchange broken: {msg[:16]!r}")
            self._peer_fifo = msg[4:]
        return self._ring_recv[:nbytes].view(dtype)

    def _ring_hop(self, send_arr: np.ndarray):
        """One hop: signal ready, one-sided write to next, confirm done.

        The per-hop READY from the receiver is what licenses the writer to
        reuse the landing window — without it hop s+1 could overwrite data
        the receiver is still consuming from hop s.
        """
        self._prev.send(b"R")
        if self._next.recv(timeout_ms=30000) != b"R":
            raise IOError("ring protocol: expected READY")
        from uccl_tpu.p2p.channel import FifoItem

        item = FifoItem.unpack(self._peer_fifo)
        self._next.write(
            send_arr, item.slice(0, send_arr.nbytes).pack()
        )
        self._next.send(b"D")
        if self._prev.recv(timeout_ms=30000) != b"D":
            raise IOError("ring protocol: expected DONE")

    def all_reduce(self, x: np.ndarray) -> np.ndarray:
        """Ring allreduce of a host array across the process group (sum).

        Chunked ring: reduce-scatter then all-gather, n-1 hops each, every
        hop a one-sided chunked write through the channel.
        """
        n = self.world
        if n == 1:
            return x.copy()
        flat = np.ascontiguousarray(x).reshape(-1).astype(x.dtype)
        pad = (-flat.size) % n
        if pad:
            flat = np.concatenate([flat, np.zeros(pad, x.dtype)])
        buf = flat.reshape(n, -1).copy()
        recv = self._setup_ring_buf(buf[0].nbytes, buf.dtype)
        r = self.rank
        # reduce-scatter: chunk j accumulates around the ring, lands at member j
        for s in range(n - 1):
            send_slot = (r - s - 1) % n
            recv_slot = (r - s - 2) % n
            self._ring_hop(buf[send_slot])
            buf[recv_slot] += recv
        # all-gather: circulate owned slots
        for s in range(n - 1):
            send_slot = (r - s) % n
            recv_slot = (r - s - 1) % n
            self._ring_hop(buf[send_slot])
            buf[recv_slot] = recv
        out = buf.reshape(-1)
        if pad:
            out = out[:-pad]
        return out.reshape(x.shape)

    def all_gather(self, x: np.ndarray) -> np.ndarray:
        """Gather equal-shaped host arrays from every rank: out[i] = rank i's x."""
        n = self.world
        out = np.empty((n,) + x.shape, x.dtype)
        out[self.rank] = x
        if n == 1:
            return out
        recv = self._setup_ring_buf(x.nbytes, x.dtype).reshape(x.shape)
        cur = np.ascontiguousarray(x)
        for s in range(n - 1):
            self._ring_hop(cur)
            src = (self.rank - s - 1) % n
            out[src] = recv
            cur = recv.copy()  # a real copy: recv is reused as the landing
            # buffer next hop while cur is simultaneously being sent
        return out

    def all_to_all(self, x: np.ndarray) -> np.ndarray:
        """x: [world, ...] — row j goes to rank j; out[i] = rank i's row for us.

        This is the cross-pod EP exchange primitive (the DCN leg of a
        pod-spanning dispatch/combine — reference EP spans hosts the same
        way, through its CPU proxies). Current schedule: ring all-gather of
        the full buffer + local column select — correct at any world size;
        a direct pairwise schedule (n× less traffic) is a planned
        optimization for large pod counts.
        """
        n = self.world
        if x.shape[0] != n:
            raise ValueError(f"all_to_all needs leading dim {n}, got {x.shape}")
        gathered = self.all_gather(x)  # [n, n, ...]
        return np.ascontiguousarray(gathered[:, self.rank])

    def barrier(self):
        self.all_reduce(np.zeros(1, np.float32))


def hierarchical_all_reduce(comm, dcn: DcnGroup, x):
    """Two-level allreduce: ICI reduce-scatter → DCN allreduce → ICI all-gather.

    ``comm`` is an on-mesh :class:`~uccl_tpu.collective.Communicator`
    (rank-dim convention, x: [local_world, N]); ``dcn`` spans pods. Each pod
    moves only N/local_world bytes over DCN and per device only its shard
    crosses the host link — the hierarchical bandwidth win (the moral
    equivalent of the reference's multi-engine NIC split). Result: every
    member of every pod holds the global sum, NCCL-allreduce shaped.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    local = comm.world
    n = x.shape[1]
    shard = comm.reduce_scatter(x)  # [local_world, N/local]: row i = chunk i
    reduced = dcn.all_reduce(np.asarray(shard))  # host staging + DCN exchange
    # back onto the mesh shard-wise (N/local per device over the host link),
    # then the final hop is a true ICI all-gather + on-device broadcast
    shard_dev = comm.device_put(reduced)
    gathered = comm.all_gather(shard_dev)  # replicated [local, N/local]
    out_sharding = NamedSharding(comm.mesh, comm._ranked(1))
    return jax.jit(
        lambda g: jnp.broadcast_to(g.reshape(1, -1), (local, n)),
        out_shardings=out_sharding,
    )(gathered)
