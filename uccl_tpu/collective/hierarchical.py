"""Hierarchical cross-pod collectives: ICI inside the mesh, DCN between pods.

The reference's multi-NIC/multi-engine split re-expressed for TPU scale-out
(SURVEY.md §7 step 4): within a pod, collectives ride ICI via the mesh
(Communicator); between pods — where the host owns the wire — the transfer
engine moves the data. The canonical hierarchical allreduce:

  1. reduce_scatter over the local mesh axis (ICI) — each host ends with a
     reduced shard,
  2. allreduce of that shard across pods over DCN (ring over Channels),
  3. all_gather back over ICI.

``DcnGroup`` is the cross-pod communicator: N processes, rank i connected to
its ring neighbors through multipath Channels, bootstrap via the OOB store.
Works between any processes with TCP reach — the same code path drives
pod-to-pod DCN on real deployments and localhost process pairs in tests.
"""

from __future__ import annotations

import threading
from typing import List, Optional

import numpy as np

from uccl_tpu.p2p.channel import Channel, ChannelAcceptor, FifoItem
from uccl_tpu.p2p.endpoint import Endpoint
from uccl_tpu.parallel.distributed import Session, exchange_json
from uccl_tpu.utils.config import param
from uccl_tpu.utils.logging import get_logger

_log = get_logger("COLL")

# DCN congestion control (reference: kSenderCCA, transport_config.h:96).
# One controller per group, on the ring tx channel — every write on this
# endpoint shares the one token-bucket pacer it actuates.
_cc_algo = param("cc", "off", help="DCN congestion control: off|timely|swift")


def _local_ip() -> str:
    """Address peers should dial: UCCL_TPU_HOST_IP env, else the hostname's
    address, else loopback (single-host default)."""
    import os
    import socket

    ip = os.environ.get("UCCL_TPU_HOST_IP")
    if ip:
        return ip
    try:
        ip = socket.gethostbyname(socket.gethostname())
        if ip and not ip.startswith("127."):
            return ip
    except OSError:
        pass
    return "127.0.0.1"


class DcnGroup:
    """Cross-process collective group over the DCN transfer engine.

    Bootstraps a bidirectional ring: every rank connects a Channel to its
    next neighbor and accepts one from its previous neighbor (addresses via
    the session's OOB store). ``tag`` must be unique per group per session
    (ranks must create groups in the same order).
    """

    def __init__(self, sess: Session, n_paths: int = 2, tag: str = "0"):
        self.rank = sess.rank
        self.world = sess.world
        self.n_paths = n_paths
        self.ep = Endpoint(n_engines=max(2, n_paths))
        self._addrs = exchange_json(
            sess,
            f"dcn_group/{tag}/addr",
            {"ip": _local_ip(), "port": self.ep.port},
        )
        self._next: Optional[Channel] = None
        self._prev: Optional[Channel] = None
        self._ring_mr: Optional[int] = None
        self._ring_recv: Optional[np.ndarray] = None
        self._peer_fifo: Optional[bytes] = None
        # full-mesh state (built lazily on first pairwise op)
        self._mesh: dict = {}  # peer rank -> Channel
        self._mesh_buf: Optional[np.ndarray] = None
        self._mesh_mr: Optional[int] = None
        self._mesh_seg = 0  # bytes per source region in the landing buffer
        self._mesh_fifos: dict = {}  # peer -> FifoItem into MY region on peer
        # all_to_all pipelined-license state (parity double buffering):
        # per-peer call counters, received-consume-license high-water marks,
        # and an epoch bumped on every landing-buffer regrow so stale
        # license messages from the previous buffer generation are discarded
        self._a2a_w: dict = {}  # peer -> my completed writes toward it
        self._a2a_r: dict = {}  # peer -> my completed reads from it
        self._a2a_lic: dict = {}  # peer -> highest C index received
        self._a2a_epoch = 0
        # Inbound channels arrive tagged by the dialer's meta; the acceptor
        # dispatches any interleaving of concurrent dialers (full mesh).
        self._inbound: dict = {}
        self._inbound_cv = threading.Condition()
        self._broken = False  # poisoned after a failed descriptor exchange
        # Elastic membership: collectives run over the ACTIVE ranks; heal()
        # drops dead peers and re-links the ring among survivors (the group-
        # level closure of the reference's add/remove_remote_endpoint,
        # p2p/engine.h:269,273 — the endpoint-level verbs are connect()/
        # remove_conn() on self.ep).
        self._active: List[int] = list(range(self.world))
        self._heal_epoch = 0
        self._acceptor = (
            ChannelAcceptor(self.ep, self._on_inbound) if self.world > 1 else None
        )
        if self.world > 1:
            try:
                self._ring_connect()
            except Exception:
                # Don't leak the acceptor thread + native endpoint when the
                # bootstrap dies (a peer crashed post-rendezvous).
                self.close()
                raise

    def _member_tag(self) -> bytes:
        """Digest of the ACTIVE membership. Channel metas carry it so two
        ranks only pair channels when their membership views agree — and a
        survivor that healed through different intermediate batches (e.g.
        heal([2]) then heal([2,3]) vs one heal([2,3])) still converges with
        peers once the views match, which a per-rank call counter cannot
        guarantee."""
        import hashlib

        return hashlib.md5(
            ",".join(map(str, self._active)).encode()
        ).hexdigest()[:8].encode()

    def _ring_connect(self) -> None:
        """(Re)link the bidirectional ring over the active ranks; channel
        metas carry the membership digest so survivors with diverged views
        never cross-wire."""
        n = len(self._active)
        if n <= 1:
            self._next = self._prev = None
            return
        pos = self._active.index(self.rank)
        nxt_rank = self._active[(pos + 1) % n]
        prv_rank = self._active[(pos - 1) % n]
        a = self._addrs[nxt_rank]
        self._next = Channel.connect(
            self.ep, a["ip"], a["port"], self.n_paths,
            meta=b"ring:%s:%d" % (self._member_tag(), self.rank),
        )
        self._prev = self._wait_inbound(
            b"ring:%s:%d" % (self._member_tag(), prv_rank)
        )
        algo = str(_cc_algo.get())
        if algo != "off":
            self._next.enable_cc(algo)

    def _on_inbound(self, chan: Channel):
        with self._inbound_cv:
            self._inbound[bytes(chan.meta)] = chan
            self._inbound_cv.notify_all()

    def _wait_inbound(self, meta: bytes, timeout_s: float = 60.0) -> Channel:
        with self._inbound_cv:
            if not self._inbound_cv.wait_for(
                lambda: meta in self._inbound, timeout=timeout_s
            ):
                raise ConnectionError(
                    f"bootstrap failed: no inbound channel {meta!r}"
                )
            return self._inbound[meta]

    def heal(self, dead_ranks) -> None:
        """Drop dead peers and re-link the ring among survivors.

        Every survivor must call heal() with the same dead set (e.g. from a
        HeartbeatMonitor on_failure, or after a collective raised). After it
        returns, ring collectives and broadcast run over the survivors; the
        positions of remaining ranks shift to close the gap.
        """
        dead = set(dead_ranks)
        if self.rank in dead:
            raise RuntimeError("cannot heal a group from a dead rank")
        if not dead & set(self._active):
            return
        self._active = [r for r in self._active if r not in dead]
        self._heal_epoch += 1
        # Mesh channels are torn down WHOLESALE, survivors included: an
        # aborted collective may have left half-consumed R/D control bytes
        # (or a poisoned descriptor exchange) on any of them; fresh epoch-
        # tagged channels re-establish lazily with clean queues.
        for r, ch in list(self._mesh.items()):
            ch.close()
        self._mesh.clear()
        self._mesh_fifos.clear()
        self._mesh_buf = None
        self._mesh_seg = 0
        if self._mesh_mr is not None:
            self.ep.dereg(self._mesh_mr)
            self._mesh_mr = None
        self._broken = False
        for ch in (self._next, self._prev):
            if ch is not None:
                ch.close()
        self._next = self._prev = None
        # ring landing state must re-exchange over the new neighbors
        self._ring_recv = None
        self._peer_fifo = None
        if self._ring_mr is not None:
            self.ep.dereg(self._ring_mr)
            self._ring_mr = None
        self._ring_connect()
        _log.warning(
            "healed ring: epoch %d, active ranks %s", self._heal_epoch,
            self._active,
        )

    @property
    def active_world(self) -> int:
        return len(self._active)

    @property
    def pos(self) -> int:
        return self._active.index(self.rank)

    def close(self):
        if self._next is not None:
            self._next.disable_cc()
        if self._acceptor is not None:
            self._acceptor.close()
        self.ep.close()

    # ------------------------------------------------------------------
    def _setup_ring_buf(self, nbytes: int, dtype) -> np.ndarray:
        """(Re)advertise the hop landing buffer: one byte-window serves every
        hop of every collective (no per-hop registrations to leak); it only
        regrows — and re-exchanges descriptors — when a larger payload
        arrives, which happens in lockstep on all ranks (SPMD collectives)."""
        if self._ring_recv is None or self._ring_recv.nbytes < nbytes:
            if self._ring_mr is not None:
                self.ep.dereg(self._ring_mr)
            self._ring_recv = np.empty(max(nbytes, 1), np.uint8)
            self._ring_mr = self.ep.reg(self._ring_recv)
            fifo = self.ep.advertise(self._ring_mr)
            self._prev.send(b"FIFO" + fifo)
            msg = self._next.recv(timeout_ms=30000)
            if not msg.startswith(b"FIFO"):
                raise IOError(f"ring fifo exchange broken: {msg[:16]!r}")
            self._peer_fifo = msg[4:]
        return self._ring_recv[:nbytes].view(dtype)

    def _ring_hop(self, send_arr: np.ndarray):
        """One hop: signal ready, one-sided write to next, confirm done.

        The per-hop READY from the receiver is what licenses the writer to
        reuse the landing window — without it hop s+1 could overwrite data
        the receiver is still consuming from hop s.
        """
        self._prev.send(b"R")
        if self._next.recv(timeout_ms=30000) != b"R":
            raise IOError("ring protocol: expected READY")
        item = FifoItem.unpack(self._peer_fifo)
        self._next.write(
            send_arr, item.slice(0, send_arr.nbytes).pack()
        )
        self._next.send(b"D")
        if self._prev.recv(timeout_ms=30000) != b"D":
            raise IOError("ring protocol: expected DONE")

    def all_reduce(self, x: np.ndarray) -> np.ndarray:
        """Ring allreduce of a host array across the process group (sum).

        Chunked ring: reduce-scatter then all-gather, n-1 hops each, every
        hop a one-sided chunked write through the channel. Runs over the
        ACTIVE ranks (post-heal survivors included).
        """
        n = self.active_world
        if n == 1:
            return x.copy()
        flat = np.ascontiguousarray(x).reshape(-1).astype(x.dtype)
        pad = (-flat.size) % n
        if pad:
            flat = np.concatenate([flat, np.zeros(pad, x.dtype)])
        buf = flat.reshape(n, -1).copy()
        recv = self._setup_ring_buf(buf[0].nbytes, buf.dtype)
        r = self.pos
        # reduce-scatter: chunk j accumulates around the ring, lands at member j
        for s in range(n - 1):
            send_slot = (r - s - 1) % n
            recv_slot = (r - s - 2) % n
            self._ring_hop(buf[send_slot])
            buf[recv_slot] += recv
        # all-gather: circulate owned slots
        for s in range(n - 1):
            send_slot = (r - s) % n
            recv_slot = (r - s - 1) % n
            self._ring_hop(buf[send_slot])
            buf[recv_slot] = recv
        out = buf.reshape(-1)
        if pad:
            out = out[:-pad]
        return out.reshape(x.shape)

    def all_gather(self, x: np.ndarray) -> np.ndarray:
        """Gather equal-shaped host arrays from every active rank:
        out[i] = the array of the i-th ACTIVE rank (== rank i before any
        heal)."""
        n = self.active_world
        out = np.empty((n,) + x.shape, x.dtype)
        out[self.pos] = x
        if n == 1:
            return out
        recv = self._setup_ring_buf(x.nbytes, x.dtype).reshape(x.shape)
        cur = np.ascontiguousarray(x)
        for s in range(n - 1):
            self._ring_hop(cur)
            src = (self.pos - s - 1) % n
            out[src] = recv
            cur = recv.copy()  # a real copy: recv is reused as the landing
            # buffer next hop while cur is simultaneously being sent
        return out

    # ------------------------------------------------------------------
    # Pairwise-mesh machinery (channels built per edge, on demand)

    def _ensure_peers(self, peers):
        """Direct channels to the given peers (SPMD: both ends of every edge
        must request it in the same collective call).

        Dialing rule: the lower rank dials, the higher rank waits for the
        acceptor to file the inbound channel — deterministic and
        deadlock-free since accepting happens on a background thread.
        """
        for j in sorted(peers):
            if j == self.rank or j in self._mesh:
                continue
            if self.rank < j:
                a = self._addrs[j]
                self._mesh[j] = Channel.connect(
                    self.ep, a["ip"], a["port"], self.n_paths,
                    meta=b"mesh:%s:%d" % (self._member_tag(), self.rank),
                )
            else:
                self._mesh[j] = self._wait_inbound(
                    b"mesh:%s:%d" % (self._member_tag(), j)
                )

    def _setup_mesh_buf(self, seg: int, peers):
        """Per-source landing regions: one buffer of world segments; peer j
        may only write region j (its own advertised window — the engine
        enforces the byte range). Regrows in lockstep (SPMD payload sizes).

        Descriptor exchange: a regrow re-exchanges over EVERY existing mesh
        channel (both ends of each channel are in the same collective, so
        sends and receives pair up); otherwise only new peers exchange.
        State commits after the exchange completes — a mid-exchange failure
        poisons the group (control channels may hold half-consumed MF
        messages; no later op can be trusted)."""
        if self._broken:
            raise IOError("DcnGroup poisoned by an earlier failed exchange")
        peers = set(peers) - {self.rank}
        self._ensure_peers(peers)
        seg_needed = max(seg, 1)
        regrow = self._mesh_buf is None or seg_needed > self._mesh_seg
        if regrow:
            exchange = dict(self._mesh)  # every existing channel
        else:
            exchange = {j: self._mesh[j] for j in peers if j not in self._mesh_fifos}
        if not exchange:
            return
        try:
            if regrow:
                new_buf = np.empty(self.world * seg_needed, np.uint8)
                new_mr = self.ep.reg(new_buf)
            else:
                new_buf, new_mr, seg_needed = (
                    self._mesh_buf, self._mesh_mr, self._mesh_seg
                )
            for j, ch in exchange.items():
                fifo = self.ep.advertise(
                    new_mr, offset=j * seg_needed, length=seg_needed
                )
                ch.send(b"MF" + fifo)
            fifos = {}
            for j, ch in exchange.items():
                # _ctrl_recv, not raw recv: up to two deferred all_to_all
                # consume-acks can sit queued on a mesh channel (consumed
                # lazily at call i+2), and a regrow right after an
                # all_to_all must skip them, not poison the group
                msg = self._ctrl_recv(ch, j)
                if not msg.startswith(b"MF"):
                    raise IOError(f"mesh fifo exchange broken: {msg[:8]!r}")
                fifos[j] = FifoItem.unpack(msg[2:])
        except Exception:
            self._broken = True
            raise
        if regrow:
            if self._mesh_mr is not None:
                self.ep.dereg(self._mesh_mr)
            self._mesh_buf, self._mesh_mr = new_buf, new_mr
            self._mesh_seg = seg_needed
            self._mesh_fifos = fifos
            # New buffer generation: outstanding all_to_all consume-licenses
            # refer to the old regions — bump the epoch (stale messages get
            # discarded on receipt) and restart the parity counters, which
            # is collectively consistent because regrow itself is (SPMD
            # payload sizes).
            self._a2a_epoch += 1
            self._a2a_w.clear()
            self._a2a_r.clear()
            self._a2a_lic.clear()
        else:
            self._mesh_fifos.update(fifos)

    def _mesh_region(self, src: int, nbytes: int) -> np.ndarray:
        off = src * self._mesh_seg
        return self._mesh_buf[off : off + nbytes]

    def _ctrl_recv(self, ch, peer: int, timeout_ms: int = 30000) -> bytes:
        """recv for the broadcast R/D handshake that tolerates lagging
        all_to_all consume-licenses on the shared mesh channel (an AC for
        my call i is only consumed at my call i+2, so up to two can sit
        queued when another verb takes the channel)."""
        import struct

        while True:
            m = ch.recv(timeout_ms=timeout_ms)
            if len(m) == 10 and m[:2] == b"AC":
                ep_, i_ = struct.unpack("<II", m[2:])
                if ep_ == self._a2a_epoch and i_ > self._a2a_lic.get(peer, -1):
                    self._a2a_lic[peer] = i_
                continue
            return m

    # -- all_to_all pipelined-license protocol -------------------------
    #
    # The old protocol paid TWO serialized round trips per step (send R,
    # wait R before any byte moves; then D both ways). With parity
    # double-buffered landing regions the license becomes deferred: call i
    # writes parity i%2 and only needs the peer's consume-ack of call i-2 —
    # which, at steady state, arrived during an earlier wait. One blocking
    # round trip (the data-arrival AD) per step remains; measured on the
    # loopback cross-pod bench this roughly halves control latency. The
    # reference gets the same effect from pre-posted receive FIFOs
    # (UcclFlow::post_fifo advertisement, collective/rdma/transport.h:1457
    # — receivers advertise ahead so senders never wait to start).
    #
    # Wire messages (tagged so broadcast's R/D and stale generations can
    # never be confused): b"AD"/b"AC" + <epoch u32, call-index u32>.

    @staticmethod
    def _a2a_msg(kind: bytes, epoch: int, idx: int) -> bytes:
        import struct

        return kind + struct.pack("<II", epoch, idx)

    def _a2a_wait(self, ch, peer: int, kind: str, idx: int,
                  timeout_ms: int = 30000) -> None:
        """Consume tagged messages from ``peer`` until the wanted one:
        kind "C" waits for a consume-license with index >= idx (stashing the
        high-water mark); kind "D" waits for the data-arrival of exactly
        call idx. Messages from older epochs (pre-regrow) are discarded."""
        import struct

        while True:
            if kind == "C" and self._a2a_lic.get(peer, -1) >= idx:
                return
            m = ch.recv(timeout_ms=timeout_ms)
            if len(m) == 10 and m[:2] in (b"AD", b"AC"):
                ep_, i_ = struct.unpack("<II", m[2:])
                if ep_ != self._a2a_epoch:
                    continue  # stale generation (buffer since regrown)
                if m[:2] == b"AC":
                    if i_ > self._a2a_lic.get(peer, -1):
                        self._a2a_lic[peer] = i_
                    continue
                if kind == "D" and i_ == idx:
                    return
                raise IOError(
                    f"all_to_all: data frame {i_} while awaiting "
                    f"{kind}:{idx} from rank {peer}"
                )
            raise IOError(f"all_to_all: unexpected control message {m[:8]!r}")

    def all_to_all(self, x: np.ndarray, schedule=None,
                   path_floor: Optional[float] = None) -> np.ndarray:
        """x: [world, ...] — row j goes to rank j; out[i] = rank i's row for us.

        This is the cross-pod EP exchange primitive (the DCN leg of a
        pod-spanning dispatch/combine — reference EP proxies post direct
        per-peer writes the same way, ep/src/rdma.cpp:1554,1718). Pairwise
        stepped schedule over the full mesh: at step s, write your row for
        rank (r+s) directly into its landing region while rank (r-s) writes
        yours — each rank moves (world-1) rows total. Writes are licensed by
        the deferred parity protocol above, so the only blocking wait per
        step is the peer's data arrival.

        ``schedule`` — an optional ``(rounds, K)`` pair from
        :func:`uccl_tpu.ep.a2a_sched.wire_schedule` — replaces the fixed
        hop order with the contention-aware round order: each round's
        K-designated edges form a partial matching (no pod's NIC carries
        two transfers at once) and heavy inter-pod flows go first. Only
        K-designated edges cross the DCN — the device wire's shadow
        padding never ships here (host predication has no rendezvous to
        deadlock). Every write still rides the multipath Channel (SACK +
        PathQuality steering). Same bytes, same result, any order; all
        pods must pass the SAME schedule (it is SPMD state).

        ``path_floor`` (scheduled path only, ISSUE 19) — consult each
        mesh channel's cross-transfer
        :meth:`~uccl_tpu.p2p.channel.Channel.link_score`: edges whose
        link EWMA has sunk below the floor are **demoted** to the tail of
        this invocation instead of stalling the healthy rounds behind a
        sick link. The execution order becomes all sends (healthy rounds
        first, degraded last — a send never blocks on peer progress
        within an invocation: the deferred license it waits for was
        shipped two invocations ago and the write itself is one-sided),
        then all recvs (same split — each blocks only on its own peer's
        data frame, on an independent channel, tagged with its exact call
        index). That makes the reordering a purely LOCAL decision: ranks
        may disagree about which edges are degraded (link scores are
        per-endpoint observations, not SPMD state) and the exchange still
        cannot deadlock — only the waits' order changes, never the
        landing regions or call indices. Demotions land on
        ``dcn_a2a_demotions_total{dir}``.
        """
        n = self.active_world
        if x.shape[0] != n:
            raise ValueError(f"all_to_all needs leading dim {n}, got {x.shape}")
        x = np.ascontiguousarray(x)
        out = np.empty_like(x)
        me = self.pos
        out[me] = x[me]
        if n == 1:
            return out
        row = x[0]
        self._setup_mesh_buf(2 * row.nbytes, self._active)  # parity pair
        epoch = self._a2a_epoch

        def _send_row(dst_pos: int) -> None:
            dst = self._active[dst_pos]
            ch_dst = self._mesh[dst]
            wi = self._a2a_w.get(dst, 0)
            if wi >= 2:  # license: dst consumed call wi-2 from this parity
                self._a2a_wait(ch_dst, dst, "C", wi - 2)
            item = self._mesh_fifos[dst]
            ch_dst.write(
                x[dst_pos],
                item.slice((wi % 2) * row.nbytes, row.nbytes).pack(),
            )
            ch_dst.send(self._a2a_msg(b"AD", epoch, wi))
            self._a2a_w[dst] = wi + 1

        def _recv_row(src_pos: int) -> None:
            src = self._active[src_pos]
            ch_src = self._mesh[src]
            ri = self._a2a_r.get(src, 0)
            self._a2a_wait(ch_src, src, "D", ri)
            off = src * self._mesh_seg + (ri % 2) * row.nbytes
            out[src_pos] = (
                self._mesh_buf[off: off + row.nbytes]
                .view(x.dtype)
                .reshape(row.shape)
            )
            ch_src.send(self._a2a_msg(b"AC", epoch, ri))
            self._a2a_r[src] = ri + 1

        if schedule is None:
            for s in range(1, n):
                _send_row((me + s) % n)
                _recv_row((me - s) % n)
            return out

        rounds, k_mat = schedule
        perms = [tuple(getattr(rnd, "perm", rnd)) for rnd in rounds]
        k_mat = np.asarray(k_mat)
        # completeness BEFORE any wire traffic: K must designate every
        # off-diagonal pair to a round that actually carries it, or some
        # row would never arrive
        if k_mat.shape != (n, n):
            raise ValueError(f"schedule K is {k_mat.shape}, want {(n, n)}")
        for s in range(n):
            for d in range(n):
                if s == d:
                    continue
                r = int(k_mat[s, d])
                if not (0 <= r < len(perms)) or perms[r][s] != d:
                    raise ValueError(
                        f"schedule round {r} does not carry pair ({s}, {d})"
                    )
        sends: List[int] = []  # designated peer positions, round order
        recvs: List[int] = []
        for r, perm in enumerate(perms):
            if sorted(perm) != list(range(n)):
                raise ValueError(
                    f"schedule round {perm} is not a permutation of "
                    f"range({n})"
                )
            dst_pos = perm[me]
            src_pos = perm.index(me)
            if dst_pos != me and int(k_mat[me, dst_pos]) == r:
                sends.append(dst_pos)
            if src_pos != me and int(k_mat[src_pos, me]) == r:
                recvs.append(src_pos)
        if path_floor is None:
            # round-interleaved (the contention-aware order the schedule
            # encodes): K designates each of my edges to exactly one
            # round, so zipping the two lists back is the original loop
            si = ri = 0
            for r, perm in enumerate(perms):
                if si < len(sends) and int(k_mat[me, sends[si]]) == r:
                    _send_row(sends[si])
                    si += 1
                if ri < len(recvs) and int(k_mat[recvs[ri], me]) == r:
                    _recv_row(recvs[ri])
                    ri += 1
            return out

        def _degraded(pos: int) -> bool:
            score = self._mesh[self._active[pos]].link_score()
            return score is not None and score < path_floor

        demoted_s = [p for p in sends if _degraded(p)]
        demoted_r = [p for p in recvs if _degraded(p)]
        if demoted_s or demoted_r:
            from uccl_tpu.obs import counters as _obsc

            c = _obsc.counter(
                "dcn_a2a_demotions_total",
                "scheduled-a2a edges pushed to the invocation tail "
                "because their link-quality EWMA sank below path_floor",
            )
            if demoted_s:
                c.inc(len(demoted_s), dir="send")
            if demoted_r:
                c.inc(len(demoted_r), dir="recv")
        for p in [q for q in sends if q not in demoted_s] + demoted_s:
            _send_row(p)
        for p in [q for q in recvs if q not in demoted_r] + demoted_r:
            _recv_row(p)
        return out

    def broadcast(self, x: np.ndarray, root: int = 0) -> np.ndarray:
        """Rooted broadcast: every rank returns root's x. Binomial tree —
        ceil(log2 world) rounds; each rank walks only its own edges of the
        SHARED tree schedule (``utils.topology.bcast_tree_rounds`` — the
        same arithmetic the on-mesh ``plan.tree_broadcast`` lowers and the
        planner's tree cost features charge, so the host and device trees
        cannot drift) and sends at most log(world) copies. The decision
        lands on ``collective_plan_total{verb="broadcast", algo="tree"}``
        beside the on-mesh verbs (compat/dist.broadcast shims here)."""
        from uccl_tpu.obs import counters as _obsc
        from uccl_tpu.utils.topology import bcast_tree_rounds

        n = self.active_world
        if n == 1:
            return x.copy()
        if root not in self._active:
            raise ValueError(f"broadcast root {root} is not an active rank")
        root_pos = self._active.index(root)
        me = self.pos
        rounds = bcast_tree_rounds(n, root_pos)  # position-space pairs
        # Only this rank's tree edges — log(world) channels, not a full mesh.
        partners = set()
        for pairs in rounds:
            for s, d in pairs:
                if s == me:
                    partners.add(self._active[d])
                elif d == me:
                    partners.add(self._active[s])
        _obsc.counter("collective_plan_total").inc(
            algo="tree", chunks=1, wire_dtype="none", outcome="explicit",
            verb="broadcast",
        )
        self._setup_mesh_buf(x.nbytes, partners)
        buf = (np.ascontiguousarray(x).copy() if me == root_pos
               else np.empty_like(x))
        for pairs in rounds:
            for s, d in pairs:
                if s == me:  # this round's holder: fan out
                    dst = self._active[d]
                    ch = self._mesh[dst]
                    if self._ctrl_recv(ch, dst) != b"R":
                        raise IOError("broadcast: expected READY")
                    item = self._mesh_fifos[dst]
                    ch.write(buf, item.slice(0, buf.nbytes).pack())
                    ch.send(b"D")
                elif d == me:  # this round's receiver
                    src = self._active[s]
                    ch = self._mesh[src]
                    ch.send(b"R")
                    if self._ctrl_recv(ch, src) != b"D":
                        raise IOError("broadcast: expected DONE")
                    flat = self._mesh_region(src, buf.nbytes).view(buf.dtype)
                    buf = flat.reshape(x.shape).copy()
        return buf

    def barrier(self):
        self.all_reduce(np.zeros(1, np.float32))


def hierarchical_all_reduce(comm, dcn: DcnGroup, x):
    """Two-level allreduce: ICI reduce-scatter → DCN allreduce → ICI all-gather.

    ``comm`` is an on-mesh :class:`~uccl_tpu.collective.Communicator`
    (rank-dim convention, x: [local_world, N]); ``dcn`` spans pods. Per-host
    DCN traffic is O(N) (the ring moves all local shards, ~2N in+out per
    host); the hierarchical win is on the *device* side: each device moves
    only its N/local_world shard across the host link, and the pod-internal
    reduction/broadcast legs ride ICI (the moral equivalent of the
    reference's multi-engine NIC split). Result: every member of every pod
    holds the global sum, NCCL-allreduce shaped.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from uccl_tpu.collective import plan as _plan

    local = comm.world
    n = x.shape[1]
    # the cross-pod decision rides the same plan surface as the on-mesh
    # algos: ICI ring legs at (alpha, beta) + the DCN ring middle at the
    # dcn beta — benches and check_obs see "hier" beside "bidir"/"hd"
    model = _plan.get_planner().model
    wire_bytes = n * jnp.dtype(x.dtype).itemsize
    pred = model.predict("hier", local, wire_bytes,
                         dcn_world=max(dcn.active_world, 1))
    _plan.PLAN_TOTAL.inc(algo="hier", chunks=1, wire_dtype="none",
                         outcome="explicit")
    _plan.PLAN_PREDICTED.set(pred, algo="hier", chunks=1, wire_dtype="none")
    shard = comm.reduce_scatter(x)  # [local_world, N/local]: row i = chunk i
    reduced = dcn.all_reduce(np.asarray(shard))  # host staging + DCN exchange
    # back onto the mesh shard-wise (N/local per device over the host link),
    # then the final hop is a true ICI all-gather + on-device broadcast
    shard_dev = comm.device_put(reduced)
    # the AG leg stays the XLA lowering: the cross-pod schedule was already
    # planned as ONE "hier" decision above — re-planning its inner leg
    # would double-emit and could swap a kernel into a path priced as xla
    gathered = comm.all_gather(shard_dev, algo="xla")  # replicated

    out_sharding = NamedSharding(comm.mesh, comm._ranked(1))
    return jax.jit(
        lambda g: jnp.broadcast_to(g.reshape(1, -1), (local, n)),
        out_shardings=out_sharding,
    )(gathered)
