"""Collectives layer: NCCL-shaped host API lowered to XLA collectives on the mesh.

The analog of the reference's ``collective/`` pillar (NCCL net plugin +
transports, SURVEY.md §2.1): same API *shape* — allreduce / allgather /
reducescatter / alltoall / broadcast / send-recv — but lowered to
``lax.psum``/``all_gather``/``psum_scatter``/``all_to_all``/``ppermute`` inside
``shard_map`` over the ICI mesh rather than a userspace packet transport.

Two surfaces:

* :class:`Communicator` — eager host API over global arrays with an explicit
  leading rank dimension (one "NCCL buffer" per mesh-axis member). This is what
  nccl-tests-style harnesses and the benchmark driver use.
* :mod:`uccl_tpu.collective.ops` — per-shard wrappers for use *inside* user
  shard_map/pjit code (the compiled path models use).
"""

from uccl_tpu.collective.communicator import Communicator, ReduceOp
from uccl_tpu.collective import ops

__all__ = ["Communicator", "ReduceOp", "ops"]
