"""Host-side NCCL-shaped Communicator over a mesh axis.

The analog of the reference's NCCL-plugin surface (collective/rdma/nccl_plugin.cc:
pluginIsend/pluginIrecv + the ncclAllReduce/... family the plugin serves): a host
object with the familiar collective verbs, executing compiled XLA collectives over
the ICI mesh.

Buffer model: NCCL ranks each own a local buffer; the global-array analog here is a
leading **rank dimension** of size ``world`` sharded over the communicator's mesh
axes. ``all_reduce(x)[i] == sum_j x[j]`` etc. Each distinct (op, shape, dtype,
kwargs) compiles once and is cached — the moral equivalent of the reference's
per-comm setup cost, after which calls are hot-path only.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from uccl_tpu.utils.jaxcompat import shard_map

from uccl_tpu.parallel.mesh import AXIS, get_mesh, mesh_axis_size
from uccl_tpu.utils.logging import get_logger
from uccl_tpu.utils.topology import ppermute_pairs

_log = get_logger("COLL")

Axis = Union[str, Tuple[str, ...]]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    AVG = "mean"
    PROD = "prod"


def _as_tuple(axis: Axis) -> Tuple[str, ...]:
    return (axis,) if isinstance(axis, str) else tuple(axis)


class Communicator:
    """Collective communicator over one (or a tuple of) mesh axes.

    Equivalent role to an ``ncclComm_t`` bound to the reference's transport
    (RDMAEndpoint + engines); here `mesh axes` + cached compiled collectives.
    """

    def __init__(self, mesh: Optional[Mesh] = None, axis: Axis = AXIS.DP):
        self.mesh = mesh if mesh is not None else get_mesh()
        self.axes = _as_tuple(axis)
        for a in self.axes:
            if a not in self.mesh.shape:
                raise ValueError(f"axis {a!r} not in mesh axes {tuple(self.mesh.shape)}")
        self.world = mesh_axis_size(self.mesh, self.axes)
        self._cache = {}
        # request → resolved (algo, chunks, wire_dtype): planner emission
        # happens ONCE per distinct resolution (per-compile semantics, the
        # repo's counter idiom) — hot-path/timed-loop calls skip straight
        # to the compiled-fn cache with no obs work in the measured time
        self._plan_memo = {}

    # -- internals ---------------------------------------------------------

    def _axis_name(self):
        return self.axes if len(self.axes) > 1 else self.axes[0]

    def _ranked(self, extra_dims: int = 0) -> P:
        """PartitionSpec sharding the leading rank dim over the comm axes."""
        return P(self.axes, *([None] * extra_dims))

    def _compiled(self, key, build):
        fn = self._cache.get(key)
        if fn is None:
            fn = build()
            self._cache[key] = fn
        return fn

    def _shard_jit(self, fn, in_spec: P, out_spec: P):
        mapped = shard_map(
            fn, mesh=self.mesh, in_specs=(in_spec,), out_specs=out_spec, check_vma=False
        )
        return jax.jit(mapped)

    def _check(self, x: jax.Array):
        if x.ndim < 1 or x.shape[0] != self.world:
            raise ValueError(
                f"expected leading rank dim of size {self.world}, got shape {x.shape}"
            )

    def device_put(self, x) -> jax.Array:
        """Lay a host array with a leading rank dim out across the comm axes."""
        x = jnp.asarray(x)
        self._check(x)
        spec = self._ranked(x.ndim - 1)
        return jax.device_put(x, NamedSharding(self.mesh, spec))

    # -- collectives -------------------------------------------------------

    def _payload_shape(self, x: jax.Array) -> Tuple[int, ...]:
        """One member's payload shape (the rank dim stripped) — what the
        planner's wire-byte arithmetic sees."""
        return tuple(x.shape[1:]) if x.ndim > 1 else (1,)

    def _pallas_ok(self) -> bool:
        """Can the device-kernel candidates (bidir) address this mesh? A
        single comm axis always; plus either a real TPU lowering, the
        faithful interpreter (MESH coordinates), or a single-named-axis
        mesh for the legacy discharge interpreter (flat logical ids)."""
        if len(self.axes) != 1:
            return False
        from uccl_tpu.collective import dma as _dma

        interpret = _dma.resolve_interpret(None)
        if not interpret or _dma.faithful_sync(interpret):
            return True
        return len(self.mesh.shape) == 1

    def _resolve_ar_plan(self, x, op, algo, wire_dtype):
        """Resolve one all_reduce request to (algo, chunks, wire_dtype),
        emitting the planner decision and counting any quant downgrade —
        called once per distinct request (the _plan_memo guard)."""
        from uccl_tpu.collective import plan as _plan

        planner = _plan.get_planner()
        payload_shape = self._payload_shape(x)
        worlds = tuple(self.mesh.shape[a] for a in self.axes)
        plan_ = None
        if algo == "auto":
            if op != ReduceOp.SUM:
                algo = "xla"  # the explicit plans are sum-only
                if wire_dtype is not None:
                    # counted, never silent: the xla lowering of a non-sum
                    # op cannot carry a quantized wire
                    from uccl_tpu.collective import dma as _dma

                    _dma.record_fallback(
                        "all_reduce_plan", "quant_algo", detail="xla",
                        msg=f"non-sum all_reduce ({op!r}) plans xla, which "
                            f"cannot carry a quantized wire; shipping full "
                            f"precision",
                    )
                    wire_dtype = None
            else:
                plan_ = planner.plan_all_reduce(
                    payload_shape, x.dtype, self.world,
                    n_axes=len(self.axes), worlds=worlds,
                    wire_dtype=wire_dtype, pallas_ok=self._pallas_ok(),
                )
                algo = plan_.algo
                if wire_dtype is not None and algo not in ("pallas",
                                                           "bidir"):
                    from uccl_tpu.collective import dma as _dma

                    _dma.record_fallback(
                        "all_reduce_plan", "quant_algo", detail=algo,
                        msg=f"all_reduce plan {algo!r} cannot carry a "
                            f"quantized wire; shipping full precision",
                    )
                    wire_dtype = None
        if algo not in ("xla", "ring", "hd", "torus", "pallas", "bidir"):
            raise ValueError(f"unknown all_reduce algo {algo!r}")
        if plan_ is None:
            plan_ = planner.plan_explicit(
                algo, payload_shape, x.dtype, self.world,
                n_axes=len(self.axes), worlds=worlds, wire_dtype=wire_dtype,
            )
        return plan_.algo, plan_.chunks, wire_dtype

    def all_reduce(
        self, x: jax.Array, op: str = ReduceOp.SUM, algo: str = "xla",
        wire_dtype=None,
    ) -> jax.Array:
        """out[i] = reduce_j x[j] for every rank i.

        ``algo="xla"`` lowers to lax.psum (XLA's collective schedule);
        ``algo="ring"`` runs the explicit bidirectional chunk-ring schedule
        from :mod:`uccl_tpu.collective.plan` (sum only);
        ``algo="hd"`` runs the log-step recursive halving-doubling plan
        (sum only; power-of-two worlds, ring fallback otherwise);
        ``algo="torus"`` runs the 2D axis-pair chunk-graph schedule (sum
        only; the communicator must span exactly two mesh axes);
        ``algo="pallas"`` runs the same ring schedule as device-level
        remote-DMA kernels (:mod:`uccl_tpu.collective.pallas_ccl`; sum only,
        single-axis, VMEM-budget fallback to the plan lowering);
        ``algo="bidir"`` pairs two counter-rotating pallas ring kernels on
        paired collective ids, each carrying half the payload (sum only,
        single-axis — :func:`~uccl_tpu.collective.pallas_ccl.
        bidir_all_reduce`, FlexLink-style both-directions utilization);
        ``algo="auto"`` asks the :class:`~uccl_tpu.collective.plan.
        CollectivePlanner` — the alpha-beta-gamma cost model over actual
        WIRE bytes (quantized payloads shift the thresholds), with
        UCCL_TPU_AR_ALGO still honored as a forced-calibration override.
        Every resolution (modeled, forced, or explicit) is emitted on
        ``collective_plan_total``.

        ``wire_dtype="fp8"|"int8"`` (pallas/bidir algos) block-quantizes
        the wire payloads — per-hop quantized reduce-scatter with
        input-precision accumulation plus a quantize-once all-gather
        (docs/QUANT_WIRE.md error model). With ``algo="auto"`` the planner
        prices algorithms at the quantized wire size; if the winner cannot
        carry a quantized wire the payload ships full precision — counted
        on ``ep_wire_fallback_total`` (reason ``quant_algo``), never
        silently.
        """
        self._check(x)
        if wire_dtype is not None and algo not in ("pallas", "bidir",
                                                   "auto"):
            raise ValueError(
                "wire_dtype quantization rides the pallas/bidir allreduce "
                "only"
            )
        ax = self._axis_name()
        from uccl_tpu.collective import plan as _plan

        # resolve the request to a plan ONCE per distinct (request, forced
        # override) — the memo keeps planner emission + quant-downgrade
        # counting per-compile, so the hot path and timed bench iterations
        # never pay obs work. The forced-algo param is part of the memo key
        # so flipping UCCL_TPU_AR_ALGO between calls still re-plans.
        req = (op, algo, x.shape, x.dtype, wire_dtype,
               _plan._AR_FORCE_ALGO.get() if algo == "auto" else "")
        memo = self._plan_memo.get(req)
        if memo is None:
            memo = self._resolve_ar_plan(x, op, algo, wire_dtype)
            self._plan_memo[req] = memo
        algo, chunks, wire_dtype = memo
        # cache key carries the RESOLVED plan (algo + chunks + wire_dtype),
        # never the "auto" spelling: two calls whose plans resolve apart
        # (env override flipped, wire_dtype shifted a threshold) must not
        # share a compiled fn
        key = ("ar", op, algo, chunks, x.shape, x.dtype, wire_dtype)

        def build():
            def f(v):
                if algo in ("pallas", "bidir"):
                    if op != ReduceOp.SUM:
                        raise ValueError(
                            f"{algo} allreduce supports sum only"
                        )
                    if len(self.axes) != 1:
                        raise ValueError(
                            f"{algo} allreduce rings a single mesh axis"
                        )
                    from uccl_tpu.collective import pallas_ccl

                    if algo == "bidir":
                        return pallas_ccl.bidir_all_reduce(
                            v, ax, wire_dtype=wire_dtype
                        )
                    return pallas_ccl.ring_all_reduce(
                        v, ax, wire_dtype=wire_dtype
                    )
                if algo in ("ring", "hd"):
                    if op != ReduceOp.SUM:
                        raise ValueError(f"{algo} allreduce supports sum only")
                    from uccl_tpu.collective.plan import (
                        hd_all_reduce,
                        ring_all_reduce,
                    )

                    fn = hd_all_reduce if algo == "hd" else ring_all_reduce
                    return fn(v, ax)
                if algo == "torus":
                    if op != ReduceOp.SUM:
                        raise ValueError("torus allreduce supports sum only")
                    if len(self.axes) != 2:
                        raise ValueError(
                            "torus allreduce needs a 2-axis communicator"
                        )
                    from uccl_tpu.collective.plan import torus_all_reduce

                    return torus_all_reduce(v, self.axes)
                if op == ReduceOp.SUM:
                    return lax.psum(v, ax)
                if op == ReduceOp.MAX:
                    return lax.pmax(v, ax)
                if op == ReduceOp.MIN:
                    return lax.pmin(v, ax)
                if op == ReduceOp.AVG:
                    return lax.pmean(v, ax)
                if op == ReduceOp.PROD:
                    g = lax.all_gather(v, ax, axis=0, tiled=True)
                    return jnp.prod(g, axis=0, keepdims=True)
                raise ValueError(f"unsupported op {op!r}")

            spec = self._ranked(x.ndim - 1)
            return self._shard_jit(f, spec, spec)

        return self._compiled(key, build)(x)

    def _resolve_ag_plan(self, x, algo, wire_dtype):
        """Resolve one all_gather request to (algo, wire_dtype), emitting
        the planner decision (verb="all_gather") and counting any quant
        downgrade — once per distinct request (the _plan_memo guard)."""
        from uccl_tpu.collective import plan as _plan

        planner = _plan.get_planner()
        payload_shape = self._payload_shape(x)
        worlds = tuple(self.mesh.shape[a] for a in self.axes)
        if algo == "auto":
            p = planner.plan_all_gather(
                payload_shape, x.dtype, self.world,
                n_axes=len(self.axes), worlds=worlds,
                wire_dtype=wire_dtype, pallas_ok=self._pallas_ok(),
            )
            algo = p.algo
            if wire_dtype is not None and algo not in ("ring", "bidir"):
                from uccl_tpu.collective import dma as _dma

                _dma.record_fallback(
                    "all_gather_plan", "quant_algo", detail=algo,
                    msg=f"all_gather plan {algo!r} cannot carry a "
                        f"quantized wire; shipping full precision",
                )
                wire_dtype = None
            return algo, wire_dtype
        if algo not in ("xla", "ring", "bidir"):
            raise ValueError(f"unknown all_gather algo {algo!r}")
        planner.plan_explicit(
            algo, payload_shape, x.dtype, self.world,
            n_axes=len(self.axes), worlds=worlds, wire_dtype=wire_dtype,
            verb="all_gather",
        )
        return algo, wire_dtype

    def all_gather(self, x: jax.Array, algo: str = "auto",
                   wire_dtype=None) -> jax.Array:
        """Every rank receives the concatenation over the rank dim: out is
        the same global array, fully replicated (NCCL allgather
        semantics).

        ``algo="xla"`` lowers to lax.all_gather; ``algo="ring"`` runs the
        write-once pallas ring kernel
        (:func:`~uccl_tpu.collective.pallas_ccl.ring_all_gather`);
        ``algo="bidir"`` pairs two counter-rotating AG kernels, each
        carrying half the payload; ``algo="auto"`` (the default) asks the
        :class:`~uccl_tpu.collective.plan.CollectivePlanner` — priced at
        actual wire bytes, emitted on ``collective_plan_total`` with
        ``verb="all_gather"``. ``wire_dtype="fp8"|"int8"`` (ring/bidir)
        block-quantizes the contributed payload ONCE and forwards wire
        bytes verbatim: one quantize round trip of error, all members
        identical. Full precision stays bit-exact (pure data movement)."""
        self._check(x)
        if wire_dtype is not None and algo not in ("ring", "bidir", "auto"):
            raise ValueError(
                "wire_dtype quantization rides the ring/bidir all_gather "
                "only"
            )
        ax = self._axis_name()
        req = ("ag", algo, x.shape, x.dtype, wire_dtype)
        memo = self._plan_memo.get(req)
        if memo is None:
            memo = self._resolve_ag_plan(x, algo, wire_dtype)
            self._plan_memo[req] = memo
        algo, wire_dtype = memo
        key = ("ag", algo, x.shape, x.dtype, wire_dtype)

        def build():
            def f(v):
                if algo in ("ring", "bidir"):
                    if len(self.axes) != 1:
                        raise ValueError(
                            f"{algo} all_gather rings a single mesh axis"
                        )
                    from uccl_tpu.collective import pallas_ccl

                    fn = (pallas_ccl.bidir_all_gather if algo == "bidir"
                          else pallas_ccl.ring_all_gather)
                    return fn(v, ax, wire_dtype=wire_dtype)
                return lax.all_gather(v, ax, axis=0, tiled=True)

            return self._shard_jit(f, self._ranked(x.ndim - 1), P(*([None] * x.ndim)))

        return self._compiled(key, build)(x)

    def _resolve_rs_plan(self, x, algo, wire_dtype):
        """Resolve one reduce_scatter request to (algo, wire_dtype),
        emitting the planner decision (verb="reduce_scatter") and counting
        any quant downgrade — once per distinct request (the _plan_memo
        guard), same shape as _resolve_ag_plan."""
        from uccl_tpu.collective import plan as _plan

        planner = _plan.get_planner()
        payload_shape = self._payload_shape(x)
        worlds = tuple(self.mesh.shape[a] for a in self.axes)
        if algo == "auto":
            p = planner.plan_reduce_scatter(
                payload_shape, x.dtype, self.world,
                n_axes=len(self.axes), worlds=worlds,
                wire_dtype=wire_dtype, pallas_ok=self._pallas_ok(),
            )
            algo = p.algo
            if wire_dtype is not None and algo != "ring":
                from uccl_tpu.collective import dma as _dma

                _dma.record_fallback(
                    "reduce_scatter_plan", "quant_algo", detail=algo,
                    msg=f"reduce_scatter plan {algo!r} cannot carry a "
                        f"quantized wire; shipping full precision",
                )
                wire_dtype = None
            return algo, wire_dtype
        if algo not in ("xla", "ring"):
            raise ValueError(f"unknown reduce_scatter algo {algo!r}")
        planner.plan_explicit(
            algo, payload_shape, x.dtype, self.world,
            n_axes=len(self.axes), worlds=worlds, wire_dtype=wire_dtype,
            verb="reduce_scatter",
        )
        return algo, wire_dtype

    def reduce_scatter(self, x: jax.Array, op: str = ReduceOp.SUM,
                       algo: str = "auto", wire_dtype=None) -> jax.Array:
        """x: [world, N, ...] (each rank contributes a full buffer); out:
        [world, N/world, ...] with out[i] = reduce_j x[j] chunk i.

        ``algo="xla"`` lowers to lax.psum_scatter; ``algo="ring"`` runs
        the RS half of the pallas ring pair
        (:func:`~uccl_tpu.collective.pallas_ccl.ring_reduce_scatter` —
        write-once reducing hops, with its bit-identical lax mirror past
        the VMEM budget); ``algo="auto"`` (the default) asks the
        :class:`~uccl_tpu.collective.plan.CollectivePlanner` — priced at
        wire bytes under the ONE alpha-beta-gamma model, emitted on
        ``collective_plan_total`` with ``verb="reduce_scatter"`` — so all
        four verbs are planner-arbitrated. ``wire_dtype="fp8"|"int8"``
        (ring only) block-quantizes every hop's partial sum: one quantize
        round trip of error per hop."""
        self._check(x)
        if x.ndim < 2 or x.shape[1] % self.world != 0:
            raise ValueError(
                f"reduce_scatter payload dim {x.shape} must divide world {self.world}"
            )
        if op != ReduceOp.SUM:
            raise NotImplementedError("reduce_scatter supports sum only")
        if wire_dtype is not None and algo not in ("ring", "auto"):
            raise ValueError(
                "wire_dtype quantization rides the ring reduce_scatter only"
            )
        ax = self._axis_name()
        req = ("rs", algo, x.shape, x.dtype, wire_dtype)
        memo = self._plan_memo.get(req)
        if memo is None:
            memo = self._resolve_rs_plan(x, algo, wire_dtype)
            self._plan_memo[req] = memo
        algo, wire_dtype = memo
        key = ("rs", algo, x.shape, x.dtype, wire_dtype)

        def build():
            def f(v):
                if algo == "ring":
                    if len(self.axes) != 1:
                        raise ValueError(
                            "ring reduce_scatter rings a single mesh axis"
                        )
                    from uccl_tpu.collective import pallas_ccl

                    return pallas_ccl.ring_reduce_scatter(
                        v[0], ax, wire_dtype=wire_dtype
                    )[None]
                return lax.psum_scatter(v, ax, scatter_dimension=1, tiled=True)

            spec = self._ranked(x.ndim - 1)
            return self._shard_jit(f, spec, spec)

        return self._compiled(key, build)(x)

    def all_to_all(self, x: jax.Array) -> jax.Array:
        """x: [world, world, ...]; out[i, j] = x[j, i] (transpose of the first
        two dims, moved over the wire — NCCL alltoall semantics)."""
        self._check(x)
        if x.ndim < 2 or x.shape[1] != self.world:
            raise ValueError(f"all_to_all needs shape [world, world, ...], got {x.shape}")
        ax = self._axis_name()
        key = ("a2a", x.shape, x.dtype)

        def build():
            def f(v):
                # v: [1, world, ...]; block j of dim 1 goes to rank j, and the
                # block received from rank j lands at position j of dim 1 —
                # i.e. out[i, j] = x[j, i].
                return lax.all_to_all(v, ax, split_axis=1, concat_axis=1, tiled=True)

            spec = self._ranked(x.ndim - 1)
            return self._shard_jit(f, spec, spec)

        return self._compiled(key, build)(x)

    def _resolve_bcast_plan(self, x, algo, wire_dtype):
        """Resolve one broadcast request to (algo, wire_dtype), emitting
        the planner decision (verb="broadcast") and counting any quant
        downgrade — once per distinct request (the _plan_memo guard)."""
        from uccl_tpu.collective import plan as _plan

        planner = _plan.get_planner()
        payload_shape = self._payload_shape(x)
        worlds = tuple(self.mesh.shape[a] for a in self.axes)
        if algo == "auto":
            p = planner.plan_broadcast(
                payload_shape, x.dtype, self.world,
                n_axes=len(self.axes), worlds=worlds,
                wire_dtype=wire_dtype, pallas_ok=self._pallas_ok(),
            )
            algo = p.algo
            if wire_dtype is not None and algo != "scatter_ag":
                from uccl_tpu.collective import dma as _dma

                _dma.record_fallback(
                    "broadcast_plan", "quant_algo", detail=algo,
                    msg=f"broadcast plan {algo!r} cannot carry a "
                        f"quantized wire; shipping full precision",
                )
                wire_dtype = None
            return algo, wire_dtype
        if algo not in ("xla", "tree", "scatter_ag", "psum"):
            raise ValueError(f"unknown broadcast algo {algo!r}")
        planner.plan_explicit(
            algo, payload_shape, x.dtype, self.world,
            n_axes=len(self.axes), worlds=worlds, wire_dtype=wire_dtype,
            verb="broadcast",
        )
        return algo, wire_dtype

    def broadcast(self, x: jax.Array, root: int = 0, algo: str = "auto",
                  wire_dtype=None) -> jax.Array:
        """out[i] = x[root] for every i.

        ``algo="xla"`` lowers to the lax scatter-allgather schedule
        (:func:`~uccl_tpu.collective.pallas_ccl.
        scatter_gather_broadcast_lax` — direct root→j chunk ppermutes +
        one ring all-gather), replacing the legacy psum-of-zeros lowering
        that shipped the full payload through a reduction plus world-1
        adds of zeros; ``algo="tree"`` runs the binomial tree
        (:func:`~uccl_tpu.collective.plan.tree_broadcast` — log2(n)
        full-payload rounds, the alpha-dominated range);
        ``algo="scatter_ag"`` runs the pallas scatter-allgather kernel
        pair (root scatters S/n chunks, a counter-rotating all-gather
        pair completes — the bandwidth-optimal decomposition, PAPERS.md);
        ``algo="psum"`` keeps the legacy masked-psum lowering as the
        counter-audited baseline; ``algo="auto"`` (the default) asks the
        planner — emitted on ``collective_plan_total`` with
        ``verb="broadcast"``. ``wire_dtype="fp8"|"int8"`` (scatter_ag)
        quantizes the all-gather legs once: one round trip of error,
        every member identical; full precision is bit-exact on every
        algo (pure data movement — psum aside, which adds zeros)."""
        self._check(x)
        if not 0 <= root < self.world:
            raise ValueError(f"root {root} outside world {self.world}")
        if wire_dtype is not None and algo not in ("scatter_ag", "auto"):
            raise ValueError(
                "wire_dtype quantization rides the scatter_ag broadcast "
                "only"
            )
        ax = self._axis_name()
        req = ("bc", algo, x.shape, x.dtype, wire_dtype)
        memo = self._plan_memo.get(req)
        if memo is None:
            memo = self._resolve_bcast_plan(x, algo, wire_dtype)
            self._plan_memo[req] = memo
        algo, wire_dtype = memo
        key = ("bc", root, algo, x.shape, x.dtype, wire_dtype)

        def build():
            def f(v):
                from uccl_tpu.collective import pallas_ccl
                from uccl_tpu.collective import plan as _plan

                if algo == "scatter_ag":
                    if len(self.axes) != 1:
                        raise ValueError(
                            "scatter_ag broadcast rings a single mesh axis"
                        )
                    return pallas_ccl.scatter_ag_broadcast(
                        v, ax, root, wire_dtype=wire_dtype
                    )
                if algo == "tree":
                    return _plan.tree_broadcast(v, ax, root)
                if algo == "psum":
                    # the legacy lowering, kept as the wire-byte baseline:
                    # mask every non-root contribution to zero, then psum —
                    # a full-payload reduction whose every hop carries the
                    # whole buffer (counted at the up-and-down tree volume
                    # 2S; a ring-lowered psum would pay 2(n-1)/n·S, still
                    # ~2x the scatter-allgather's ~S — docs/PLAN_BENCH.md)
                    pallas_ccl._count_wire_bytes(
                        "bcast", "psum", None, 2 * v.size * v.dtype.itemsize
                    )
                    idx = lax.axis_index(ax).reshape((1,) * v.ndim)
                    masked = jnp.where(idx == root, v, jnp.zeros_like(v))
                    return lax.psum(masked, ax)
                return pallas_ccl.scatter_gather_broadcast_lax(v, ax, root)

            spec = self._ranked(x.ndim - 1)
            return self._shard_jit(f, spec, spec)

        return self._compiled(key, build)(x)

    def permute(self, x: jax.Array, perm: Sequence[Tuple[int, int]]) -> jax.Array:
        """Point-to-point sends: out[dst] = x[src] for each (src, dst); ranks not
        named as a dst receive zeros (lax.ppermute semantics — this is the
        send/recv primitive the P2P-over-ICI path uses)."""
        self._check(x)
        ax = self._axis_name()
        perm = tuple((int(s), int(d)) for s, d in perm)
        key = ("pp", perm, x.shape, x.dtype)

        def build():
            def f(v):
                return lax.ppermute(v, ax, perm=list(perm))

            spec = self._ranked(x.ndim - 1)
            return self._shard_jit(f, spec, spec)

        return self._compiled(key, build)(x)

    def ring_shift(self, x: jax.Array, shift: int = 1) -> jax.Array:
        return self.permute(x, ppermute_pairs(self.world, shift))

    def send_recv(self, x: jax.Array, src: int, dst: int) -> jax.Array:
        return self.permute(x, [(src, dst)])

    def barrier(self) -> None:
        """Execute a tiny allreduce and block on it."""
        token = jnp.zeros((self.world, 1), jnp.float32)
        jax.block_until_ready(self.all_reduce(self.device_put(token)))
