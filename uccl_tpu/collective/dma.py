"""Shared building blocks for the device-level Pallas remote-DMA kernels.

Factored out of :mod:`uccl_tpu.collective.pallas_ccl` (the ring collectives)
so the EP all-to-all kernels (:mod:`uccl_tpu.ep.pallas_a2a`) reuse the exact
machinery the rings proved on the real v5e: chunk padding to VPU tiles,
MESH-coordinate neighbor addressing, the interpret-mode resolution and its
single-core-host payload ceiling, the VMEM budget gate, and the entry
barriers. The synchronization *design* (write-once slots, 2-deep semaphore
rotation, credit-granted flow control) lives with each kernel — the slot
arithmetic differs between a ring and an all-to-all — but the primitives and
constants here are the common substrate.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental.pallas import tpu as pltpu

from uccl_tpu.utils import config as _config
from uccl_tpu.utils import jaxcompat as _jc
from uccl_tpu.obs import counters as _obsc

LANES = 128
# Pad each chunk to a multiple of 8x128 elements (one f32 sublane tile;
# Mosaic masks the partial tile for narrower dtypes). Kept small on purpose:
# the TPU interpreter backing the CPU tests deadlocks when a single
# interpret-mode buffer reaches ~128 KiB on a 1-core host (XLA:CPU runs the
# buffer-init callback on the same starved pool a blocking semaphore-wait
# callback occupies — measured threshold between 96 and 128 KiB), so small
# payloads must not be padded into that range.
CHUNK_QUANTUM = 8 * LANES

MAX_VMEM_BYTES = _config.param(
    "PALLAS_CCL_MAX_BYTES",
    8 << 20,
    int,
    "per-shard payload ceiling for the VMEM-resident pallas remote-DMA"
    " kernels (ring collectives and the EP all-to-all); larger buffers fall"
    " back to the XLA collective lowering",
)
MAX_INTERP_BYTES = _config.param(
    "PALLAS_CCL_INTERP_MAX_BYTES",
    64 << 10,
    int,
    "payload ceiling when running under the TPU interpreter (CPU tests): "
    "single-core hosts deadlock interpret-mode buffers around 128 KiB, so "
    "bigger payloads fall back to the XLA lowering there",
)

MESH = pltpu.DeviceIdType.MESH

# Every transparent pallas-wire downgrade (chunked → unchunked → lax)
# increments this counter with its site (`what`) and `reason` — benches and
# the /metrics surface read it instead of re-deriving the gate arithmetic
# (the old `pallas_wire_active` heuristic). Declared at import so the
# series exists (as 0) before the first fallback. Increments happen at
# TRACE time — once per compiled program, the granularity at which the
# wire decision is actually made; a jit cache hit re-runs the traced
# choice without re-counting.
WIRE_FALLBACK = _obsc.counter(
    "ep_wire_fallback_total",
    "transparent pallas-wire downgrades (chunked->unchunked->lax) by "
    "site (what) and reason",
)
_fallback_logged = set()  # (what, reason, detail): log once per shape


def record_fallback(what: str, reason: str, detail=None, msg=None) -> None:
    """Count a transparent wire downgrade and log it ONCE per
    (what, reason, detail) — ``detail`` carries the shape/bytes that made
    this occurrence distinct, so a new shape logs again but a hot loop
    doesn't spam."""
    WIRE_FALLBACK.inc(what=what, reason=reason)
    key = (what, reason, detail)
    if key in _fallback_logged:
        return
    _fallback_logged.add(key)
    from uccl_tpu.utils.logging import log

    log("INFO", "CCL",
        msg or f"pallas {what}: falling back ({reason}, {detail})")

# collective_id allocation for kernels that may be IN FLIGHT concurrently.
# Mosaic's entry-barrier semaphore is keyed by collective_id, so two kernels
# sharing one id must never overlap; the chunk pipeline deliberately keeps
# dispatch chunk c+1 and combine chunk c-1 airborne while chunk c computes,
# so each family rotates its own 2-parity id pair (the launch-granularity
# form of the kernels' internal 2-parity slot rotation), and tie_chunk()
# orders chunk c after chunk c-2 so at most TWO same-family kernels are
# ever in flight — the invariant that makes a 2-id rotation (and the
# 2-resident-pair chunk_budget charge) sound at any n_chunks. fp8 wire
# payloads ride two exchanges (values + scales) with no data dependency
# between them, so scales ride the value id shifted by CID_SCALE_OFFSET.
# Allocation: 0 = the ring collectives (pallas_ccl default),
# {2,3}/{4,5}/{6,7} = dispatch/combine/generic-a2a value lanes,
# {10,11}/{12,13}/{14,15} = their scale lanes, {16,17} = the bidir
# allreduce's paired fwd/bwd ring kernels (airborne CONCURRENTLY by
# design — the FlexLink counter-rotating pair — so they must never share
# an id), {24,25} = their scale lanes.
CID_EP_DISPATCH = 2  # dispatch chunks rotate {2, 3}
CID_EP_COMBINE = 4  # combine chunks rotate {4, 5}
CID_A2A = 6  # the generic/unchunked EP all-to-all lane, rotating {6, 7}
CID_SCALE_OFFSET = 8  # fp8 scale exchange = value id + 8
CID_RING_BIDIR = 16  # bidir allreduce: fwd ring 16, bwd ring 17
# bidir all-gather pair {18, 19} (scales {26, 27}) and the broadcast's
# counter-rotating AG pair {20, 21} (scales {28, 29}) — same concurrency
# rationale as CID_RING_BIDIR: the paired kernels are airborne at once, so
# they must never share a barrier id, and a broadcast overlapping a
# standalone all-gather must not alias either.
CID_AG_BIDIR = 18
CID_BCAST = 20
# scheduled EP a2a: Birkhoff permutation rounds rotate {22, 23} (one round
# kernel per permutation, globally tie_chunk'd at depth 2 across chunks AND
# rounds — one linear launch sequence, so the 2-id rotation stays sound);
# scale lanes {30, 31} via CID_SCALE_OFFSET. A scheduled combine may be
# airborne while a scheduled dispatch is still draining (same rationale as
# the unscheduled {2,3}/{4,5} split), so it gets its own pair {32, 33}
# (scales {40, 41}).
CID_SCHED = 22
CID_SCHED_COMBINE = 32


def chunk_collective_id(base: int, chunk: int) -> int:
    """2-deep rotation: chunk kernels alternate ``base``/``base+1`` so chunk
    c+1 can enter while chunk c-1 drains, without sharing barrier/credit
    semaphores — the double-buffer discipline at kernel-launch granularity.
    Sound only together with :func:`tie_chunk`, which keeps chunk c and the
    id-sharing chunk c-2 from ever being airborne at once."""
    return base + (chunk & 1)


def tie_chunk(x, prev):
    """The launch-granularity credit of the chunk pipeline: order chunk c's
    kernel input after chunk c-2's OUTPUT, so the two chunks sharing a
    collective id parity can never be in flight together (and no more than
    two chunk kernels — the 2 resident pairs chunk_budget charges — ever
    are). ``prev`` is chunk c-2's result (or None for c < 2); the tie is a
    real dataflow edge (``lax.optimization_barrier``), not a host sync, so
    chunk c+1 still overlaps chunk c freely."""
    if prev is None:
        return x
    x, _ = lax.optimization_barrier((x, prev))
    return x


def pad_capacity(cap: int, n_chunks: int) -> int:
    """Round a capacity/slot count up to a multiple of ``n_chunks`` — the ONE
    rounding rule for every chunked EP pipeline (the device-level chunked
    wire pads its slot axis with empty slots by this rule; the host-level
    cross-pod pipeline sizes its per-pod capacity with it), so the two
    pipelines cannot drift on drop semantics."""
    n_chunks = max(1, int(n_chunks))
    if cap % n_chunks:
        cap += n_chunks - cap % n_chunks
    return cap


def chunk_budget(world: int, chunk_elems_per_peer: int, itemsize: int,
                 what: str, interpret=None, resident_kernels: int = 2,
                 quiet: bool = False) -> bool:
    """Budget gate for the double-buffered chunk pipeline:
    ``resident_kernels`` chunk kernels are resident at once, each holding a
    send+recv pair of ``[world, m]`` padded slots. A single chunked
    exchange keeps 2 (the 2-deep rotation); the fully pipelined MoE layer
    keeps 4 — tie_chunk bounds each FAMILY (dispatch, combine) to two in
    flight, and both families are airborne while a chunk's GEMM runs.
    Charged up front so the pipeline falls back to the unchunked wire as a
    whole instead of degrading mid-flight.

    Under the interpreter the residency multiplier does NOT apply: that
    ceiling exists to keep any single interpret-mode buffer below the
    1-core deadlock threshold (see CHUNK_QUANTUM), chunk kernels run
    sequentially there, and chunking SHRINKS per-kernel buffers — charging
    residency would perversely gate the chunked wire harder than the
    unchunked one it falls back to."""
    m = padded_chunk_elems(chunk_elems_per_peer)
    interpret = resolve_interpret(interpret)
    pair = 2 * world * m * itemsize
    return check_budget(pair if interpret else resident_kernels * pair,
                        what, interpret, quiet=quiet)


def scale_rows(rows: int) -> int:
    """Rows of the packed per-row scale buffer a quantized-wire kernel
    DMAs beside its payload: one f32 scale per 128-lane payload row
    (the rings' block rule), packed LANES scales per buffer row —
    ``ceil(rows / LANES)``."""
    return -(-rows // LANES)


def pack_row_scales(s: jax.Array, srows: int) -> jax.Array:
    """[..., rows] per-row f32 scales → the [..., srows, LANES] wire buffer
    (zero-padded tail; a zero scale dequantizes padding to exact zeros —
    ops.quant's guard). Pure layout: values are untouched, so kernel and
    lax-mirror stay bit-identical through a pack/unpack round trip."""
    *lead, rows = s.shape
    pad = srows * LANES - rows
    if pad:
        s = jnp.pad(s, [(0, 0)] * len(lead) + [(0, pad)])
    return s.reshape(*lead, srows, LANES)


def unpack_row_scales(sp: jax.Array, rows: int) -> jax.Array:
    """Inverse of :func:`pack_row_scales`: [..., srows, LANES] → [..., rows]."""
    *lead, srows, lanes = sp.shape
    return sp.reshape(*lead, srows * lanes)[..., :rows]


def pad_chunks(flat: jax.Array, parts: int) -> Tuple[jax.Array, int, int]:
    """Split ``flat`` into ``parts`` equal chunks of k elements (tail
    zero-padded), then pad EACH chunk to m (a CHUNK_QUANTUM multiple) — the
    chunk boundaries are semantic (DMA slots), so padding must be per-chunk,
    not appended to the buffer tail. Returns ([parts, m//128, 128], k, m)."""
    k = -(-flat.size // parts)
    m = -(-k // CHUNK_QUANTUM) * CHUNK_QUANTUM
    tail = parts * k - flat.size
    if tail:
        flat = jnp.concatenate([flat, jnp.zeros((tail,), flat.dtype)])
    x2 = flat.reshape(parts, k)
    if m > k:
        x2 = jnp.pad(x2, ((0, 0), (0, m - k)))
    return x2.reshape(parts, m // LANES, LANES), k, m


def interpret_default() -> bool:
    """Real Mosaic lowering only exists on TPU backends; anywhere else the
    kernels run under the TPU interpreter (which simulates remote DMAs and
    semaphores faithfully on host devices)."""
    return jax.default_backend() != "tpu"


def resolve_interpret(interpret) -> bool:
    return interpret_default() if interpret is None else bool(interpret)


# pallas_call's interpret= value and the compiler params, version-bridged
# (uccl_tpu.utils.jaxcompat): the faithful InterpretParams interpreter on
# modern jax, the legacy discharge interpreter (plain True) on jax 0.4.x.
interp = _jc.tpu_interpret_params
compiler_params = _jc.tpu_compiler_params


def faithful_sync(interpret: bool) -> bool:
    """True when semaphore/barrier traffic is real: compiled Mosaic, or the
    faithful InterpretParams interpreter. False under the legacy discharge
    interpreter (jax 0.4.x), where remote semaphore signals are not
    implemented — but where every remote DMA discharges into a synchronous
    cross-device gather, so per-DMA global ordering (and thus correctness of
    the data movement) is implied and the elided sync is not load-bearing."""
    return not (interpret and not _jc.FAITHFUL_PALLAS_INTERPRET)


def neighbors(axis, n: int, d: int):
    r = lax.axis_index(axis)
    right = lax.rem(r + d + n, n)
    left = lax.rem(r - d + n, n)
    return r, right, left


def mesh_id(axis, idx):
    """Address a peer by mesh coordinate on the collective axis only — the
    other mesh axes default to this device's own coordinates, so kernels work
    on any axis of any mesh (the sub-axis case of a pp×dp×cp×tp mesh). A
    tuple axis (e.g. the EP world over ("dp", "cp")) decomposes the flat
    index row-major, matching lax.axis_index's linearization."""
    if isinstance(axis, (tuple, list)):
        out = {}
        rem = idx
        for a in reversed(axis):
            s = lax.axis_size(a)
            out[a] = lax.rem(rem, s)
            rem = rem // s
        return out
    return {axis: idx}


def remote_kwargs(axis, idx, faithful: bool) -> dict:
    """device_id kwargs for make_async_remote_copy / semaphore_signal.

    Faithful mode addresses by MESH coordinates (sub-axis safe). The legacy
    discharge interpreter supports neither MESH dicts nor multi-axis meshes —
    there the flat index along the (single) shard axis IS the logical id."""
    if faithful:
        return dict(device_id=mesh_id(axis, idx), device_id_type=MESH)
    return dict(device_id=idx, device_id_type=pltpu.DeviceIdType.LOGICAL)


def ring_barrier(axis, left, right):
    """Neighbor barrier: both ring neighbors' kernels are live (skew along
    the ring is then bounded transitively by the data dependencies)."""
    sem = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(sem, inc=1, device_id=mesh_id(axis, left),
                           device_id_type=MESH)
    pltpu.semaphore_signal(sem, inc=1, device_id=mesh_id(axis, right),
                           device_id_type=MESH)
    pltpu.semaphore_wait(sem, 2)


def all_barrier(axis, n: int):
    """Full-peer barrier: every member's kernel is live. The all-to-all
    pattern needs this stronger form — its very first DMA may target ANY
    peer's buffers, so neighbor liveness (transitive, eventually) is not
    enough at the moment the DMA issues."""
    sem = pltpu.get_barrier_semaphore()
    r = lax.axis_index(axis)
    for i in range(1, n):
        pltpu.semaphore_signal(
            sem, inc=1, device_id=mesh_id(axis, lax.rem(r + i, n)),
            device_id_type=MESH,
        )
    pltpu.semaphore_wait(sem, n - 1)


def budget_limit(interpret: bool) -> int:
    """The effective payload ceiling (no logging): the VMEM budget, further
    clamped by the interpreter's per-buffer deadlock ceiling under interpret
    mode. Exposed so observers (benches labeling which transport actually
    carried an arm) share the gate's arithmetic instead of mirroring it."""
    limit = MAX_VMEM_BYTES.get()
    if interpret:
        limit = min(limit, MAX_INTERP_BYTES.get())
    return limit


def padded_chunk_elems(elems_per_peer: int) -> int:
    """Elements per peer after the CHUNK_QUANTUM padding pad_chunks applies
    — the m in the kernels' [world, m] slot layout."""
    return -(-elems_per_peer // CHUNK_QUANTUM) * CHUNK_QUANTUM


def check_budget(nbytes: int, what: str, interpret: bool,
                 quiet: bool = False) -> bool:
    """``quiet`` suppresses the fallback counter AND log — for observers
    asking what the gate WOULD decide, not taking the fallback (a quiet
    probe must not inflate the fallback series the benches now read)."""
    limit = budget_limit(interpret)
    if nbytes > limit:
        if not quiet:
            record_fallback(
                what,
                "interpret_budget" if interpret else "vmem_budget",
                detail=nbytes,
                msg=(f"pallas {what}: {nbytes}B exceeds "
                     f"{'interpreter' if interpret else 'VMEM'} budget "
                     f"{limit}B; falling back to the XLA collective "
                     "lowering"),
            )
        return False
    return True
