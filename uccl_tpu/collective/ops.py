"""Per-shard collective wrappers for use inside shard_map / pjit.

These are the device-side contract of the collectives pillar: thin, uniformly-named
wrappers over ``jax.lax`` collectives so model/parallel code never spells raw lax
names (and so the chunk-graph scheduler can later swap implementations without
touching call sites). All take ``axis`` as a mesh axis name or tuple of names.

Telemetry: every wrapper tallies itself on the obs registry
(``collective_traced_calls_total`` / ``collective_traced_bytes_total``,
labeled by op). These functions run at TRACE time — inside jit — so the
counts are per *compiled program*, not per execution: the honest host-side
signal for "which collectives does this program issue, over how many
per-shard bytes" (docs/OBSERVABILITY.md). Runtime device timing belongs to
``jax.profiler``.
"""

from __future__ import annotations

from typing import Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from uccl_tpu.obs import counters as _obsc
from uccl_tpu.utils.topology import ppermute_pairs

Axis = Union[str, Tuple[str, ...]]

_CALLS = _obsc.counter(
    "collective_traced_calls_total",
    "collective ops traced into compiled programs, by op",
)
_BYTES = _obsc.counter(
    "collective_traced_bytes_total",
    "per-shard payload bytes of traced collective ops, by op",
)


def _tally(op: str, x) -> None:
    try:
        nbytes = int(x.size) * jnp.dtype(x.dtype).itemsize
    except Exception:
        return  # never let telemetry break a trace
    _CALLS.inc(op=op)
    _BYTES.inc(nbytes, op=op)


def all_reduce(x: jax.Array, axis: Axis, op: str = "sum",
               algo: str = "xla") -> jax.Array:
    """``algo="auto"`` routes a sum through the
    :class:`~uccl_tpu.collective.plan.CollectivePlanner` at trace time
    (per-shard form: the plan-library candidates are the lax lowerings —
    xla | hd — since a per-shard call site cannot vouch for kernel
    addressability); any other op, or ``algo="xla"``, stays on the XLA
    collective. The decision lands on ``collective_plan_total`` like every
    planner decision."""
    _tally("all_reduce", x)
    if op == "sum" and algo == "auto":
        from uccl_tpu.collective import plan as _plan

        n_axes = len(axis) if isinstance(axis, tuple) else 1
        world = lax.axis_size(axis)
        planner = _plan.get_planner()
        shape = tuple(x.shape) or (1,)
        plan_ = planner.plan_all_reduce(shape, x.dtype, world,
                                        n_axes=n_axes, emit=False)
        lowerable = {"xla", "hd", "ring"} | (
            {"torus"} if n_axes == 2 else set())
        exec_algo = plan_.algo if plan_.algo in lowerable else "xla"
        if exec_algo != plan_.algo:
            # a forced kernel algo (bidir/pallas via UCCL_TPU_AR_ALGO) this
            # per-shard site cannot lower — counted, never silent, and the
            # plan counter records what actually runs
            from uccl_tpu.collective import dma as _dma

            _dma.record_fallback(
                "ops_all_reduce", "no_lowering", detail=plan_.algo,
                msg=f"per-shard all_reduce cannot lower planned "
                    f"{plan_.algo!r}; running the xla collective",
            )
        planner.plan_explicit(exec_algo, shape, x.dtype, world,
                              n_axes=n_axes, outcome=plan_.outcome)
        if exec_algo == "hd":
            return _plan.hd_all_reduce(x, axis)
        if exec_algo == "ring":
            return _plan.ring_all_reduce(x, axis)
        if exec_algo == "torus":
            return _plan.torus_all_reduce(x, tuple(axis))
        return lax.psum(x, axis)
    if op == "sum":
        return lax.psum(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    if op == "mean":
        return lax.pmean(x, axis)
    raise ValueError(f"unsupported reduce op {op!r}")


def all_gather(x: jax.Array, axis: Axis, *, dim: int = 0, tiled: bool = True) -> jax.Array:
    _tally("all_gather", x)
    return lax.all_gather(x, axis, axis=dim, tiled=tiled)


def reduce_scatter(x: jax.Array, axis: Axis, *, dim: int = 0) -> jax.Array:
    _tally("reduce_scatter", x)
    return lax.psum_scatter(x, axis, scatter_dimension=dim, tiled=True)


def all_to_all(
    x: jax.Array, axis: Axis, *, split_dim: int, concat_dim: int, tiled: bool = True
) -> jax.Array:
    _tally("all_to_all", x)
    return lax.all_to_all(x, axis, split_axis=split_dim, concat_axis=concat_dim, tiled=tiled)


def ppermute(x: jax.Array, axis: Axis, perm: Sequence[Tuple[int, int]]) -> jax.Array:
    _tally("ppermute", x)
    return lax.ppermute(x, axis, perm=list(perm))


def ring_shift(x: jax.Array, axis: str, shift: int = 1) -> jax.Array:
    """Rotate shards around the ring: member i's value goes to member i+shift."""
    _tally("ring_shift", x)
    return lax.ppermute(x, axis, perm=ppermute_pairs(lax.axis_size(axis), shift))


def axis_index(axis: Axis) -> jax.Array:
    return lax.axis_index(axis)


def axis_size(axis: Axis) -> int:
    return lax.axis_size(axis)


def broadcast(x: jax.Array, axis: Axis, root: int = 0) -> jax.Array:
    """Every member ends with the root member's value."""
    _tally("broadcast", x)
    g = lax.all_gather(x, axis, axis=0, tiled=False)
    return g[root]
