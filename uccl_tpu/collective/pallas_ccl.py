"""Device-level ring collectives: Pallas remote-DMA kernels on the ICI torus.

This is the layer the reference's value proposition lives in: UCCL beats the
vendor stack by owning the transport under an unchanged API — its engine hot
loop schedules chunks onto 32 UC QPs itself (collective/rdma/transport.cc:443,
chunk spraying :2186) and the next-gen ukernel executes chunk graphs with
persistent device workers (experimental/ukernel/src/ccl/executor.h:26-60).
The TPU analog of "owning the wire" is issuing the inter-chip DMAs from
inside a kernel instead of letting XLA schedule a collective: each hop is a
``pltpu.make_async_remote_copy`` between neighbor chips, double-buffered,
with credit-based flow control — no per-step XLA dispatch, payload resident
in VMEM, and both ICI ring directions drivable concurrently from one kernel
(the torus form of multipath spraying).

Three per-shard entry points (used inside ``shard_map`` like their
:mod:`uccl_tpu.collective.plan` counterparts, which remain the lax.ppermute
lowering of the same schedules):

* :func:`ring_all_gather`   — chunks circulate; direct buf→buf remote DMA.
* :func:`ring_reduce_scatter` — partials circulate via staging buffers.
* :func:`ring_all_reduce`   — RS phase + AG phase in ONE kernel launch,
  optionally bidirectional (payload halved over counter-rotating rings).

Synchronization design (the part that must be right):

* Neighbor barrier at kernel entry (and between the RS and AG phases of the
  fused allreduce): a remote DMA may not target a neighbor's scratch before
  that neighbor's kernel is live (or, at the phase boundary, before its
  sends from the target slot have drained).
* Write-once slots (AG): each buf slot is written exactly once, so data can
  never be clobbered; semaphores count arrivals.
* Credit flow control: ring skew is bounded only by data dependencies — with
  every device but one making progress, the upstream neighbor can run up to
  n-1 steps ahead, overrunning a 2-deep buffer/semaphore rotation. Each
  consumer therefore grants its upstream neighbor an explicit credit
  (``semaphore_signal`` of the sender's ack semaphore) after consuming a
  slot; senders wait for a credit from step 2 on (two slots start free).
  Signals and waits are balanced so every semaphore drains to zero.

Quantized wire (``wire_dtype="fp8"|"int8"`` — the EQuARX move, PAPERS.md:
quantize AllReduce payloads on the wire for ~2-4x fewer bytes with bounded
loss impact):

* every hop moves a block-scaled payload (one f32 scale per 128-lane row,
  the shared :mod:`uccl_tpu.ops.quant` codec) plus its scale sidecar on the
  ``collective_id + CID_SCALE_OFFSET`` lane;
* **reduce-scatter quantizes in the send path and dequantizes in the recv
  path BEFORE accumulating in the input precision** — partial sums are
  never stored in wire precision, so the error is one quantize round trip
  per hop (additive over the n-1 hops), never compounding;
* all-gather payloads are quantized ONCE and forwarded verbatim (write-once
  slots make forwarding exact), so every member pays exactly one round trip;
* the budget/addressability fallbacks ride a **bit-identical pure-lax
  mirror** of the same per-hop math (same codec calls, same slot
  arithmetic), counted on ``ep_wire_fallback_total`` like every transparent
  downgrade — a quantized collective is never silently full-precision and
  never silently off the kernel path. Non-float payloads downgrade to the
  full-precision wire with reason ``quant_dtype``.

Wire bytes are tallied at TRACE time (once per compiled program, the same
per-compile semantics as ``dma.record_fallback``) on the shared
``ep_bytes_total{verb,wire,wire_dtype}`` counter: per-shard bytes actually
sent over the wire for one call — quantized payload + scale sidecar, not
logical element bytes — so benches read effective bus bandwidth straight
off counter deltas (docs/QUANT_WIRE.md).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from uccl_tpu.collective import dma as _dma
from uccl_tpu.obs import counters as _obsc
from uccl_tpu.ops import quant as _quant
from uccl_tpu.utils.topology import ppermute_pairs

# Shared substrate (uccl_tpu.collective.dma) — also used by the EP
# all-to-all kernels (uccl_tpu.ep.pallas_a2a). The underscored aliases keep
# this module's long-standing surface (tests reset _MAX_VMEM_BYTES, etc.).
_LANES = _dma.LANES
_CHUNK_QUANTUM = _dma.CHUNK_QUANTUM
_MAX_VMEM_BYTES = _dma.MAX_VMEM_BYTES
_MAX_INTERP_BYTES = _dma.MAX_INTERP_BYTES
_MESH = _dma.MESH
_pad_chunks = _dma.pad_chunks
_interpret_default = _dma.interpret_default
_resolve_interpret = _dma.resolve_interpret
_interp = _dma.interp
_neighbors = _dma.neighbors
_mesh_id = _dma.mesh_id
_barrier = _dma.ring_barrier

# the same family ep.buffer's verbs count on — get-or-create by name
# returns the one shared registry family
_WIRE_BYTES = _obsc.counter(
    "ep_bytes_total",
    "actual wire bytes moved by EP verbs and ring collectives (quantized "
    "payload + f32 scale sidecar when a wire_dtype applies, raw element "
    "bytes otherwise), by verb, wire, and wire_dtype",
)


def _count_wire_bytes(verb: str, wire: str, wire_dtype, nbytes: int) -> None:
    """Tally one call's per-shard wire bytes at trace time (per-compile
    semantics — a jit cache hit re-runs the traced exchange without
    re-counting; benches diff around a compiling call)."""
    _WIRE_BYTES.inc(nbytes, verb=verb, wire=wire,
                    wire_dtype=wire_dtype or "none")


def _ring_wire_dtype(x: jax.Array, wire_dtype, what: str):
    """Validate a ring's wire_dtype and downgrade non-float payloads to the
    full-precision wire — counted, never silent."""
    wire_dtype = _quant.resolve_wire_dtype(wire_dtype)
    if wire_dtype is not None and not jnp.issubdtype(
        jnp.dtype(x.dtype), jnp.floating
    ):
        _dma.record_fallback(
            what, "quant_dtype", detail=jnp.dtype(x.dtype).name,
            msg=f"pallas {what}: wire_dtype={wire_dtype!r} needs a float "
                f"payload, got {jnp.dtype(x.dtype).name}; shipping full "
                "precision",
        )
        return None
    return wire_dtype


def _hop_wire_bytes(m: int, itemsize: int, wire_dtype) -> int:
    """Bytes ONE ring hop of an m-element chunk moves: raw payload, or the
    1-byte quantized payload + packed f32 row-scale sidecar."""
    if wire_dtype is None:
        return m * itemsize
    srows = _dma.scale_rows(m // _LANES)
    return m + srows * _LANES * 4


def _quantize_rows(chunk, wire_dtype):
    """Per-row block quantization of a [..., rows, LANES] chunk — the rings'
    block rule (block = one 128-lane row). Returns (q same shape, scales
    [..., rows, 1] f32) via the shared codec."""
    return _quant.quantize_block(chunk, wire_dtype, _LANES)


def _dequantize_rows(q, scales, dtype):
    """Inverse of :func:`_quantize_rows` (scales [..., rows, 1])."""
    return _quant.dequantize_block(q, scales, _LANES, dtype)


def _ag_phase(axis, n, dirs, buf_ref, send_sem, recv_sem, ack_sem,
              faithful=True):
    """All-gather rings on ``buf_ref[:, h]`` for each stream h (one ring per
    direction in ``dirs``, all DMAs of a step issued before any wait): n-1
    steps of direct buf→buf remote DMA — chunk j lives at slot j on every
    member, so the destination slot equals the source slot and every slot is
    write-once. ``faithful`` is static: the legacy discharge interpreter
    (jax 0.4.x) implements no remote semaphore signals, so the credit
    traffic is elided there — subsumed by its per-DMA global ordering."""
    nbrs = [_neighbors(axis, n, d) for d in dirs]

    def step(s, _):
        descs = []
        for h, d in enumerate(dirs):
            r, right, _left = nbrs[h]
            send_slot = lax.rem(r - d * s + s * n + n, n)

            if faithful:

                @pl.when(s >= 2)
                def _(h=h):  # credit from downstream: slot s%2 consumed
                    pltpu.semaphore_wait(ack_sem.at[h], 1)

            sl = lax.rem(s, 2)
            rdma = pltpu.make_async_remote_copy(
                src_ref=buf_ref.at[send_slot, h],
                dst_ref=buf_ref.at[send_slot, h],
                send_sem=send_sem.at[h, sl],
                recv_sem=recv_sem.at[h, sl],
                **_dma.remote_kwargs(axis, right, faithful),
            )
            rdma.start()
            descs.append(rdma)
        for h, d in enumerate(dirs):
            _r, _right, left = nbrs[h]
            descs[h].wait_recv()  # slot (r - d(s+1)) arrived

            if faithful:

                @pl.when(s <= n - 4)
                def _(h=h, left=left):  # grant upstream its step-(s+2) send
                    pltpu.semaphore_signal(
                        ack_sem.at[h], inc=1,
                        **_dma.remote_kwargs(axis, left, faithful),
                    )

        for rdma in descs:
            rdma.wait_send()
        return 0

    lax.fori_loop(0, n - 1, step, 0)


def _rs_phase(axis, n, dirs, buf_ref, stage_ref, send_sem, recv_sem,
              ack_sem, faithful=True):
    """Reduce-scatter rings on ``buf_ref[:, h]`` per stream: partial sums
    circulate through 2-slot staging; member r ends holding slot r fully
    reduced. Slot arithmetic matches plan.plan_reduce_scatter
    (send_off=-(s+1), recv_off=-(s+2)). ``faithful``: see :func:`_ag_phase`."""
    nbrs = [_neighbors(axis, n, d) for d in dirs]

    def step(s, _):
        descs = []
        for h, d in enumerate(dirs):
            r, right, _left = nbrs[h]
            send_slot = lax.rem(r - d * (s + 1) + (s + 1) * n + n, n)

            if faithful:

                @pl.when(s >= 2)
                def _(h=h):  # credit: downstream consumed staging slot s%2
                    pltpu.semaphore_wait(ack_sem.at[h], 1)

            sl = lax.rem(s, 2)
            rdma = pltpu.make_async_remote_copy(
                src_ref=buf_ref.at[send_slot, h],
                dst_ref=stage_ref.at[h, sl],
                send_sem=send_sem.at[h, sl],
                recv_sem=recv_sem.at[h, sl],
                **_dma.remote_kwargs(axis, right, faithful),
            )
            rdma.start()
            descs.append(rdma)
        sl = lax.rem(s, 2)
        for h, d in enumerate(dirs):
            r, _right, left = nbrs[h]
            recv_slot = lax.rem(r - d * (s + 2) + (s + 2) * n + n, n)
            descs[h].wait_recv()
            # fold the arrived partial into the slot sent next step
            buf_ref[recv_slot, h] = (
                buf_ref[recv_slot, h] + stage_ref[h, sl]
            )

            if faithful:

                @pl.when(s <= n - 4)
                def _(h=h, left=left):  # staging consumed — grant step s+2
                    pltpu.semaphore_signal(
                        ack_sem.at[h], inc=1,
                        **_dma.remote_kwargs(axis, left, faithful),
                    )

        for rdma in descs:
            rdma.wait_send()
        return 0

    lax.fori_loop(0, n - 1, step, 0)


def _rs_phase_q(axis, n, dirs, buf_ref, qsend_ref, ssend_ref, qstage_ref,
                sstage_ref, send_sem, recv_sem, ssend_sem, srecv_sem,
                ack_sem, faithful, wire_dtype, rows, srows, dtype):
    """The quantized-wire reduce-scatter phase: identical slot/credit
    schedule to :func:`_rs_phase`, but each hop's send path quantizes the
    partial sum into a wire-dtype scratch + packed row scales (TWO remote
    DMAs per hop per stream — payload and scale sidecar, no data dependency
    between them) and the recv path dequantizes BEFORE accumulating into
    ``buf_ref`` in the input precision. Partial sums never live in wire
    precision (the EQuARX error-bounding rule): the error is one quantize
    round trip per hop. The payload and scale staging slots of a step are
    consumed together, so ONE ack credit per stream gates both — the
    credit-window arithmetic is untouched."""
    nbrs = [_neighbors(axis, n, d) for d in dirs]

    def step(s, _):
        descs = []
        for h, d in enumerate(dirs):
            r, right, _left = nbrs[h]
            send_slot = lax.rem(r - d * (s + 1) + (s + 1) * n + n, n)

            if faithful:

                @pl.when(s >= 2)
                def _(h=h):  # credit: downstream consumed staging slot s%2
                    pltpu.semaphore_wait(ack_sem.at[h], 1)

            # quantize the send path: wire payload + packed row scales
            q, sc = _quantize_rows(buf_ref[send_slot, h], wire_dtype)
            qsend_ref[h] = q
            ssend_ref[h] = _dma.pack_row_scales(sc[..., 0], srows)
            sl = lax.rem(s, 2)
            rq = pltpu.make_async_remote_copy(
                src_ref=qsend_ref.at[h],
                dst_ref=qstage_ref.at[h, sl],
                send_sem=send_sem.at[h, sl],
                recv_sem=recv_sem.at[h, sl],
                **_dma.remote_kwargs(axis, right, faithful),
            )
            rs_ = pltpu.make_async_remote_copy(
                src_ref=ssend_ref.at[h],
                dst_ref=sstage_ref.at[h, sl],
                send_sem=ssend_sem.at[h, sl],
                recv_sem=srecv_sem.at[h, sl],
                **_dma.remote_kwargs(axis, right, faithful),
            )
            rq.start()
            rs_.start()
            descs.append((rq, rs_))
        sl = lax.rem(s, 2)
        for h, d in enumerate(dirs):
            r, _right, left = nbrs[h]
            recv_slot = lax.rem(r - d * (s + 2) + (s + 2) * n + n, n)
            rq, rs_ = descs[h]
            rq.wait_recv()
            rs_.wait_recv()
            # dequantize, THEN accumulate in the input precision
            sc = _dma.unpack_row_scales(sstage_ref[h, sl], rows)
            deq = _dequantize_rows(qstage_ref[h, sl], sc[..., None], dtype)
            buf_ref[recv_slot, h] = buf_ref[recv_slot, h] + deq

            if faithful:

                @pl.when(s <= n - 4)
                def _(h=h, left=left):  # staging consumed — grant step s+2
                    pltpu.semaphore_signal(
                        ack_sem.at[h], inc=1,
                        **_dma.remote_kwargs(axis, left, faithful),
                    )

        for rq, rs_ in descs:
            rq.wait_send()
            rs_.wait_send()
        return 0

    lax.fori_loop(0, n - 1, step, 0)


def _scratch(n_streams, rows, dtype, with_staging):
    shapes = [
        pltpu.SemaphoreType.DMA((n_streams, 2)),  # send
        pltpu.SemaphoreType.DMA((n_streams, 2)),  # recv
        pltpu.SemaphoreType.REGULAR((n_streams,)),  # ack credits
    ]
    if with_staging:
        shapes.insert(
            0, pltpu.VMEM((n_streams, 2, rows, _LANES), dtype)
        )
    return shapes


def _quant_scratch(n_streams, rows, srows, wire_dtype):
    """Wire scratch + semaphores of the quantized RS phase: send/stage pairs
    for the payload (wire dtype) and the packed row scales (f32), payload
    DMA sems, scale DMA sems, and the shared ack credits."""
    wdt = _quant.wire_payload_dtype(wire_dtype)
    return [
        pltpu.VMEM((n_streams, rows, _LANES), wdt),  # qsend
        pltpu.VMEM((n_streams, srows, _LANES), jnp.float32),  # ssend
        pltpu.VMEM((n_streams, 2, rows, _LANES), wdt),  # qstage
        pltpu.VMEM((n_streams, 2, srows, _LANES), jnp.float32),  # sstage
        pltpu.SemaphoreType.DMA((n_streams, 2)),  # payload send
        pltpu.SemaphoreType.DMA((n_streams, 2)),  # payload recv
        pltpu.SemaphoreType.DMA((n_streams, 2)),  # scale send
        pltpu.SemaphoreType.DMA((n_streams, 2)),  # scale recv
        pltpu.SemaphoreType.REGULAR((n_streams,)),  # ack credits (shared)
    ]


_check_budget = _dma.check_budget


# ---------------------------------------------------------------------------
# Pure-lax mirrors of the quantized schedules. These are the budget /
# addressability fallbacks of the quantized entries and MUST stay
# bit-identical to the kernels: same codec calls (uccl_tpu.ops.quant), same
# slot arithmetic (plan.py offsets), same accumulate-in-input-precision
# order. tests/test_quant_wire.py pins kernel == mirror exactly.


def _mirror_rs_hops(buf, axis, n, d, wire_dtype, dtype):
    """n-1 quantized reduce-scatter hops on ``buf`` [n, rows, LANES]:
    send_off −(s+1), recv_off −(s+2) (plan.plan_reduce_scatter), each hop
    quantize→ppermute(payload, scales)→dequantize→accumulate."""
    pairs = ppermute_pairs(n, d)
    r = lax.axis_index(axis)
    for s in range(n - 1):
        send_slot = jnp.mod(r - d * (s + 1), n)
        recv_slot = jnp.mod(r - d * (s + 2), n)
        chunk = lax.dynamic_index_in_dim(buf, send_slot, 0, keepdims=False)
        q, sc = _quantize_rows(chunk, wire_dtype)
        qg = lax.ppermute(q, axis, pairs)
        sg = lax.ppermute(sc, axis, pairs)
        deq = _dequantize_rows(qg, sg, dtype)
        cur = lax.dynamic_index_in_dim(buf, recv_slot, 0, keepdims=False)
        buf = lax.dynamic_update_index_in_dim(buf, cur + deq, recv_slot, 0)
    return buf


def _mirror_ag_hops(buf, axis, n, d):
    """n-1 verbatim all-gather hops on ``buf`` [n, ...] (send_off −s,
    recv_off −(s+1), plan.plan_all_gather) — payload dtype untouched, so a
    quantized buffer is forwarded exactly like the kernel's write-once
    slots."""
    pairs = ppermute_pairs(n, d)
    r = lax.axis_index(axis)
    for s in range(n - 1):
        send_slot = jnp.mod(r - d * s, n)
        recv_slot = jnp.mod(r - d * (s + 1), n)
        chunk = lax.dynamic_index_in_dim(buf, send_slot, 0, keepdims=False)
        got = lax.ppermute(chunk, axis, pairs)
        buf = lax.dynamic_update_index_in_dim(buf, got, recv_slot, 0)
    return buf


def _mirror_quant_ar_stream(buf, axis, n, d, wire_dtype, dtype):
    """One stream of the quantized allreduce in pure lax: quantized RS hops
    (input-precision accumulator), quantize the reduced slot ONCE, verbatim
    AG of payload + scales, dequantize every slot. buf: [n, rows, LANES]."""
    buf = _mirror_rs_hops(buf, axis, n, d, wire_dtype, dtype)
    r = lax.axis_index(axis)
    mine = lax.dynamic_index_in_dim(buf, r, 0, keepdims=False)
    q, sc = _quantize_rows(mine, wire_dtype)
    qbuf = jnp.zeros((n,) + q.shape, q.dtype)
    qbuf = lax.dynamic_update_index_in_dim(qbuf, q, r, 0)
    sbuf = jnp.zeros((n,) + sc.shape, sc.dtype)
    sbuf = lax.dynamic_update_index_in_dim(sbuf, sc, r, 0)
    qbuf = _mirror_ag_hops(qbuf, axis, n, d)
    sbuf = _mirror_ag_hops(sbuf, axis, n, d)
    return _dequantize_rows(qbuf, sbuf, dtype)


def _ag_ring(chunk, axis, n, *, direction, interpret, faithful,
             collective_id):
    """One write-once all-gather ring kernel on a [1, rows, LANES] chunk of
    any dtype → [n, 1, rows, LANES]. The payload core of ring_all_gather,
    reused verbatim for the quantized wire's payload and scale exchanges
    (forwarding is dtype-agnostic)."""
    rows = chunk.shape[1]

    def kernel(x_ref, buf_ref, send_sem, recv_sem, ack_sem):
        r, right, left = _neighbors(axis, n, direction)
        if faithful:
            _barrier(axis, left, right)
        buf_ref[r, 0] = x_ref[0]
        _ag_phase(axis, n, (direction,), buf_ref, send_sem, recv_sem,
                  ack_sem, faithful)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n, 1, rows, _LANES), chunk.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=_scratch(1, rows, chunk.dtype, with_staging=False),
        compiler_params=_dma.compiler_params(collective_id),
        interpret=_interp(interpret),
    )(chunk)


def ring_all_gather(x: jax.Array, axis, *, direction: int = 1,
                    interpret=None, collective_id: int = 0,
                    wire_dtype=None, count: bool = True) -> jax.Array:
    """Per-shard ``[k, ...] -> [n*k, ...]`` ring all-gather as one Pallas
    kernel (n-1 neighbor DMA hops). Falls back to the plan lowering when the
    gathered buffer exceeds the VMEM budget.

    ``wire_dtype``: quantize the payload once (shared block codec, one f32
    scale per 128-lane row) and circulate payload + scale sidecar — every
    member dequantizes the same wire bytes, so the result is identical on
    all members and one quantize round trip from the input.

    ``count=False`` suppresses the ``ep_bytes_total`` tally — for callers
    that compose this ring into a larger schedule and count the WHOLE
    schedule's bytes under their own verb (scatter_ag_broadcast), so no
    byte is ever counted on two series."""
    n = lax.axis_size(axis)
    if n == 1:
        return x
    interpret = _resolve_interpret(interpret)
    wire_dtype = _ring_wire_dtype(x, wire_dtype, "all_gather")
    k = x.shape[0]
    flat = x.reshape(-1)
    chunk, _, m = _pad_chunks(flat, 1)  # [1, rows, 128]
    rows = m // _LANES
    faithful = _dma.faithful_sync(interpret)
    if wire_dtype is not None and direction == -1 and not faithful:
        # The legacy discharge interpreter (jax 0.4.x) mis-propagates the
        # sharding of the REVERSE-ring payload+scale gather pair (XLA
        # Array::Reshape check failure at compile). An all-gather's result
        # is direction-independent — write-once verbatim forwarding — so
        # ride the forward ring there: the counter-rotation only buys
        # concurrency on substrates with real DMAs, which the discharge
        # interpreter serializes anyway. Bit-identical output either way.
        direction = 1
    itemsize = x.dtype.itemsize
    hop_bytes = _hop_wire_bytes(m, itemsize, wire_dtype)

    if wire_dtype is None:
        if not _check_budget(n * x.size * itemsize, "all_gather",
                             interpret):
            from uccl_tpu.collective import plan

            if count:
                _count_wire_bytes("ring_all_gather", "lax", None,
                                  (n - 1) * hop_bytes)
            return plan.ring_all_gather(x, axis)
        if count:
            _count_wire_bytes("ring_all_gather", "pallas", None,
                              (n - 1) * hop_bytes)
        buf = _ag_ring(chunk, axis, n, direction=direction,
                       interpret=interpret, faithful=faithful,
                       collective_id=collective_id)
        out = buf.reshape(n, m)[:, : flat.size]
        return out.reshape((n * k,) + x.shape[1:])

    # quantized wire: quantize ONCE, gather payload + packed scales
    srows = _dma.scale_rows(rows)
    q, sc = _quantize_rows(chunk, wire_dtype)  # [1,rows,128], [1,rows,1]
    if not _check_budget(n * hop_bytes, "all_gather", interpret):
        from uccl_tpu.collective import plan

        if count:
            _count_wire_bytes("ring_all_gather", "lax", wire_dtype,
                              (n - 1) * hop_bytes)
        qg = plan.ring_all_gather(q, axis)  # [n, rows, 128]
        sg = plan.ring_all_gather(sc, axis)  # [n, rows, 1]
        out = _dequantize_rows(qg, sg, x.dtype)
    else:
        if count:
            _count_wire_bytes("ring_all_gather", "pallas", wire_dtype,
                              (n - 1) * hop_bytes)
        sp = _dma.pack_row_scales(sc[..., 0], srows)  # [1, srows, 128]
        qbuf = _ag_ring(q, axis, n, direction=direction,
                        interpret=interpret, faithful=faithful,
                        collective_id=collective_id)
        sbuf = _ag_ring(sp, axis, n, direction=direction,
                        interpret=interpret, faithful=faithful,
                        collective_id=collective_id + _dma.CID_SCALE_OFFSET)
        scg = _dma.unpack_row_scales(sbuf, rows)  # [n, 1, rows]
        out = _dequantize_rows(qbuf, scg[..., None], x.dtype)
    out = out.reshape(n, m)[:, : flat.size]
    return out.reshape((n * k,) + x.shape[1:])


def ring_reduce_scatter(x: jax.Array, axis, *, direction: int = 1,
                        interpret=None, collective_id: int = 0,
                        wire_dtype=None) -> jax.Array:
    """Per-shard ``[n*k, ...] -> [k, ...]``: member r keeps reduced slot r
    (sum), matching plan.ring_reduce_scatter.

    ``wire_dtype``: every hop's partial sum crosses the wire block-quantized
    (payload + row-scale sidecar) and is dequantized before accumulating in
    the input precision — one quantize round trip of error per hop."""
    n = lax.axis_size(axis)
    if n == 1:
        return x
    # validate BEFORE the budget fallback: an over-budget indivisible
    # payload must raise, not silently misalign in the plan lowering
    if x.shape[0] % n:
        raise ValueError(f"leading dim {x.shape[0]} not divisible by {n}")
    interpret = _resolve_interpret(interpret)
    wire_dtype = _ring_wire_dtype(x, wire_dtype, "reduce_scatter")
    k = x.shape[0] // n
    chunks, per, m = _pad_chunks(x.reshape(-1), n)  # [n, rows, 128]
    rows = m // _LANES
    itemsize = x.dtype.itemsize
    hop_bytes = _hop_wire_bytes(m, itemsize, wire_dtype)
    faithful = _dma.faithful_sync(interpret)

    if wire_dtype is None:
        if not _check_budget(rs_charge(x.size, itemsize, n, None, interpret),
                             "reduce_scatter", interpret):
            from uccl_tpu.collective import plan

            _count_wire_bytes("ring_reduce_scatter", "lax", None,
                              (n - 1) * hop_bytes)
            return plan.ring_reduce_scatter(x, axis)
        _count_wire_bytes("ring_reduce_scatter", "pallas", None,
                          (n - 1) * hop_bytes)
        chunks = chunks.reshape(n, 1, rows, _LANES)

        def kernel(x_ref, out_ref, buf_ref, stage_ref, send_sem, recv_sem,
                   ack_sem):
            r, right, left = _neighbors(axis, n, direction)
            if faithful:
                _barrier(axis, left, right)
            buf_ref[...] = x_ref[...]
            _rs_phase(axis, n, (direction,), buf_ref, stage_ref, send_sem,
                      recv_sem, ack_sem, faithful)
            out_ref[...] = buf_ref[r, 0]

        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((rows, _LANES), x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.VMEM((n, 1, rows, _LANES), x.dtype)]
            + _scratch(1, rows, x.dtype, with_staging=True),
            compiler_params=_dma.compiler_params(collective_id),
            interpret=_interp(interpret),
        )(chunks)
        return out.reshape(-1)[:per].reshape((k,) + x.shape[1:])

    # quantized wire: accumulator stays input precision; the wire scratches
    # (send + 2-slot staging for payload and scales) ride on top
    srows = _dma.scale_rows(rows)
    charge = rs_charge(x.size, itemsize, n, wire_dtype, interpret)
    if not _check_budget(charge, "reduce_scatter", interpret):
        _count_wire_bytes("ring_reduce_scatter", "lax", wire_dtype,
                          (n - 1) * hop_bytes)
        buf = _mirror_rs_hops(chunks, axis, n, direction, wire_dtype,
                              x.dtype)
        r = lax.axis_index(axis)
        out = lax.dynamic_index_in_dim(buf, r, 0, keepdims=False)
        return out.reshape(-1)[:per].reshape((k,) + x.shape[1:])
    _count_wire_bytes("ring_reduce_scatter", "pallas", wire_dtype,
                      (n - 1) * hop_bytes)
    chunks = chunks.reshape(n, 1, rows, _LANES)

    def kernel(x_ref, out_ref, buf_ref, qsend, ssend, qstage, sstage,
               send_sem, recv_sem, ssend_sem, srecv_sem, ack_sem):
        r, right, left = _neighbors(axis, n, direction)
        if faithful:
            _barrier(axis, left, right)
        buf_ref[...] = x_ref[...]
        _rs_phase_q(axis, n, (direction,), buf_ref, qsend, ssend, qstage,
                    sstage, send_sem, recv_sem, ssend_sem, srecv_sem,
                    ack_sem, faithful, wire_dtype, rows, srows, x.dtype)
        out_ref[...] = buf_ref[r, 0]

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((n, 1, rows, _LANES), x.dtype)]
        + _quant_scratch(1, rows, srows, wire_dtype),
        compiler_params=_dma.compiler_params(collective_id),
        interpret=_interp(interpret),
    )(chunks)
    return out.reshape(-1)[:per].reshape((k,) + x.shape[1:])


def ring_all_reduce(x: jax.Array, axis, *, bidirectional: bool = True,
                    direction: int = 1, interpret=None,
                    collective_id: int = 0, wire_dtype=None) -> jax.Array:
    """Per-shard allreduce (sum) as ONE kernel: reduce-scatter phase, phase
    barrier, all-gather phase. With ``bidirectional=True`` the payload is
    split over two counter-rotating rings whose DMAs are issued back to back
    each step — both ICI directions of the axis carry traffic concurrently
    (the torus form of UCCL's multipath spraying, transport.cc:2186), from
    inside a single kernel rather than two serialized collectives.
    ``direction`` rotates the single ring when ``bidirectional=False`` —
    the stream primitive :func:`bidir_all_reduce` pairs a +1 and a -1 ring
    as separate concurrently-airborne kernels.

    ``wire_dtype="fp8"|"int8"`` quantizes the wire (module docstring): the
    RS phase quantizes each hop's send and dequantizes before accumulating
    in input precision; the reduced slot is then quantized ONCE and the AG
    phase forwards wire bytes verbatim (payload on the RS semaphores after
    the phase barrier, scales on their own semaphore set). Total error:
    n-1 per-hop round trips into the sum, plus one on the gathered copy."""
    n = lax.axis_size(axis)
    if n == 1:
        return x
    interpret = _resolve_interpret(interpret)
    wire_dtype = _ring_wire_dtype(x, wire_dtype, "all_reduce")
    n_streams = 2 if bidirectional else 1
    dirs = (1, -1) if bidirectional else (direction,)
    shape = x.shape
    flat = x.reshape(-1)
    # [n*S, rows, 128], slot-major then stream
    view, k, m = _pad_chunks(flat, n * n_streams)
    rows = m // _LANES
    view = view.reshape(n, n_streams, rows, _LANES)
    itemsize = x.dtype.itemsize
    hop_bytes = _hop_wire_bytes(m, itemsize, wire_dtype)
    wire_total = 2 * (n - 1) * n_streams * hop_bytes
    faithful = _dma.faithful_sync(interpret)

    if wire_dtype is None:
        if not _check_budget(x.size * itemsize, "all_reduce", interpret):
            from uccl_tpu.collective import plan

            _count_wire_bytes("ring_all_reduce", "lax", None, wire_total)
            return plan.ring_all_reduce(x, axis,
                                        bidirectional=bidirectional,
                                        direction=direction)
        _count_wire_bytes("ring_all_reduce", "pallas", None, wire_total)

        def kernel(x_ref, buf_ref, stage_ref, send_sem, recv_sem, ack_sem):
            r = lax.axis_index(axis)
            right = lax.rem(r + 1, n)
            left = lax.rem(r - 1 + n, n)
            if faithful:
                _barrier(axis, left, right)
            buf_ref[...] = x_ref[...]
            _rs_phase(axis, n, dirs, buf_ref, stage_ref, send_sem,
                      recv_sem, ack_sem, faithful)
            # Phase barrier: my AG write into a neighbor's buf slot must
            # land after that neighbor's RS sends from it have drained (its
            # RS loop waits every send_sem, so "RS done" implies the reads
            # completed).
            if faithful:
                _barrier(axis, left, right)
            _ag_phase(axis, n, dirs, buf_ref, send_sem, recv_sem, ack_sem,
                      faithful)

        buf = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n, n_streams, rows, _LANES),
                                           x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=_scratch(n_streams, rows, x.dtype,
                                    with_staging=True),
            compiler_params=_dma.compiler_params(collective_id),
            interpret=_interp(interpret),
        )(view)
        out = buf.reshape(n * n_streams, m)[:, :k]
        return out.reshape(-1)[: flat.size].reshape(shape)

    # quantized wire: input-precision accumulator + wire-dtype AG buffers
    # + PER-STREAM send/2-slot-staging wire scratch (_quant_scratch)
    srows = _dma.scale_rows(rows)
    charge = (x.size * itemsize + n * n_streams * hop_bytes
              + n_streams * 3 * hop_bytes)
    if not _check_budget(charge, "all_reduce", interpret):
        _count_wire_bytes("ring_all_reduce", "lax", wire_dtype, wire_total)
        streams = [
            _mirror_quant_ar_stream(view[:, h], axis, n, d, wire_dtype,
                                    x.dtype)
            for h, d in enumerate(dirs)
        ]
        buf = jnp.stack(streams, axis=1)  # [n, S, rows, LANES]
        out = buf.reshape(n * n_streams, m)[:, :k]
        return out.reshape(-1)[: flat.size].reshape(shape)
    _count_wire_bytes("ring_all_reduce", "pallas", wire_dtype, wire_total)
    wdt = _quant.wire_payload_dtype(wire_dtype)

    def kernel(x_ref, buf_ref, qsend, ssend, qstage, sstage, send_sem,
               recv_sem, ssend_sem, srecv_sem, ack_sem, qbuf, sbuf,
               sack_sem):
        r = lax.axis_index(axis)
        right = lax.rem(r + 1, n)
        left = lax.rem(r - 1 + n, n)
        if faithful:
            _barrier(axis, left, right)
        buf_ref[...] = x_ref[...]
        _rs_phase_q(axis, n, dirs, buf_ref, qsend, ssend, qstage, sstage,
                    send_sem, recv_sem, ssend_sem, srecv_sem, ack_sem,
                    faithful, wire_dtype, rows, srows, x.dtype)
        # Phase barrier: the payload AG reuses the RS payload semaphores —
        # an early AG signal must not race a neighbor still in its RS loop.
        if faithful:
            _barrier(axis, left, right)
        # quantize the reduced slot ONCE; AG forwards wire bytes verbatim
        # (write-once slots), every member dequantizing the same bytes
        for h in range(n_streams):
            q, sc = _quantize_rows(buf_ref[r, h], wire_dtype)
            qbuf[r, h] = q
            sbuf[r, h] = _dma.pack_row_scales(sc[..., 0], srows)
        _ag_phase(axis, n, dirs, qbuf, send_sem, recv_sem, ack_sem,
                  faithful)
        # the scale AG rides the scale semaphores + its own credits —
        # disjoint from the payload AG's set, so no barrier between them
        _ag_phase(axis, n, dirs, sbuf, ssend_sem, srecv_sem, sack_sem,
                  faithful)
        scg = _dma.unpack_row_scales(sbuf[...], rows)  # [n, S, rows]
        buf_ref[...] = _dequantize_rows(qbuf[...], scg[..., None], x.dtype)

    buf = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n, n_streams, rows, _LANES),
                                       x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=_quant_scratch(n_streams, rows, srows, wire_dtype)
        + [
            pltpu.VMEM((n, n_streams, rows, _LANES), wdt),  # qbuf (AG)
            pltpu.VMEM((n, n_streams, srows, _LANES), jnp.float32),  # sbuf
            pltpu.SemaphoreType.REGULAR((n_streams,)),  # scale-AG credits
        ],
        compiler_params=_dma.compiler_params(collective_id),
        interpret=_interp(interpret),
    )(view)
    out = buf.reshape(n * n_streams, m)[:, :k]
    return out.reshape(-1)[: flat.size].reshape(shape)


# ---------------------------------------------------------------------------
# The bidir allreduce: paired counter-rotating ring KERNELS (FlexLink move)
#
# ring_all_reduce(bidirectional=True) drives both ICI directions from inside
# ONE kernel — its two streams share the kernel's entry barrier, phase
# barrier and fori_loop, so the slower direction gates the faster every
# step. bidir_all_reduce generalizes pallas_a2a's fwd/bwd stream pairing to
# rings at LAUNCH granularity instead: two unidirectional ring kernels on
# paired collective ids (dma.CID_RING_BIDIR / +1 — Mosaic's entry-barrier
# semaphore is keyed by id, so distinct ids are what lets both kernels be
# airborne at once), each carrying half the payload, with no data
# dependency between them — XLA issues both and each ring runs at its own
# pace over its own ICI direction (FlexLink's ~2x link utilization,
# PAPERS.md). It composes with wire_dtype like any ring, and its budget
# fallback is the bit-identical lax mirror of the same directed schedules —
# counted on ep_wire_fallback_total AND collective_plan_total
# (outcome="fallback"), never silent.


def _directed_ar_mirror(hx, axis, n, d, wire_dtype):
    """The pure-lax mirror of ONE directed allreduce ring on a flat payload
    ``hx``: the plan lowering (full precision) or the quantized stream
    mirror — exactly what the directed kernel computes, bit for bit."""
    if wire_dtype is None:
        from uccl_tpu.collective import plan

        return plan.ring_all_reduce(hx, axis, bidirectional=False,
                                    direction=d)
    chunks, k, m = _pad_chunks(hx.reshape(-1), n)  # [n, rows, 128]
    buf = _mirror_quant_ar_stream(chunks, axis, n, d, wire_dtype, hx.dtype)
    return buf.reshape(n, m)[:, :k].reshape(-1)[: hx.size]


def bidir_pair_charge(nelems: int, itemsize: int, n: int, wire_dtype,
                      interpret) -> int:
    """VMEM charge of the bidir kernel pair on a flat ``nelems`` payload
    over a world of ``n`` — THE arithmetic :func:`bidir_all_reduce`'s
    budget gate charges AND the planner's quiet eligibility probe
    (``CollectivePlanner._bidir_budget_ok``) checks, shared so auto can
    never plan a pair the gate would immediately downgrade."""
    half = nelems // 2
    halves = (half, nelems - half)

    def _charge(ne: int) -> int:
        m = _dma.padded_chunk_elems(-(-ne // n))
        if wire_dtype is None:
            return ne * itemsize
        hb = _hop_wire_bytes(m, itemsize, wire_dtype)
        # accumulator + wire-dtype AG buffers + send/2-slot staging scratch
        return ne * itemsize + n * hb + 3 * hb

    charges = [_charge(h) for h in halves]
    # Both kernels are airborne CONCURRENTLY by design, so the VMEM charge
    # is their sum; under the interpreter kernels run sequentially and the
    # ceiling is per-buffer deadlock avoidance — charge the larger half.
    return max(charges) if interpret else sum(charges)


def bidir_all_reduce(x: jax.Array, axis, *, interpret=None,
                     collective_id=None, wire_dtype=None) -> jax.Array:
    """Per-shard allreduce (sum) over TWO counter-rotating ring kernels on
    paired collective ids: the payload is split in half, the first half
    rings forward (+1), the second backward (-1), both kernels airborne
    concurrently (docstring above). ``wire_dtype`` quantizes each ring's
    wire exactly like :func:`ring_all_reduce`'s (scale sidecars ride
    ``collective_id + CID_SCALE_OFFSET`` per ring)."""
    n = lax.axis_size(axis)
    if n == 1:
        return x
    interpret = _resolve_interpret(interpret)
    wire_dtype = _ring_wire_dtype(x, wire_dtype, "all_reduce_bidir")
    if collective_id is None:
        collective_id = _dma.CID_RING_BIDIR
    shape = x.shape
    flat = x.reshape(-1)
    half = flat.size // 2
    if half == 0:  # nothing to split: one directed ring carries it
        return ring_all_reduce(x, axis, bidirectional=False,
                               interpret=interpret,
                               collective_id=collective_id,
                               wire_dtype=wire_dtype)
    halves = (flat[:half], flat[half:])
    itemsize = x.dtype.itemsize
    pair_charge = bidir_pair_charge(flat.size, itemsize, n, wire_dtype,
                                    interpret)
    if not _check_budget(pair_charge, "all_reduce_bidir", interpret):
        # Counted pair-level downgrade: BOTH rings ride their bit-identical
        # lax mirrors as a unit (a half-kernel half-mirror split would tie
        # the surviving kernel to the mirror's XLA schedule — the concurrency
        # the pairing exists for would be gone, silently).
        from uccl_tpu.collective import plan

        plan.PLAN_TOTAL.inc(algo="bidir", chunks=2,
                            wire_dtype=wire_dtype or "none",
                            outcome="fallback")
        wire_total = sum(
            2 * (n - 1) * _hop_wire_bytes(
                _dma.padded_chunk_elems(-(-h.size // n)), itemsize,
                wire_dtype)
            for h in halves
        )
        _count_wire_bytes("ring_all_reduce_bidir", "lax", wire_dtype,
                          wire_total)
        outs = [
            _directed_ar_mirror(h, axis, n, d, wire_dtype)
            for h, d in zip(halves, (1, -1))
        ]
        return jnp.concatenate(outs).reshape(shape)
    # The pair gate passing implies each half passes its own kernel gate
    # (half charge <= pair charge <= limit), so neither inner call can
    # secretly downgrade — the pair flies as a pair or falls as a pair.
    fwd = ring_all_reduce(halves[0], axis, bidirectional=False, direction=1,
                          interpret=interpret, collective_id=collective_id,
                          wire_dtype=wire_dtype)
    bwd = ring_all_reduce(halves[1], axis, bidirectional=False,
                          direction=-1, interpret=interpret,
                          collective_id=collective_id + 1,
                          wire_dtype=wire_dtype)
    return jnp.concatenate([fwd, bwd]).reshape(shape)


# ---------------------------------------------------------------------------
# Broadcast / all-gather as first-class planned verbs (ISSUE 14).
#
# The other half of the collective layer: serving fleets replicate one
# buffer to N peers constantly (replica spin-up, warm spares, RL weight
# refresh), and the bandwidth-optimal form is the scatter-allgather
# decomposition (Network-Offloaded Bandwidth-Optimal Broadcast and
# Allgather, PAPERS.md): the root scatters S/N chunks — (N-1)/N of the
# payload leaves the root exactly ONCE — and a counter-rotating all-gather
# pair completes every member's copy, vs the legacy masked full-payload
# psum that ships the whole buffer through a reduction plus world-1 adds of
# zeros. Everything below reuses the ring substrate verbatim: write-once AG
# slots, credit rotation, wire_dtype quantize-once-forward-verbatim, paired
# collective ids, counted budget fallbacks onto bit-identical lax mirrors.


def rs_charge(nelems: int, itemsize: int, n: int, wire_dtype,
              interpret) -> int:
    """VMEM charge of ONE reduce-scatter ring kernel on a flat ``nelems``
    payload: the full-precision accumulator, plus — when the wire is
    quantized — the send + 2-slot staging wire scratches. EXACTLY what
    ring_reduce_scatter's own gate charges, shared with the planner's
    quiet probe (``CollectivePlanner._rs_budget_ok``)."""
    del interpret  # per-kernel charge; the limit differs, not the charge
    if wire_dtype is None:
        return nelems * itemsize
    m = _dma.padded_chunk_elems(-(-nelems // n))
    return nelems * itemsize + 3 * _hop_wire_bytes(m, itemsize, wire_dtype)


def ag_charge(nelems: int, itemsize: int, n: int, wire_dtype,
              interpret) -> int:
    """VMEM charge of ONE all-gather ring kernel on a flat ``nelems``
    payload: the gathered buffer (full precision) or the gathered wire
    payload + scale sidecar (quantized) — EXACTLY what ring_all_gather's
    own gate charges, shared with the planner's quiet probe."""
    del interpret  # per-kernel charge; the limit differs, not the charge
    if wire_dtype is None:
        return n * nelems * itemsize
    m = _dma.padded_chunk_elems(nelems)
    return n * _hop_wire_bytes(m, itemsize, wire_dtype)


def ag_pair_charge(nelems: int, itemsize: int, n: int, wire_dtype,
                   interpret) -> int:
    """Charge of the counter-rotating all-gather PAIR (bidir_all_gather):
    both kernels airborne concurrently → sum of the halves; under the
    interpreter kernels run sequentially and the ceiling is per-buffer —
    charge the larger half (the bidir_pair_charge convention)."""
    half = nelems // 2
    halves = (half, nelems - half) if half else (nelems,)
    charges = [ag_charge(h, itemsize, n, wire_dtype, interpret)
               for h in halves]
    return max(charges) if interpret else sum(charges)


def bcast_pair_charge(nelems: int, itemsize: int, n: int, wire_dtype,
                      interpret) -> int:
    """Charge of the scatter-allgather broadcast on a flat ``nelems``
    payload: the AG pair over ONE padded S/n chunk (the scatter leg is
    lax ppermutes — no kernel residency)."""
    m = _dma.padded_chunk_elems(-(-nelems // n))
    return ag_pair_charge(m, itemsize, n, wire_dtype, interpret)


def _ag_lax_mirror(x, axis, n, wire_dtype):
    """The pure-lax mirror of one all-gather ring on per-shard ``x``
    [k, ...]: the plan lowering of the same write-once schedule. With a
    wire dtype: quantize ONCE (same padded chunk view as the kernel),
    gather payload + scales verbatim, dequantize — bit-identical to the
    kernel, because forwarding moves bytes verbatim and every member
    dequantizes the same bytes. Without one, pure data movement — exact
    by construction (and direction-independent, so one mirror covers
    both rings of a pair)."""
    from uccl_tpu.collective import plan

    if wire_dtype is None:
        return plan.ring_all_gather(x, axis)
    k = x.shape[0]
    flat = x.reshape(-1)
    chunk, _, m = _pad_chunks(flat, 1)  # [1, rows, 128] — the kernel's view
    q, sc = _quantize_rows(chunk, wire_dtype)
    qg = plan.ring_all_gather(q, axis)  # [n, rows, 128]
    sg = plan.ring_all_gather(sc, axis)  # [n, rows, 1]
    out = _dequantize_rows(qg, sg, x.dtype)
    out = out.reshape(n, m)[:, : flat.size]
    return out.reshape((n * k,) + x.shape[1:])


def _ag_pair_lax_mirror(flat, axis, n, wire_dtype):
    """The pure-lax mirror of the counter-rotating AG PAIR on a flat
    payload: the same half split, per-half :func:`_ag_lax_mirror`, and
    block-wise reassembly to ``[n, flat.size]`` — THE one fallback the
    bidir all-gather and the scatter-allgather broadcast both ride, so
    the two cannot drift."""
    half = flat.size // 2
    outs = [_ag_lax_mirror(flat[:half], axis, n, wire_dtype),
            _ag_lax_mirror(flat[half:], axis, n, wire_dtype)]
    return jnp.concatenate(
        [outs[0].reshape(n, half), outs[1].reshape(n, flat.size - half)],
        axis=1,
    )


def bidir_all_gather(x: jax.Array, axis, *, interpret=None,
                     collective_id=None, wire_dtype=None,
                     count: bool = True) -> jax.Array:
    """Per-shard ``[k, ...] -> [n*k, ...]`` all-gather over TWO
    counter-rotating ring kernels on paired collective ids (the FlexLink
    pairing of :func:`bidir_all_reduce`, applied to the write-once AG
    schedule): the flat payload is split in half, the first half rings
    forward, the second backward, each kernel carrying half the serial
    volume concurrently. ``wire_dtype`` quantizes each half once at the
    source and forwards wire bytes verbatim (one round trip of error,
    members identical). The budget fallback rides the bit-identical lax
    mirror as a pair — counted on ``ep_wire_fallback_total`` AND
    ``collective_plan_total{verb="all_gather", outcome="fallback"}``."""
    n = lax.axis_size(axis)
    if n == 1:
        return x
    interpret = _resolve_interpret(interpret)
    wire_dtype = _ring_wire_dtype(x, wire_dtype, "all_gather_bidir")
    if collective_id is None:
        collective_id = _dma.CID_AG_BIDIR
    k = x.shape[0]
    shape = x.shape
    flat = x.reshape(-1)
    half = flat.size // 2
    if half == 0:  # nothing to split: one directed ring carries it
        return ring_all_gather(x, axis, interpret=interpret,
                               collective_id=collective_id,
                               wire_dtype=wire_dtype, count=count)
    halves = (flat[:half], flat[half:])
    itemsize = x.dtype.itemsize
    pair_charge = ag_pair_charge(flat.size, itemsize, n, wire_dtype,
                                 interpret)
    if not _check_budget(pair_charge, "all_gather_bidir", interpret):
        from uccl_tpu.collective import plan

        plan.PLAN_TOTAL.inc(algo="bidir", chunks=2,
                            wire_dtype=wire_dtype or "none",
                            outcome="fallback", verb="all_gather")
        if count:
            wire_total = sum(
                (n - 1) * _hop_wire_bytes(_dma.padded_chunk_elems(h.size),
                                          itemsize, wire_dtype)
                for h in halves
            )
            _count_wire_bytes("ring_all_gather", "lax", wire_dtype,
                              wire_total)
        out = _ag_pair_lax_mirror(flat, axis, n, wire_dtype)  # [n, S]
    else:
        # pair gate passing implies each half passes its own ring gate
        # (half charge <= pair charge <= limit): the pair flies as a pair
        outs = [
            ring_all_gather(halves[0], axis, direction=1,
                            interpret=interpret,
                            collective_id=collective_id,
                            wire_dtype=wire_dtype, count=count),
            ring_all_gather(halves[1], axis, direction=-1,
                            interpret=interpret,
                            collective_id=collective_id + 1,
                            wire_dtype=wire_dtype, count=count),
        ]
        # outs[i]: [n * half_i] — member j's half at block j; reassemble
        # so block j is member j's FULL flat payload
        out = jnp.concatenate(
            [outs[0].reshape(n, half), outs[1].reshape(n, flat.size - half)],
            axis=1,
        )
    return out.reshape((n * k,) + shape[1:])


def _scatter_from_root(chunks, axis, n, root):
    """Per-shard rooted scatter on a ``[n, ...]`` chunk view: member r
    ends holding ROOT's chunk r (the root keeps its own). Direct
    (root → j) ppermutes — (n-1)/n of the payload leaves the root exactly
    once, and the selects are pure (no adds), so every received chunk is
    bit-identical to the root's bytes."""
    r = lax.axis_index(axis)
    my_chunk = lax.dynamic_index_in_dim(chunks, r, 0, keepdims=False)
    for j in range(n):
        if j == root:
            continue
        got = lax.ppermute(chunks[j], axis, [(root, j)])
        my_chunk = jnp.where(r == j, got, my_chunk)
    return my_chunk


def _bcast_wire_bytes(n: int, m: int, itemsize: int, wire_dtype) -> int:
    """Counter-audited per-member wire bytes of one scatter-allgather
    broadcast: the root's (n-1) scatter chunks amortized over the world
    (only the root sends that leg) + the AG pair's (n-1) hops per half.
    The scatter leg ships full precision (raw chunk ppermutes); the AG
    legs ship the wire dtype."""
    scatter = -(-(n - 1) * m * itemsize // n)
    h1 = m // 2
    ag = sum(
        (n - 1) * _hop_wire_bytes(_dma.padded_chunk_elems(h), itemsize,
                                  wire_dtype)
        for h in ((h1, m - h1) if h1 else (m,))
    )
    return scatter + ag


def scatter_ag_broadcast(x: jax.Array, axis, root: int = 0, *,
                         interpret=None, collective_id=None,
                         wire_dtype=None) -> jax.Array:
    """Per-shard rooted broadcast: every member returns the ROOT's ``x``,
    as the bandwidth-optimal scatter-allgather decomposition — the root
    scatters S/n chunks (direct ppermutes, (n-1)/n·S leaves the root
    once), then the counter-rotating pallas all-gather pair completes
    every member's copy (~(n-1)/n·S per member vs the masked psum's full
    reduction volume). Full precision is BIT-exact (pure data movement);
    ``wire_dtype`` quantizes the AG legs once per chunk — one round trip
    of error, every member identical. Budget fallback: the bit-identical
    lax mirror (same scatter, the pair's AG mirror), counted on
    ``ep_wire_fallback_total{what="broadcast"}`` AND
    ``collective_plan_total{verb="broadcast", outcome="fallback"}``."""
    n = lax.axis_size(axis)
    if n == 1:
        return x
    interpret = _resolve_interpret(interpret)
    wire_dtype = _ring_wire_dtype(x, wire_dtype, "broadcast")
    if collective_id is None:
        collective_id = _dma.CID_BCAST
    shape = x.shape
    flat = x.reshape(-1)
    chunks, kk, m = _pad_chunks(flat, n)  # [n, rows, 128]
    itemsize = x.dtype.itemsize
    wire_total = _bcast_wire_bytes(n, m, itemsize, wire_dtype)
    pair_charge = bcast_pair_charge(flat.size, itemsize, n, wire_dtype,
                                    interpret)
    kernel_ok = _check_budget(pair_charge, "broadcast", interpret)
    if not kernel_ok:
        from uccl_tpu.collective import plan

        plan.PLAN_TOTAL.inc(algo="scatter_ag", chunks=2,
                            wire_dtype=wire_dtype or "none",
                            outcome="fallback", verb="broadcast")
    # the WHOLE schedule's bytes (scatter leg + both AG legs) land once,
    # here, under verb="bcast" — the composed all-gather runs count=False
    # so no byte is ever tallied on two series, and kernel and fallback
    # report identically
    _count_wire_bytes("bcast", "pallas" if kernel_ok else "lax",
                      wire_dtype, wire_total)
    my_chunk = _scatter_from_root(chunks, axis, n, root)  # [rows, 128]
    if kernel_ok:
        gathered = bidir_all_gather(
            my_chunk, axis, interpret=interpret,
            collective_id=collective_id, wire_dtype=wire_dtype,
            count=False,
        )  # [n*rows, 128]
    else:
        gathered = _ag_pair_lax_mirror(my_chunk.reshape(-1), axis, n,
                                       wire_dtype)  # [n, m]
    out = gathered.reshape(n, m)[:, :kk]
    return out.reshape(-1)[: flat.size].reshape(shape)


def scatter_gather_broadcast_lax(x: jax.Array, axis,
                                 root: int = 0) -> jax.Array:
    """The planned ``xla`` broadcast lowering (per-shard): the same
    scatter-allgather schedule in pure lax — direct root→j chunk
    ppermutes + one plan.ring_all_gather — replacing the legacy
    psum-of-zeros (which shipped the full payload through a reduction
    plus world-1 adds of zeros). Bit-exact (pure data movement); wire
    bytes counted on ``ep_bytes_total{verb="bcast", wire="xla"}`` so the
    reduction vs the psum baseline is a counter delta, not model math."""
    n = lax.axis_size(axis)
    if n == 1:
        return x
    shape = x.shape
    flat = x.reshape(-1)
    chunks, kk, m = _pad_chunks(flat, n)
    itemsize = x.dtype.itemsize
    scatter = -(-(n - 1) * m * itemsize // n)
    _count_wire_bytes("bcast", "xla", None,
                      scatter + (n - 1) * m * itemsize)
    from uccl_tpu.collective import plan

    my_chunk = _scatter_from_root(chunks, axis, n, root)
    gathered = plan.ring_all_gather(my_chunk, axis)  # [n*rows, 128]
    out = gathered.reshape(n, m)[:, :kk]
    return out.reshape(-1)[: flat.size].reshape(shape)
