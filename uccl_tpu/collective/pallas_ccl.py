"""Device-level ring collectives: Pallas remote-DMA kernels on the ICI torus.

This is the layer the reference's value proposition lives in: UCCL beats the
vendor stack by owning the transport under an unchanged API — its engine hot
loop schedules chunks onto 32 UC QPs itself (collective/rdma/transport.cc:443,
chunk spraying :2186) and the next-gen ukernel executes chunk graphs with
persistent device workers (experimental/ukernel/src/ccl/executor.h:26-60).
The TPU analog of "owning the wire" is issuing the inter-chip DMAs from
inside a kernel instead of letting XLA schedule a collective: each hop is a
``pltpu.make_async_remote_copy`` between neighbor chips, double-buffered,
with credit-based flow control — no per-step XLA dispatch, payload resident
in VMEM, and both ICI ring directions drivable concurrently from one kernel
(the torus form of multipath spraying).

Three per-shard entry points (used inside ``shard_map`` like their
:mod:`uccl_tpu.collective.plan` counterparts, which remain the lax.ppermute
lowering of the same schedules):

* :func:`ring_all_gather`   — chunks circulate; direct buf→buf remote DMA.
* :func:`ring_reduce_scatter` — partials circulate via staging buffers.
* :func:`ring_all_reduce`   — RS phase + AG phase in ONE kernel launch,
  optionally bidirectional (payload halved over counter-rotating rings).

Synchronization design (the part that must be right):

* Neighbor barrier at kernel entry (and between the RS and AG phases of the
  fused allreduce): a remote DMA may not target a neighbor's scratch before
  that neighbor's kernel is live (or, at the phase boundary, before its
  sends from the target slot have drained).
* Write-once slots (AG): each buf slot is written exactly once, so data can
  never be clobbered; semaphores count arrivals.
* Credit flow control: ring skew is bounded only by data dependencies — with
  every device but one making progress, the upstream neighbor can run up to
  n-1 steps ahead, overrunning a 2-deep buffer/semaphore rotation. Each
  consumer therefore grants its upstream neighbor an explicit credit
  (``semaphore_signal`` of the sender's ack semaphore) after consuming a
  slot; senders wait for a credit from step 2 on (two slots start free).
  Signals and waits are balanced so every semaphore drains to zero.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from uccl_tpu.collective import dma as _dma

# Shared substrate (uccl_tpu.collective.dma) — also used by the EP
# all-to-all kernels (uccl_tpu.ep.pallas_a2a). The underscored aliases keep
# this module's long-standing surface (tests reset _MAX_VMEM_BYTES, etc.).
_LANES = _dma.LANES
_CHUNK_QUANTUM = _dma.CHUNK_QUANTUM
_MAX_VMEM_BYTES = _dma.MAX_VMEM_BYTES
_MAX_INTERP_BYTES = _dma.MAX_INTERP_BYTES
_MESH = _dma.MESH
_pad_chunks = _dma.pad_chunks
_interpret_default = _dma.interpret_default
_resolve_interpret = _dma.resolve_interpret
_interp = _dma.interp
_neighbors = _dma.neighbors
_mesh_id = _dma.mesh_id
_barrier = _dma.ring_barrier


def _ag_phase(axis, n, dirs, buf_ref, send_sem, recv_sem, ack_sem,
              faithful=True):
    """All-gather rings on ``buf_ref[:, h]`` for each stream h (one ring per
    direction in ``dirs``, all DMAs of a step issued before any wait): n-1
    steps of direct buf→buf remote DMA — chunk j lives at slot j on every
    member, so the destination slot equals the source slot and every slot is
    write-once. ``faithful`` is static: the legacy discharge interpreter
    (jax 0.4.x) implements no remote semaphore signals, so the credit
    traffic is elided there — subsumed by its per-DMA global ordering."""
    nbrs = [_neighbors(axis, n, d) for d in dirs]

    def step(s, _):
        descs = []
        for h, d in enumerate(dirs):
            r, right, _left = nbrs[h]
            send_slot = lax.rem(r - d * s + s * n + n, n)

            if faithful:

                @pl.when(s >= 2)
                def _(h=h):  # credit from downstream: slot s%2 consumed
                    pltpu.semaphore_wait(ack_sem.at[h], 1)

            sl = lax.rem(s, 2)
            rdma = pltpu.make_async_remote_copy(
                src_ref=buf_ref.at[send_slot, h],
                dst_ref=buf_ref.at[send_slot, h],
                send_sem=send_sem.at[h, sl],
                recv_sem=recv_sem.at[h, sl],
                **_dma.remote_kwargs(axis, right, faithful),
            )
            rdma.start()
            descs.append(rdma)
        for h, d in enumerate(dirs):
            _r, _right, left = nbrs[h]
            descs[h].wait_recv()  # slot (r - d(s+1)) arrived

            if faithful:

                @pl.when(s <= n - 4)
                def _(h=h, left=left):  # grant upstream its step-(s+2) send
                    pltpu.semaphore_signal(
                        ack_sem.at[h], inc=1,
                        **_dma.remote_kwargs(axis, left, faithful),
                    )

        for rdma in descs:
            rdma.wait_send()
        return 0

    lax.fori_loop(0, n - 1, step, 0)


def _rs_phase(axis, n, dirs, buf_ref, stage_ref, send_sem, recv_sem,
              ack_sem, faithful=True):
    """Reduce-scatter rings on ``buf_ref[:, h]`` per stream: partial sums
    circulate through 2-slot staging; member r ends holding slot r fully
    reduced. Slot arithmetic matches plan.plan_reduce_scatter
    (send_off=-(s+1), recv_off=-(s+2)). ``faithful``: see :func:`_ag_phase`."""
    nbrs = [_neighbors(axis, n, d) for d in dirs]

    def step(s, _):
        descs = []
        for h, d in enumerate(dirs):
            r, right, _left = nbrs[h]
            send_slot = lax.rem(r - d * (s + 1) + (s + 1) * n + n, n)

            if faithful:

                @pl.when(s >= 2)
                def _(h=h):  # credit: downstream consumed staging slot s%2
                    pltpu.semaphore_wait(ack_sem.at[h], 1)

            sl = lax.rem(s, 2)
            rdma = pltpu.make_async_remote_copy(
                src_ref=buf_ref.at[send_slot, h],
                dst_ref=stage_ref.at[h, sl],
                send_sem=send_sem.at[h, sl],
                recv_sem=recv_sem.at[h, sl],
                **_dma.remote_kwargs(axis, right, faithful),
            )
            rdma.start()
            descs.append(rdma)
        sl = lax.rem(s, 2)
        for h, d in enumerate(dirs):
            r, _right, left = nbrs[h]
            recv_slot = lax.rem(r - d * (s + 2) + (s + 2) * n + n, n)
            descs[h].wait_recv()
            # fold the arrived partial into the slot sent next step
            buf_ref[recv_slot, h] = (
                buf_ref[recv_slot, h] + stage_ref[h, sl]
            )

            if faithful:

                @pl.when(s <= n - 4)
                def _(h=h, left=left):  # staging consumed — grant step s+2
                    pltpu.semaphore_signal(
                        ack_sem.at[h], inc=1,
                        **_dma.remote_kwargs(axis, left, faithful),
                    )

        for rdma in descs:
            rdma.wait_send()
        return 0

    lax.fori_loop(0, n - 1, step, 0)


def _scratch(n_streams, rows, dtype, with_staging):
    shapes = [
        pltpu.SemaphoreType.DMA((n_streams, 2)),  # send
        pltpu.SemaphoreType.DMA((n_streams, 2)),  # recv
        pltpu.SemaphoreType.REGULAR((n_streams,)),  # ack credits
    ]
    if with_staging:
        shapes.insert(
            0, pltpu.VMEM((n_streams, 2, rows, _LANES), dtype)
        )
    return shapes


_check_budget = _dma.check_budget


def ring_all_gather(x: jax.Array, axis, *, direction: int = 1,
                    interpret=None, collective_id: int = 0) -> jax.Array:
    """Per-shard ``[k, ...] -> [n*k, ...]`` ring all-gather as one Pallas
    kernel (n-1 neighbor DMA hops). Falls back to the plan lowering when the
    gathered buffer exceeds the VMEM budget."""
    n = lax.axis_size(axis)
    if n == 1:
        return x
    interpret = _resolve_interpret(interpret)
    if not _check_budget(n * x.size * x.dtype.itemsize, "all_gather",
                         interpret):
        from uccl_tpu.collective import plan

        return plan.ring_all_gather(x, axis)
    k = x.shape[0]
    flat = x.reshape(-1)
    chunk, _, m = _pad_chunks(flat, 1)  # [1, rows, 128]
    rows = m // _LANES
    faithful = _dma.faithful_sync(interpret)

    def kernel(x_ref, buf_ref, send_sem, recv_sem, ack_sem):
        r, right, left = _neighbors(axis, n, direction)
        if faithful:
            _barrier(axis, left, right)
        buf_ref[r, 0] = x_ref[0]
        _ag_phase(axis, n, (direction,), buf_ref, send_sem, recv_sem,
                  ack_sem, faithful)

    buf = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n, 1, rows, _LANES), x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=_scratch(1, rows, x.dtype, with_staging=False),
        compiler_params=_dma.compiler_params(collective_id),
        interpret=_interp(interpret),
    )(chunk)
    out = buf.reshape(n, m)[:, : flat.size]
    return out.reshape((n * k,) + x.shape[1:])


def ring_reduce_scatter(x: jax.Array, axis, *, direction: int = 1,
                        interpret=None, collective_id: int = 0) -> jax.Array:
    """Per-shard ``[n*k, ...] -> [k, ...]``: member r keeps reduced slot r
    (sum), matching plan.ring_reduce_scatter."""
    n = lax.axis_size(axis)
    if n == 1:
        return x
    # validate BEFORE the budget fallback: an over-budget indivisible
    # payload must raise, not silently misalign in the plan lowering
    if x.shape[0] % n:
        raise ValueError(f"leading dim {x.shape[0]} not divisible by {n}")
    interpret = _resolve_interpret(interpret)
    if not _check_budget(x.size * x.dtype.itemsize, "reduce_scatter",
                         interpret):
        from uccl_tpu.collective import plan

        return plan.ring_reduce_scatter(x, axis)
    k = x.shape[0] // n
    chunks, per, m = _pad_chunks(x.reshape(-1), n)  # [n, rows, 128]
    rows = m // _LANES
    chunks = chunks.reshape(n, 1, rows, _LANES)
    faithful = _dma.faithful_sync(interpret)

    def kernel(x_ref, out_ref, buf_ref, stage_ref, send_sem, recv_sem,
               ack_sem):
        r, right, left = _neighbors(axis, n, direction)
        if faithful:
            _barrier(axis, left, right)
        buf_ref[...] = x_ref[...]
        _rs_phase(axis, n, (direction,), buf_ref, stage_ref, send_sem,
                  recv_sem, ack_sem, faithful)
        out_ref[...] = buf_ref[r, 0]

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((n, 1, rows, _LANES), x.dtype)]
        + _scratch(1, rows, x.dtype, with_staging=True),
        compiler_params=_dma.compiler_params(collective_id),
        interpret=_interp(interpret),
    )(chunks)
    return out.reshape(-1)[:per].reshape((k,) + x.shape[1:])


def ring_all_reduce(x: jax.Array, axis, *, bidirectional: bool = True,
                    interpret=None, collective_id: int = 0) -> jax.Array:
    """Per-shard allreduce (sum) as ONE kernel: reduce-scatter phase, phase
    barrier, all-gather phase. With ``bidirectional=True`` the payload is
    split over two counter-rotating rings whose DMAs are issued back to back
    each step — both ICI directions of the axis carry traffic concurrently
    (the torus form of UCCL's multipath spraying, transport.cc:2186), from
    inside a single kernel rather than two serialized collectives."""
    n = lax.axis_size(axis)
    if n == 1:
        return x
    interpret = _resolve_interpret(interpret)
    if not _check_budget(x.size * x.dtype.itemsize, "all_reduce", interpret):
        from uccl_tpu.collective import plan

        return plan.ring_all_reduce(x, axis, bidirectional=bidirectional)
    n_streams = 2 if bidirectional else 1
    dirs = (1, -1)[:n_streams]
    shape = x.shape
    flat = x.reshape(-1)
    # [n*S, rows, 128], slot-major then stream
    view, k, m = _pad_chunks(flat, n * n_streams)
    rows = m // _LANES
    view = view.reshape(n, n_streams, rows, _LANES)
    faithful = _dma.faithful_sync(interpret)

    def kernel(x_ref, buf_ref, stage_ref, send_sem, recv_sem, ack_sem):
        r = lax.axis_index(axis)
        right = lax.rem(r + 1, n)
        left = lax.rem(r - 1 + n, n)
        if faithful:
            _barrier(axis, left, right)
        buf_ref[...] = x_ref[...]
        _rs_phase(axis, n, dirs, buf_ref, stage_ref, send_sem, recv_sem,
                  ack_sem, faithful)
        # Phase barrier: my AG write into a neighbor's buf slot must land
        # after that neighbor's RS sends from it have drained (its RS loop
        # waits every send_sem, so "RS done" implies the reads completed).
        if faithful:
            _barrier(axis, left, right)
        _ag_phase(axis, n, dirs, buf_ref, send_sem, recv_sem, ack_sem,
                  faithful)

    buf = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n, n_streams, rows, _LANES), x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=_scratch(n_streams, rows, x.dtype, with_staging=True),
        compiler_params=_dma.compiler_params(collective_id),
        interpret=_interp(interpret),
    )(view)
    out = buf.reshape(n * n_streams, m)[:, :k]
    return out.reshape(-1)[: flat.size].reshape(shape)
