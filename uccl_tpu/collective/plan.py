"""Chunk-graph collective planner: plan → lower → execute.

The TPU-native re-design of the reference's next-gen ukernel CCL stack
(experimental/ukernel: ``build_coll_algo`` emits a Chunk DAG —
src/ccl/algo/chunk_graph.h:12-31 — ``lower_algo``/``build_tiled`` tiles it,
and an Executor sprays ops over backends per BFS layer, src/ccl/executor.h:26)
and of UCCL-Tran's multipath packet spraying (chunks sprayed over 32 QP paths,
collective/rdma/transport.cc:2186). On a TPU torus the "paths" are the two ICI
directions of each ring axis, so spraying becomes: split the buffer into chunk
streams and run counter-rotating rings concurrently, each step a
``lax.ppermute`` hop overlapped with the local combine — XLA schedules the hop
asynchronously, which is the overlap the reference gets from engine threads.

Layers:
* :class:`RingPlan` — the plan: phases of ring steps with slot index formulas
  (pure data; inspectable, testable without a mesh).
* :func:`lower` — turns a plan into a per-shard step function for ``lax.scan``.
* :func:`execute` — runs a plan inside shard_map code.
* Builders: :func:`plan_all_reduce` (reduce-scatter + all-gather ring,
  optionally bidirectional), :func:`plan_all_gather`, :func:`plan_reduce_scatter`.
"""

from __future__ import annotations

import dataclasses
from typing import List, Literal, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from uccl_tpu.utils import config as _config
from uccl_tpu.utils.topology import ppermute_pairs

Axis = Union[str, Tuple[str, ...]]


@dataclasses.dataclass(frozen=True)
class RingStep:
    """One hop of a ring schedule, in rank-relative slot arithmetic.

    Member ``r`` sends chunk slot ``(r + dir*send_off) % n`` to its
    ``dir``-neighbor; the chunk received lands in slot
    ``(r + dir*recv_off) % n``. ``combine`` says whether the received chunk
    reduces into the local slot (reduce-scatter phase) or overwrites it
    (all-gather phase). Builders bake the step index into the offsets, so a
    plan is a flat list of constant-offset hops — the chunk DAG in its
    SPMD-normal form.
    """

    dir: int  # +1 = forward ring, -1 = reverse ring
    send_off: int
    recv_off: int
    combine: bool


@dataclasses.dataclass(frozen=True)
class RingPlan:
    """A full collective schedule over one ring of ``world`` members."""

    world: int
    n_slots: int  # chunks the buffer is split into
    steps: Tuple[RingStep, ...]
    name: str = "ring"

    @property
    def n_steps(self) -> int:
        return len(self.steps)

    def validate(self) -> None:
        for st in self.steps:
            if st.dir not in (-1, 1):
                raise ValueError(f"bad direction {st.dir}")


def plan_reduce_scatter(world: int, direction: int = 1) -> RingPlan:
    """Ring reduce-scatter: n-1 steps. Step s: member r sends slot
    (r - dir*(s+1)) and reduces the received chunk into slot (r - dir*(s+2));
    chunk j accumulates along the ring and lands fully-reduced at member j."""
    steps = tuple(
        RingStep(direction, send_off=-(s + 1), recv_off=-(s + 2), combine=True)
        for s in range(world - 1)
    )
    return RingPlan(world, world, steps, "reduce_scatter")


def plan_all_gather(world: int, direction: int = 1) -> RingPlan:
    """Ring all-gather: n-1 steps circulating owned slots; member r owns slot
    r at entry (which is exactly where reduce-scatter leaves things)."""
    steps = tuple(
        RingStep(direction, send_off=-s, recv_off=-(s + 1), combine=False)
        for s in range(world - 1)
    )
    return RingPlan(world, world, steps, "all_gather")


def plan_all_reduce(world: int, direction: int = 1) -> RingPlan:
    """Ring allreduce = reduce-scatter phase then all-gather phase."""
    rs = plan_reduce_scatter(world, direction).steps
    ag = plan_all_gather(world, direction).steps
    return RingPlan(world, world, rs + ag, "all_reduce")


def _hop(buf, axis, n: int, dir: int, send_off: int, recv_off: int,
         combine: bool):
    """The core ring-hop primitive: one rank-relative send/recv on ``buf``
    whose dim 0 indexes the axis's chunk slots. Shared by the RingPlan
    lowering and the chunk-graph executor so the slot arithmetic lives in
    exactly one place."""
    r = lax.axis_index(axis)
    send_slot = (r + dir * send_off) % n
    recv_slot = (r + dir * recv_off) % n
    chunk = lax.dynamic_index_in_dim(buf, send_slot, axis=0, keepdims=False)
    got = lax.ppermute(chunk, axis, ppermute_pairs(n, dir))
    cur = lax.dynamic_index_in_dim(buf, recv_slot, axis=0, keepdims=False)
    new = cur + got if combine else got
    return lax.dynamic_update_index_in_dim(buf, new, recv_slot, axis=0)


def lower(plan: RingPlan, axis: Axis):
    """Lower a plan to a per-shard step function.

    Returns ``step_fn(buf, s) -> buf`` where ``buf`` is ``[n_slots, ...]`` and
    ``s`` is the (python int) step index; unrolled so slot indices lower to
    constants per member.
    """
    plan.validate()
    n = plan.world

    def step_fn(buf, s):
        st = plan.steps[s]
        return _hop(buf, axis, n, st.dir, st.send_off, st.recv_off, st.combine)

    return step_fn


def execute(plan: RingPlan, x: jax.Array, axis: Axis) -> jax.Array:
    """Run a plan on per-shard data ``x`` (any shape; flattened into slots).

    For ``all_reduce`` the result is the full reduction, reshaped like ``x``.
    Pads to a multiple of n_slots internally.
    """
    shape = x.shape
    flat = x.reshape(-1)
    n = plan.n_slots
    pad = (-flat.size) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    buf = flat.reshape(n, -1)
    step_fn = lower(plan, axis)
    for s in range(plan.n_steps):  # unrolled: slot indices become constants
        buf = step_fn(buf, s)
    out = buf.reshape(-1)
    if pad:
        out = out[:-pad]
    return out.reshape(shape)


def ring_all_reduce(
    x: jax.Array, axis: Axis, *, bidirectional: bool = True
) -> jax.Array:
    """Bandwidth-optimal ring allreduce as an explicit chunk schedule.

    With ``bidirectional=True`` the buffer is split in half and two
    counter-rotating rings run concurrently — both ICI directions of the axis
    carry traffic every step (the torus analog of UCCL's multipath spraying).
    """
    n = lax.axis_size(axis)
    if n == 1:
        return x
    if not bidirectional:
        return execute(plan_all_reduce(n), x, axis)
    flat = x.reshape(-1)
    half = flat.size // 2
    fwd = execute(plan_all_reduce(n), flat[:half], axis)
    rev_plan = RingPlan(
        n,
        n,
        tuple(dataclasses.replace(s, dir=-s.dir) for s in plan_all_reduce(n).steps),
        "all_reduce_rev",
    )
    bwd = execute(rev_plan, flat[half:], axis)
    return jnp.concatenate([fwd, bwd]).reshape(x.shape)


# ---------------------------------------------------------------------------
# Chunk DAG (the general layer): ops with dependencies, executed by BFS layer
#
# The reference's ukernel emits a Chunk DAG with deps, tiles it, and executes
# per BFS layer over async backends (chunk_graph.h:12-31, lower.h:13-41,
# executor.h:26-60). The TPU-normal form: every op is a ring-style hop on ONE
# mesh axis acting on ONE chunk stream; ops in the same BFS layer are
# independent, so their ppermutes are all issued before any result is
# consumed and XLA's async scheduler overlaps them — multi-ring and
# multi-axis (torus) schedules fall out of the dep structure.


@dataclasses.dataclass(frozen=True)
class ChunkOp:
    """One DAG node: a ring hop on ``axes[axis_idx]`` over chunk stream
    ``stream``. Slot arithmetic is rank-relative exactly like RingStep.

    ``shard_axis``: when set, the op first restricts the slot view to this
    member's OWN slot group along that axis (dynamic index by its coordinate)
    and rings only that group — the hierarchical-bandwidth move (e.g. the 2D
    torus middle phase rings 1/a of the buffer, not all of it)."""

    id: int
    deps: Tuple[int, ...]
    axis_idx: int
    dir: int
    send_off: int
    recv_off: int
    combine: bool
    stream: int = 0
    shard_axis: int | None = None


@dataclasses.dataclass(frozen=True)
class ChunkGraph:
    """A collective as a dependency DAG of chunk ops over mesh axes.

    ``worlds[i]`` is the ring size of ``axes[i]`` (validated against the mesh
    at execution). ``n_streams`` buffer partitions let independent schedules
    (e.g. counter-rotating rings) run concurrently.
    """

    axes: Tuple[str, ...]
    worlds: Tuple[int, ...]
    n_streams: int
    ops: Tuple[ChunkOp, ...]
    name: str = "graph"

    def validate(self) -> None:
        ids = {op.id for op in self.ops}
        if len(ids) != len(self.ops):
            raise ValueError("duplicate op ids")
        for op in self.ops:
            if not 0 <= op.axis_idx < len(self.axes):
                raise ValueError(f"op {op.id}: bad axis index {op.axis_idx}")
            if op.dir not in (-1, 1):
                raise ValueError(f"op {op.id}: bad direction {op.dir}")
            if not 0 <= op.stream < self.n_streams:
                raise ValueError(f"op {op.id}: bad stream {op.stream}")
            if op.shard_axis is not None:
                if not 0 <= op.shard_axis < len(self.axes):
                    raise ValueError(f"op {op.id}: bad shard axis")
                if op.shard_axis == op.axis_idx:
                    raise ValueError(f"op {op.id}: shard axis == ring axis")
            for d in op.deps:
                if d not in ids:
                    raise ValueError(f"op {op.id}: unknown dep {d}")

    def layers(self) -> List[List[ChunkOp]]:
        """Topological BFS layers: ops whose deps are all satisfied by
        earlier layers. Raises on cycles."""
        remaining = {op.id: op for op in self.ops}
        done: set = set()
        out: List[List[ChunkOp]] = []
        while remaining:
            layer = [
                op for op in remaining.values()
                if all(d in done for d in op.deps)
            ]
            if not layer:
                raise ValueError(f"cycle in chunk graph {self.name}")
            layer.sort(key=lambda op: op.id)
            out.append(layer)
            for op in layer:
                done.add(op.id)
                del remaining[op.id]
        return out


def graph_from_ring(plan: RingPlan, axis: str) -> ChunkGraph:
    """Lift a linear RingPlan into DAG form (each step depends on the last)."""
    ops = tuple(
        ChunkOp(
            id=i,
            deps=(i - 1,) if i else (),
            axis_idx=0,
            dir=st.dir,
            send_off=st.send_off,
            recv_off=st.recv_off,
            combine=st.combine,
        )
        for i, st in enumerate(plan.steps)
    )
    return ChunkGraph((axis,), (plan.world,), 1, ops, plan.name)


def graph_bidirectional_all_reduce(world: int, axis: str) -> ChunkGraph:
    """Two counter-rotating rings on independent streams: every BFS layer
    carries one hop in EACH ICI direction of the axis (the torus analog of
    UCCL's multipath spraying, transport.cc:2186)."""
    fwd = plan_all_reduce(world, 1).steps
    ops: List[ChunkOp] = []
    for i, st in enumerate(fwd):
        ops.append(ChunkOp(2 * i, (2 * (i - 1),) if i else (), 0, st.dir,
                           st.send_off, st.recv_off, st.combine, stream=0))
        ops.append(ChunkOp(2 * i + 1, (2 * (i - 1) + 1,) if i else (), 0,
                           -st.dir, st.send_off, st.recv_off, st.combine,
                           stream=1))
    return ChunkGraph((axis,), (world,), 2, tuple(ops), "all_reduce_bidir")


def graph_torus_all_reduce(
    worlds: Tuple[int, int], axes: Tuple[str, str]
) -> ChunkGraph:
    """2D-torus (axis-pair) allreduce: reduce-scatter along axis 0, allreduce
    the scattered shard along axis 1, all-gather back along axis 0 — each
    phase a ring on its own axis, chained by deps. Bandwidth per member:
    2(a-1)/a + 2(b-1)/(a·b) of the buffer vs 2(ab-1)/(ab) for one flat ring,
    but with hops only between torus NEIGHBORS on both axes (a flat ring over
    a 2D slice must snake, paying non-neighbor links)."""
    a, b = worlds
    ax0, ax1 = axes
    ops: List[ChunkOp] = []
    nid = 0
    last = None

    def add(axis_idx, st, shard_axis=None):
        nonlocal nid, last
        ops.append(ChunkOp(nid, (last,) if last is not None else (), axis_idx,
                           st.dir, st.send_off, st.recv_off, st.combine,
                           shard_axis=shard_axis))
        last = nid
        nid += 1

    for st in plan_reduce_scatter(a).steps:
        add(0, st)
    # middle phase rings ONLY the axis-0 shard this member owns: 1/a of the
    # buffer per hop (the hierarchical bandwidth structure)
    for st in plan_all_reduce(b).steps:
        add(1, st, shard_axis=0)
    for st in plan_all_gather(a).steps:
        add(0, st)
    return ChunkGraph((ax0, ax1), (a, b), 1, tuple(ops), "all_reduce_torus2d")


def execute_graph(graph: ChunkGraph, x: jax.Array):
    """Run a chunk graph on per-shard data ``x`` inside shard_map code.

    The buffer is split into ``n_streams`` streams; each stream is chunked
    into slots. Ring ops index slots rank-relatively on their own axis.
    For the 2D torus the slot layout is hierarchical: axis-0 slots subdivide
    into axis-1 slots ([a, b, ...] view), which is what makes phase 2 operate
    on the axis-0 shard this member keeps.
    """
    graph.validate()
    worlds = tuple(lax.axis_size(ax) for ax in graph.axes)
    if worlds != graph.worlds:
        raise ValueError(f"mesh axis sizes {worlds} != plan worlds {graph.worlds}")

    shape = x.shape
    flat = x.reshape(-1)
    total_slots = 1
    for w in graph.worlds:
        total_slots *= w
    per_stream = graph.n_streams * total_slots
    pad = (-flat.size) % per_stream
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    streams = list(flat.reshape(graph.n_streams, total_slots, -1))

    def ring_hop(arr, dim, op: ChunkOp):
        """One rank-relative ring hop on `arr` whose `dim` indexes the slots
        of the op's mesh axis."""
        ax = graph.axes[op.axis_idx]
        n = graph.worlds[op.axis_idx]
        work = jnp.moveaxis(arr, dim, 0)
        work = _hop(work, ax, n, op.dir, op.send_off, op.recv_off, op.combine)
        return jnp.moveaxis(work, 0, dim)

    def apply_op(op: ChunkOp, buf):
        # hierarchical slot view: [w0, w1, ..., payload]
        view = buf.reshape(graph.worlds + (-1,))
        if op.shard_axis is None:
            view = ring_hop(view, op.axis_idx, op)
        else:
            rs = lax.axis_index(graph.axes[op.shard_axis])
            sub = lax.dynamic_index_in_dim(
                view, rs, axis=op.shard_axis, keepdims=False
            )
            dim = op.axis_idx - (1 if op.axis_idx > op.shard_axis else 0)
            sub = ring_hop(sub, dim, op)
            view = lax.dynamic_update_index_in_dim(
                view, sub, rs, axis=op.shard_axis
            )
        return view.reshape(total_slots, -1)

    for layer in graph.layers():
        # Dep-independent ops still conflict when they touch the SAME
        # stream's buffer (their slot updates would clobber), so within a
        # layer ops chain per stream; ops on different streams stay pure
        # dataflow-parallel and XLA issues their ppermutes concurrently.
        for op in layer:
            streams[op.stream] = apply_op(op, streams[op.stream])

    out = jnp.stack(streams).reshape(-1)
    if pad:
        out = out[:-pad]
    return out.reshape(shape)


def torus_all_reduce(x: jax.Array, axes: Tuple[str, str]) -> jax.Array:
    """Axis-pair allreduce over a 2D torus slice (per-shard fn)."""
    worlds = (lax.axis_size(axes[0]), lax.axis_size(axes[1]))
    if worlds[0] == 1:
        return ring_all_reduce(x, axes[1])
    if worlds[1] == 1:
        return ring_all_reduce(x, axes[0])
    return execute_graph(graph_torus_all_reduce(worlds, axes), x)


def tree_broadcast(x: jax.Array, axis: Axis, root: int = 0) -> jax.Array:
    """Binomial-tree broadcast over a mesh axis (per-shard fn): at round t,
    members with virtual rank < 2^t forward to virtual rank + 2^t via a
    partial ppermute; everyone else passes zeros and keeps its value. log2(n)
    rounds vs one big all-gather."""
    n = lax.axis_size(axis)
    if n == 1:
        return x
    r = lax.axis_index(axis)
    vr = (r - root) % n
    cur = jnp.where(vr == 0, x, jnp.zeros_like(x))
    mask = 1
    while mask < n:
        pairs = [
            (((v + root) % n), ((v + mask + root) % n))
            for v in range(mask)
            if v + mask < n
        ]
        got = lax.ppermute(cur, axis, pairs)
        receiving = (vr >= mask) & (vr < 2 * mask)
        cur = jnp.where(receiving, got, cur)
        mask <<= 1
    return cur


def ring_reduce_scatter(x: jax.Array, axis: Axis) -> jax.Array:
    """x: [n*k, ...] per-shard → [k, ...]: member r keeps reduced slot r."""
    n = lax.axis_size(axis)
    if n == 1:
        return x
    out = execute(plan_reduce_scatter(n), x, axis)
    r = lax.axis_index(axis)
    per = x.shape[0] // n
    return lax.dynamic_slice_in_dim(out, r * per, per, axis=0)


def ring_all_gather(x: jax.Array, axis: Axis) -> jax.Array:
    """x: [k, ...] per-shard → [n*k, ...] every member holds all slots."""
    n = lax.axis_size(axis)
    if n == 1:
        return x
    r = lax.axis_index(axis)
    k = x.shape[0]
    buf = jnp.zeros((n, k) + x.shape[1:], x.dtype)
    buf = lax.dynamic_update_index_in_dim(buf, x, r, axis=0)
    step_fn = lower(plan_all_gather(n), axis)
    for s in range(n - 1):
        buf = step_fn(buf, s)
    return buf.reshape((n * k,) + x.shape[1:])


# ---------------------------------------------------------------------------
# Recursive halving-doubling (latency-optimal) + the algorithm selector
#
# The reference's lite-collective ships an *algorithm selector over many
# execution plans* (experimental/lite/lite-collective/collective/: selector +
# allreduce kernel variants); NCCL itself switches ring<->tree by size. This
# is that role for the TPU build: halving-doubling gives 2*log2(W) hops
# (vs the ring's 2(W-1)) at the same per-member byte volume, so it wins when
# the alpha (per-hop latency) term dominates — small payloads, large worlds.


def hd_all_reduce(x: jax.Array, axis: Axis) -> jax.Array:
    """Recursive-halving reduce-scatter + recursive-doubling all-gather
    (per-shard fn). Power-of-two axis size; falls back to the ring plan
    otherwise. 2*log2(W) ppermute steps, bandwidth-optimal total volume.

    Rank-relative bookkeeping: reduce-scatter consumes rank bits MSB-first
    (distance W/2 .. 1); member r ends owning chunk slot r. All-gather
    mirrors LSB-first (distance 1 .. W/2), merging base = base & ~dist each
    step. Slice sizes are python ints (static); offsets are traced.
    """
    n = lax.axis_size(axis)
    if n == 1:
        return x
    if n & (n - 1):
        return ring_all_reduce(x, axis)
    shape = x.shape
    flat = x.reshape(-1)
    pad = (-flat.size) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    buf = flat.reshape(n, -1)
    r = lax.axis_index(axis)

    # Reduce-scatter: halve the live span every step, reduce into the kept half.
    base = jnp.zeros((), jnp.int32)
    span, dist = n, n // 2
    while dist >= 1:
        half = span // 2
        upper = (r & dist) != 0  # this member keeps the upper half?
        keep_start = base + jnp.where(upper, half, 0)
        send_start = base + jnp.where(upper, 0, half)
        chunk = lax.dynamic_slice_in_dim(buf, send_start, half, axis=0)
        got = lax.ppermute(chunk, axis, [(i, i ^ dist) for i in range(n)])
        kept = lax.dynamic_slice_in_dim(buf, keep_start, half, axis=0)
        buf = lax.dynamic_update_slice_in_dim(buf, kept + got, keep_start, 0)
        base, span, dist = keep_start, half, dist // 2

    # All-gather: double the owned span every step (base ends at 0, span n).
    span, dist = 1, 1
    while dist < n:
        chunk = lax.dynamic_slice_in_dim(buf, base, span, axis=0)
        got = lax.ppermute(chunk, axis, [(i, i ^ dist) for i in range(n)])
        buf = lax.dynamic_update_slice_in_dim(buf, got, base ^ dist, 0)
        base = base & ~dist
        span, dist = span * 2, dist * 2

    out = buf.reshape(-1)
    if pad:
        out = out[:-pad]
    return out.reshape(shape)


_AR_SMALL_BYTES = _config.param(
    "AR_HD_MAX_BYTES",
    1 << 18,
    int,
    "all_reduce auto-selector: payloads at or under this many bytes prefer "
    "the log-step halving-doubling plan over a ring (alpha-dominated range)",
)
_AR_FORCE_ALGO = _config.param(
    "AR_ALGO",
    "",
    str,
    "override the all_reduce auto-selector with a fixed algorithm "
    "(xla|ring|hd|torus|pallas)",
)


def select_all_reduce_algo(
    nbytes: int, world: int, n_axes: int = 1
) -> str:
    """Pick an allreduce algorithm from the plan library (the lite-collective
    selector role). Policy is the standard alpha-beta model, recalibratable
    via UCCL_TPU_AR_HD_MAX_BYTES / overridable via UCCL_TPU_AR_ALGO:

    * world 1 → "xla" (no comm; let the compiler elide it).
    * explicit override set → that.
    * small payloads (≤ AR_HD_MAX_BYTES), power-of-two world → "hd"
      (2 log W hops beat 2(W-1) when per-hop latency dominates).
    * large payloads over a 2D axis pair → "torus" (both ICI axis rings
      carry traffic, shard-restricted middle phase).
    * everything else → "xla": measured on this repo's substrates XLA's own
      schedule wins the bandwidth range on-mesh (docs/PLAN_BENCH.md — honest
      default; the explicit plans exist for the cross-pod/overlap cases and
      for recalibration on real multi-chip ICI).
    """
    forced = _AR_FORCE_ALGO.get()
    if forced:
        return forced
    if world <= 1:
        return "xla"
    if nbytes <= _AR_SMALL_BYTES.get() and world & (world - 1) == 0:
        return "hd"
    if n_axes == 2:
        return "torus"
    return "xla"
