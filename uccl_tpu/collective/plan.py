"""Chunk-graph collective planner: plan → lower → execute.

The TPU-native re-design of the reference's next-gen ukernel CCL stack
(experimental/ukernel: ``build_coll_algo`` emits a Chunk DAG —
src/ccl/algo/chunk_graph.h:12-31 — ``lower_algo``/``build_tiled`` tiles it,
and an Executor sprays ops over backends per BFS layer, src/ccl/executor.h:26)
and of UCCL-Tran's multipath packet spraying (chunks sprayed over 32 QP paths,
collective/rdma/transport.cc:2186). On a TPU torus the "paths" are the two ICI
directions of each ring axis, so spraying becomes: split the buffer into chunk
streams and run counter-rotating rings concurrently, each step a
``lax.ppermute`` hop overlapped with the local combine — XLA schedules the hop
asynchronously, which is the overlap the reference gets from engine threads.

Layers:
* :class:`RingPlan` — the plan: phases of ring steps with slot index formulas
  (pure data; inspectable, testable without a mesh).
* :func:`lower` — turns a plan into a per-shard step function for ``lax.scan``.
* :func:`execute` — runs a plan inside shard_map code.
* Builders: :func:`plan_all_reduce` (reduce-scatter + all-gather ring,
  optionally bidirectional), :func:`plan_all_gather`, :func:`plan_reduce_scatter`.
"""

from __future__ import annotations

import dataclasses
from typing import List, Literal, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from uccl_tpu.utils.topology import ppermute_pairs

Axis = Union[str, Tuple[str, ...]]


@dataclasses.dataclass(frozen=True)
class RingStep:
    """One hop of a ring schedule, in rank-relative slot arithmetic.

    Member ``r`` sends chunk slot ``(r + dir*send_off) % n`` to its
    ``dir``-neighbor; the chunk received lands in slot
    ``(r + dir*recv_off) % n``. ``combine`` says whether the received chunk
    reduces into the local slot (reduce-scatter phase) or overwrites it
    (all-gather phase). Builders bake the step index into the offsets, so a
    plan is a flat list of constant-offset hops — the chunk DAG in its
    SPMD-normal form.
    """

    dir: int  # +1 = forward ring, -1 = reverse ring
    send_off: int
    recv_off: int
    combine: bool


@dataclasses.dataclass(frozen=True)
class RingPlan:
    """A full collective schedule over one ring of ``world`` members."""

    world: int
    n_slots: int  # chunks the buffer is split into
    steps: Tuple[RingStep, ...]
    name: str = "ring"

    @property
    def n_steps(self) -> int:
        return len(self.steps)

    def validate(self) -> None:
        for st in self.steps:
            if st.dir not in (-1, 1):
                raise ValueError(f"bad direction {st.dir}")


def plan_reduce_scatter(world: int, direction: int = 1) -> RingPlan:
    """Ring reduce-scatter: n-1 steps. Step s: member r sends slot
    (r - dir*(s+1)) and reduces the received chunk into slot (r - dir*(s+2));
    chunk j accumulates along the ring and lands fully-reduced at member j."""
    steps = tuple(
        RingStep(direction, send_off=-(s + 1), recv_off=-(s + 2), combine=True)
        for s in range(world - 1)
    )
    return RingPlan(world, world, steps, "reduce_scatter")


def plan_all_gather(world: int, direction: int = 1) -> RingPlan:
    """Ring all-gather: n-1 steps circulating owned slots; member r owns slot
    r at entry (which is exactly where reduce-scatter leaves things)."""
    steps = tuple(
        RingStep(direction, send_off=-s, recv_off=-(s + 1), combine=False)
        for s in range(world - 1)
    )
    return RingPlan(world, world, steps, "all_gather")


def plan_all_reduce(world: int, direction: int = 1) -> RingPlan:
    """Ring allreduce = reduce-scatter phase then all-gather phase."""
    rs = plan_reduce_scatter(world, direction).steps
    ag = plan_all_gather(world, direction).steps
    return RingPlan(world, world, rs + ag, "all_reduce")


def lower(plan: RingPlan, axis: Axis):
    """Lower a plan to a per-shard step function.

    Returns ``step_fn(buf, s) -> buf`` where ``buf`` is ``[n_slots, ...]`` and
    ``s`` is the (python int) step index; unrolled so slot indices lower to
    constants per member.
    """
    plan.validate()
    n = plan.world

    def step_fn(buf, s):
        st = plan.steps[s]
        r = lax.axis_index(axis)
        send_slot = (r + st.dir * st.send_off) % n
        recv_slot = (r + st.dir * st.recv_off) % n
        chunk = lax.dynamic_index_in_dim(buf, send_slot, axis=0, keepdims=False)
        got = lax.ppermute(chunk, axis, ppermute_pairs(n, st.dir))
        cur = lax.dynamic_index_in_dim(buf, recv_slot, axis=0, keepdims=False)
        new = cur + got if st.combine else got
        return lax.dynamic_update_index_in_dim(buf, new, recv_slot, axis=0)

    return step_fn


def execute(plan: RingPlan, x: jax.Array, axis: Axis) -> jax.Array:
    """Run a plan on per-shard data ``x`` (any shape; flattened into slots).

    For ``all_reduce`` the result is the full reduction, reshaped like ``x``.
    Pads to a multiple of n_slots internally.
    """
    shape = x.shape
    flat = x.reshape(-1)
    n = plan.n_slots
    pad = (-flat.size) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    buf = flat.reshape(n, -1)
    step_fn = lower(plan, axis)
    for s in range(plan.n_steps):  # unrolled: slot indices become constants
        buf = step_fn(buf, s)
    out = buf.reshape(-1)
    if pad:
        out = out[:-pad]
    return out.reshape(shape)


def ring_all_reduce(
    x: jax.Array, axis: Axis, *, bidirectional: bool = True
) -> jax.Array:
    """Bandwidth-optimal ring allreduce as an explicit chunk schedule.

    With ``bidirectional=True`` the buffer is split in half and two
    counter-rotating rings run concurrently — both ICI directions of the axis
    carry traffic every step (the torus analog of UCCL's multipath spraying).
    """
    n = lax.axis_size(axis)
    if n == 1:
        return x
    if not bidirectional:
        return execute(plan_all_reduce(n), x, axis)
    flat = x.reshape(-1)
    half = flat.size // 2
    fwd = execute(plan_all_reduce(n), flat[:half], axis)
    rev_plan = RingPlan(
        n,
        n,
        tuple(dataclasses.replace(s, dir=-s.dir) for s in plan_all_reduce(n).steps),
        "all_reduce_rev",
    )
    bwd = execute(rev_plan, flat[half:], axis)
    return jnp.concatenate([fwd, bwd]).reshape(x.shape)


def ring_reduce_scatter(x: jax.Array, axis: Axis) -> jax.Array:
    """x: [n*k, ...] per-shard → [k, ...]: member r keeps reduced slot r."""
    n = lax.axis_size(axis)
    if n == 1:
        return x
    out = execute(plan_reduce_scatter(n), x, axis)
    r = lax.axis_index(axis)
    per = x.shape[0] // n
    return lax.dynamic_slice_in_dim(out, r * per, per, axis=0)


def ring_all_gather(x: jax.Array, axis: Axis) -> jax.Array:
    """x: [k, ...] per-shard → [n*k, ...] every member holds all slots."""
    n = lax.axis_size(axis)
    if n == 1:
        return x
    r = lax.axis_index(axis)
    k = x.shape[0]
    buf = jnp.zeros((n, k) + x.shape[1:], x.dtype)
    buf = lax.dynamic_update_index_in_dim(buf, x, r, axis=0)
    step_fn = lower(plan_all_gather(n), axis)
    for s in range(n - 1):
        buf = step_fn(buf, s)
    return buf.reshape((n * k,) + x.shape[1:])
