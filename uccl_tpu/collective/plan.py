"""Chunk-graph collective planner: plan → lower → execute.

The TPU-native re-design of the reference's next-gen ukernel CCL stack
(experimental/ukernel: ``build_coll_algo`` emits a Chunk DAG —
src/ccl/algo/chunk_graph.h:12-31 — ``lower_algo``/``build_tiled`` tiles it,
and an Executor sprays ops over backends per BFS layer, src/ccl/executor.h:26)
and of UCCL-Tran's multipath packet spraying (chunks sprayed over 32 QP paths,
collective/rdma/transport.cc:2186). On a TPU torus the "paths" are the two ICI
directions of each ring axis, so spraying becomes: split the buffer into chunk
streams and run counter-rotating rings concurrently, each step a
``lax.ppermute`` hop overlapped with the local combine — XLA schedules the hop
asynchronously, which is the overlap the reference gets from engine threads.

Layers:
* :class:`RingPlan` — the plan: phases of ring steps with slot index formulas
  (pure data; inspectable, testable without a mesh).
* :func:`lower` — turns a plan into a per-shard step function for ``lax.scan``.
* :func:`execute` — runs a plan inside shard_map code.
* Builders: :func:`plan_all_reduce` (reduce-scatter + all-gather ring,
  optionally bidirectional), :func:`plan_all_gather`, :func:`plan_reduce_scatter`.
"""

from __future__ import annotations

import dataclasses
from typing import List, Literal, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from uccl_tpu.obs import counters as _obsc
from uccl_tpu.obs import tracer as _obstr
from uccl_tpu.utils import config as _config
from uccl_tpu.utils.topology import ppermute_pairs

Axis = Union[str, Tuple[str, ...]]


@dataclasses.dataclass(frozen=True)
class RingStep:
    """One hop of a ring schedule, in rank-relative slot arithmetic.

    Member ``r`` sends chunk slot ``(r + dir*send_off) % n`` to its
    ``dir``-neighbor; the chunk received lands in slot
    ``(r + dir*recv_off) % n``. ``combine`` says whether the received chunk
    reduces into the local slot (reduce-scatter phase) or overwrites it
    (all-gather phase). Builders bake the step index into the offsets, so a
    plan is a flat list of constant-offset hops — the chunk DAG in its
    SPMD-normal form.
    """

    dir: int  # +1 = forward ring, -1 = reverse ring
    send_off: int
    recv_off: int
    combine: bool


@dataclasses.dataclass(frozen=True)
class RingPlan:
    """A full collective schedule over one ring of ``world`` members."""

    world: int
    n_slots: int  # chunks the buffer is split into
    steps: Tuple[RingStep, ...]
    name: str = "ring"

    @property
    def n_steps(self) -> int:
        return len(self.steps)

    def validate(self) -> None:
        for st in self.steps:
            if st.dir not in (-1, 1):
                raise ValueError(f"bad direction {st.dir}")


def plan_reduce_scatter(world: int, direction: int = 1) -> RingPlan:
    """Ring reduce-scatter: n-1 steps. Step s: member r sends slot
    (r - dir*(s+1)) and reduces the received chunk into slot (r - dir*(s+2));
    chunk j accumulates along the ring and lands fully-reduced at member j."""
    steps = tuple(
        RingStep(direction, send_off=-(s + 1), recv_off=-(s + 2), combine=True)
        for s in range(world - 1)
    )
    return RingPlan(world, world, steps, "reduce_scatter")


def plan_all_gather(world: int, direction: int = 1) -> RingPlan:
    """Ring all-gather: n-1 steps circulating owned slots; member r owns slot
    r at entry (which is exactly where reduce-scatter leaves things)."""
    steps = tuple(
        RingStep(direction, send_off=-s, recv_off=-(s + 1), combine=False)
        for s in range(world - 1)
    )
    return RingPlan(world, world, steps, "all_gather")


def plan_all_reduce(world: int, direction: int = 1) -> RingPlan:
    """Ring allreduce = reduce-scatter phase then all-gather phase."""
    rs = plan_reduce_scatter(world, direction).steps
    ag = plan_all_gather(world, direction).steps
    return RingPlan(world, world, rs + ag, "all_reduce")


def _hop(buf, axis, n: int, dir: int, send_off: int, recv_off: int,
         combine: bool):
    """The core ring-hop primitive: one rank-relative send/recv on ``buf``
    whose dim 0 indexes the axis's chunk slots. Shared by the RingPlan
    lowering and the chunk-graph executor so the slot arithmetic lives in
    exactly one place."""
    r = lax.axis_index(axis)
    send_slot = (r + dir * send_off) % n
    recv_slot = (r + dir * recv_off) % n
    chunk = lax.dynamic_index_in_dim(buf, send_slot, axis=0, keepdims=False)
    got = lax.ppermute(chunk, axis, ppermute_pairs(n, dir))
    cur = lax.dynamic_index_in_dim(buf, recv_slot, axis=0, keepdims=False)
    new = cur + got if combine else got
    return lax.dynamic_update_index_in_dim(buf, new, recv_slot, axis=0)


def lower(plan: RingPlan, axis: Axis):
    """Lower a plan to a per-shard step function.

    Returns ``step_fn(buf, s) -> buf`` where ``buf`` is ``[n_slots, ...]`` and
    ``s`` is the (python int) step index; unrolled so slot indices lower to
    constants per member.
    """
    plan.validate()
    n = plan.world

    def step_fn(buf, s):
        st = plan.steps[s]
        return _hop(buf, axis, n, st.dir, st.send_off, st.recv_off, st.combine)

    return step_fn


def execute(plan: RingPlan, x: jax.Array, axis: Axis) -> jax.Array:
    """Run a plan on per-shard data ``x`` (any shape; flattened into slots).

    For ``all_reduce`` the result is the full reduction, reshaped like ``x``.
    Pads to a multiple of n_slots internally.
    """
    shape = x.shape
    flat = x.reshape(-1)
    n = plan.n_slots
    pad = (-flat.size) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    buf = flat.reshape(n, -1)
    step_fn = lower(plan, axis)
    for s in range(plan.n_steps):  # unrolled: slot indices become constants
        buf = step_fn(buf, s)
    out = buf.reshape(-1)
    if pad:
        out = out[:-pad]
    return out.reshape(shape)


def ring_all_reduce(
    x: jax.Array, axis: Axis, *, bidirectional: bool = True,
    direction: int = 1,
) -> jax.Array:
    """Bandwidth-optimal ring allreduce as an explicit chunk schedule.

    With ``bidirectional=True`` the buffer is split in half and two
    counter-rotating rings run concurrently — both ICI directions of the axis
    carry traffic every step (the torus analog of UCCL's multipath spraying).
    ``direction`` picks the single ring's rotation when
    ``bidirectional=False`` — the lax mirror of a directed pallas ring must
    hop (and therefore accumulate) in the SAME order to stay bit-identical.
    """
    n = lax.axis_size(axis)
    if n == 1:
        return x
    if not bidirectional:
        return execute(plan_all_reduce(n, direction), x, axis)
    flat = x.reshape(-1)
    half = flat.size // 2
    fwd = execute(plan_all_reduce(n), flat[:half], axis)
    rev_plan = RingPlan(
        n,
        n,
        tuple(dataclasses.replace(s, dir=-s.dir) for s in plan_all_reduce(n).steps),
        "all_reduce_rev",
    )
    bwd = execute(rev_plan, flat[half:], axis)
    return jnp.concatenate([fwd, bwd]).reshape(x.shape)


# ---------------------------------------------------------------------------
# Chunk DAG (the general layer): ops with dependencies, executed by BFS layer
#
# The reference's ukernel emits a Chunk DAG with deps, tiles it, and executes
# per BFS layer over async backends (chunk_graph.h:12-31, lower.h:13-41,
# executor.h:26-60). The TPU-normal form: every op is a ring-style hop on ONE
# mesh axis acting on ONE chunk stream; ops in the same BFS layer are
# independent, so their ppermutes are all issued before any result is
# consumed and XLA's async scheduler overlaps them — multi-ring and
# multi-axis (torus) schedules fall out of the dep structure.


@dataclasses.dataclass(frozen=True)
class ChunkOp:
    """One DAG node: a ring hop on ``axes[axis_idx]`` over chunk stream
    ``stream``. Slot arithmetic is rank-relative exactly like RingStep.

    ``shard_axis``: when set, the op first restricts the slot view to this
    member's OWN slot group along that axis (dynamic index by its coordinate)
    and rings only that group — the hierarchical-bandwidth move (e.g. the 2D
    torus middle phase rings 1/a of the buffer, not all of it)."""

    id: int
    deps: Tuple[int, ...]
    axis_idx: int
    dir: int
    send_off: int
    recv_off: int
    combine: bool
    stream: int = 0
    shard_axis: int | None = None


@dataclasses.dataclass(frozen=True)
class ChunkGraph:
    """A collective as a dependency DAG of chunk ops over mesh axes.

    ``worlds[i]`` is the ring size of ``axes[i]`` (validated against the mesh
    at execution). ``n_streams`` buffer partitions let independent schedules
    (e.g. counter-rotating rings) run concurrently.
    """

    axes: Tuple[str, ...]
    worlds: Tuple[int, ...]
    n_streams: int
    ops: Tuple[ChunkOp, ...]
    name: str = "graph"

    def validate(self) -> None:
        ids = {op.id for op in self.ops}
        if len(ids) != len(self.ops):
            raise ValueError("duplicate op ids")
        for op in self.ops:
            if not 0 <= op.axis_idx < len(self.axes):
                raise ValueError(f"op {op.id}: bad axis index {op.axis_idx}")
            if op.dir not in (-1, 1):
                raise ValueError(f"op {op.id}: bad direction {op.dir}")
            if not 0 <= op.stream < self.n_streams:
                raise ValueError(f"op {op.id}: bad stream {op.stream}")
            if op.shard_axis is not None:
                if not 0 <= op.shard_axis < len(self.axes):
                    raise ValueError(f"op {op.id}: bad shard axis")
                if op.shard_axis == op.axis_idx:
                    raise ValueError(f"op {op.id}: shard axis == ring axis")
            for d in op.deps:
                if d not in ids:
                    raise ValueError(f"op {op.id}: unknown dep {d}")

    def layers(self) -> List[List[ChunkOp]]:
        """Topological BFS layers: ops whose deps are all satisfied by
        earlier layers. Raises on cycles."""
        remaining = {op.id: op for op in self.ops}
        done: set = set()
        out: List[List[ChunkOp]] = []
        while remaining:
            layer = [
                op for op in remaining.values()
                if all(d in done for d in op.deps)
            ]
            if not layer:
                raise ValueError(f"cycle in chunk graph {self.name}")
            layer.sort(key=lambda op: op.id)
            out.append(layer)
            for op in layer:
                done.add(op.id)
                del remaining[op.id]
        return out


def graph_from_ring(plan: RingPlan, axis: str) -> ChunkGraph:
    """Lift a linear RingPlan into DAG form (each step depends on the last)."""
    ops = tuple(
        ChunkOp(
            id=i,
            deps=(i - 1,) if i else (),
            axis_idx=0,
            dir=st.dir,
            send_off=st.send_off,
            recv_off=st.recv_off,
            combine=st.combine,
        )
        for i, st in enumerate(plan.steps)
    )
    return ChunkGraph((axis,), (plan.world,), 1, ops, plan.name)


def graph_bidirectional_all_reduce(world: int, axis: str) -> ChunkGraph:
    """Two counter-rotating rings on independent streams: every BFS layer
    carries one hop in EACH ICI direction of the axis (the torus analog of
    UCCL's multipath spraying, transport.cc:2186)."""
    fwd = plan_all_reduce(world, 1).steps
    ops: List[ChunkOp] = []
    for i, st in enumerate(fwd):
        ops.append(ChunkOp(2 * i, (2 * (i - 1),) if i else (), 0, st.dir,
                           st.send_off, st.recv_off, st.combine, stream=0))
        ops.append(ChunkOp(2 * i + 1, (2 * (i - 1) + 1,) if i else (), 0,
                           -st.dir, st.send_off, st.recv_off, st.combine,
                           stream=1))
    return ChunkGraph((axis,), (world,), 2, tuple(ops), "all_reduce_bidir")


def graph_torus_all_reduce(
    worlds: Tuple[int, int], axes: Tuple[str, str]
) -> ChunkGraph:
    """2D-torus (axis-pair) allreduce: reduce-scatter along axis 0, allreduce
    the scattered shard along axis 1, all-gather back along axis 0 — each
    phase a ring on its own axis, chained by deps. Bandwidth per member:
    2(a-1)/a + 2(b-1)/(a·b) of the buffer vs 2(ab-1)/(ab) for one flat ring,
    but with hops only between torus NEIGHBORS on both axes (a flat ring over
    a 2D slice must snake, paying non-neighbor links)."""
    a, b = worlds
    ax0, ax1 = axes
    ops: List[ChunkOp] = []
    nid = 0
    last = None

    def add(axis_idx, st, shard_axis=None):
        nonlocal nid, last
        ops.append(ChunkOp(nid, (last,) if last is not None else (), axis_idx,
                           st.dir, st.send_off, st.recv_off, st.combine,
                           shard_axis=shard_axis))
        last = nid
        nid += 1

    for st in plan_reduce_scatter(a).steps:
        add(0, st)
    # middle phase rings ONLY the axis-0 shard this member owns: 1/a of the
    # buffer per hop (the hierarchical bandwidth structure)
    for st in plan_all_reduce(b).steps:
        add(1, st, shard_axis=0)
    for st in plan_all_gather(a).steps:
        add(0, st)
    return ChunkGraph((ax0, ax1), (a, b), 1, tuple(ops), "all_reduce_torus2d")


def execute_graph(graph: ChunkGraph, x: jax.Array):
    """Run a chunk graph on per-shard data ``x`` inside shard_map code.

    The buffer is split into ``n_streams`` streams; each stream is chunked
    into slots. Ring ops index slots rank-relatively on their own axis.
    For the 2D torus the slot layout is hierarchical: axis-0 slots subdivide
    into axis-1 slots ([a, b, ...] view), which is what makes phase 2 operate
    on the axis-0 shard this member keeps.
    """
    graph.validate()
    worlds = tuple(lax.axis_size(ax) for ax in graph.axes)
    if worlds != graph.worlds:
        raise ValueError(f"mesh axis sizes {worlds} != plan worlds {graph.worlds}")

    shape = x.shape
    flat = x.reshape(-1)
    total_slots = 1
    for w in graph.worlds:
        total_slots *= w
    per_stream = graph.n_streams * total_slots
    pad = (-flat.size) % per_stream
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    streams = list(flat.reshape(graph.n_streams, total_slots, -1))

    def ring_hop(arr, dim, op: ChunkOp):
        """One rank-relative ring hop on `arr` whose `dim` indexes the slots
        of the op's mesh axis."""
        ax = graph.axes[op.axis_idx]
        n = graph.worlds[op.axis_idx]
        work = jnp.moveaxis(arr, dim, 0)
        work = _hop(work, ax, n, op.dir, op.send_off, op.recv_off, op.combine)
        return jnp.moveaxis(work, 0, dim)

    def apply_op(op: ChunkOp, buf):
        # hierarchical slot view: [w0, w1, ..., payload]
        view = buf.reshape(graph.worlds + (-1,))
        if op.shard_axis is None:
            view = ring_hop(view, op.axis_idx, op)
        else:
            rs = lax.axis_index(graph.axes[op.shard_axis])
            sub = lax.dynamic_index_in_dim(
                view, rs, axis=op.shard_axis, keepdims=False
            )
            dim = op.axis_idx - (1 if op.axis_idx > op.shard_axis else 0)
            sub = ring_hop(sub, dim, op)
            view = lax.dynamic_update_index_in_dim(
                view, sub, rs, axis=op.shard_axis
            )
        return view.reshape(total_slots, -1)

    for layer in graph.layers():
        # Dep-independent ops still conflict when they touch the SAME
        # stream's buffer (their slot updates would clobber), so within a
        # layer ops chain per stream; ops on different streams stay pure
        # dataflow-parallel and XLA issues their ppermutes concurrently.
        for op in layer:
            streams[op.stream] = apply_op(op, streams[op.stream])

    out = jnp.stack(streams).reshape(-1)
    if pad:
        out = out[:-pad]
    return out.reshape(shape)


def torus_all_reduce(x: jax.Array, axes: Tuple[str, str]) -> jax.Array:
    """Axis-pair allreduce over a 2D torus slice (per-shard fn)."""
    worlds = (lax.axis_size(axes[0]), lax.axis_size(axes[1]))
    if worlds[0] == 1:
        return ring_all_reduce(x, axes[1])
    if worlds[1] == 1:
        return ring_all_reduce(x, axes[0])
    return execute_graph(graph_torus_all_reduce(worlds, axes), x)


def tree_broadcast(x: jax.Array, axis: Axis, root: int = 0) -> jax.Array:
    """Binomial-tree broadcast over a mesh axis (per-shard fn): at round t,
    members with virtual rank < 2^t forward to virtual rank + 2^t via a
    partial ppermute; everyone else passes zeros and keeps its value. log2(n)
    rounds vs one big all-gather. The round schedule is the shared
    ``utils.topology.bcast_tree_rounds`` arithmetic — the same edges the
    host-side DCN broadcast walks."""
    from uccl_tpu.utils.topology import bcast_tree_rounds

    n = lax.axis_size(axis)
    if n == 1:
        return x
    r = lax.axis_index(axis)
    vr = (r - root) % n
    cur = jnp.where(vr == 0, x, jnp.zeros_like(x))
    mask = 1
    for pairs in bcast_tree_rounds(n, root):
        got = lax.ppermute(cur, axis, pairs)
        receiving = (vr >= mask) & (vr < 2 * mask)
        cur = jnp.where(receiving, got, cur)
        mask <<= 1
    return cur


def ring_reduce_scatter(x: jax.Array, axis: Axis) -> jax.Array:
    """x: [n*k, ...] per-shard → [k, ...]: member r keeps reduced slot r."""
    n = lax.axis_size(axis)
    if n == 1:
        return x
    out = execute(plan_reduce_scatter(n), x, axis)
    r = lax.axis_index(axis)
    per = x.shape[0] // n
    return lax.dynamic_slice_in_dim(out, r * per, per, axis=0)


def ring_all_gather(x: jax.Array, axis: Axis) -> jax.Array:
    """x: [k, ...] per-shard → [n*k, ...] every member holds all slots."""
    n = lax.axis_size(axis)
    if n == 1:
        return x
    r = lax.axis_index(axis)
    k = x.shape[0]
    buf = jnp.zeros((n, k) + x.shape[1:], x.dtype)
    buf = lax.dynamic_update_index_in_dim(buf, x, r, axis=0)
    step_fn = lower(plan_all_gather(n), axis)
    for s in range(n - 1):
        buf = step_fn(buf, s)
    return buf.reshape((n * k,) + x.shape[1:])


# ---------------------------------------------------------------------------
# Recursive halving-doubling (latency-optimal) + the cost-model planner
#
# The reference's lite-collective ships an *algorithm selector over many
# execution plans* (experimental/lite/lite-collective/collective/: selector +
# allreduce kernel variants); NCCL itself switches ring<->tree by size. This
# is that role for the TPU build: halving-doubling gives 2*log2(W) hops
# (vs the ring's 2(W-1)) at the same per-member byte volume, so it wins when
# the alpha (per-hop latency) term dominates — small payloads, large worlds.


def hd_all_reduce(x: jax.Array, axis: Axis) -> jax.Array:
    """Recursive-halving reduce-scatter + recursive-doubling all-gather
    (per-shard fn). Power-of-two axis size; falls back to the ring plan
    otherwise. 2*log2(W) ppermute steps, bandwidth-optimal total volume.

    Rank-relative bookkeeping: reduce-scatter consumes rank bits MSB-first
    (distance W/2 .. 1); member r ends owning chunk slot r. All-gather
    mirrors LSB-first (distance 1 .. W/2), merging base = base & ~dist each
    step. Slice sizes are python ints (static); offsets are traced.
    """
    n = lax.axis_size(axis)
    if n == 1:
        return x
    if n & (n - 1):
        return ring_all_reduce(x, axis)
    shape = x.shape
    flat = x.reshape(-1)
    pad = (-flat.size) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    buf = flat.reshape(n, -1)
    r = lax.axis_index(axis)

    # Reduce-scatter: halve the live span every step, reduce into the kept half.
    base = jnp.zeros((), jnp.int32)
    span, dist = n, n // 2
    while dist >= 1:
        half = span // 2
        upper = (r & dist) != 0  # this member keeps the upper half?
        keep_start = base + jnp.where(upper, half, 0)
        send_start = base + jnp.where(upper, 0, half)
        chunk = lax.dynamic_slice_in_dim(buf, send_start, half, axis=0)
        got = lax.ppermute(chunk, axis, [(i, i ^ dist) for i in range(n)])
        kept = lax.dynamic_slice_in_dim(buf, keep_start, half, axis=0)
        buf = lax.dynamic_update_slice_in_dim(buf, kept + got, keep_start, 0)
        base, span, dist = keep_start, half, dist // 2

    # All-gather: double the owned span every step (base ends at 0, span n).
    span, dist = 1, 1
    while dist < n:
        chunk = lax.dynamic_slice_in_dim(buf, base, span, axis=0)
        got = lax.ppermute(chunk, axis, [(i, i ^ dist) for i in range(n)])
        buf = lax.dynamic_update_slice_in_dim(buf, got, base ^ dist, 0)
        base = base & ~dist
        span, dist = span * 2, dist * 2

    out = buf.reshape(-1)
    if pad:
        out = out[:-pad]
    return out.reshape(shape)


_AR_SMALL_BYTES = _config.param(
    "AR_HD_MAX_BYTES",
    1 << 18,
    int,
    "all_reduce planner: wire payloads at or under this many bytes are "
    "eligible for the log-step halving-doubling plan (the calibrated "
    "alpha-dominated range; the cost model arbitrates inside it)",
)
_AR_FORCE_ALGO = _config.param(
    "AR_ALGO",
    "",
    str,
    "override the all_reduce planner with a fixed algorithm "
    "(xla|ring|hd|torus|pallas|bidir) — forced calibration: the planner "
    "still runs and emits its decision, with outcome 'forced'",
)

# ---------------------------------------------------------------------------
# The cost-model planner (tentpole of the topology-aware collective work).
#
# UCCL's transport sprays chunks over many paths with a pluggable selection
# policy (PAPER.md §0.1); FAST schedules all-to-all traffic off a cost model
# and FlexLink pairs counter-rotating streams to recover idle reverse-link
# bandwidth (PAPERS.md). The TPU expression: an alpha-beta-gamma model over
# the plan library — per-hop latency (alpha), per-WIRE-byte time (beta, fed
# by ops.quant.wire_bytes_of so fp8/int8 payloads shift the crossover
# points), and per-kernel-launch overhead (gamma) — picking both the
# algorithm (xla | hd | ring | bidir | torus | hier) and the chunk depth,
# and emitting every decision through the obs layer
# (``collective_plan_total`` + a ``collective_plan`` trace instant) so
# benches label arms off REAL decisions, never mirrored selector math.
#
# Default constants are STRUCTURAL-ICI derived (a ring hop between torus
# neighbors is cheap, an XLA collective dispatch is not, a flat XLA
# schedule over a 2D slice snakes across non-neighbor links, a
# counter-rotating pair fills both ICI directions) — recalibratable in one
# command from recorded bench JSON via scripts/plan_calibrate.py, which
# fits these exact env params (docs/PLAN_BENCH.md round-8 addendum).

_PLAN_ALPHA = _config.param(
    "PLAN_ALPHA_US", 1.0, float,
    "planner cost model: per-ring-hop latency in us (neighbor DMA issue + "
    "sync) — the alpha of the alpha-beta-gamma model",
)
_PLAN_BETA = _config.param(
    "PLAN_BETA_US_PER_BYTE", 1.0e-3, float,
    "planner cost model: serial wire time per byte per member in us (beta; "
    "1e-3 = 1 GB/s per ICI direction)",
)
_PLAN_GAMMA = _config.param(
    "PLAN_GAMMA_US", 5.0, float,
    "planner cost model: per-kernel-launch overhead in us (gamma) — what "
    "an extra chunk/stream launch costs",
)
_PLAN_XLA_ALPHA = _config.param(
    "PLAN_XLA_ALPHA_US", 40.0, float,
    "planner cost model: fixed dispatch cost of one XLA-scheduled "
    "collective in us",
)
_PLAN_XLA_BETA = _config.param(
    "PLAN_XLA_BETA_US_PER_BYTE", 1.7e-3, float,
    "planner cost model: per-byte time of the XLA collective schedule on a "
    "single ring axis in us",
)
_PLAN_XLA_SNAKE = _config.param(
    "PLAN_XLA_SNAKE", 2.0, float,
    "planner cost model: byte-time penalty of a flat XLA schedule over a "
    "2D torus slice (non-neighbor snake links) relative to one axis",
)
_PLAN_DCN_BETA = _config.param(
    "PLAN_DCN_BETA_US_PER_BYTE", 1.0e-2, float,
    "planner cost model: per-byte time of the cross-pod DCN leg in us "
    "(hierarchical allreduce middle phase)",
)

# get-or-create: the one family every plan decision lands on. Labels:
# algo, chunks, wire_dtype, outcome (model|forced|explicit|fallback).
PLAN_TOTAL = _obsc.counter(
    "collective_plan_total",
    "collective planner decisions by algorithm, chunk/stream depth, wire "
    "dtype and outcome (model = cost model chose, forced = UCCL_TPU_AR_ALGO"
    " calibration override, explicit = caller named the algo, fallback = a "
    "planned kernel degraded to its counted lax mirror)",
)
PLAN_PREDICTED = _obsc.gauge(
    "collective_plan_predicted_us",
    "the cost model's predicted time (us) of the last plan decision per "
    "{algo, chunks, wire_dtype} — benches read modeled cost off this "
    "instead of mirroring the model arithmetic",
)


@dataclasses.dataclass(frozen=True)
class CostModel:
    """Alpha-beta-gamma constants of the planner (all in us / us-per-byte).

    ``predict`` and ``features`` are the ONE arithmetic shared by the
    planner, the benches' modeled-cost column and scripts/plan_calibrate.py
    (which least-squares these exact features against measured times)."""

    alpha_us: float
    beta_us_per_byte: float
    gamma_us: float
    xla_alpha_us: float
    xla_beta_us_per_byte: float
    xla_snake: float
    dcn_beta_us_per_byte: float = 1.0e-2

    @classmethod
    def from_env(cls) -> "CostModel":
        return cls(
            alpha_us=_PLAN_ALPHA.get(),
            beta_us_per_byte=_PLAN_BETA.get(),
            gamma_us=_PLAN_GAMMA.get(),
            xla_alpha_us=_PLAN_XLA_ALPHA.get(),
            xla_beta_us_per_byte=_PLAN_XLA_BETA.get(),
            xla_snake=_PLAN_XLA_SNAKE.get(),
            dcn_beta_us_per_byte=_PLAN_DCN_BETA.get(),
        )

    def predict(self, algo: str, world: int, wire_bytes: int,
                n_axes: int = 1, worlds=None, dcn_world: int = 1) -> float:
        """Predicted us of one allreduce of ``wire_bytes`` per member.
        ``dcn_world`` (algo "hier" only) adds the cross-pod DCN ring
        middle at the dcn beta — the ONE hier arithmetic
        hierarchical_all_reduce's emission and any plan_explicit("hier")
        share."""
        if world <= 1 and dcn_world <= 1:
            return 0.0
        if algo == "xla":
            snake = self.xla_snake if n_axes > 1 else 1.0
            return (self.xla_alpha_us
                    + self.xla_beta_us_per_byte * snake * wire_bytes)
        hops, serial_bytes, launches = cost_features(
            algo, world, wire_bytes, worlds=worlds
        )
        t = (self.alpha_us * hops
             + self.beta_us_per_byte * serial_bytes
             + self.gamma_us * launches)
        if algo == "hier" and dcn_world > 1:
            t += (self.dcn_beta_us_per_byte
                  * 2.0 * (dcn_world - 1) / dcn_world * wire_bytes)
        return t

    def predict_verb(self, verb: str, algo: str, world: int,
                     wire_bytes: int, n_axes: int = 1,
                     worlds=None) -> float:
        """Predicted us of one ``verb`` collective — the allreduce surface
        delegates to :meth:`predict`; broadcast/all_gather charge the SAME
        alpha/beta/gamma constants over their own schedule features
        (:func:`verb_cost_features`) and the same xla line over their own
        wire volume (:func:`xla_wire_volume`), so one calibration fits
        every verb."""
        if verb == "all_reduce":
            return self.predict(algo, world, wire_bytes, n_axes, worlds)
        if world <= 1:
            return 0.0
        if algo in ("xla", "psum"):
            snake = self.xla_snake if n_axes > 1 else 1.0
            vol = xla_wire_volume(verb, world, wire_bytes)
            return self.xla_alpha_us + self.xla_beta_us_per_byte * snake * vol
        hops, serial_bytes, launches = verb_cost_features(
            verb, algo, world, wire_bytes, worlds=worlds
        )
        return (self.alpha_us * hops
                + self.beta_us_per_byte * serial_bytes
                + self.gamma_us * launches)


def torus_split(world: int) -> Tuple[int, int]:
    """The (a, b) factor pair of ``world`` closest to square — the planner's
    stand-in torus shape when only the flat world size is known (a caller
    with real axis sizes passes them via ``worlds``)."""
    a = int(world ** 0.5)
    while a > 1 and world % a:
        a -= 1
    return (max(a, 1), world // max(a, 1))


def cost_features(algo: str, world: int, wire_bytes: int,
                  worlds=None) -> Tuple[float, float, int]:
    """(hops, serial wire bytes per member, kernel launches) of one
    allreduce under ``algo`` — the design matrix row plan_calibrate.py fits
    alpha/beta/gamma against, and the terms CostModel.predict charges.

    ``serial wire bytes`` is the byte volume on the critical path: the
    bidir pair carries half the payload per direction CONCURRENTLY (the
    FlexLink ~2x move), so its serial volume is half the ring's.
    """
    w = world
    b = float(wire_bytes)
    if algo in ("ring", "pallas"):
        return 2.0 * (w - 1), 2.0 * (w - 1) / w * b, 1
    if algo == "bidir":
        return 2.0 * (w - 1), (w - 1) / w * b, 2
    if algo == "hd":
        if w & (w - 1):  # ring fallback worlds
            return 2.0 * (w - 1), 2.0 * (w - 1) / w * b, 1
        import math

        return 2.0 * math.log2(w), 2.0 * (w - 1) / w * b, 1
    if algo == "torus":
        a, bb = worlds if worlds and len(worlds) == 2 else torus_split(w)
        if a == 1 or bb == 1:  # degenerate: routes through the flat ring
            return 2.0 * (w - 1), 2.0 * (w - 1) / w * b, 1
        hops = 2.0 * (a - 1) + 2.0 * (bb - 1)
        vol = (2.0 * (a - 1) / a + 2.0 * (bb - 1) / (a * bb)) * b
        return hops, vol, 1
    if algo == "hier":
        # ICI reduce-scatter + all-gather legs around the DCN ring middle:
        # the local legs are ring-shaped, the DCN leg is charged by the
        # caller at dcn beta (hierarchical_all_reduce).
        return 2.0 * (w - 1), 2.0 * (w - 1) / w * b, 1
    if algo == "xla":
        return 1.0, b, 1
    raise ValueError(f"unknown plan algo {algo!r}")


def xla_wire_volume(verb: str, world: int, wire_bytes: int) -> float:
    """Per-member byte volume the xla line of ``verb`` is priced (and
    calibrated) over: allreduce and broadcast move ~one payload per member,
    an all-gather's per-member contribution crosses the wire world-1
    times, a reduce-scatter ships the (w-1)/w of each member's payload
    that reduces elsewhere. The ONE volume arithmetic
    CostModel.predict_verb and scripts/plan_calibrate.py share."""
    if verb == "all_gather":
        return float((world - 1) * wire_bytes)
    if verb == "reduce_scatter":
        return float(world - 1) / float(world) * wire_bytes
    return float(wire_bytes)


def verb_cost_features(verb: str, algo: str, world: int, wire_bytes: int,
                      worlds=None) -> Tuple[float, float, int]:
    """(hops, serial wire bytes per member, kernel launches) of one
    broadcast / all_gather under ``algo`` — the design-matrix row
    convention of :func:`cost_features` extended to the new verbs (ISSUE
    14), shared by CostModel.predict_verb and plan_calibrate.py.

    Broadcast:
    * ``tree`` — binomial tree (bcast_tree_rounds): ceil(log2 w) rounds,
      each shipping the FULL payload along the critical path.
    * ``scatter_ag`` — the bandwidth-optimal scatter-allgather
      decomposition: the root's serial scatter leg ((w-1)/w of the
      payload leaves the root once) plus a counter-rotating all-gather
      PAIR (each ring carries half of the (w-1)/w·S gather volume
      concurrently — the FlexLink move).

    All-gather (``wire_bytes`` = one member's CONTRIBUTED wire bytes):
    * ``ring`` — w-1 write-once hops, each member forwarding its slot.
    * ``bidir`` — the counter-rotating pair: half the serial volume,
      two launches.
    """
    w = world
    b = float(wire_bytes)
    if verb == "all_reduce":
        return cost_features(algo, w, b, worlds=worlds)
    import math

    if verb == "broadcast":
        if algo == "tree":
            r = math.ceil(math.log2(max(w, 2)))
            return float(r), float(r) * b, 1
        if algo == "scatter_ag":
            return 2.0 * (w - 1), 1.5 * (w - 1) / w * b, 2
        if algo == "xla":
            return 1.0, b, 1
        raise ValueError(f"unknown broadcast algo {algo!r}")
    if verb == "all_gather":
        if algo in ("ring", "pallas"):
            return float(w - 1), float(w - 1) * b, 1
        if algo == "bidir":
            return float(w - 1), (w - 1) * b / 2.0, 2
        if algo == "xla":
            return 1.0, float(w - 1) * b, 1
        raise ValueError(f"unknown all_gather algo {algo!r}")
    if verb == "reduce_scatter":
        # ``wire_bytes`` = one member's FULL [w*k, ...] input bytes; the RS
        # half of the ring pair ships (w-1)/w of it over w-1 reducing hops.
        if algo in ("ring", "pallas"):
            return float(w - 1), (w - 1) / float(w) * b, 1
        if algo == "xla":
            return 1.0, (w - 1) / float(w) * b, 1
        raise ValueError(f"unknown reduce_scatter algo {algo!r}")
    raise ValueError(f"unknown plan verb {verb!r}")


@dataclasses.dataclass(frozen=True)
class Plan:
    """One planner decision: what will carry the collective and why."""

    algo: str
    chunks: int  # concurrent streams/kernels (bidir = 2) or chunk depth
    wire_dtype: Optional[str]
    world: int
    wire_bytes: int
    predicted_us: float
    outcome: str  # "model" | "forced" | "explicit"
    # which collective verb the decision is for. Allreduce decisions keep
    # their PR-7 label set on collective_plan_total (no verb label — the
    # pinned back-compat series); broadcast/all_gather decisions add a
    # verb= label so the fleet can be audited per verb (ISSUE 14).
    verb: str = "all_reduce"


class CollectivePlanner:
    """Cost-model-driven algorithm + chunk-depth selection for collectives.

    The decision point every auto allreduce and EP chunk-depth resolution
    flows through (Communicator.all_reduce(algo="auto"),
    ep.ops.resolve_chunks). Every decision — modeled, forced via
    UCCL_TPU_AR_ALGO, or explicitly named by the caller — is emitted on
    ``collective_plan_total{algo,chunks,wire_dtype,outcome}`` plus a
    ``collective_plan`` trace instant carrying the model's predicted cost,
    so benches and check_obs read REAL decisions off the obs layer.
    """

    def __init__(self, model: Optional[CostModel] = None):
        self._model = model

    @property
    def model(self) -> CostModel:
        return self._model if self._model is not None else CostModel.from_env()

    # -- wire-byte accounting ------------------------------------------------

    @staticmethod
    def wire_bytes(payload_shape, dtype, wire_dtype) -> int:
        from uccl_tpu.ops import quant as _quant

        return _quant.wire_bytes_of(tuple(payload_shape), dtype,
                                    _quant.resolve_wire_dtype(wire_dtype))

    # -- the allreduce decision ----------------------------------------------

    def plan_all_reduce(self, payload_shape, dtype, world: int, *,
                        n_axes: int = 1, worlds=None, wire_dtype=None,
                        pallas_ok: bool = False, emit: bool = True) -> Plan:
        """Pick the allreduce algorithm for a per-member payload.

        ``payload_shape``/``dtype`` describe ONE member's buffer;
        ``wire_dtype`` shifts every byte term to actual wire bytes (the
        fp8/int8 payload + scale sidecar), so a quantized payload crosses
        the hd/torus/ring thresholds at its WIRE size, not its logical
        size — but a winner that cannot CARRY a quantized wire (anything
        but the pallas/bidir kernels) is re-labeled and re-priced at the
        full-precision bytes it will actually ship, so the emitted
        decision never claims a quantized hd/xla/torus that cannot exist
        (the caller counts the quant downgrade on the fallback counter).
        ``pallas_ok`` gates the device-kernel candidates (bidir):
        the caller asserts its mesh is kernel-addressable; the planner
        additionally quiet-probes the VMEM/interpret budget so auto never
        picks a kernel that would immediately downgrade (a FORCED bidir
        still exercises the counted fallback).
        """
        from uccl_tpu.ops import quant as _quant

        wire_dtype = _quant.resolve_wire_dtype(wire_dtype)
        m = self.model
        wire_bytes = self.wire_bytes(payload_shape, dtype, wire_dtype)

        def _final(algo: str, cost, outcome: str) -> Plan:
            wd, wb, c = wire_dtype, wire_bytes, cost
            if wd is not None and algo not in ("pallas", "bidir"):
                # selection was priced at wire bytes (the ISSUE-pinned
                # threshold rule), but this winner ships full precision
                wd = None
                wb = self.wire_bytes(payload_shape, dtype, None)
                c = None
            if c is None:
                c = m.predict(algo, world, wb, n_axes, worlds)
            plan_ = Plan(algo, 2 if algo == "bidir" else 1, wd, world, wb,
                         c, outcome)
            return self._emit(plan_) if emit else plan_

        forced = _AR_FORCE_ALGO.get()
        if forced:
            return _final(forced, None, "forced")
        if world <= 1:
            return _final("xla", 0.0, "model")

        candidates = ["xla"]
        if world & (world - 1) == 0 and wire_bytes <= _AR_SMALL_BYTES.get():
            # the calibrated alpha-dominated range (UCCL_TPU_AR_HD_MAX_BYTES
            # — honored as a hard eligibility cap, the model arbitrates
            # inside it)
            candidates.append("hd")
        if n_axes == 2:
            candidates.append("torus")
        if pallas_ok and n_axes == 1 and self._bidir_budget_ok(
                payload_shape, dtype, wire_dtype, world):
            candidates.append("bidir")

        best, best_cost = "xla", None
        for algo in candidates:
            cost = m.predict(algo, world, wire_bytes, n_axes, worlds)
            if best_cost is None or cost < best_cost:
                best, best_cost = algo, cost
        return _final(best, best_cost, "model")

    def plan_explicit(self, algo: str, payload_shape, dtype, world: int, *,
                      n_axes: int = 1, worlds=None, wire_dtype=None,
                      emit: bool = True, outcome: str = "explicit",
                      verb: str = "all_reduce") -> Plan:
        """Record a caller-named algorithm as a plan (outcome "explicit",
        overridable when relaying a decision made elsewhere — e.g. the
        per-shard wrapper recording the algo it actually lowered under the
        original plan's outcome) with the model's predicted cost beside it
        — how bench arms get a modeled time without mirroring the model.
        ``verb`` extends the surface to broadcast/all_gather decisions
        (priced via predict_verb, emitted with a verb= label)."""
        from uccl_tpu.ops import quant as _quant

        wire_dtype = _quant.resolve_wire_dtype(wire_dtype)
        wire_bytes = self.wire_bytes(payload_shape, dtype, wire_dtype)
        try:
            pred = self.model.predict_verb(verb, algo, world, wire_bytes,
                                           n_axes, worlds)
        except ValueError:
            pred = 0.0  # un-modeled algo: recorded, not priced
        plan_ = Plan(algo, 2 if algo in ("bidir", "scatter_ag") else 1,
                     wire_dtype, world, wire_bytes, pred, outcome, verb)
        return self._emit(plan_) if emit else plan_

    # -- the broadcast / all_gather decisions (ISSUE 14) ---------------------

    def plan_broadcast(self, payload_shape, dtype, world: int, *,
                       n_axes: int = 1, worlds=None, wire_dtype=None,
                       pallas_ok: bool = False, emit: bool = True) -> Plan:
        """Pick the broadcast algorithm for a per-member payload:
        ``xla`` (the lax ppermute scatter + ring all-gather lowering),
        ``tree`` (binomial — alpha-dominated small payloads), or
        ``scatter_ag`` (the pallas scatter-allgather kernel pair —
        bandwidth range, quantizable wire). Selection is priced at WIRE
        bytes (quantized payloads shift the crossovers AND the budget
        probe, per the PR 7 rule); a winner that cannot carry a quantized
        wire (xla/tree) is re-labeled and re-priced at full precision —
        the caller counts the downgrade."""
        from uccl_tpu.ops import quant as _quant

        wire_dtype = _quant.resolve_wire_dtype(wire_dtype)
        m = self.model
        wire_bytes = self.wire_bytes(payload_shape, dtype, wire_dtype)

        def _final(algo: str, cost, outcome: str) -> Plan:
            wd, wb, c = wire_dtype, wire_bytes, cost
            if wd is not None and algo != "scatter_ag":
                wd = None
                wb = self.wire_bytes(payload_shape, dtype, None)
                c = None
            if c is None:
                c = m.predict_verb("broadcast", algo, world, wb, n_axes,
                                   worlds)
            plan_ = Plan(algo, 2 if algo == "scatter_ag" else 1, wd, world,
                         wb, c, outcome, "broadcast")
            return self._emit(plan_) if emit else plan_

        if world <= 1:
            return _final("xla", 0.0, "model")
        candidates = ["xla", "tree"]
        if pallas_ok and n_axes == 1 and self._bcast_budget_ok(
                payload_shape, dtype, wire_dtype, world):
            candidates.append("scatter_ag")
        best, best_cost = "xla", None
        for algo in candidates:
            cost = m.predict_verb("broadcast", algo, world, wire_bytes,
                                  n_axes, worlds)
            if best_cost is None or cost < best_cost:
                best, best_cost = algo, cost
        return _final(best, best_cost, "model")

    def plan_all_gather(self, payload_shape, dtype, world: int, *,
                        n_axes: int = 1, worlds=None, wire_dtype=None,
                        pallas_ok: bool = False, emit: bool = True) -> Plan:
        """Pick the all-gather algorithm for one member's CONTRIBUTED
        payload: ``xla`` (lax.all_gather), ``ring`` (the pallas write-once
        ring kernel), or ``bidir`` (the counter-rotating pair — half the
        serial volume). Same wire-byte pricing + quant re-label rule as
        the other verbs; the kernel candidates are budget-probed quietly
        so auto never plans a kernel whose first act is a counted
        downgrade."""
        from uccl_tpu.ops import quant as _quant

        wire_dtype = _quant.resolve_wire_dtype(wire_dtype)
        m = self.model
        wire_bytes = self.wire_bytes(payload_shape, dtype, wire_dtype)

        def _final(algo: str, cost, outcome: str) -> Plan:
            wd, wb, c = wire_dtype, wire_bytes, cost
            if wd is not None and algo not in ("ring", "bidir"):
                wd = None
                wb = self.wire_bytes(payload_shape, dtype, None)
                c = None
            if c is None:
                c = m.predict_verb("all_gather", algo, world, wb, n_axes,
                                   worlds)
            plan_ = Plan(algo, 2 if algo == "bidir" else 1, wd, world, wb,
                         c, outcome, "all_gather")
            return self._emit(plan_) if emit else plan_

        if world <= 1:
            return _final("xla", 0.0, "model")
        candidates = ["xla"]
        if pallas_ok and n_axes == 1:
            if self._ag_budget_ok(payload_shape, dtype, wire_dtype, world,
                                  pair=False):
                candidates.append("ring")
            if self._ag_budget_ok(payload_shape, dtype, wire_dtype, world,
                                  pair=True):
                candidates.append("bidir")
        best, best_cost = "xla", None
        for algo in candidates:
            cost = m.predict_verb("all_gather", algo, world, wire_bytes,
                                  n_axes, worlds)
            if best_cost is None or cost < best_cost:
                best, best_cost = algo, cost
        return _final(best, best_cost, "model")

    def plan_reduce_scatter(self, payload_shape, dtype, world: int, *,
                            n_axes: int = 1, worlds=None, wire_dtype=None,
                            pallas_ok: bool = False,
                            emit: bool = True) -> Plan:
        """Pick the reduce-scatter algorithm for one member's FULL
        ``[world*k, ...]`` input: ``xla`` (lax.psum_scatter) or ``ring``
        (the RS half of the pallas ring pair — write-once reducing hops,
        with its bit-identical lax mirror past the budget). The fourth and
        final verb under the ONE alpha-beta-gamma model: same wire-byte
        pricing, quant re-label rule and quiet budget probing as the
        others."""
        from uccl_tpu.ops import quant as _quant

        wire_dtype = _quant.resolve_wire_dtype(wire_dtype)
        m = self.model
        wire_bytes = self.wire_bytes(payload_shape, dtype, wire_dtype)

        def _final(algo: str, cost, outcome: str) -> Plan:
            wd, wb, c = wire_dtype, wire_bytes, cost
            if wd is not None and algo != "ring":
                wd = None
                wb = self.wire_bytes(payload_shape, dtype, None)
                c = None
            if c is None:
                c = m.predict_verb("reduce_scatter", algo, world, wb,
                                   n_axes, worlds)
            plan_ = Plan(algo, 1, wd, world, wb, c, outcome,
                         "reduce_scatter")
            return self._emit(plan_) if emit else plan_

        if world <= 1:
            return _final("xla", 0.0, "model")
        candidates = ["xla"]
        if pallas_ok and n_axes == 1 and self._rs_budget_ok(
                payload_shape, dtype, wire_dtype, world):
            candidates.append("ring")
        best, best_cost = "xla", None
        for algo in candidates:
            cost = m.predict_verb("reduce_scatter", algo, world, wire_bytes,
                                  n_axes, worlds)
            if best_cost is None or cost < best_cost:
                best, best_cost = algo, cost
        return _final(best, best_cost, "model")

    # -- scheduled EP a2a ----------------------------------------------------

    def plan_ep_a2a(self, payload_shape, dtype, world: int, *,
                    skew: float = 1.0, n_rounds=None, wire_dtype=None,
                    n_chunks: int = 1, chunk_elems_per_peer=None,
                    emit: bool = True) -> Plan:
        """Arbitrate the EP all-to-all wire ORDER: ``ep_streams`` (the fixed
        counter-rotating 2-stream kernel) vs ``ep_sched`` (the
        contention-aware Birkhoff round schedule, uccl_tpu.ep.a2a_sched).

        ``payload_shape`` is one member's full [W, ...] exchange buffer;
        ``skew`` is a2a_sched.skew(traffic) — hottest-port/mean-port
        off-diagonal load. The fixed streams serialize behind the hottest
        port (serial bytes = skew x the mean per-member volume), while the
        scheduled wire moves every row concurrently round by round but
        pays gamma per round kernel: under the ONE cost model the
        crossover sits where (skew - 1) x beta x bytes outgrows
        (rounds - 1) x gamma, so uniform matrices (skew 1) keep the
        streams and skewed routing flips to the schedule. ``n_chunks``
        is the buffer's chunk-pipeline depth: the scheduled path budgets
        per chunk (dma.chunk_budget), so chunked buffers can schedule
        payloads the monolithic gate would refuse — callers that know
        the device layout pass ``chunk_elems_per_peer`` (per-chunk
        per-peer element count, the gate's own quantity) so the probe
        charges EXACTLY what _scheduled_chunked will. Decisions land on
        collective_plan_total{verb="ep_a2a"} like every other verb."""
        m = self.model
        wire_bytes = self.wire_bytes(payload_shape, dtype, wire_dtype)
        if world <= 1:
            plan_ = Plan("ep_streams", 1, wire_dtype, world, wire_bytes,
                         0.0, "model", "ep_a2a")
            return self._emit(plan_) if emit else plan_
        rounds = int(n_rounds) if n_rounds else world - 1
        skew = max(1.0, float(skew))
        # mean per-member a2a volume: (w-1)/w of the buffer leaves home
        mean_bytes = (world - 1) / float(world) * wire_bytes
        streams_us = (m.alpha_us * (world - 1)
                      + m.beta_us_per_byte * skew * mean_bytes
                      + m.gamma_us)
        sched_us = (m.alpha_us * rounds
                    + m.beta_us_per_byte * mean_bytes
                    + m.gamma_us * rounds)
        if (sched_us < streams_us
                and self._ep_sched_budget_ok(
                    payload_shape, dtype, wire_dtype, world,
                    n_chunks=n_chunks,
                    chunk_elems_per_peer=chunk_elems_per_peer)):
            algo, cost, chunks = "ep_sched", sched_us, rounds
        else:
            algo, cost, chunks = "ep_streams", streams_us, 1
        plan_ = Plan(algo, chunks, wire_dtype, world, wire_bytes, cost,
                     "model", "ep_a2a")
        return self._emit(plan_) if emit else plan_

    def _ep_sched_budget_ok(self, payload_shape, dtype, wire_dtype,
                            world: int, n_chunks: int = 1,
                            chunk_elems_per_peer=None) -> bool:
        """Quiet probe of the scheduled-round kernel budget — charges
        EXACTLY what pallas_a2a.scheduled_all_to_all's gate charges (the
        [W, ...] send view + one round slot, two kernels airborne), so
        auto never schedules rounds whose first act is a counted
        downgrade onto the unscheduled wire. With ``n_chunks > 1`` the
        device runs _scheduled_chunked, whose gate is dma.chunk_budget on
        the PER-CHUNK per-peer footprint: callers that know the device
        layout pass it as ``chunk_elems_per_peer`` (exact mirror);
        otherwise the probe estimates ceil(elems / (world x n_chunks)) —
        the un-padded footprint, close enough that the 1024-element wire
        quantum usually absorbs the slot-padding difference."""
        from uccl_tpu.collective import dma as _dma

        elems = self._payload_elems(payload_shape)
        itemsize = 1 if wire_dtype else jnp.dtype(dtype).itemsize
        interpret = _dma.resolve_interpret(None)
        if n_chunks > 1:
            per_peer = chunk_elems_per_peer
            if per_peer is None:
                per_peer = -(-elems // (world * int(n_chunks)))
            return _dma.chunk_budget(world, int(per_peer), itemsize,
                                     "ep_a2a_sched", interpret,
                                     quiet=True)
        m = _dma.padded_chunk_elems(-(-elems // world))
        charge = 2 * (world + 1) * m * itemsize
        return charge <= _dma.budget_limit(interpret)

    def _rs_budget_ok(self, payload_shape, dtype, wire_dtype,
                      world: int) -> bool:
        """Quiet probe of the reduce-scatter ring kernel budget
        (pallas_ccl.rs_charge — the gate's own arithmetic)."""
        from uccl_tpu.collective import dma as _dma
        from uccl_tpu.collective import pallas_ccl as _pccl

        elems = self._payload_elems(payload_shape)
        itemsize = jnp.dtype(dtype).itemsize
        interpret = _dma.resolve_interpret(None)
        charge = _pccl.rs_charge(elems, itemsize, world, wire_dtype,
                                 interpret)
        return charge <= _dma.budget_limit(interpret)

    def _bidir_budget_ok(self, payload_shape, dtype, wire_dtype,
                         world: int) -> bool:
        """Quiet budget probe: would the paired bidir kernels fit? Charges
        EXACTLY what the pair gate charges (pallas_ccl.bidir_pair_charge —
        one shared arithmetic) against the gate's own limit
        (dma.budget_limit), counts nothing — auto must not plan a kernel
        whose first act is a counted downgrade."""
        from uccl_tpu.collective import dma as _dma
        from uccl_tpu.collective import pallas_ccl as _pccl

        elems = 1
        for s in payload_shape:
            elems *= int(s)
        itemsize = jnp.dtype(dtype).itemsize
        interpret = _dma.resolve_interpret(None)
        charge = _pccl.bidir_pair_charge(elems, itemsize, world, wire_dtype,
                                         interpret)
        return charge <= _dma.budget_limit(interpret)

    @staticmethod
    def _payload_elems(payload_shape) -> int:
        elems = 1
        for s in payload_shape:
            elems *= int(s)
        return elems

    def _ag_budget_ok(self, payload_shape, dtype, wire_dtype, world: int,
                      *, pair: bool) -> bool:
        """Quiet probe of the all-gather kernel budget — charges EXACTLY
        what the ring/pair gate charges (pallas_ccl.ag_charge /
        ag_pair_charge, one shared arithmetic), counts nothing."""
        from uccl_tpu.collective import dma as _dma
        from uccl_tpu.collective import pallas_ccl as _pccl

        elems = self._payload_elems(payload_shape)
        itemsize = jnp.dtype(dtype).itemsize
        interpret = _dma.resolve_interpret(None)
        fn = _pccl.ag_pair_charge if pair else _pccl.ag_charge
        charge = fn(elems, itemsize, world, wire_dtype, interpret)
        return charge <= _dma.budget_limit(interpret)

    def _bcast_budget_ok(self, payload_shape, dtype, wire_dtype,
                         world: int) -> bool:
        """Quiet probe of the scatter-allgather broadcast kernel budget
        (pallas_ccl.bcast_pair_charge — the gate's own arithmetic)."""
        from uccl_tpu.collective import dma as _dma
        from uccl_tpu.collective import pallas_ccl as _pccl

        elems = self._payload_elems(payload_shape)
        itemsize = jnp.dtype(dtype).itemsize
        interpret = _dma.resolve_interpret(None)
        charge = _pccl.bcast_pair_charge(elems, itemsize, world, wire_dtype,
                                         interpret)
        return charge <= _dma.budget_limit(interpret)

    # -- EP chunk depth -------------------------------------------------------

    def ep_auto_depth(self, exchange_bytes: int, capacity: int) -> int:
        """Auto chunk depth for the pipelined EP layer: 2 is the minimum
        that buys dispatch/compute/combine overlap; deeper pipelines pay
        gamma per extra launch, so depth grows only once the modeled wire
        time dwarfs it (64x / 256x gamma — conservative: the budget gate
        still arbitrates the final depth)."""
        m = self.model
        wire_us = m.beta_us_per_byte * exchange_bytes
        depth = 2
        if wire_us >= 256 * m.gamma_us:
            depth = 8
        elif wire_us >= 64 * m.gamma_us:
            depth = 4
        return max(1, min(depth, capacity))

    def record_ep_chunks(self, resolved: int, *, wire: str,
                         wire_dtype=None, auto: bool = False) -> int:
        """Emit an EP chunk-depth resolution on the plan counter (algo
        "ep_a2a") — ep_bench labels its arms off this series. ``auto``
        marks an n_chunks=0 request, where the cost model (ep_auto_depth)
        chose the depth: outcome "model"; a caller-pinned depth records
        "explicit" (same outcome semantics as the allreduce decisions —
        OBSERVABILITY.md catalog)."""
        del wire  # the resolution, not the wire kind, decides the outcome
        PLAN_TOTAL.inc(algo="ep_a2a", chunks=resolved,
                       wire_dtype=wire_dtype or "none",
                       outcome="model" if auto else "explicit")
        return resolved

    # -- emission -------------------------------------------------------------

    def _emit(self, plan_: Plan) -> Plan:
        # allreduce keeps the PR-7 label set (benches/tests pin those exact
        # series keys); the new verbs carry an explicit verb= label
        extra = {} if plan_.verb == "all_reduce" else {"verb": plan_.verb}
        PLAN_TOTAL.inc(algo=plan_.algo, chunks=plan_.chunks,
                       wire_dtype=plan_.wire_dtype or "none",
                       outcome=plan_.outcome, **extra)
        PLAN_PREDICTED.set(plan_.predicted_us, algo=plan_.algo,
                           chunks=plan_.chunks,
                           wire_dtype=plan_.wire_dtype or "none", **extra)
        _obstr.instant(
            "collective_plan", track="wire", algo=plan_.algo,
            chunks=plan_.chunks, wire_dtype=plan_.wire_dtype or "none",
            outcome=plan_.outcome, world=plan_.world,
            wire_bytes=plan_.wire_bytes, verb=plan_.verb,
            predicted_us=round(plan_.predicted_us, 2),
        )
        return plan_


_PLANNER = CollectivePlanner()


def get_planner() -> CollectivePlanner:
    """The process-wide planner (model constants re-read from env params on
    every decision, so tests/calibration overrides take effect live)."""
    return _PLANNER


def select_all_reduce_algo(
    nbytes: int, world: int, n_axes: int = 1
) -> str:
    """Back-compat selector surface: one planner decision on a flat
    ``nbytes`` payload (full-precision wire, no device-kernel candidates —
    the host-side callers that only know a byte count). Emits through the
    planner like every decision; quantization-aware callers use
    :meth:`CollectivePlanner.plan_all_reduce` with shape/dtype/wire_dtype.
    """
    return get_planner().plan_all_reduce(
        (max(1, nbytes // 4),), jnp.float32, world, n_axes=n_axes
    ).algo
