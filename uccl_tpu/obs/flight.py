"""Black-box flight recorder — the active half of the obs spine.

The tracer ring and the counter registry are *passive*: traces dump at
exit, metrics are read when someone scrapes. By the time a chaos arm or
a fleet run has visibly gone wrong, the evidence of *why* has fallen off
the back of the ring. The flight recorder closes that gap: it is armed
once per process (``enable(out_dir)``), instrumentation sites across the
stack call :func:`trigger` when a pathology fires, and the recorder
atomically freezes everything a post-mortem needs into ONE self-
describing JSON bundle:

* the last N tracer ring events (what led up to the trigger),
* the full counter/gauge/histogram registry as Prometheus text (so the
  bundle is parseable by the same ``obs/aggregate.py`` parser every
  other tool uses),
* every registered state provider's snapshot — per-path SACK/CC state
  from the windowed channel, engine slot/scheduler occupancy, fleet
  directory state — captured at trigger time,
* the trigger's own context (which peer died, which path stormed, how
  far the RTO backed off).

Trigger taxonomy (``TRIGGERS``) is closed on purpose — ``doctor`` maps
each kind to a root-cause narrative, and ``check_obs --flight`` asserts
bundle/counter agreement per kind:

* ``conservation``       — the serving invariant broke
  (submitted != completed+active+queued+rejected+expired+lost)
* ``peer_dead``          — a FailureDetector HEALTHY→DEAD transition
  (or a fleet worker latching a dead cache owner)
* ``retx_storm``         — SACK retransmit count crossed the armed
  threshold inside one windowed transfer
* ``rto_backoff``        — the Jacobson RTO backed off past the armed
  ceiling (sustained loss / blackout, not isolated drops)
* ``ctrl_storm``         — disagg control-plane retries crossed the
  armed threshold (notif plane lossy or peer unresponsive)
* ``slo_burn``           — a multi-window burn-rate monitor alerted
  (obs/slo.py)
* ``step_stall``         — one engine ``step()`` exceeded the armed
  wall-clock budget
* ``uncaught_exception`` — a serve/bench driver died; the excepthook
  dumps before the process unwinds

Discipline over volume: dumps are **deduplicated** (one bundle per
(kind, key) — a dead peer dumps once, not once per tick), **rate
limited** (``min_interval_s`` between bundles), and **capped**
(``max_dumps`` per recorder). Every written bundle counts on
``obs_flight_dumps_total{trigger=...}`` (incremented BEFORE the
registry snapshot, so a bundle always shows its own dump); every
suppressed one counts on ``obs_flight_suppressed_total{reason=...}``.
A clean run writes nothing and both counters stay zero — the chaos
bench's clean arm asserts exactly that.

Everything is a no-op (one ``is None`` check) until :func:`enable` is
called, so the hooks threaded through the hot paths cost nothing in
normal operation.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional

from uccl_tpu.obs import counters as _counters
from uccl_tpu.obs import tracer as _tracer

SCHEMA = "uccl_tpu.flight/1"

TRIGGERS = (
    "conservation",
    "peer_dead",
    "retx_storm",
    "rto_backoff",
    "ctrl_storm",
    "slo_burn",
    "step_stall",
    "uncaught_exception",
)

_DUMPS = _counters.counter(
    "obs_flight_dumps_total",
    "flight-recorder post-mortem bundles written, by trigger kind")
_SUPPRESSED = _counters.counter(
    "obs_flight_suppressed_total",
    "flight triggers that fired but wrote no bundle, by reason "
    "(disabled excluded: an unarmed recorder is not a suppression)")


def _jsonable(obj):
    """Best-effort deep conversion to JSON-encodable values — a state
    provider returning a numpy scalar or a tuple key must degrade to a
    string, never kill the dump (the dump IS the diagnostic channel)."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set)):
        return [_jsonable(v) for v in obj]
    for attr in ("item", "tolist"):  # numpy scalars/arrays
        fn = getattr(obj, attr, None)
        if callable(fn):
            try:
                return _jsonable(fn())
            except Exception:
                break
    return repr(obj)


class FlightRecorder:
    """Bounded post-mortem bundle writer. One per process is the intended
    shape (module singleton via :func:`enable`), but the class is direct-
    constructible for tests — ``clock`` is injectable so rate-limit and
    dedup behavior are testable without sleeping."""

    def __init__(self, out_dir: str, *, last_events: int = 256,
                 max_dumps: int = 16, min_interval_s: float = 0.25,
                 clock: Callable[[], float] = time.monotonic):
        self.out_dir = out_dir
        self.last_events = int(last_events)
        self.max_dumps = int(max_dumps)
        self.min_interval_s = float(min_interval_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._providers: Dict[str, Callable[[], Dict]] = {}
        self._fired: set = set()     # (kind, key) dedup
        self._last_dump_t: Optional[float] = None
        self._seq = 0
        self.bundles: List[str] = []  # every path written, oldest first
        os.makedirs(out_dir, exist_ok=True)

    # -- state providers -----------------------------------------------------
    def register_provider(self, name: str, fn: Callable[[], Dict]) -> None:
        """Attach a live-state source captured into every future bundle.
        Names collide last-writer-wins (a re-created engine replaces its
        predecessor's provider rather than leaking it)."""
        with self._lock:
            self._providers[name] = fn

    def unregister_provider(self, name: str) -> None:
        with self._lock:
            self._providers.pop(name, None)

    # -- the trigger path ----------------------------------------------------
    def trigger(self, kind: str, key: Optional[str] = None,
                **context) -> Optional[str]:
        """Freeze-and-dump. Returns the bundle path, or None when the
        trigger was suppressed (dedup / rate / cap). ``key`` scopes
        dedup: pass a stable identity (peer name, transfer id) so ONE
        fault produces ONE bundle no matter how often its symptom
        re-fires; ``key=None`` skips dedup entirely."""
        if kind not in TRIGGERS:
            raise ValueError(f"unknown flight trigger {kind!r} "
                             f"(known: {TRIGGERS})")
        now = self.clock()
        with self._lock:
            if key is not None:
                dk = (kind, key)
                if dk in self._fired:
                    _SUPPRESSED.inc(reason="dedup")
                    return None
                self._fired.add(dk)
            if self._seq >= self.max_dumps:
                _SUPPRESSED.inc(reason="cap")
                return None
            if (self._last_dump_t is not None
                    and now - self._last_dump_t < self.min_interval_s):
                _SUPPRESSED.inc(reason="rate")
                return None
            self._last_dump_t = now
            self._seq += 1
            seq = self._seq
            providers = dict(self._providers)

        # count FIRST: the bundle's own registry snapshot must show this
        # dump, so check_obs can assert bundle-count == counter value.
        _DUMPS.inc(trigger=kind)
        t = _tracer.get_tracer()
        if t is not None:
            t.instant("flight_dump", track="flight", trigger=kind,
                      **{k: v for k, v in context.items()
                         if isinstance(v, (str, int, float, bool))})
        bundle = self._collect(kind, key, context, providers, seq)
        path = os.path.join(self.out_dir,
                            f"flight_{seq:03d}_{kind}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(bundle, f, indent=1)
            f.write("\n")
        os.replace(tmp, path)  # atomic: a reader never sees a torn bundle
        with self._lock:
            self.bundles.append(path)
        return path

    def _collect(self, kind, key, context, providers, seq) -> Dict:
        from uccl_tpu.obs import export as _export

        t = _tracer.get_tracer()
        events: List[Dict] = []
        dropped = 0
        if t is not None:
            evs = t.events()[-self.last_events:]
            dropped = t.dropped
            for e in evs:
                d = {"name": e.name, "ph": e.ph, "ts_us": e.ts_us,
                     "track": e.track}
                if e.dur_us is not None:
                    d["dur_us"] = e.dur_us
                if e.fid is not None:
                    d["fid"] = e.fid
                if e.args:
                    d["args"] = _jsonable(e.args)
                events.append(d)
        state = {}
        for name, fn in providers.items():
            try:
                state[name] = _jsonable(fn())
            except Exception as e:  # a broken provider must not lose the dump
                state[name] = {"error": repr(e)}
        return {
            "schema": SCHEMA,
            "seq": seq,
            "trigger": {
                "kind": kind,
                "key": key,
                "t_mono_s": self.clock(),
                "t_wall_s": time.time(),
                "ts_us": t.now_us() if t is not None else None,
                "context": _jsonable(context),
            },
            "host": {"pid": os.getpid(),
                     "hostname": socket.gethostname(),
                     "argv": list(sys.argv)},
            "events": events,
            "events_dropped_from_ring": dropped,
            "state": state,
            "metrics_prom": _export.prometheus_text(),
            "registry": _counters.REGISTRY.snapshot(),
        }


# -- module singleton (mirrors tracer.enable/disable) ------------------------

_recorder: Optional[FlightRecorder] = None


def enable(out_dir: str, **kw) -> FlightRecorder:
    """Arm the process-wide recorder. Re-enabling replaces the previous
    recorder (fresh dedup/cap state) but keeps nothing from it — benches
    re-arm per fault arm to isolate attribution."""
    global _recorder
    _recorder = FlightRecorder(out_dir, **kw)
    return _recorder


def disable() -> None:
    global _recorder
    _recorder = None


def enabled() -> bool:
    return _recorder is not None


def get_recorder() -> Optional[FlightRecorder]:
    return _recorder


def trigger(kind: str, key: Optional[str] = None,
            **context) -> Optional[str]:
    """The hook every instrumentation site calls. Free when unarmed."""
    if _recorder is None:
        return None
    return _recorder.trigger(kind, key=key, **context)


def register_provider(name: str, fn: Callable[[], Dict]) -> None:
    if _recorder is not None:
        _recorder.register_provider(name, fn)


def unregister_provider(name: str) -> None:
    if _recorder is not None:
        _recorder.unregister_provider(name)


def record_exception(exc: BaseException,
                     where: str = "driver") -> Optional[str]:
    """Dump on a driver-level failure (callers re-raise after)."""
    tb = traceback.format_exception(type(exc), exc, exc.__traceback__)
    return trigger("uncaught_exception",
                   key=f"{where}:{type(exc).__name__}",
                   where=where, exc_type=type(exc).__name__,
                   exc=str(exc), traceback_tail="".join(tb)[-4000:])


_prev_excepthook = None


def install_excepthook(where: str = "driver") -> None:
    """Chain onto ``sys.excepthook`` so an uncaught crash in a serve or
    bench driver writes its post-mortem before the interpreter unwinds.
    Idempotent; the previous hook still runs (the traceback still
    prints)."""
    global _prev_excepthook
    if _prev_excepthook is not None:
        return
    _prev_excepthook = sys.excepthook

    def hook(exc_type, exc, tb):
        try:
            e = exc if exc is not None else exc_type()
            if e.__traceback__ is None and tb is not None:
                e = e.with_traceback(tb)
            record_exception(e, where=where)
        except Exception:
            pass  # the ORIGINAL traceback must still reach the user
        _prev_excepthook(exc_type, exc, tb)

    sys.excepthook = hook
