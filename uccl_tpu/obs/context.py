"""Cross-process trace context + clock-offset estimation.

The PR 5 tracer gives every *process* a timeline; a fleet needs every
*request* to own one timeline across processes. Two host-only pieces,
both jax-free (the Dapper/W3C trace-context shape, PAPERS.md):

* :class:`TraceContext` — a ``trace_id`` (one per request, minted once at
  the ingress ``submit``/``Router.submit``) plus the minting side's
  ``span_id``. The context rides VERBATIM in the disagg BEGIN notif and
  is stamped onto every remote-side event, so a merged trace groups all
  of one request's spans under one id no matter which process emitted
  them. ``flow_id`` derives the Chrome-trace flow-event id from the
  trace_id, so the prefill-side ``kv_stream.tx`` span and the decode-side
  ``kv_stream.import`` span bind into one Perfetto arrow without any
  coordination beyond the id itself.
* :func:`estimate_clock_offset` — the NTP-style RTT-midpoint estimate the
  disagg HELLO handshake uses to relate two processes' wall clocks, so
  ``scripts/trace_merge.py`` can place both processes' events on one
  causally ordered timeline (no GRANT before its BEGIN).

Minting is counted on ``obs_trace_contexts_total`` so benches can stamp
how many request timelines an arm produced (a pure counter delta).
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from uccl_tpu.obs.counters import counter

__all__ = [
    "TraceContext", "new_context", "new_trace_id", "new_span_id",
    "flow_id", "estimate_clock_offset",
]

_MINTED = counter(
    "obs_trace_contexts_total",
    "trace contexts minted at request ingress (one per request timeline)",
)


def new_trace_id() -> str:
    """A 16-hex-char trace id (64 random bits — the W3C short form)."""
    return secrets.token_hex(8)


def new_span_id() -> str:
    """An 8-hex-char span id (32 random bits)."""
    return secrets.token_hex(4)


@dataclass(frozen=True)
class TraceContext:
    """One request's identity across processes: the trace id plus the
    minting side's root span id (the remote side's spans are children)."""

    trace_id: str
    span_id: str

    def to_wire(self) -> Dict[str, str]:
        """The JSON-ready form that rides control-plane notifs (BEGIN)."""
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @staticmethod
    def from_wire(d: Optional[Dict]) -> Optional["TraceContext"]:
        """Parse a wire dict; None (or a malformed dict) yields None —
        a peer without trace context must not break the control plane."""
        if not isinstance(d, dict):
            return None
        tid, sid = d.get("trace_id"), d.get("span_id")
        if not tid or not sid:
            return None
        return TraceContext(str(tid), str(sid))


def new_context() -> TraceContext:
    """Mint a fresh context (counted on ``obs_trace_contexts_total``)."""
    _MINTED.inc()
    return TraceContext(new_trace_id(), new_span_id())


def flow_id(trace_id: str) -> int:
    """Deterministic Chrome-trace flow-event id for a trace id: both
    processes derive the SAME id from the id that already crossed the
    wire, so the s/f pair binds with no extra coordination. 60 bits keeps
    the JSON integer exact in every double-based parser."""
    return int(trace_id[:15], 16)


def estimate_clock_offset(t0: float, t1: float, t2: float, t3: float
                          ) -> Tuple[float, float]:
    """RTT-midpoint clock-offset estimate (the NTP formula).

    ``t0``/``t3`` are the LOCAL clock at ping send / pong receive;
    ``t1``/``t2`` are the PEER clock at ping receive / pong send. Returns
    ``(offset, rtt)`` in the inputs' units, where ``offset`` estimates
    ``peer_clock - local_clock`` and ``rtt`` is the network round trip
    excluding the peer's processing time. The estimate is exact under
    symmetric path delays; an asymmetric path biases it by at most
    ``rtt / 2`` (the classic bound — tested in tests/test_trace_fleet.py).
    """
    rtt = (t3 - t0) - (t2 - t1)
    offset = ((t1 - t0) + (t2 - t3)) / 2.0
    return offset, rtt
