"""Pull-based fleet metrics federation: N worker `/metrics` → one snapshot.

``ServingMetrics.merged`` concatenates raw sample lists — exact, but only
possible for engines living in ONE process. A fleet's workers export
Prometheus text (live ``/metrics`` via :class:`~uccl_tpu.obs.export.
MetricsServer`, or ``--metrics-out`` files); this module scrapes N such
targets and builds one fleet snapshot the way Prometheus federation does:

* every scraped series is re-emitted with a ``replica="<label>"`` label,
  so per-worker views survive in the aggregate;
* **counters and histograms additionally SUM across replicas** into
  unlabeled fleet series — histogram ``_bucket``/``_sum``/``_count``
  lines with identical bucket edges add into one correct fleet
  distribution (the merge-safety property sample concatenation lacks
  across processes), and :func:`fleet_quantile` reads p50/p95 off the
  summed buckets;
* gauges (and untyped lines like the serving percentile extras) stay
  per-replica only — summing last-write-wins values is meaningless.

Targets are files or ``http://`` URLs, optionally labeled
(``label=target``); scraping is stdlib ``urllib`` — no new dependencies.

CLI (the qa/ci fleet smoke arm, docs/OBSERVABILITY.md)::

    python -m uccl_tpu.obs.aggregate --out fleet.prom \\
        prefill=/tmp/prefill.prom decode=http://127.0.0.1:9100/metrics
"""

from __future__ import annotations

import argparse
import re
import sys
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple

from uccl_tpu.obs.counters import (
    escape_label_value, fmt_value, histogram_quantile, sanitize_name,
)

__all__ = [
    "parse_prometheus", "scrape", "aggregate", "fleet_text",
    "fleet_quantile", "counter_resets", "main",
]

# one sample line: name{labels} value (labels optional; the value is
# validated by float() below, so scientific notation / inf / nan all pass)
_SAMPLE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$'
)
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

LabelKey = Tuple[Tuple[str, str], ...]


def _unescape(v: str) -> str:
    return v.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")


def parse_prometheus(text: str) -> Tuple[Dict[str, str],
                                         Dict[str, Dict[LabelKey, float]]]:
    """Prometheus text → (``{series name: type}``, ``{series name:
    {sorted-label-tuple: value}}``). Histogram component series
    (``x_bucket``/``x_sum``/``x_count``) keep their full names; the type
    map holds the FAMILY name (``x``) as ``histogram``."""
    types: Dict[str, str] = {}
    samples: Dict[str, Dict[LabelKey, float]] = {}
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln:
            continue
        if ln.startswith("#"):
            parts = ln.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3].strip()
            continue
        m = _SAMPLE.match(ln)
        if not m:
            continue  # tolerate foreign lines — a scrape must not die
        name, lbl, val = m.group(1), m.group(2), m.group(3)
        try:
            v = float(val)
        except ValueError:
            continue
        labels = tuple(sorted(
            (k, _unescape(raw)) for k, raw in _LABEL.findall(lbl or "")
        ))
        samples.setdefault(name, {})[labels] = v
    return types, samples


def _series_kind(name: str, types: Dict[str, str]) -> str:
    """Summability class of a series: its declared type, or its histogram
    family's when the name is a ``_bucket``/``_sum``/``_count`` leaf."""
    t = types.get(name)
    if t is not None:
        return t
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            if types.get(base) == "histogram":
                return "histogram"
    return "untyped"


def scrape(target: str, timeout_s: float = 5.0) -> str:
    """One target's Prometheus text: ``http(s)://`` URLs are fetched
    (append ``/metrics`` when the URL has no path), anything else is read
    as a file."""
    if target.startswith(("http://", "https://")):
        url = target
        if url.rstrip("/").count("/") < 3:  # scheme://host:port only
            url = url.rstrip("/") + "/metrics"
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            return r.read().decode()
    with open(target) as f:
        return f.read()


def _le_sort_key(le: str) -> float:
    return float("inf") if le == "+Inf" else float(le)


def _check_bucket_bounds(
        types: Dict[str, str],
        per_replica: Dict[str, Dict[LabelKey, Dict[str, float]]]) -> None:
    """Hard-fail when two replicas export the same histogram family with
    DIFFERENT bucket bounds — summing mismatched ``le`` grids yields a
    silently wrong fleet distribution (each replica's counts land in a
    grid the other never observed into), which is worse than no answer."""
    for name, by_label in per_replica.items():
        if (not name.endswith("_bucket")
                or _series_kind(name, types) != "histogram"):
            continue
        # non-le label set -> replica -> its set of le bounds
        groups: Dict[LabelKey, Dict[str, set]] = {}
        for labels, by_rep in by_label.items():
            d = dict(labels)
            le = d.pop("le", None)
            if le is None:
                continue
            key = tuple(sorted(d.items()))
            for rep in by_rep:
                groups.setdefault(key, {}).setdefault(rep, set()).add(le)
        for key, reps in groups.items():
            bounds = {rep: tuple(sorted(les, key=_le_sort_key))
                      for rep, les in reps.items()}
            if len(set(bounds.values())) > 1:
                detail = "; ".join(
                    f"{rep}: [{', '.join(b)}]"
                    for rep, b in sorted(bounds.items())
                )
                lbl = ",".join(f'{k}="{v}"' for k, v in key)
                raise ValueError(
                    f"histogram {name!r}"
                    + (f" {{{lbl}}}" if lbl else "")
                    + f" has mismatched bucket bounds across replicas — "
                      f"summing them would be silently wrong ({detail})"
                )


def counter_resets(prev: Dict, cur: Dict) -> List[Tuple]:
    """Restarted-worker detection between two :func:`aggregate`
    snapshots of the SAME targets: a cumulative series (counter or
    histogram component) can only grow within one process lifetime, so a
    per-replica DECREASE means that replica restarted and its counters
    reset to zero — naive deltas (``cur - prev``) go negative and any
    rate computed over the pair is garbage. Returns
    ``[(replica, series, labels, prev_value, cur_value), ...]``,
    empty when every cumulative series grew monotonically."""
    resets: List[Tuple] = []
    for name, by_label in cur["per_replica"].items():
        if _series_kind(name, cur["types"]) not in ("counter",
                                                    "histogram"):
            continue
        prev_by_label = prev["per_replica"].get(name, {})
        for labels, by_rep in by_label.items():
            prev_reps = prev_by_label.get(labels, {})
            for rep, v in by_rep.items():
                pv = prev_reps.get(rep)
                if pv is not None and v < pv:
                    resets.append((rep, name, labels, pv, v))
    return resets


def aggregate(scrapes: Sequence[Tuple[str, str]]) -> Dict:
    """Federate ``[(replica label, prometheus text), ...]`` into one
    snapshot dict: ``types``, ``per_replica`` (name → label-tuple →
    replica → value) and ``fleet`` (name → label-tuple → summed value,
    counters + histogram components only)."""
    types: Dict[str, str] = {}
    per_replica: Dict[str, Dict[LabelKey, Dict[str, float]]] = {}
    fleet: Dict[str, Dict[LabelKey, float]] = {}
    replicas: List[str] = []
    for label, text in scrapes:
        replicas.append(label)
        t, samples = parse_prometheus(text)
        for name, kind in t.items():
            prev = types.setdefault(name, kind)
            if prev != kind:
                raise ValueError(
                    f"series {name!r} is {prev} on one replica and "
                    f"{kind} on another — the fleet cannot sum it"
                )
        for name, by_label in samples.items():
            slot = per_replica.setdefault(name, {})
            for labels, v in by_label.items():
                slot.setdefault(labels, {})[label] = v
    _check_bucket_bounds(types, per_replica)
    for name, by_label in per_replica.items():
        if _series_kind(name, types) not in ("counter", "histogram"):
            continue
        fleet[name] = {
            labels: sum(by_rep.values())
            for labels, by_rep in by_label.items()
        }
    return {"replicas": replicas, "types": types,
            "per_replica": per_replica, "fleet": fleet}


def _line(name: str, labels: LabelKey, value: float,
          extra: Optional[Tuple[str, str]] = None) -> str:
    pairs = list(labels) + ([extra] if extra else [])
    if pairs:
        lbl = ",".join(
            f'{sanitize_name(k)}="{escape_label_value(str(v))}"'
            for k, v in sorted(pairs)
        )
        return f"{name}{{{lbl}}} {fmt_value(value)}"
    return f"{name} {fmt_value(value)}"


def fleet_text(agg: Dict) -> str:
    """The aggregate as Prometheus text: fleet-summed series first
    (unlabeled-replica), then every per-replica series relabeled with
    ``replica="<label>"``."""
    lines: List[str] = []
    for name, kind in sorted(agg["types"].items()):
        lines.append(f"# TYPE {name} {kind}")
    for name in sorted(agg["fleet"]):
        for labels, v in sorted(agg["fleet"][name].items()):
            lines.append(_line(name, labels, v))
    for name in sorted(agg["per_replica"]):
        for labels, by_rep in sorted(agg["per_replica"][name].items()):
            for rep, v in sorted(by_rep.items()):
                lines.append(_line(name, labels, v, ("replica", rep)))
    return "\n".join(lines) + "\n"


def fleet_quantile(agg: Dict, family: str, q: float,
                   replica: Optional[str] = None) -> Optional[float]:
    """Quantile estimate off a histogram family's bucket counts —
    fleet-summed by default, one replica's when ``replica`` is given.
    None when the family is absent or empty."""
    name = f"{family}_bucket"
    if replica is None:
        by_label = agg["fleet"].get(name, {})
        flat = {labels: v for labels, v in by_label.items()}
    else:
        flat = {labels: by_rep.get(replica)
                for labels, by_rep in agg["per_replica"].get(name,
                                                             {}).items()
                if by_rep.get(replica) is not None}
    buckets: List[Tuple[float, float]] = []  # (upper, cumulative count)
    for labels, v in flat.items():
        le = dict(labels).get("le")
        if le is None:
            continue
        upper = float("inf") if le == "+Inf" else float(le)
        buckets.append((upper, v))
    if not buckets:
        return None
    buckets.sort()
    uppers = [u for u, _ in buckets if u != float("inf")]
    cum = [c for _, c in buckets]
    counts = [cum[0]] + [cum[i] - cum[i - 1] for i in range(1, len(cum))]
    return histogram_quantile(uppers, counts, q)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m uccl_tpu.obs.aggregate",
        description="Federate N worker /metrics scrapes (URLs or files) "
                    "into one fleet Prometheus snapshot.",
    )
    ap.add_argument("targets", nargs="+",
                    help="label=target pairs (target: a .prom file or an "
                         "http://host:port[/metrics] URL); a bare target "
                         "gets the label r<index>")
    ap.add_argument("--out", default="",
                    help="write the fleet snapshot here (default: stdout)")
    ap.add_argument("--timeout", type=float, default=5.0,
                    help="per-target scrape timeout, seconds")
    args = ap.parse_args(argv)

    scrapes = []
    for i, spec in enumerate(args.targets):
        # label=target, but never split inside a URL scheme
        if "=" in spec and not spec.startswith(("http://", "https://")):
            label, target = spec.split("=", 1)
        else:
            label, target = f"r{i}", spec
        scrapes.append((label, scrape(target, args.timeout)))
    agg = aggregate(scrapes)
    text = fleet_text(agg)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"aggregate: {len(scrapes)} replica(s), "
              f"{sum(len(v) for v in agg['per_replica'].values())} series "
              f"-> {args.out}")
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
