"""Thread-safe, ring-buffered event tracer (the NPKit analog, host-side).

The reference ships NPKit GPU event tracing (SURVEY.md §5): fixed-size
per-channel event buffers filled by the kernels and dumped to a
Chrome-trace post-hoc. The TPU reproduction's device timeline already
belongs to ``jax.profiler`` (utils/tracing.py); what was missing is the
HOST event spine — request lifecycles, engine steps, wire windows — with
the same properties the NPKit design proves out:

* **bounded memory**: events land in a ring buffer (``deque(maxlen=...)``);
  a long-lived server can trace forever, old events fall off the back and
  are counted in ``dropped``.
* **thread-safe**: any runtime thread may record; one lock per record,
  nothing else shared.
* **zero-cost when disabled**: the module-level helpers check one bool and
  return a cached no-op context manager — no allocation, no lock, no
  timestamp read.
* **monotonic timestamps**: ``time.perf_counter`` relative to the tracer's
  epoch, in microseconds (the Chrome-trace unit), so spans from different
  threads land on one consistent timeline.

Tracks: every event carries a ``track`` label — the Chrome-trace exporter
maps each distinct label to a tid row. ``track=None`` means "this thread's
auto track" (``thread-<n>`` in first-seen order), so concurrent writers
never interleave on one row; instrumentation that owns a logical timeline
(a request, the engine loop, the wire) passes an explicit label instead.

Event phases follow the Chrome-trace vocabulary: ``X`` (complete span with
a duration — what :func:`span`/:meth:`Tracer.complete` emit), ``B``/``E``
(open/close pairs for spans that cross call boundaries), ``i`` (instant).
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import Dict, List, NamedTuple, Optional

__all__ = [
    "Event", "Tracer", "enable", "disable", "enabled", "get_tracer",
    "span", "instant", "begin", "end", "complete",
]


class Event(NamedTuple):
    """One trace event. ``ts_us`` is microseconds since the tracer's epoch;
    ``dur_us`` is only meaningful for ``ph == "X"``; ``args`` is a small
    JSON-ready dict (or None)."""

    name: str
    ph: str  # "X" | "B" | "E" | "i"
    ts_us: float
    dur_us: float
    track: str
    args: Optional[dict]


class Tracer:
    """Ring-buffered event recorder. All methods are thread-safe."""

    def __init__(self, capacity: int = 65536):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._buf: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._threads: Dict[int, str] = {}  # ident -> auto track label
        self.dropped = 0

    # -- clock ---------------------------------------------------------------
    def now_us(self) -> float:
        """Microseconds since this tracer's epoch (monotonic)."""
        return (time.perf_counter() - self._t0) * 1e6

    # -- recording -----------------------------------------------------------
    def _track(self, track: Optional[str]) -> str:
        if track is not None:
            return track
        ident = threading.get_ident()
        t = self._threads.get(ident)
        if t is None:
            # racy get-then-set is fine: both racers write the same mapping
            # only if they share an ident, which they cannot
            with self._lock:
                t = self._threads.setdefault(
                    ident, f"thread-{len(self._threads)}"
                )
        return t

    def _record(self, ev: Event) -> None:
        with self._lock:
            if len(self._buf) == self.capacity:
                self.dropped += 1
            self._buf.append(ev)

    def instant(self, name: str, track: Optional[str] = None,
                **args) -> None:
        self._record(Event(name, "i", self.now_us(), 0.0,
                           self._track(track), args or None))

    def begin(self, name: str, track: Optional[str] = None, **args) -> None:
        self._record(Event(name, "B", self.now_us(), 0.0,
                           self._track(track), args or None))

    def end(self, name: str, track: Optional[str] = None) -> None:
        self._record(Event(name, "E", self.now_us(), 0.0,
                           self._track(track), None))

    def complete(self, name: str, ts_us: float, dur_us: float,
                 track: Optional[str] = None, **args) -> None:
        """Record a finished span ("X") from explicit timestamps — the form
        instrumentation uses when ONE measured window yields spans on
        several tracks (e.g. a batched prefill covering many requests)."""
        self._record(Event(name, "X", ts_us, max(0.0, dur_us),
                           self._track(track), args or None))

    @contextlib.contextmanager
    def span(self, name: str, track: Optional[str] = None, **args):
        """Context manager: one "X" event spanning the with-block."""
        t0 = self.now_us()
        try:
            yield
        finally:
            self.complete(name, t0, self.now_us() - t0, track, **args)

    # -- readout -------------------------------------------------------------
    def events(self) -> List[Event]:
        """Snapshot of the ring (oldest first)."""
        with self._lock:
            return list(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self.dropped = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)


# -- module-level singleton (what instrumentation calls) ---------------------
_tracer: Optional[Tracer] = None  # None = disabled: the zero-cost check


class _NullSpan:
    """Reusable no-op context manager — the disabled-tracer fast path
    allocates nothing."""

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def enable(capacity: int = 65536) -> Tracer:
    """Install (or replace) the global tracer and return it."""
    global _tracer
    _tracer = Tracer(capacity)
    return _tracer


def disable() -> None:
    global _tracer
    _tracer = None


def enabled() -> bool:
    return _tracer is not None


def get_tracer() -> Optional[Tracer]:
    return _tracer


def span(name: str, track: Optional[str] = None, **args):
    """Span on the global tracer; a cached no-op when tracing is off."""
    t = _tracer
    if t is None:
        return _NULL_SPAN
    return t.span(name, track, **args)


def instant(name: str, track: Optional[str] = None, **args) -> None:
    t = _tracer
    if t is not None:
        t.instant(name, track, **args)


def begin(name: str, track: Optional[str] = None, **args) -> None:
    t = _tracer
    if t is not None:
        t.begin(name, track, **args)


def end(name: str, track: Optional[str] = None) -> None:
    t = _tracer
    if t is not None:
        t.end(name, track)


def complete(name: str, ts_us: float, dur_us: float,
             track: Optional[str] = None, **args) -> None:
    t = _tracer
    if t is not None:
        t.complete(name, ts_us, dur_us, track, **args)
