"""Thread-safe, ring-buffered event tracer (the NPKit analog, host-side).

The reference ships NPKit GPU event tracing (SURVEY.md §5): fixed-size
per-channel event buffers filled by the kernels and dumped to a
Chrome-trace post-hoc. The TPU reproduction's device timeline already
belongs to ``jax.profiler`` (utils/tracing.py); what was missing is the
HOST event spine — request lifecycles, engine steps, wire windows — with
the same properties the NPKit design proves out:

* **bounded memory**: events land in a ring buffer (``deque(maxlen=...)``);
  a long-lived server can trace forever, old events fall off the back and
  are counted in ``dropped``.
* **thread-safe**: any runtime thread may record; one lock per record,
  nothing else shared.
* **zero-cost when disabled**: the module-level helpers check one bool and
  return a cached no-op context manager — no allocation, no lock, no
  timestamp read.
* **monotonic timestamps**: ``time.perf_counter`` relative to the tracer's
  epoch, in microseconds (the Chrome-trace unit), so spans from different
  threads land on one consistent timeline.

Tracks: every event carries a ``track`` label — the Chrome-trace exporter
maps each distinct label to a tid row. ``track=None`` means "this thread's
auto track" (``thread-<n>`` in first-seen order), so concurrent writers
never interleave on one row; instrumentation that owns a logical timeline
(a request, the engine loop, the wire) passes an explicit label instead.

Event phases follow the Chrome-trace vocabulary: ``X`` (complete span with
a duration — what :func:`span`/:meth:`Tracer.complete` emit), ``B``/``E``
(open/close pairs for spans that cross call boundaries), ``i`` (instant),
and ``s``/``f`` flow start/finish pairs (:meth:`Tracer.flow`) whose shared
``fid`` binds two spans — possibly in DIFFERENT processes' traces, once
merged by ``scripts/trace_merge.py`` — into one Perfetto arrow.

Fleet clocks: each tracer records ``wall_epoch_us`` (the wall-clock time of
its monotonic ts 0) at construction, and :meth:`set_clock_offset` stores
the process's estimated wall-clock offset from the fleet's reference
process (the disagg HELLO clock exchange, obs/context.py). Both land in
the exported trace's ``otherData.clock`` so the merge tool can place N
per-process traces on one causally ordered timeline.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import Dict, List, NamedTuple, Optional

from uccl_tpu.obs.counters import counter as _counter

# Ring overflow as a REGISTRY counter, not only `Tracer.dropped`: the
# per-process attribute reaches the Chrome trace's otherData, but a
# fleet federator only sees what Prometheus text carries — this family
# makes trace loss visible across workers (obs/aggregate.py sums it).
_EVENTS_DROPPED = _counter(
    "obs_trace_events_dropped_total",
    "trace events evicted from the bounded ring before export — "
    "nonzero means the Chrome trace is missing its oldest history")

__all__ = [
    "Event", "Tracer", "enable", "disable", "enabled", "get_tracer",
    "span", "instant", "begin", "end", "complete",
    "flow_start", "flow_end", "set_clock_offset",
]


class Event(NamedTuple):
    """One trace event. ``ts_us`` is microseconds since the tracer's epoch;
    ``dur_us`` is only meaningful for ``ph == "X"``; ``args`` is a small
    JSON-ready dict (or None); ``fid`` is the flow-event id, set only for
    ``ph in ("s", "f")``."""

    name: str
    ph: str  # "X" | "B" | "E" | "i" | "s" | "f"
    ts_us: float
    dur_us: float
    track: str
    args: Optional[dict]
    fid: Optional[int] = None


class Tracer:
    """Ring-buffered event recorder. All methods are thread-safe."""

    def __init__(self, capacity: int = 65536):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._buf: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        # wall anchor first, monotonic epoch immediately after: the pair
        # relates ts 0 to the wall clock (the merge tool's per-file
        # alignment anchor); the sub-µs gap between the two reads is far
        # below the cross-process offset the anchor exists to absorb
        self.wall_epoch_us = time.time() * 1e6
        self._t0 = time.perf_counter()
        self._threads: Dict[int, str] = {}  # ident -> auto track label
        self.dropped = 0
        # this process's estimated wall-clock offset from the fleet's
        # reference process (0 until a clock exchange sets it); clock_meta
        # carries the estimate's provenance (rtt, peer, source)
        self.clock_offset_us = 0.0
        self.clock_meta: Dict = {}

    # -- clock ---------------------------------------------------------------
    def now_us(self) -> float:
        """Microseconds since this tracer's epoch (monotonic)."""
        return (time.perf_counter() - self._t0) * 1e6

    def set_clock_offset(self, offset_us: float, **meta) -> None:
        """Record this process's estimated wall-clock offset from the
        fleet's reference process (``local_wall - reference_wall``, µs).
        The merge tool subtracts it when aligning this trace's timestamps
        (docs/OBSERVABILITY.md). ``meta`` (rtt_us, peer, ...) is exported
        verbatim in the trace's ``otherData.clock``."""
        with self._lock:
            self.clock_offset_us = float(offset_us)
            self.clock_meta = dict(meta)

    # -- recording -----------------------------------------------------------
    def _track(self, track: Optional[str]) -> str:
        if track is not None:
            return track
        ident = threading.get_ident()
        t = self._threads.get(ident)
        if t is None:
            # racy get-then-set is fine: both racers write the same mapping
            # only if they share an ident, which they cannot
            with self._lock:
                t = self._threads.setdefault(
                    ident, f"thread-{len(self._threads)}"
                )
        return t

    def _record(self, ev: Event) -> None:
        with self._lock:
            if len(self._buf) == self.capacity:
                self.dropped += 1
                _EVENTS_DROPPED.inc()
            self._buf.append(ev)

    def instant(self, name: str, track: Optional[str] = None,
                **args) -> None:
        self._record(Event(name, "i", self.now_us(), 0.0,
                           self._track(track), args or None))

    def begin(self, name: str, track: Optional[str] = None, **args) -> None:
        self._record(Event(name, "B", self.now_us(), 0.0,
                           self._track(track), args or None))

    def end(self, name: str, track: Optional[str] = None) -> None:
        self._record(Event(name, "E", self.now_us(), 0.0,
                           self._track(track), None))

    def complete(self, name: str, ts_us: float, dur_us: float,
                 track: Optional[str] = None, **args) -> None:
        """Record a finished span ("X") from explicit timestamps — the form
        instrumentation uses when ONE measured window yields spans on
        several tracks (e.g. a batched prefill covering many requests)."""
        self._record(Event(name, "X", ts_us, max(0.0, dur_us),
                           self._track(track), args or None))

    def flow(self, name: str, ph: str, fid: int,
             track: Optional[str] = None,
             ts_us: Optional[float] = None) -> None:
        """Record a flow start ("s") or finish ("f") event. The s/f pair
        sharing ``fid`` (and ``name``) binds the spans enclosing their
        timestamps into one Perfetto arrow — pass ``ts_us`` INSIDE the
        span the flow should attach to (Chrome binds a flow event to the
        slice containing its timestamp on that track)."""
        if ph not in ("s", "f"):
            raise ValueError(f"flow phase must be 's' or 'f', got {ph!r}")
        self._record(Event(name, ph,
                           self.now_us() if ts_us is None else ts_us,
                           0.0, self._track(track), None, int(fid)))

    @contextlib.contextmanager
    def span(self, name: str, track: Optional[str] = None, **args):
        """Context manager: one "X" event spanning the with-block."""
        t0 = self.now_us()
        try:
            yield
        finally:
            self.complete(name, t0, self.now_us() - t0, track, **args)

    # -- readout -------------------------------------------------------------
    def events(self) -> List[Event]:
        """Snapshot of the ring (oldest first)."""
        with self._lock:
            return list(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self.dropped = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)


# -- module-level singleton (what instrumentation calls) ---------------------
_tracer: Optional[Tracer] = None  # None = disabled: the zero-cost check


class _NullSpan:
    """Reusable no-op context manager — the disabled-tracer fast path
    allocates nothing."""

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def enable(capacity: int = 65536) -> Tracer:
    """Install (or replace) the global tracer and return it."""
    global _tracer
    _tracer = Tracer(capacity)
    return _tracer


def disable() -> None:
    global _tracer
    _tracer = None


def enabled() -> bool:
    return _tracer is not None


def get_tracer() -> Optional[Tracer]:
    return _tracer


def span(name: str, track: Optional[str] = None, **args):
    """Span on the global tracer; a cached no-op when tracing is off."""
    t = _tracer
    if t is None:
        return _NULL_SPAN
    return t.span(name, track, **args)


def instant(name: str, track: Optional[str] = None, **args) -> None:
    t = _tracer
    if t is not None:
        t.instant(name, track, **args)


def begin(name: str, track: Optional[str] = None, **args) -> None:
    t = _tracer
    if t is not None:
        t.begin(name, track, **args)


def end(name: str, track: Optional[str] = None) -> None:
    t = _tracer
    if t is not None:
        t.end(name, track)


def complete(name: str, ts_us: float, dur_us: float,
             track: Optional[str] = None, **args) -> None:
    t = _tracer
    if t is not None:
        t.complete(name, ts_us, dur_us, track, **args)


def flow_start(name: str, fid: int, track: Optional[str] = None,
               ts_us: Optional[float] = None) -> None:
    t = _tracer
    if t is not None:
        t.flow(name, "s", fid, track, ts_us)


def flow_end(name: str, fid: int, track: Optional[str] = None,
             ts_us: Optional[float] = None) -> None:
    t = _tracer
    if t is not None:
        t.flow(name, "f", fid, track, ts_us)


def set_clock_offset(offset_us: float, **meta) -> None:
    """Record the process's clock offset on the global tracer (no-op when
    tracing is off — the estimate still lives on whoever measured it)."""
    t = _tracer
    if t is not None:
        t.set_clock_offset(offset_us, **meta)
