"""uccl_tpu.obs — unified observability: event tracing + telemetry registry.

The framework-wide telemetry spine (docs/OBSERVABILITY.md). Three layers,
all host-only and jax-free:

* :mod:`uccl_tpu.obs.tracer` — thread-safe ring-buffered event tracer
  (spans + instants, monotonic timestamps, per-thread tracks, bounded
  memory, zero-cost when disabled);
* :mod:`uccl_tpu.obs.counters` — labeled counter/gauge/histogram registry
  + pull sources (absorbs and supersedes ``utils.stats``'s registration
  surface); histograms are the merge-safe fleet latency surface
  (:mod:`uccl_tpu.obs.aggregate` sums N workers' exports);
* :mod:`uccl_tpu.obs.context` — cross-process trace context (trace ids
  minted at request ingress, carried in disagg control notifs, bound
  across processes by Chrome-trace flow events) + the RTT-midpoint
  clock-offset estimator behind ``scripts/trace_merge.py``;
* :mod:`uccl_tpu.obs.chrome_trace` / :mod:`uccl_tpu.obs.export` — the
  Chrome-trace/Perfetto JSON exporter and the Prometheus-text ``/metrics``
  + JSON ``/snapshot`` surfaces (file dump via ``--trace-out`` /
  ``--metrics-out`` on every CLI; live HTTP in ``serve --server``).

Instrumentation idiom::

    from uccl_tpu import obs

    obs.counter("ep_wire_fallback_total").inc(reason="vmem_budget")
    with obs.span("engine.step", track="engine", queued=3):
        ...
    obs.instant("first_token", track=req.track)

Everything is a no-op (one bool check) until ``obs.enable_tracing()`` /
``--trace-out`` turns the tracer on; counters are always live (they are
just dict adds).
"""

from uccl_tpu.obs.counters import (  # noqa: F401
    DEFAULT_LATENCY_BUCKETS, REGISTRY, CounterFamily, GaugeFamily,
    HistogramFamily, Registry, bucket_width, counter, escape_label_value,
    gauge, histogram, histogram_quantile, log_buckets, sanitize_name,
)
from uccl_tpu.obs.context import (  # noqa: F401
    TraceContext, estimate_clock_offset, flow_id, new_context,
)
from uccl_tpu.obs.tracer import (  # noqa: F401
    Event, Tracer, begin, complete, end, flow_end, flow_start, get_tracer,
    instant, set_clock_offset, span,
)
from uccl_tpu.obs.tracer import enable as enable_tracing  # noqa: F401
from uccl_tpu.obs.tracer import disable as disable_tracing  # noqa: F401
from uccl_tpu.obs.tracer import enabled as tracing_enabled  # noqa: F401
from uccl_tpu.obs.export import (  # noqa: F401
    SCHEMA_VERSION, MetricsServer, add_cli_args, dump_at_exit,
    dump_from_args, json_snapshot, prometheus_text, setup_from_args,
    write_metrics, write_trace,
)
from uccl_tpu.obs.chrome_trace import to_chrome_trace  # noqa: F401
from uccl_tpu.obs.flight import (  # noqa: F401
    FlightRecorder, TRIGGERS, install_excepthook, record_exception,
)
from uccl_tpu.obs.flight import enable as enable_flight  # noqa: F401
from uccl_tpu.obs.flight import disable as disable_flight  # noqa: F401
from uccl_tpu.obs.flight import enabled as flight_enabled  # noqa: F401
from uccl_tpu.obs.flight import get_recorder as get_flight  # noqa: F401
from uccl_tpu.obs.flight import (  # noqa: F401
    register_provider as flight_provider,
)
from uccl_tpu.obs.flight import trigger as flight_trigger  # noqa: F401
from uccl_tpu.obs.flight import (  # noqa: F401
    unregister_provider as flight_unregister,
)
from uccl_tpu.obs.slo import (  # noqa: F401
    Alert, BurnRateMonitor, Objective, serving_objectives,
)

__all__ = [
    "REGISTRY", "CounterFamily", "GaugeFamily", "HistogramFamily",
    "Registry", "counter", "gauge", "histogram", "histogram_quantile",
    "bucket_width", "log_buckets", "DEFAULT_LATENCY_BUCKETS",
    "sanitize_name", "escape_label_value", "Event", "Tracer",
    "begin", "complete", "end", "get_tracer", "instant", "span",
    "flow_start", "flow_end", "set_clock_offset",
    "TraceContext", "new_context", "flow_id", "estimate_clock_offset",
    "enable_tracing", "disable_tracing", "tracing_enabled",
    "SCHEMA_VERSION", "MetricsServer", "add_cli_args", "dump_at_exit",
    "dump_from_args", "json_snapshot", "prometheus_text", "setup_from_args",
    "write_metrics", "write_trace", "to_chrome_trace",
    "FlightRecorder", "TRIGGERS", "enable_flight", "disable_flight",
    "flight_enabled", "get_flight", "flight_trigger", "flight_provider",
    "flight_unregister", "record_exception", "install_excepthook",
    "Alert", "BurnRateMonitor", "Objective", "serving_objectives",
]
