"""Export surfaces for the obs registry + tracer.

Three consumers, one source of truth (:data:`uccl_tpu.obs.counters.REGISTRY`
and the global tracer):

* **Prometheus text** (:func:`prometheus_text`) — counters/gauges with
  labels, histogram families as merge-safe ``_bucket``/``_sum``/``_count``
  lines (identical log-spaced edges in every process, so N workers'
  exports SUM — obs/aggregate.py federates them), the live tracer's ring
  drops as ``obs_trace_dropped_total``, plus every pull source's numeric
  leaves flattened to gauges (``<source>_<path>``), all through the shared
  sanitizer. Declared-but-empty families export an unlabeled 0 sample (or
  an all-zero histogram) so dashboards and CI can assert a series exists
  before its first event.
* **JSON snapshot** (:func:`json_snapshot`) — the registry's snapshot plus
  tracer stats, schema-versioned.
* **files / HTTP** — ``--trace-out`` / ``--metrics-out`` dump files from
  any CLI (:func:`add_cli_args` / :func:`setup_from_args` /
  :func:`dump_from_args`); :class:`MetricsServer` is the live ``/metrics``
  + ``/snapshot`` surface ``serve --server`` exposes (stdlib
  ``http.server`` on a daemon thread — no new dependencies).
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional

from uccl_tpu.obs import chrome_trace, tracer as _tracer
from uccl_tpu.obs.counters import (
    REGISTRY, Registry, escape_label_value, fmt_value, sanitize_name,
)

__all__ = [
    "SCHEMA_VERSION", "prometheus_text", "json_snapshot",
    "write_metrics", "write_trace", "MetricsServer",
    "add_cli_args", "setup_from_args", "dump_from_args",
]

# version of the exported JSON shapes (snapshot + the serve/serving_bench
# summary lines that embed it); bump on breaking renames
SCHEMA_VERSION = 1


def _flatten(prefix: str, node, out: Dict[str, float]) -> None:
    """Numeric leaves of a nested source dict → flat sanitized gauge names
    (non-numeric leaves are dropped; bools are not numbers here)."""
    if isinstance(node, dict):
        for k, v in node.items():
            _flatten(f"{prefix}_{k}" if prefix else str(k), v, out)
    elif isinstance(node, (int, float)) and not isinstance(node, bool):
        out[sanitize_name(prefix)] = float(node)


def _label_str(labels: Dict[str, str]) -> str:
    return ",".join(
        f'{sanitize_name(k)}="{escape_label_value(str(v))}"'
        for k, v in sorted(labels.items())
    )


def _histogram_lines(fam, lines: List[str]) -> None:
    """One labeled histogram as Prometheus ``_bucket``/``_sum``/``_count``
    lines (cumulative buckets, inclusive ``le``, ``+Inf`` last). Identical
    bucket edges across processes make these lines SUMMABLE — the merge
    property obs/aggregate.py federates on."""
    name = sanitize_name(fam.name)
    samples = fam.hist_samples()
    if not samples:
        # declared-but-empty: an all-zero unlabeled histogram, so the
        # series is assertable before its first observation (the counter
        # families' rule, docs/OBSERVABILITY.md)
        samples = [({}, [0] * (len(fam.uppers) + 1), 0.0)]
    for labels, counts, total in samples:
        lbl = _label_str(labels)
        cum = 0
        for ub, c in zip(list(fam.uppers) + ["+Inf"], counts):
            cum += c
            le = ub if isinstance(ub, str) else _fmt(ub)
            sep = "," if lbl else ""
            lines.append(f'{name}_bucket{{{lbl}{sep}le="{le}"}} {cum}')
        suffix = f"{{{lbl}}}" if lbl else ""
        lines.append(f"{name}_sum{suffix} {_fmt(total)}")
        lines.append(f"{name}_count{suffix} {cum}")


def prometheus_text(registry: Registry = REGISTRY,
                    extra_lines: Optional[List[str]] = None) -> str:
    """The registry in Prometheus text exposition format."""
    lines: List[str] = []
    for fam in registry.families():
        name = sanitize_name(fam.name)
        if fam.help:
            lines.append(f"# HELP {name} {fam.help}")
        lines.append(f"# TYPE {name} {fam.kind}")
        if fam.kind == "histogram":
            _histogram_lines(fam, lines)
            continue
        samples = fam.samples()
        if not samples:
            # a declared family with no events yet still exports its series
            lines.append(f"{name} 0")
            continue
        for labels, value in samples:
            if labels:
                lines.append(
                    f"{name}{{{_label_str(labels)}}} {_fmt(value)}"
                )
            else:
                lines.append(f"{name} {_fmt(value)}")
    # the tracer's silent ring drops, surfaced as a counter: a truncated
    # trace is visible in every scrape, not just in the dump footer
    t = _tracer.get_tracer()
    lines.append("# TYPE obs_trace_dropped_total counter")
    lines.append(
        f"obs_trace_dropped_total {int(t.dropped) if t is not None else 0}"
    )
    for src, snap in sorted(registry.sources_snapshot().items()):
        flat: Dict[str, float] = {}
        _flatten(sanitize_name(src), snap, flat)
        for name, value in sorted(flat.items()):
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {_fmt(value)}")
    if extra_lines:
        lines.extend(extra_lines)
    return "\n".join(lines) + "\n"


_fmt = fmt_value  # shared with aggregate.py so the exporters cannot drift


def json_snapshot(registry: Registry = REGISTRY) -> Dict:
    snap = registry.snapshot()
    snap["schema_version"] = SCHEMA_VERSION
    t = _tracer.get_tracer()
    snap["tracer"] = {
        "enabled": t is not None,
        "events": len(t) if t is not None else 0,
        "dropped": t.dropped if t is not None else 0,
    }
    return snap


def write_metrics(path: str, registry: Registry = REGISTRY,
                  extra_lines: Optional[List[str]] = None) -> str:
    with open(path, "w") as f:
        f.write(prometheus_text(registry, extra_lines))
    return path


def write_trace(path: str, process_name: str = "uccl_tpu") -> str:
    return chrome_trace.dump(path, process_name=process_name)


class MetricsServer:
    """``/metrics`` (Prometheus text) + ``/snapshot`` (JSON) on a daemon
    thread. ``extra_lines_fn`` lets the owner append live series (the
    serving engine's percentile lines) to each /metrics scrape.

    ``port=0`` (the default) binds an EPHEMERAL port — the fleet-safe
    choice: two workers starting on one host with a fixed default port
    would race to bind and one would crash. The bound port is always on
    ``self.port`` and in the start log; a fleet aggregator
    (obs/aggregate.py) collects the per-worker ports from there."""

    def __init__(self, port: int = 0, registry: Registry = REGISTRY,
                 extra_lines_fn=None):
        import http.server

        reg = registry
        extra = extra_lines_fn

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib casing)
                if self.path.rstrip("/") == "/metrics":
                    body = prometheus_text(
                        reg, extra() if extra is not None else None
                    ).encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path.rstrip("/") == "/snapshot":
                    body = json.dumps(json_snapshot(reg)).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # keep scrapes off stderr
                pass

        self._httpd = http.server.ThreadingHTTPServer(
            ("127.0.0.1", port), Handler
        )
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        from uccl_tpu.utils.logging import log

        log("INFO", "metrics server listening on 127.0.0.1:%d "
            "(/metrics + /snapshot)", self.port, subsys="UTIL")

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


# -- CLI wiring (every entry point shares these three calls) -----------------
def add_cli_args(ap) -> None:
    """``--trace-out`` / ``--metrics-out`` / ``--metrics-port`` on any
    argparse parser."""
    ap.add_argument("--trace-out", default="",
                    help="write a Chrome-trace/Perfetto JSON of the run "
                         "here (enables the event tracer)")
    ap.add_argument("--metrics-out", default="",
                    help="write the Prometheus-text metrics snapshot here "
                         "at exit")
    ap.add_argument("--metrics-port", type=int, default=0,
                    help="serve live /metrics + /snapshot on this local "
                         "port for the run's duration (0 = off)")
    ap.add_argument("--flight-dir", default="",
                    help="arm the flight recorder: post-mortem bundles "
                         "(obs/flight.py) land in this directory on "
                         "trigger; the driver's excepthook is installed "
                         "so an uncaught crash dumps too")


def setup_from_args(args, capacity: int = 65536) -> None:
    """Enable the tracer when the CLI asked for a trace, and arm the
    flight recorder when it asked for a bundle directory. Call before
    the instrumented work starts."""
    if getattr(args, "trace_out", ""):
        _tracer.enable(capacity)
    if getattr(args, "flight_dir", ""):
        from uccl_tpu.obs import flight as _flight

        _flight.enable(args.flight_dir)
        _flight.install_excepthook()


_dumped_args: set = set()  # id(args) namespaces an explicit dump already ran


def dump_from_args(args, extra_lines: Optional[List[str]] = None,
                   process_name: str = "uccl_tpu") -> List[str]:
    """Write the files the CLI asked for; returns the paths written.
    ``process_name`` labels the trace's process row — per-role names
    (``uccl_tpu.prefill``/``uccl_tpu.decode``) keep merged fleet traces
    readable (scripts/trace_merge.py)."""
    written = []
    if getattr(args, "trace_out", ""):
        written.append(write_trace(args.trace_out, process_name))
    if getattr(args, "metrics_out", ""):
        written.append(write_metrics(args.metrics_out,
                                     extra_lines=extra_lines))
    _dumped_args.add(id(args))
    return written


def dump_at_exit(args) -> None:
    """Crash-safety net: dump at interpreter exit UNLESS an explicit
    :func:`dump_from_args` already ran for these args — a successful run's
    richer dump (e.g. with the serving engine's percentile lines appended)
    must not be overwritten by the bare registry state. A traced run that
    dies mid-flight still leaves its partial trace on disk, which is
    exactly when the trace is most needed."""
    import atexit

    def _fallback():
        if id(args) not in _dumped_args:
            dump_from_args(args)

    atexit.register(_fallback)
