"""Counter/gauge registry — the unified telemetry surface of the framework.

The reference's engines each keep a STATS block that a 2 s reporter thread
prints (transport.cc:1797); our :mod:`uccl_tpu.utils.stats` reproduced the
reporter but left every subsystem to invent its own numbers. This registry
is the one place those numbers now live:

* **counters** — monotonic, labeled (``wire_fallback.inc(reason="budget")``):
  bytes moved per collective, pallas→lax fallback events with recorded
  reasons, admission rejections, traced-collective tallies.
* **gauges** — last-write-wins, labeled: slot-pool high-water, occupancy,
  resolved chunk-pipeline depth.
* **histograms** — bounded log-spaced buckets, labeled
  (``ttft_hist.observe(0.012)``): the MERGE-SAFE latency surface. Sample
  lists (``ServingMetrics.ttft_s``) give exact percentiles within one
  process but cannot be combined across processes by anything but raw
  concatenation; histograms with identical bucket edges SUM — N workers'
  ``_bucket`` counts add into one fleet distribution whose quantiles are
  correct to a bucket width (the Prometheus argument, PAPERS.md).
  :mod:`uccl_tpu.obs.aggregate` is that summation.
* **sources** — pull callbacks (the old ``utils.stats`` registration
  surface, absorbed here: :class:`uccl_tpu.utils.stats.StatsRegistry` now
  delegates to this registry, so everything the stats thread printed is
  also exported through /metrics and /snapshot).

Everything is host-only, jax-free and thread-safe; reading never blocks
writers for longer than a dict copy. Export lives in
:mod:`uccl_tpu.obs.export` (Prometheus text + JSON snapshot).

Label keys/values are kept verbatim here; sanitization to the Prometheus
grammar happens once at export (:func:`sanitize_name` /
:func:`escape_label_value` — shared with serving/metrics.py so the two
exporters cannot drift).
"""

from __future__ import annotations

import bisect
import re
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "CounterFamily", "GaugeFamily", "HistogramFamily", "Registry",
    "REGISTRY", "counter", "gauge", "histogram", "sanitize_name",
    "escape_label_value", "fmt_value", "log_buckets",
    "histogram_quantile", "bucket_width", "DEFAULT_LATENCY_BUCKETS",
]

LabelKey = Tuple[Tuple[str, str], ...]  # sorted (k, v) pairs

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_NAME_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_name(name: str) -> str:
    """Coerce to the Prometheus metric-name grammar
    ``[a-zA-Z_:][a-zA-Z0-9_:]*`` (invalid chars → ``_``, digit-led names
    get a ``_`` prefix). The ONE sanitizer every exporter shares."""
    if _NAME_OK.match(name):
        return name
    name = _NAME_BAD_CHARS.sub("_", name)
    if not name or name[0].isdigit():
        name = "_" + name
    return name


def escape_label_value(v: str) -> str:
    """Escape a label value for the Prometheus text format."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def fmt_value(v: float) -> str:
    """Full-precision Prometheus sample value: integral floats as ints,
    everything else via repr (round-trip exact). Shared by export.py and
    aggregate.py — a %g-style shortening would silently corrupt large
    counters (1e7-scale byte totals) and break sum cross-checks."""
    return str(int(v)) if float(v).is_integer() else repr(float(v))


def _label_key(labels: Dict[str, str]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Family:
    """Shared labeled-sample storage for counters and gauges."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._samples: Dict[LabelKey, float] = {}
        self._lock = threading.Lock()

    def get(self, **labels) -> float:
        with self._lock:
            return self._samples.get(_label_key(labels), 0.0)

    def samples(self) -> List[Tuple[Dict[str, str], float]]:
        with self._lock:
            items = list(self._samples.items())
        return [(dict(k), v) for k, v in items]

    def total(self) -> float:
        with self._lock:
            return sum(self._samples.values())

    def clear(self) -> None:
        with self._lock:
            self._samples.clear()


class CounterFamily(_Family):
    """Monotonic counter, optionally labeled."""

    kind = "counter"

    def inc(self, by: float = 1.0, **labels) -> None:
        if by < 0:
            raise ValueError(f"counter {self.name} cannot decrease ({by})")
        k = _label_key(labels)
        with self._lock:
            self._samples[k] = self._samples.get(k, 0.0) + by


class GaugeFamily(_Family):
    """Last-write-wins gauge, optionally labeled."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._samples[_label_key(labels)] = float(value)

    def max(self, value: float, **labels) -> None:
        """Raise-only set (high-water marks)."""
        k = _label_key(labels)
        with self._lock:
            self._samples[k] = max(self._samples.get(k, value), float(value))


def log_buckets(lo: float, hi: float, per_decade: int = 4
                ) -> Tuple[float, ...]:
    """Log-spaced histogram upper bounds covering [lo, hi]: ``per_decade``
    edges per factor of 10, rounded to 6 significant digits so every
    process derives BIT-IDENTICAL edges (the merge-safety precondition —
    histograms only sum when their buckets match exactly)."""
    if not (0 < lo < hi):
        raise ValueError(f"need 0 < lo < hi, got lo={lo} hi={hi}")
    if per_decade < 1:
        raise ValueError(f"per_decade must be >= 1, got {per_decade}")
    ratio = 10.0 ** (1.0 / per_decade)
    out, v = [], float(lo)
    while v < hi * (1.0 + 1e-9):
        out.append(float(f"{v:.6g}"))
        v *= ratio
    return tuple(out)


# latency seconds, 100 µs .. ~60 s at 4 buckets/decade (24 bounded buckets
# + overflow) — wide enough for TTFT under overload, fine enough that a
# bucket-width quantile error stays under ~78% of the value (10^(1/4))
DEFAULT_LATENCY_BUCKETS = log_buckets(1e-4, 60.0, per_decade=4)


def histogram_quantile(uppers: Sequence[float], counts: Sequence[int],
                       q: float) -> Optional[float]:
    """Quantile estimate from per-bucket counts (NOT cumulative):
    ``counts`` has ``len(uppers) + 1`` entries, the last the +Inf overflow.
    Linear interpolation inside the selected bucket (the Prometheus
    ``histogram_quantile`` shape), but the RANK convention matches
    ``serving.metrics.percentile`` (numpy's 1-based linear-interpolation
    rank ``1 + (n-1)q/100``) so histogram- and sample-derived percentiles
    of the same observations land in the same order statistic's bucket —
    the cross-check serving_bench stamps and ``check_obs --fleet`` gates
    on. The overflow bucket clamps to the top edge; None when empty."""
    n = sum(counts)
    if n == 0:
        return None
    target = 1.0 + (n - 1) * q / 100.0
    cum = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        if cum + c >= target:
            lo = uppers[i - 1] if i > 0 else 0.0
            if i >= len(uppers):
                return float(uppers[-1])  # overflow: clamp to the top edge
            hi = uppers[i]
            return float(lo + (hi - lo) * (target - cum) / c)
        cum += c
    return float(uppers[-1])  # pragma: no cover (target <= n always hits)


def bucket_width(uppers: Sequence[float], value: float) -> float:
    """Width of the bucket containing ``value`` — the agreement tolerance
    when cross-checking a histogram quantile against an exact sample
    percentile (check_obs --fleet)."""
    i = bisect.bisect_left(uppers, value)
    if i >= len(uppers):
        return float("inf")  # overflow bucket is unbounded
    lo = uppers[i - 1] if i > 0 else 0.0
    return float(uppers[i] - lo)


class HistogramFamily(_Family):
    """Bounded-bucket histogram, optionally labeled. Per-label-set state
    is (per-bucket counts incl. the +Inf overflow, sum of observations) —
    exactly the Prometheus ``_bucket``/``_sum``/``_count`` content, so two
    processes' exports SUM into a correct fleet distribution where
    concatenating percentile samples cannot (obs/aggregate.py)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Optional[Sequence[float]] = None):
        super().__init__(name, help)
        ups = tuple(sorted(float(b) for b in
                           (buckets if buckets is not None
                            else DEFAULT_LATENCY_BUCKETS)))
        if not ups:
            raise ValueError(f"histogram {name} needs >= 1 bucket bound")
        self.uppers = ups

    def observe(self, value: float, **labels) -> None:
        v = float(value)
        # Prometheus le is inclusive: the first upper >= v takes the count
        i = bisect.bisect_left(self.uppers, v)
        k = _label_key(labels)
        with self._lock:
            st = self._samples.get(k)
            if st is None:
                st = self._samples[k] = [[0] * (len(self.uppers) + 1), 0.0]
            st[0][i] += 1
            st[1] += v

    # _Family's float-valued surface, reinterpreted: a histogram's scalar
    # face is its observation COUNT (so snapshot()/total() stay JSON-flat)
    def get(self, **labels) -> float:
        with self._lock:
            st = self._samples.get(_label_key(labels))
            return float(sum(st[0])) if st is not None else 0.0

    def samples(self) -> List[Tuple[Dict[str, str], float]]:
        with self._lock:
            items = [(k, sum(st[0])) for k, st in self._samples.items()]
        return [(dict(k), float(v)) for k, v in items]

    def total(self) -> float:
        with self._lock:
            return float(sum(sum(st[0]) for st in self._samples.values()))

    def hist_samples(self) -> List[Tuple[Dict[str, str], List[int], float]]:
        """[(labels, per-bucket counts incl. overflow, sum)] — the export
        surface (obs/export.py writes it as _bucket/_sum/_count lines)."""
        with self._lock:
            items = [(k, list(st[0]), st[1])
                     for k, st in self._samples.items()]
        return [(dict(k), counts, s) for k, counts, s in items]

    def state(self) -> Dict[LabelKey, Tuple[Tuple[int, ...], float]]:
        """Immutable per-label snapshot — benches diff two states to get a
        window's own distribution out of the cumulative family."""
        with self._lock:
            return {k: (tuple(st[0]), st[1])
                    for k, st in self._samples.items()}

    def quantile(self, q: float, **labels) -> Optional[float]:
        with self._lock:
            st = self._samples.get(_label_key(labels))
            counts = list(st[0]) if st is not None else None
        if counts is None:
            return None
        return histogram_quantile(self.uppers, counts, q)


class Registry:
    """Named counter/gauge/histogram families + pull sources."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}
        self._sources: Dict[str, Callable[[], Dict]] = {}

    def counter(self, name: str, help: str = "") -> CounterFamily:
        return self._family(name, help, CounterFamily)

    def gauge(self, name: str, help: str = "") -> GaugeFamily:
        return self._family(name, help, GaugeFamily)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None
                  ) -> HistogramFamily:
        """Get-or-create a histogram. Re-registering with DIFFERENT
        buckets is an error — merge safety rests on every observer of a
        family sharing one set of edges."""
        fam = self._family(name, help, HistogramFamily, buckets=buckets)
        if buckets is not None and tuple(
                sorted(float(b) for b in buckets)) != fam.uppers:
            raise ValueError(
                f"histogram {name!r} already registered with different "
                f"buckets (merge safety needs one edge set per family)"
            )
        return fam

    def _family(self, name, help, cls, **kw):
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = cls(name, help, **kw)
            elif not isinstance(fam, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {fam.kind}"
                )
            if help and not fam.help:
                fam.help = help
            return fam

    def families(self) -> List[_Family]:
        with self._lock:
            return list(self._families.values())

    # -- pull sources (the absorbed utils.stats surface) ---------------------
    def register_source(self, name: str,
                        fn: Callable[[], Dict]) -> None:
        with self._lock:
            self._sources[name] = fn

    def unregister_source(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)

    def sources_snapshot(self) -> Dict[str, Dict]:
        with self._lock:
            sources = dict(self._sources)
        out = {}
        for name, fn in sources.items():
            try:
                out[name] = fn()
            except Exception as e:  # a broken source must not kill readers
                out[name] = {"error": repr(e)}
        return out

    def snapshot(self) -> Dict:
        """JSON-ready dump: counters/gauges as {name: {"label=val,...":
        value}} (empty-label samples keyed ""), plus every source's pull."""
        metrics: Dict[str, Dict[str, float]] = {}
        for fam in self.families():
            metrics[fam.name] = {
                ",".join(f"{k}={v}" for k, v in sorted(labels.items())): val
                for labels, val in fam.samples()
            }
        return {"metrics": metrics, "sources": self.sources_snapshot()}

    def reset(self) -> None:
        """Zero every family (sources are untouched) — tests and benches
        isolating per-arm deltas."""
        for fam in self.families():
            fam.clear()


REGISTRY = Registry()


def counter(name: str, help: str = "") -> CounterFamily:
    """Get-or-create a counter on the global registry."""
    return REGISTRY.counter(name, help)


def gauge(name: str, help: str = "") -> GaugeFamily:
    """Get-or-create a gauge on the global registry."""
    return REGISTRY.gauge(name, help)


def histogram(name: str, help: str = "",
              buckets: Optional[Sequence[float]] = None) -> HistogramFamily:
    """Get-or-create a histogram on the global registry (default buckets:
    :data:`DEFAULT_LATENCY_BUCKETS` — log-spaced latency seconds)."""
    return REGISTRY.histogram(name, help, buckets)
