"""Counter/gauge registry — the unified telemetry surface of the framework.

The reference's engines each keep a STATS block that a 2 s reporter thread
prints (transport.cc:1797); our :mod:`uccl_tpu.utils.stats` reproduced the
reporter but left every subsystem to invent its own numbers. This registry
is the one place those numbers now live:

* **counters** — monotonic, labeled (``wire_fallback.inc(reason="budget")``):
  bytes moved per collective, pallas→lax fallback events with recorded
  reasons, admission rejections, traced-collective tallies.
* **gauges** — last-write-wins, labeled: slot-pool high-water, occupancy,
  resolved chunk-pipeline depth.
* **sources** — pull callbacks (the old ``utils.stats`` registration
  surface, absorbed here: :class:`uccl_tpu.utils.stats.StatsRegistry` now
  delegates to this registry, so everything the stats thread printed is
  also exported through /metrics and /snapshot).

Everything is host-only, jax-free and thread-safe; reading never blocks
writers for longer than a dict copy. Export lives in
:mod:`uccl_tpu.obs.export` (Prometheus text + JSON snapshot).

Label keys/values are kept verbatim here; sanitization to the Prometheus
grammar happens once at export (:func:`sanitize_name` /
:func:`escape_label_value` — shared with serving/metrics.py so the two
exporters cannot drift).
"""

from __future__ import annotations

import re
import threading
from typing import Callable, Dict, List, Optional, Tuple

__all__ = [
    "CounterFamily", "GaugeFamily", "Registry", "REGISTRY",
    "counter", "gauge", "sanitize_name", "escape_label_value",
]

LabelKey = Tuple[Tuple[str, str], ...]  # sorted (k, v) pairs

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_NAME_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_name(name: str) -> str:
    """Coerce to the Prometheus metric-name grammar
    ``[a-zA-Z_:][a-zA-Z0-9_:]*`` (invalid chars → ``_``, digit-led names
    get a ``_`` prefix). The ONE sanitizer every exporter shares."""
    if _NAME_OK.match(name):
        return name
    name = _NAME_BAD_CHARS.sub("_", name)
    if not name or name[0].isdigit():
        name = "_" + name
    return name


def escape_label_value(v: str) -> str:
    """Escape a label value for the Prometheus text format."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_key(labels: Dict[str, str]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Family:
    """Shared labeled-sample storage for counters and gauges."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._samples: Dict[LabelKey, float] = {}
        self._lock = threading.Lock()

    def get(self, **labels) -> float:
        with self._lock:
            return self._samples.get(_label_key(labels), 0.0)

    def samples(self) -> List[Tuple[Dict[str, str], float]]:
        with self._lock:
            items = list(self._samples.items())
        return [(dict(k), v) for k, v in items]

    def total(self) -> float:
        with self._lock:
            return sum(self._samples.values())

    def clear(self) -> None:
        with self._lock:
            self._samples.clear()


class CounterFamily(_Family):
    """Monotonic counter, optionally labeled."""

    kind = "counter"

    def inc(self, by: float = 1.0, **labels) -> None:
        if by < 0:
            raise ValueError(f"counter {self.name} cannot decrease ({by})")
        k = _label_key(labels)
        with self._lock:
            self._samples[k] = self._samples.get(k, 0.0) + by


class GaugeFamily(_Family):
    """Last-write-wins gauge, optionally labeled."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._samples[_label_key(labels)] = float(value)

    def max(self, value: float, **labels) -> None:
        """Raise-only set (high-water marks)."""
        k = _label_key(labels)
        with self._lock:
            self._samples[k] = max(self._samples.get(k, value), float(value))


class Registry:
    """Named counter/gauge families + pull sources."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}
        self._sources: Dict[str, Callable[[], Dict]] = {}

    def counter(self, name: str, help: str = "") -> CounterFamily:
        return self._family(name, help, CounterFamily)

    def gauge(self, name: str, help: str = "") -> GaugeFamily:
        return self._family(name, help, GaugeFamily)

    def _family(self, name, help, cls):
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = cls(name, help)
            elif not isinstance(fam, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {fam.kind}"
                )
            if help and not fam.help:
                fam.help = help
            return fam

    def families(self) -> List[_Family]:
        with self._lock:
            return list(self._families.values())

    # -- pull sources (the absorbed utils.stats surface) ---------------------
    def register_source(self, name: str,
                        fn: Callable[[], Dict]) -> None:
        with self._lock:
            self._sources[name] = fn

    def unregister_source(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)

    def sources_snapshot(self) -> Dict[str, Dict]:
        with self._lock:
            sources = dict(self._sources)
        out = {}
        for name, fn in sources.items():
            try:
                out[name] = fn()
            except Exception as e:  # a broken source must not kill readers
                out[name] = {"error": repr(e)}
        return out

    def snapshot(self) -> Dict:
        """JSON-ready dump: counters/gauges as {name: {"label=val,...":
        value}} (empty-label samples keyed ""), plus every source's pull."""
        metrics: Dict[str, Dict[str, float]] = {}
        for fam in self.families():
            metrics[fam.name] = {
                ",".join(f"{k}={v}" for k, v in sorted(labels.items())): val
                for labels, val in fam.samples()
            }
        return {"metrics": metrics, "sources": self.sources_snapshot()}

    def reset(self) -> None:
        """Zero every family (sources are untouched) — tests and benches
        isolating per-arm deltas."""
        for fam in self.families():
            fam.clear()


REGISTRY = Registry()


def counter(name: str, help: str = "") -> CounterFamily:
    """Get-or-create a counter on the global registry."""
    return REGISTRY.counter(name, help)


def gauge(name: str, help: str = "") -> GaugeFamily:
    """Get-or-create a gauge on the global registry."""
    return REGISTRY.gauge(name, help)
