"""Chrome-trace / Perfetto JSON export of a :class:`~uccl_tpu.obs.tracer.Tracer`.

Emits the Trace Event Format's JSON object form (``{"traceEvents": [...]}``)
with ``B``/``E``/``X``/``i`` phase events plus ``M`` metadata naming the
process and one thread row per tracer track — so ``ui.perfetto.dev`` (or
``chrome://tracing``) opens the file directly and shows each request,
the engine loop, and the wire as its own labeled row.

Format notes (the parts tools are strict about):

* timestamps (``ts``) and durations (``dur``) are microseconds;
* ``X`` events must carry a non-negative ``dur``;
* ``i`` (instant) events carry a scope ``s`` ("t" = thread-scoped);
* every ``B`` should be closed by an ``E`` on the same pid/tid —
  :func:`to_chrome_trace` closes any still-open ``B`` at the trace's end
  timestamp rather than emitting an unbalanced file;
* flow events (``s``/``f``) carry ``cat`` + ``id`` (the s/f pair binds by
  both), and the finish end binds to its enclosing slice (``bp: "e"``).

Fleet metadata: ``otherData.clock`` records the tracer's wall-clock anchor
(``wall_epoch_us`` — wall time of monotonic ts 0) and the process's
estimated offset from the fleet reference clock (``offset_us``, set by the
disagg HELLO clock exchange). ``scripts/trace_merge.py`` reads exactly
these fields to align N per-process trace files onto one timeline.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from uccl_tpu.obs.tracer import Tracer, get_tracer

__all__ = ["to_chrome_trace", "dumps", "dump"]

PID = 1  # one process: the python host runtime


def to_chrome_trace(tracer: Optional[Tracer] = None, *,
                    process_name: str = "uccl_tpu") -> dict:
    """Build the Chrome-trace JSON object for ``tracer`` (default: the
    global one). Returns ``{"traceEvents": [], ...}`` when tracing is off —
    an empty but valid trace, never an error."""
    tracer = tracer if tracer is not None else get_tracer()
    events = tracer.events() if tracer is not None else []

    tids: Dict[str, int] = {}
    out: List[dict] = [{
        "name": "process_name", "ph": "M", "pid": PID,
        "args": {"name": process_name},
    }]

    def tid(track: str) -> int:
        t = tids.get(track)
        if t is None:
            t = tids[track] = len(tids) + 1
            out.append({
                "name": "thread_name", "ph": "M", "pid": PID, "tid": t,
                "args": {"name": track},
            })
            out.append({
                "name": "thread_sort_index", "ph": "M", "pid": PID,
                "tid": t, "args": {"sort_index": t},
            })
        return t

    # track open B stacks per tid so the emitted file is always balanced
    open_b: Dict[int, List[str]] = {}
    end_ts = 0.0
    for ev in events:
        t = tid(ev.track)
        end_ts = max(end_ts, ev.ts_us + (ev.dur_us if ev.ph == "X" else 0.0))
        rec = {"name": ev.name, "ph": ev.ph, "pid": PID, "tid": t,
               "ts": round(ev.ts_us, 3)}
        if ev.ph == "X":
            rec["dur"] = round(max(0.0, ev.dur_us), 3)
        elif ev.ph == "i":
            rec["s"] = "t"
        elif ev.ph in ("s", "f"):
            rec["cat"] = "flow"
            rec["id"] = ev.fid
            if ev.ph == "f":
                rec["bp"] = "e"  # bind to the enclosing slice
        elif ev.ph == "B":
            open_b.setdefault(t, []).append(ev.name)
        elif ev.ph == "E":
            stack = open_b.get(t)
            if not stack:
                continue  # E whose B fell off the ring: drop, stay balanced
            stack.pop()
        if ev.args:
            rec["args"] = dict(ev.args)
        out.append(rec)
    # close any B still open (e.g. a span in flight at dump time)
    for t, stack in open_b.items():
        for name in reversed(stack):
            out.append({"name": name, "ph": "E", "pid": PID, "tid": t,
                        "ts": round(end_ts, 3)})

    trace = {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {"producer": "uccl_tpu.obs",
                      "process_name": process_name},
    }
    if tracer is not None:
        # per-process clock metadata — the merge tool's alignment inputs
        clock = {
            "wall_epoch_us": round(tracer.wall_epoch_us, 3),
            "offset_us": round(tracer.clock_offset_us, 3),
        }
        clock.update(tracer.clock_meta)
        trace["otherData"]["clock"] = clock
        if tracer.dropped:
            trace["otherData"]["dropped_events"] = tracer.dropped
    return trace


def dumps(tracer: Optional[Tracer] = None, **kw) -> str:
    return json.dumps(to_chrome_trace(tracer, **kw))


def dump(path: str, tracer: Optional[Tracer] = None, **kw) -> str:
    """Write the trace JSON to ``path``; returns the path."""
    with open(path, "w") as f:
        json.dump(to_chrome_trace(tracer, **kw), f)
    return path
