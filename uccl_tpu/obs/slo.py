"""Multi-window SLO burn-rate monitors over the histogram families.

The serving latency histograms (TTFT/TPOT/queue-wait/step) are
*cumulative-from-start* — correct for fleet federation, useless on their
own for "are we currently violating the objective?". This module turns
them into the ROADMAP's missing **SLO pressure signal**: a monitor
periodically snapshots each objective's histogram, and evaluation diffs
the current state against the snapshot taken one window ago, giving the
window's OWN distribution out of the cumulative family (the same
state-diff idiom the benches use).

Burn rate is the standard SRE quantity: with an objective "``target``
fraction of requests complete under ``threshold_s``", the error budget
is ``1 - target``; a window whose observed violation fraction is
``error_rate`` burns budget at ``error_rate / (1 - target)`` times the
sustainable pace. Multi-window alerting pairs a long window (sustained
pain, low burn threshold) with a short one (sudden pain, high burn
threshold) so the monitor is neither twitchy nor numb — the defaults
(1 h-equivalent policy scaled to bench time) follow the Google SRE
workbook's 14.4×/6× pairing.

Violation counting is bucket-resolved: every observation in a bucket
whose upper bound exceeds ``threshold_s`` counts as a violation. Align
``threshold_s`` with a bucket upper (the families use
``DEFAULT_LATENCY_BUCKETS``) and the count is exact; otherwise it is
conservative (the straddling bucket counts against the budget).

Alerts are *events*, not just numbers: each one lands in the tracer
(``slo_burn`` instant on the ``slo`` track), counts on
``obs_slo_burn_alerts_total{objective,window}`` (federable via
``obs/aggregate.py`` like every registry counter), and fires the flight
recorder's ``slo_burn`` trigger (deduped per objective×window×labels,
so a sustained burn produces one bundle, not one per tick).

Per-tenant objectives: set ``per="tenant"`` and the objective evaluates
each label set of the family carrying that label key independently —
one tenant burning its budget alerts with ``tenant=...`` context while
the others stay quiet.
"""

from __future__ import annotations

import bisect
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from uccl_tpu.obs import counters as _counters
from uccl_tpu.obs import flight as _flight
from uccl_tpu.obs import tracer as _tracer

_ALERTS = _counters.counter(
    "obs_slo_burn_alerts_total",
    "SLO burn-rate alerts fired, by objective and evaluation window")

# (window seconds, burn-rate threshold) — short window catches sudden
# total outage fast, long window catches sustained slow burn.
DEFAULT_WINDOWS: Tuple[Tuple[float, float], ...] = ((60.0, 14.4),
                                                    (300.0, 6.0))


@dataclass(frozen=True)
class Objective:
    """'``target`` of requests observe ``metric`` <= ``threshold_s``'."""

    name: str                 # alert label, e.g. "ttft"
    metric: str               # histogram family name
    threshold_s: float
    target: float             # e.g. 0.99 -> 1% error budget
    labels: Tuple[Tuple[str, str], ...] = ()   # fixed label-set selector
    per: Optional[str] = None  # label KEY to fan out over (e.g. "tenant")

    def __post_init__(self):
        if not 0.0 < self.target < 1.0:
            raise ValueError(f"objective {self.name}: target must be in "
                             f"(0, 1), got {self.target}")


@dataclass
class Alert:
    objective: str
    window_s: float
    burn: float
    burn_threshold: float
    error_rate: float
    budget: float
    violations: int
    total: int
    threshold_s: float
    labels: Dict[str, str] = field(default_factory=dict)

    def as_dict(self) -> Dict:
        return {
            "objective": self.objective, "window_s": self.window_s,
            "burn": self.burn, "burn_threshold": self.burn_threshold,
            "error_rate": self.error_rate, "budget": self.budget,
            "violations": self.violations, "total": self.total,
            "threshold_s": self.threshold_s, "labels": dict(self.labels),
        }


class BurnRateMonitor:
    """Snapshot-diff burn-rate evaluator. Call :meth:`sample` on a
    cadence (each engine drain loop, each bench tick); call
    :meth:`evaluate` to get the alerts the current state justifies.
    ``tick`` does both. ``clock`` is injectable so a test drives hours
    of policy in microseconds."""

    def __init__(self, objectives: Sequence[Objective],
                 windows: Sequence[Tuple[float, float]] = DEFAULT_WINDOWS,
                 *, min_count: int = 1, registry=None,
                 clock: Callable[[], float] = time.monotonic):
        if not objectives:
            raise ValueError("BurnRateMonitor needs >= 1 objective")
        self.objectives = list(objectives)
        self.windows = [(float(w), float(b)) for w, b in windows]
        if not self.windows:
            raise ValueError("BurnRateMonitor needs >= 1 window")
        self.min_count = int(min_count)
        self.registry = registry if registry is not None \
            else _counters.REGISTRY
        self.clock = clock
        self.alerts_fired = 0
        # ring of (t, {family: state}) — retained one max-window deep
        self._samples: List[Tuple[float, Dict[str, Dict]]] = []
        self._retain_s = max(w for w, _ in self.windows) * 1.25 + 1.0

    def _families(self) -> Dict[str, _counters.HistogramFamily]:
        fams = {}
        for obj in self.objectives:
            if obj.metric in fams:
                continue
            fam = next((f for f in self.registry.families()
                        if f.name == obj.metric
                        and f.kind == "histogram"), None)
            if fam is not None:
                fams[obj.metric] = fam
        return fams

    def sample(self, now: Optional[float] = None) -> None:
        """Record one snapshot of every monitored family's state."""
        t = self.clock() if now is None else now
        snap = {name: fam.state() for name, fam in self._families().items()}
        self._samples.append((t, snap))
        cutoff = t - self._retain_s
        while len(self._samples) > 1 and self._samples[0][0] < cutoff:
            self._samples.pop(0)

    def evaluate(self, now: Optional[float] = None,
                 emit: bool = True) -> List[Alert]:
        """Diff current family state against each window-aged snapshot
        and return every (objective × window × label-set) whose burn
        crossed its threshold. ``emit=False`` suppresses the tracer/
        counter/flight side effects (doctor re-evaluating a bundle)."""
        t = self.clock() if now is None else now
        fams = self._families()
        cur = {name: fam.state() for name, fam in fams.items()}
        out: List[Alert] = []
        for win_s, burn_thresh in self.windows:
            base = self._snapshot_at(t - win_s)
            if base is None:
                continue  # not enough history to judge this window yet
            for obj in self.objectives:
                fam = fams.get(obj.metric)
                if fam is None:
                    continue
                for labels, viol, total in self._window_counts(
                        obj, fam, base.get(obj.metric, {}),
                        cur.get(obj.metric, {})):
                    if total < self.min_count:
                        continue
                    budget = 1.0 - obj.target
                    error_rate = viol / total
                    burn = error_rate / budget
                    if burn < burn_thresh:
                        continue
                    a = Alert(objective=obj.name, window_s=win_s,
                              burn=burn, burn_threshold=burn_thresh,
                              error_rate=error_rate, budget=budget,
                              violations=viol, total=total,
                              threshold_s=obj.threshold_s, labels=labels)
                    out.append(a)
                    if emit:
                        self._emit(a)
        return out

    def tick(self, now: Optional[float] = None) -> List[Alert]:
        alerts = self.evaluate(now)
        self.sample(now)
        return alerts

    # -- internals -----------------------------------------------------------
    def _snapshot_at(self, t: float) -> Optional[Dict[str, Dict]]:
        """Newest snapshot taken at or before ``t`` — the window base."""
        best = None
        for st, snap in self._samples:
            if st <= t:
                best = snap
            else:
                break
        return best

    def _window_counts(self, obj: Objective, fam, base: Dict, cur: Dict):
        """Yield (labels, violations, total) per evaluated label set.
        Counter resets (restarted worker) clamp to the current state
        rather than going negative."""
        uppers = fam.uppers
        # buckets strictly above the threshold violate; Prometheus le is
        # inclusive, so a bucket with upper == threshold is compliant.
        first_bad = bisect.bisect_right(uppers, obj.threshold_s)
        sel = dict(obj.labels)
        for key, (counts, _s) in cur.items():
            labels = dict(key)
            if any(labels.get(k) != v for k, v in sel.items()):
                continue
            if obj.per is not None and obj.per not in labels:
                continue
            if obj.per is None and obj.labels == () and labels:
                # an unlabeled objective reads the unlabeled series only
                continue
            bcounts = base.get(key, (None, 0.0))[0]
            delta = [c - (bcounts[i] if bcounts is not None else 0)
                     for i, c in enumerate(counts)]
            if any(d < 0 for d in delta):   # reset: restart mid-window
                delta = list(counts)
            total = sum(delta)
            viol = sum(delta[first_bad:])
            yield labels, viol, total

    def _emit(self, a: Alert) -> None:
        self.alerts_fired += 1
        win = f"{a.window_s:g}s"
        _ALERTS.inc(objective=a.objective, window=win, **a.labels)
        t = _tracer.get_tracer()
        if t is not None:
            t.instant("slo_burn", track="slo", objective=a.objective,
                      window=win, burn=round(a.burn, 3),
                      violations=a.violations, total=a.total, **a.labels)
        lkey = ",".join(f"{k}={v}" for k, v in sorted(a.labels.items()))
        _flight.trigger("slo_burn",
                        key=f"{a.objective}:{win}:{lkey}",
                        **a.as_dict())


def serving_objectives(*, ttft_s: float = 1.0, tpot_s: float = 0.25,
                       queue_wait_s: float = 1.0, step_s: float = 1.0,
                       target: float = 0.99) -> List[Objective]:
    """The stock objective set over the serving latency families —
    thresholds are per-deployment knobs, these defaults suit the CPU
    bench scale."""
    return [
        Objective("ttft", "serving_ttft_seconds", ttft_s, target),
        Objective("tpot", "serving_tpot_seconds", tpot_s, target),
        Objective("queue_wait", "serving_queue_wait_seconds",
                  queue_wait_s, target),
        Objective("step", "serving_step_seconds", step_s, target),
    ]
