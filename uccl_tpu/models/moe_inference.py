"""MoE serving: KV-cache prefill / decode / generate with EP-sharded experts.

DeepEP's low-latency mode exists for DECODE (reference ep/README — the LL
kernels target inference token-by-token latency, ep/src/internode_ll.cu).
This module puts the framework's EP paths into the serving loop they were
built for:

* **prefill** routes the whole prompt through the throughput path
  (``impl="sort"``: one argsort + capacity-bucketed all-to-all);
* **decode** runs each autoregressive step through the packed low-latency
  path (``impl="ll"``: per-expert packed rows + recv counts, grouped
  ``lax.ragged_dot`` — no padding on wire or MXU at batch-sized token
  counts, exactly the LL regime).

Experts shard over the mesh's ``dp`` axis (contiguous ownership: expert e
lives on shard ``e // E_local``, the layout both EP paths assume); the
batch shards with them and every array carries the Buffer-convention
leading shard dim. Attention/caches reuse the dense serving math
(:mod:`uccl_tpu.models.inference`).

Parity property (tested): the same weights served on a 1-shard mesh and a
W-shard mesh generate identical tokens — sharding is semantics-free.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from uccl_tpu.utils.jaxcompat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from uccl_tpu.ep import ops as ep_ops
from uccl_tpu.models.inference import (
    KVCache, SlotKVCache, _forward_cached, _forward_slots,
    greedy_acceptance, spec_advance,
)
from uccl_tpu.models.sampling import (
    broadcast_params, sample_tokens, sample_window,
)
from uccl_tpu.utils.lru import LRUFnCache

_AXIS = "dp"  # the EP/serving axis of the mesh


@dataclass(frozen=True)
class MoEServeConfig:
    vocab: int = 512
    dim: int = 128
    n_layers: int = 2
    n_heads: int = 4
    n_kv_heads: int = 2
    head_dim: int = 32
    rope_theta: float = 10000.0
    norm_eps: float = 1e-6
    moe_experts: int = 8
    moe_topk: int = 2
    moe_ffn: int = 256
    capacity_factor: float = 8.0  # ample by default: serving wants no drops
    moe_wire: str = "lax"  # "lax" | "pallas" (device-initiated a2a wire)
    moe_chunks: int = 0  # pallas chunk-pipeline depth (0 = auto: overlap
    # prefill's expert GEMMs with the dispatch/combine wire; no-op on lax)
    wire_dtype: Optional[str] = None  # None | "fp8" | "int8": block-scale
    # quantized EP wire payloads (shared ops.quant codec; one quantize
    # round trip of error per exchange — docs/QUANT_WIRE.md)


class MoEKVCache(NamedTuple):
    k: jax.Array  # [W, L, B_loc, S_max, Hkv, D]
    v: jax.Array
    length: jax.Array  # [W] int32

    @staticmethod
    def empty(cfg: MoEServeConfig, world: int, batch_local: int,
              max_seq: int, dtype=jnp.float32) -> "MoEKVCache":
        shape = (world, cfg.n_layers, batch_local, max_seq,
                 cfg.n_kv_heads, cfg.head_dim)
        return MoEKVCache(
            jnp.zeros(shape, dtype), jnp.zeros(shape, dtype),
            jnp.zeros((world,), jnp.int32),
        )


class MoESlotCache(NamedTuple):
    """Slot-pool KV cache: one length PER SLOT (not per shard) — the
    continuous-batching engine admits/frees [w, b_loc] rows independently."""

    k: jax.Array  # [W, L, B_loc, S_max, Hkv, D]
    v: jax.Array
    lengths: jax.Array  # [W, B_loc] int32

    @staticmethod
    def empty(cfg: MoEServeConfig, world: int, batch_local: int,
              max_seq: int, dtype=jnp.float32) -> "MoESlotCache":
        shape = (world, cfg.n_layers, batch_local, max_seq,
                 cfg.n_kv_heads, cfg.head_dim)
        return MoESlotCache(
            jnp.zeros(shape, dtype), jnp.zeros(shape, dtype),
            jnp.zeros((world, batch_local), jnp.int32),
        )

    # -- slot KV export/import views (the disaggregation surface) ----------
    #
    # Mirrors inference.SlotKVCache: a flat slot id s maps to grid row
    # (w, b) = (s // B_loc, s % B_loc). Exports/imports go through host
    # numpy round-trips — np.asarray gathers a sharded pool, and the next
    # shard_mapped call re-shards the rebuilt arrays — which keeps the
    # surface correct on any mesh at the cost of a pool copy per call
    # (admission-rate work, not step-rate).

    def _loc(self, slot: int):
        b_loc = self.k.shape[2]
        return slot // b_loc, slot % b_loc

    def export_rows(self, slot: int, lo: int, hi: int):
        """Host copies of rows [lo, hi): (k, v) each [L, hi-lo, Hkv, D] —
        the same per-slot layout the dense cache exports, so the disagg
        wire format is stack-independent."""
        import numpy as np

        w, b = self._loc(slot)
        return (np.asarray(self.k[w, :, b, lo:hi]),
                np.asarray(self.v[w, :, b, lo:hi]))

    def import_rows(self, slot: int, k_rows, v_rows, *,
                    length: int) -> "MoESlotCache":
        import numpy as np

        w, b = self._loc(slot)
        n = k_rows.shape[1]
        # np.array (not asarray): device gathers come back read-only
        k = np.array(self.k)
        v = np.array(self.v)
        lengths = np.array(self.lengths)
        k[w, :, b, :n] = np.asarray(k_rows, k.dtype)
        v[w, :, b, :n] = np.asarray(v_rows, v.dtype)
        lengths[w, b] = length
        return MoESlotCache(jnp.asarray(k), jnp.asarray(v),
                            jnp.asarray(lengths))

    def copy_prefix(self, dst: int, src: int, n: int) -> "MoESlotCache":
        import numpy as np

        dw, db = self._loc(dst)
        sw, sb = self._loc(src)
        k = np.array(self.k)
        v = np.array(self.v)
        lengths = np.array(self.lengths)
        k[dw, :, db, :n] = k[sw, :, sb, :n]
        v[dw, :, db, :n] = v[sw, :, sb, :n]
        lengths[dw, db] = n
        return MoESlotCache(jnp.asarray(k), jnp.asarray(v),
                            jnp.asarray(lengths))


def init_params(key: jax.Array, cfg: MoEServeConfig) -> Dict[str, Any]:
    """Global parameter tree (experts carry the full [E, ...] axis)."""
    k = jax.random.split(key, 12)
    h, l, f, e = cfg.dim, cfg.n_layers, cfg.moe_ffn, cfg.moe_experts
    qd = cfg.n_heads * cfg.head_dim
    kvd = cfg.n_kv_heads * cfg.head_dim
    s_in, s_f = 1.0 / math.sqrt(h), 1.0 / math.sqrt(f)

    def rnd(kk, shape, scale):
        return jax.random.normal(kk, shape, jnp.float32) * scale

    return {
        "embed": rnd(k[0], (cfg.vocab, h), 0.02),
        "blocks": {
            "ln1": jnp.ones((l, h), jnp.float32),
            "ln2": jnp.ones((l, h), jnp.float32),
            "wq": rnd(k[1], (l, h, qd), s_in),
            "wk": rnd(k[2], (l, h, kvd), s_in),
            "wv": rnd(k[3], (l, h, kvd), s_in),
            "wo": rnd(k[4], (l, qd, h), 1.0 / math.sqrt(qd)),
            "router": rnd(k[5], (l, h, e), s_in),
            "we_gate": rnd(k[6], (l, e, h, f), s_in),
            "we_up": rnd(k[7], (l, e, h, f), s_in),
            "we_down": rnd(k[8], (l, e, f, h), s_f),
        },
        "final_norm": jnp.ones((h,), jnp.float32),
        "head": rnd(k[9], (h, cfg.vocab), s_in),
    }


def _moe_block(cfg: MoEServeConfig, impl: str):
    """The EP MoE FFN as an :func:`inference._forward_cached`-style ``ffn``
    hook: route over the EP axis (sorted path for prefill throughput,
    packed LL for decode), experts being the LOCAL shard."""

    def moe_block(h2, lp):
        b, sq, hd = h2.shape
        flat = h2.reshape(b * sq, hd)
        router_logits = flat.astype(jnp.float32) @ lp["router"]
        out, _, _ = ep_ops.moe_ffn(
            flat, router_logits,
            lp["we_gate"], lp["we_up"], lp["we_down"],
            _AXIS,
            num_selected=cfg.moe_topk,
            capacity_factor=cfg.capacity_factor,
            impl=impl,
            wire=cfg.moe_wire,
            n_chunks=cfg.moe_chunks,
            wire_dtype=cfg.wire_dtype,
        )
        return out.reshape(b, sq, hd)

    return moe_block


def _forward_shard(params, tokens, k_cache, v_cache, length,
                   cfg: MoEServeConfig, impl: str):
    """Per-shard cached forward: the dense serving loop
    (inference._forward_cached — attention/rope/KV updates exist exactly
    once) with the FFN block swapped for the EP MoE layer. Experts are the
    LOCAL shard ([E_local, ...]); the MoE FFN exchanges tokens over the EP
    axis (sorted path for prefill throughput, packed LL for decode)."""
    cache = KVCache(k_cache, v_cache, length)
    logits, cache = _forward_cached(
        params, tokens, cache, cfg, ffn=_moe_block(cfg, impl)
    )
    return logits, cache.k, cache.v, cache.length


def _forward_shard_slots(params, tokens, k_cache, v_cache, lengths, start,
                         write_mask, cfg: MoEServeConfig, impl: str,
                         adapters=None, adapter_ids=None):
    """Per-shard masked slot forward (the continuous-batching primitive):
    the dense slot-pool loop (inference._forward_slots — per-slot positions,
    write-gated KV, per-slot attention masks) with the EP MoE FFN. Idle
    slots' dummy tokens do route through the experts — harmless: expert
    GEMM rows are independent and the ample serving capacity_factor keeps
    the wire drop-free, so active rows are bit-identical to a batch
    without the dummies. ``adapters``/``adapter_ids`` are the per-slot
    fused LoRA tables (inference._lora_delta) — the attention projections
    are dense-stack code, so the ONE fusion point serves both stacks."""
    cache = SlotKVCache(k_cache, v_cache, lengths)
    logits, cache = _forward_slots(
        params, tokens, cache, start, write_mask, cfg,
        ffn=_moe_block(cfg, impl),
        adapters=adapters, adapter_ids=adapter_ids,
    )
    return logits, cache.k, cache.v


def _strip_shard(p):
    """Drop the per-shard leading dim shard_map hands each member:
    replicated leaves carry it LEADING ([1, ...] broadcast slice); expert
    leaves carry it at axis 1 ([L, 1, E_local, ...] — the sharded W axis
    of shard_params)."""
    blocks = {}
    for name, leaf in p["blocks"].items():
        if name in ("we_gate", "we_up", "we_down"):
            blocks[name] = leaf[:, 0]
        else:
            blocks[name] = leaf[0]
    return {
        "embed": p["embed"][0],
        "blocks": blocks,
        "final_norm": p["final_norm"][0],
        "head": p["head"][0],
    }


class MoEServer:
    """Cached jitted prefill/decode over an EP mesh (one compile per shape).

    ``mesh`` must carry a ``dp`` axis; experts and batch shard over it.
    """

    def __init__(self, cfg: MoEServeConfig, mesh: Mesh):
        self.cfg = cfg
        self.mesh = mesh
        self.world = mesh.shape[_AXIS]
        if cfg.moe_experts % self.world:
            raise ValueError(
                f"the dp world {self.world} must divide moe_experts "
                f"{cfg.moe_experts}"
            )
        # the shared LRU-bounded compiled-fn pattern (utils/lru.py): a
        # long-lived serving process sweeping shapes (prefill buckets,
        # several decode batch tiers, varying scan lengths) would
        # otherwise retain a compiled executable per shape forever
        self._fns = LRUFnCache(16)

    # -- parameter placement ------------------------------------------------
    def shard_params(self, params):
        """Place the global tree for serving, ONCE: expert [E, ...] axes
        become the Buffer-convention sharded [L, W, E_local, ...]; every
        replicated leaf gains a broadcast [W] leading dim. Done here (not
        per forward) so each decode step feeds the SAME arrays through the
        jit boundary instead of re-tiling params every token."""
        w = self.world
        e_local = self.cfg.moe_experts // w

        def place(name, leaf):
            if name in ("we_gate", "we_up", "we_down"):
                l = leaf.shape[0]
                return leaf.reshape((l, w, e_local) + leaf.shape[2:])
            return jnp.broadcast_to(leaf, (w,) + leaf.shape)

        blocks = {
            name: place(name, leaf)
            for name, leaf in params["blocks"].items()
        }
        return {
            "embed": jnp.broadcast_to(
                params["embed"], (w,) + params["embed"].shape
            ),
            "blocks": blocks,
            "final_norm": jnp.broadcast_to(
                params["final_norm"], (w,) + params["final_norm"].shape
            ),
            "head": jnp.broadcast_to(
                params["head"], (w,) + params["head"].shape
            ),
        }

    def _fn(self, key, build):
        return self._fns.get(key, build)

    @staticmethod
    def _param_specs():
        # replicated leaves shard their broadcast leading [W] dim;
        # expert leaves shard the [W] at axis 1 ([L, W, E_local, ...])
        def block_spec(name):
            if name in ("we_gate", "we_up", "we_down"):
                return P(None, _AXIS)
            return P(_AXIS)

        return {
            "embed": P(_AXIS),
            "blocks": {
                name: block_spec(name)
                for name in ("ln1", "ln2", "wq", "wk", "wv", "wo",
                             "router", "we_gate", "we_up", "we_down")
            },
            "final_norm": P(_AXIS),
            "head": P(_AXIS),
        }

    def _shard_mapped(self, f, n_in, n_out):
        """jit(shard_map(f)) with params first, then n_in P(dp) arrays."""
        return jax.jit(
            shard_map(
                f, mesh=self.mesh,
                in_specs=(self._param_specs(),) + (P(_AXIS),) * n_in,
                out_specs=(P(_AXIS),) * n_out,
                check_vma=False,
            )
        )

    def _forward(self, params, tokens, cache: MoEKVCache, impl: str):
        cfg = self.cfg

        def f(p, tok, kc, vc, ln):
            logits, nk, nv, nlen = _forward_shard(
                _strip_shard(p), tok[0], kc[0], vc[0], ln[0], cfg, impl
            )
            return logits[None], nk[None], nv[None], nlen[None]

        key = ("fwd", impl, tokens.shape, cache.k.shape)
        fn = self._fn(key, lambda: self._shard_mapped(f, 4, 4))
        logits, nk, nv, nlen = fn(params, tokens, cache.k, cache.v,
                                  cache.length)
        return logits, MoEKVCache(nk, nv, nlen)

    # -- public serving API -------------------------------------------------
    def prefill(self, params, tokens, max_seq: int):
        """tokens: [W, B_loc, S_prompt] → (last logits [W, B_loc, V], cache).
        Throughput path (sorted dispatch)."""
        w, b, s = tokens.shape
        if s > max_seq:
            raise ValueError(f"prompt {s} exceeds max_seq {max_seq}")
        cache = MoEKVCache.empty(self.cfg, w, b, max_seq)
        logits, cache = self._forward(params, tokens, cache, impl="sort")
        return logits[:, :, -1], cache

    def decode_step(self, params, token, cache: MoEKVCache,
                    impl: str = "ll"):
        """token: [W, B_loc] → (logits [W, B_loc, V], cache'). Low-latency
        packed EP path by default — the DeepEP LL decode regime."""
        logits, cache = self._forward(
            params, token[..., None], cache, impl=impl
        )
        return logits[:, :, 0], cache

    # -- slot-pool serving API (continuous batching) ------------------------
    def _check_drop_free(self):
        """The slot-serving oracle guarantee (bit-exact vs one-shot
        generate) requires the EP wire to be DROP-FREE for any routing:
        per-expert capacity = floor(cf·T·topk/E) must cover the worst case
        of all T tokens picking the same expert (topk experts are distinct
        per token, so one expert receives at most T rows) — i.e.
        cf·topk ≥ E. Otherwise idle-slot dummies and co-scheduled
        neighbors could crowd a request's tokens past capacity and change
        its output depending on who shares the batch."""
        cfg = self.cfg
        if cfg.capacity_factor * cfg.moe_topk < cfg.moe_experts:
            raise ValueError(
                f"slot serving needs a drop-free EP wire: capacity_factor "
                f"({cfg.capacity_factor}) * moe_topk ({cfg.moe_topk}) must "
                f"be >= moe_experts ({cfg.moe_experts}), or request "
                f"outputs would depend on batch composition"
            )

    def slot_cache(self, batch_local: int, max_seq: int) -> MoESlotCache:
        """The engine's fixed [W, B_loc, S_max] KV pool (per-slot lengths)."""
        self._check_drop_free()
        return MoESlotCache.empty(self.cfg, self.world, batch_local, max_seq)

    @staticmethod
    def _extra_args(sampling, adapters, adapter_ids):
        """Flatten the optional sampled/adapted arguments into the flat
        P(dp)-sharded arg list ``_shard_mapped`` expects: 5 gridded
        [W, B_loc] sampling arrays, then 4 broadcast [W, ...] adapter
        tables + gridded adapter ids. The caller grids/broadcasts; the
        shard fns strip the leading shard dim."""
        extra = []
        if sampling is not None:
            extra.extend(sampling)
        if adapters is not None:
            extra.extend([adapters["wq"][0], adapters["wq"][1],
                          adapters["wv"][0], adapters["wv"][1],
                          adapter_ids])
        return extra

    @staticmethod
    def _split_extra(rest, sampled: bool, adapted: bool):
        """Inverse of :meth:`_extra_args` inside a shard fn (leading shard
        dim stripped): returns (sampling tuple | None, adapters | None,
        adapter_ids | None)."""
        rest = list(rest)
        samp = None
        if sampled:
            samp = tuple(r[0] for r in rest[:5])
            rest = rest[5:]
        adp = ids = None
        if adapted:
            adp = {"wq": (rest[0][0], rest[1][0]),
                   "wv": (rest[2][0], rest[3][0])}
            ids = rest[4][0]
        return samp, adp, ids

    def prefill_slots(self, params, tokens, prompt_lens, new_mask,
                      cache: MoESlotCache, start=None, sampling=None,
                      adapters=None, adapter_ids=None):
        """Masked batched prefill of newly admitted slots (sorted EP path)
        — resumable, mirroring :func:`inference.prefill_slots`.

        tokens: [W, B_loc, S] right-padded prompt windows; prompt_lens (FULL
        prompt lengths)/new_mask: [W, B_loc]; start: [W, B_loc] int32
        per-slot offsets (None = zeros, the whole-prompt path). Row (w, b)
        carries prompt positions [start, start+S): KV is written only there,
        attention covers [0, start+S) — chunked prefill splits the same math
        along the sequence axis (the drop-free EP wire keeps expert rows
        independent), so resuming in chunks stays bit-exact. Slots outside
        ``new_mask`` keep their KV rows and lengths — mid-decode neighbors
        are untouched. Returns (greedy token [W, B_loc] — meaningful only
        for rows whose window reaches the prompt end — and cache with
        lengths set to min(start+S, prompt_lens) on admitted slots).

        ``sampling``: per-slot gridded [W, B_loc] ``(seeds, pos0, temp,
        top_p, top_k)`` arrays — the window-end token is then the
        lockstep-keyed sample instead of the argmax (mirrors
        :func:`inference.prefill_slots`). ``adapters``/``adapter_ids``
        fuse the per-slot LoRA delta (tables broadcast [W, ...],
        ids gridded [W, B_loc])."""
        self._check_drop_free()
        cfg = self.cfg
        s = tokens.shape[-1]
        if start is None:
            start = jnp.zeros_like(prompt_lens)
        sampled, adapted = sampling is not None, adapters is not None
        extra = self._extra_args(sampling, adapters, adapter_ids)

        def f(p, tok, lens, mask, off, kc, vc, ln, *rest):
            samp, adp, ids = self._split_extra(rest, sampled, adapted)
            logits, nk, nv = _forward_shard_slots(
                _strip_shard(p), tok[0], kc[0], vc[0], ln[0],
                off[0], mask[0], cfg, "sort",
                adapters=adp, adapter_ids=ids,
            )
            last_idx = jnp.clip(lens[0] - 1 - off[0], 0, s - 1)
            last = jnp.take_along_axis(
                logits, last_idx[:, None, None], axis=1
            )[:, 0]
            if samp is None:
                t = jnp.argmax(last, axis=-1).astype(jnp.int32)
            else:
                seeds, pos0, temp, top_p, top_k = samp
                t = sample_tokens(seeds, pos0, last, temp, top_p, top_k)
            nlen = jnp.where(
                mask[0], jnp.minimum(off[0] + s, lens[0]), ln[0]
            )
            return t[None], nk[None], nv[None], nlen[None]

        key = ("prefill_slots", tokens.shape, cache.k.shape,
               sampled, adapted)
        fn = self._fn(key, lambda: self._shard_mapped(f, 7 + len(extra), 4))
        tok, nk, nv, nlen = fn(params, tokens, prompt_lens, new_mask,
                               start, cache.k, cache.v, cache.lengths,
                               *extra)
        return tok, MoESlotCache(nk, nv, nlen)

    def verify_slots(self, params, tokens, active, cache: MoESlotCache,
                     impl: str = "sort", sampling=None, adapters=None,
                     adapter_ids=None):
        """Batched draft verification over the slot pool — the speculative-
        decoding primitive, generalizing :meth:`decode_step_slots` from one
        token to a window (mirrors :func:`inference.verify_slots`).

        tokens: [W, B_loc, S] where column 0 is each slot's last committed
        token and columns 1..S-1 its drafted continuation; active:
        [W, B_loc] bool. Greedy acceptance = longest draft prefix matching
        the window's own greedy argmaxes; active slots advance their length
        by ``n_accepted + 1``; rejected-position KV is dead by the
        chunked-prefill stale-KV argument (the next window re-writes it
        before attending). Routes through the sorted EP path by default —
        the multi-token regime, like prefill; the drop-free capacity check
        keeps every routing exact regardless of window width. Returns
        (target tokens [W, B_loc, S], n_accepted [W, B_loc], cache').

        With ``sampling`` (gridded [W, B_loc] per-slot arrays), window
        column j is sampled under the lockstep key for output position
        ``pos0 + j`` — the same acceptance rule against sampled targets
        is exact rejection sampling for deterministic drafters
        (:func:`inference.verify_slots`, docs/SERVING.md)."""
        self._check_drop_free()
        cfg = self.cfg
        sampled, adapted = sampling is not None, adapters is not None
        extra = self._extra_args(sampling, adapters, adapter_ids)

        def f(p, tok, mask, kc, vc, ln, *rest):
            samp, adp, ids = self._split_extra(rest, sampled, adapted)
            logits, nk, nv = _forward_shard_slots(
                _strip_shard(p), tok[0], kc[0], vc[0], ln[0],
                ln[0], mask[0], cfg, impl,
                adapters=adp, adapter_ids=ids,
            )
            if samp is None:
                t = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            else:
                seeds, pos0, temp, top_p, top_k = samp
                t = sample_window(seeds, pos0, logits, temp, top_p, top_k)
            n_acc = greedy_acceptance(tok[0], t)
            nlen = spec_advance(ln[0], mask[0], n_acc)
            return t[None], n_acc[None], nk[None], nv[None], nlen[None]

        key = ("verify_slots", impl, tokens.shape, cache.k.shape,
               sampled, adapted)
        fn = self._fn(key, lambda: self._shard_mapped(f, 5 + len(extra), 5))
        tok, n_acc, nk, nv, nlen = fn(params, tokens, active,
                                      cache.k, cache.v, cache.lengths,
                                      *extra)
        return tok, n_acc, MoESlotCache(nk, nv, nlen)

    def decode_step_slots(self, params, token, active, cache: MoESlotCache,
                          impl: str = "ll", sampling=None, adapters=None,
                          adapter_ids=None):
        """One masked autoregressive step over the slot pool (packed LL EP
        path by default) — the S=1 case of :meth:`verify_slots`.
        token/active: [W, B_loc]; inactive slots neither write KV nor
        advance their length. Returns (next greedy-or-sampled token
        [W, B_loc], cache')."""
        tok, _, cache = self.verify_slots(params, token[..., None], active,
                                          cache, impl=impl,
                                          sampling=sampling,
                                          adapters=adapters,
                                          adapter_ids=adapter_ids)
        return tok[..., 0], cache

    def generate(self, params, prompt, new_tokens: int, max_seq: int,
                 impl: str = "ll", sampling=None):
        """Greedy (or, with ``sampling``, stochastic) decode.
        prompt: [W, B_loc, S] → tokens [W, B_loc, N].

        ``sampling`` duck-types SamplingParams: every grid row runs under
        the request's seed with lockstep keys per output index, and the
        scalars enter as traced jit arguments — the sampled one-shot
        oracle of the MoE serving stack (mirrors ``inference.generate``).

        The decode loop is ONE jitted ``lax.scan`` over ``new_tokens``
        (cached per (impl, N, shapes) like every other program here), not
        a Python loop of per-token dispatches: under the axon tunnel each
        dispatch costs ~10 ms, which at decode's ~ms-scale step time was
        the serving bottleneck (measured 131.9 tok/s on v5e where the
        compute supports far more — PERF.md round-5 step 9). The scan
        carries (token, cache) on-device and only the final [W, B_loc, N]
        token block crosses the host boundary."""
        if new_tokens < 1:
            raise ValueError(f"new_tokens must be >= 1, got {new_tokens}")
        if prompt.shape[-1] + new_tokens > max_seq:
            raise ValueError(
                f"prompt {prompt.shape[-1]} + new {new_tokens} tokens "
                f"exceed max_seq {max_seq}: the cache would overflow"
            )
        logits, cache = self.prefill(params, prompt, max_seq)
        if sampling is None:
            tok0 = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            key = ("gen", impl, new_tokens, tok0.shape, cache.k.shape)

            def build():
                def gen(p, tok, kc, vc, ln):
                    def body(carry, _):
                        tok, kc, vc, ln = carry
                        lg, c2 = self._forward(
                            p, tok[..., None], MoEKVCache(kc, vc, ln), impl
                        )
                        ntok = jnp.argmax(lg[:, :, 0],
                                          axis=-1).astype(jnp.int32)
                        return (ntok, c2.k, c2.v, c2.length), tok

                    _, toks = lax.scan(
                        body, (tok, kc, vc, ln), None, length=new_tokens
                    )
                    return jnp.moveaxis(toks, 0, -1)  # [W, B_loc, N]

                return jax.jit(gen)

            fn = self._fn(key, build)
            return fn(params, tok0, cache.k, cache.v, cache.length)

        key = ("gen_sampled", impl, new_tokens, logits.shape, cache.k.shape)

        def build():
            def gen(p, lg0, kc, vc, ln, seed, temp, top_p, top_k):
                w, b, v = lg0.shape
                seeds, temps, tps, tks = broadcast_params(
                    w * b, seed, temp, top_p, top_k
                )

                def samp(lg, pos):
                    t = sample_tokens(
                        seeds, jnp.full((w * b,), pos, jnp.int32),
                        lg.reshape(w * b, v), temps, tps, tks,
                    )
                    return t.reshape(w, b)

                tok0 = samp(lg0, jnp.int32(0))

                def body(carry, i):
                    tok, kc, vc, ln = carry
                    lg, c2 = self._forward(
                        p, tok[..., None], MoEKVCache(kc, vc, ln), impl
                    )
                    # scan step i emits output index i and samples i+1
                    ntok = samp(lg[:, :, 0], i + 1)
                    return (ntok, c2.k, c2.v, c2.length), tok

                _, toks = lax.scan(
                    body, (tok0, kc, vc, ln),
                    jnp.arange(new_tokens, dtype=jnp.int32),
                )
                return jnp.moveaxis(toks, 0, -1)  # [W, B_loc, N]

            return jax.jit(gen)

        fn = self._fn(key, build)
        return fn(params, logits, cache.k, cache.v, cache.length,
                  jnp.int32(int(sampling.seed)),
                  jnp.float32(sampling.temperature),
                  jnp.float32(sampling.top_p), jnp.int32(sampling.top_k))
