"""Stochastic token sampling shared by both serving stacks (ISSUE 18).

ONE sampling definition, used by the slot primitives (``prefill_slots`` /
``decode_step_slots`` / ``verify_slots`` on the dense AND MoE stacks) and
by the one-shot ``generate`` oracles. The engine's sampled-exactness
contract — at equal seeds, engine output is bit-identical to the vanilla
sampled oracle — rests on this module the same way greedy exactness rests
on ``jnp.argmax``: both paths call literally the same function on
bit-identical logits rows, and every per-row computation is independent
(vmapped), so batch composition cannot change a row's sample.

**Counter-based lockstep keys.** The PRNG key for a request's output
position ``i`` is ``fold_in(PRNGKey(seed), i)`` — a pure function of
(seed, output index), independent of HOW the engine reached that index.
Chunked prefill, slot reuse, preemption/resume, and speculative verify
windows all derive the identical key for the identical position, which is
what makes spec_k>0 commits same-seed EXACT (not merely distribution-
identical) against spec_k=0: see ``docs/SERVING.md``.

**Per-row temperature 0 means greedy.** ``temp <= 0`` rows return the
argmax, so one compiled sampled program serves mixed greedy/sampled
batches with no extra mask array, and the scalar-default row is plain
greedy decode.

Masking order is top-k then top-p (nucleus over the k-survivors), the
common serving convention. Ties at the k-th logit all survive (the rule is
``z >= kth``, deterministic); nucleus keeps every token whose preceding
cumulative mass is < top_p, so the most probable token always survives.
``top_p >= 1`` disables the nucleus mask EXACTLY (every token kept), not
merely approximately: the cumulative-mass test is bypassed, so float32
rounding of the running sum to 1.0 can never mask an extreme-tail token.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def fold_key(seed, pos):
    """The lockstep key for output position ``pos`` of a request seeded
    ``seed`` — both arguments may be traced (works under jit and vmap)."""
    return jax.random.fold_in(jax.random.PRNGKey(seed), pos)


def _nucleus_keep(z, top_p):
    """Boolean top-p keep mask over one logits row ``z`` [V]: a token
    stays if the cumulative mass strictly before it (descending order) is
    ``< top_p`` — the head token always stays. ``top_p >= 1`` keeps
    EVERYTHING unconditionally: over a peaked distribution the float32
    cumulative sum rounds to exactly 1.0 before the tail, so the ``<``
    test alone would mask extreme-tail tokens even though ``top_p=1.0``
    is documented as disabling the nucleus."""
    probs = jax.nn.softmax(z)
    order = jnp.argsort(-probs)
    sp = probs[order]
    keep_sorted = (((jnp.cumsum(sp) - sp) < top_p)
                   | (top_p >= jnp.float32(1.0)))
    return jnp.zeros(z.shape, bool).at[order].set(keep_sorted)


def _sample_one(seed, pos, logits, temp, top_p, top_k):
    """Sample one token from one row. All scalars traced; logits [V].

    temp <= 0 → greedy argmax (exact, no key consumed in the result);
    otherwise temperature-scale, top-k mask, top-p nucleus mask, then
    ``jax.random.categorical`` under the position's lockstep key.
    """
    v = logits.shape[-1]
    greedy = jnp.argmax(logits).astype(jnp.int32)
    z = logits.astype(jnp.float32) / jnp.maximum(temp, jnp.float32(1e-6))
    # top-k: keep the k highest logits (k <= 0 or k >= V disables)
    sorted_desc = jnp.sort(z)[::-1]
    k_eff = jnp.where((top_k <= 0) | (top_k >= v), v, top_k)
    kth = sorted_desc[jnp.clip(k_eff - 1, 0, v - 1)]
    z = jnp.where(z >= kth, z, -jnp.inf)
    # top-p: nucleus over the k-survivors
    z = jnp.where(_nucleus_keep(z, top_p), z, -jnp.inf)
    sampled = jax.random.categorical(fold_key(seed, pos), z).astype(jnp.int32)
    return jnp.where(temp > jnp.float32(0.0), sampled, greedy)


# batched row sampling: seeds/pos/temp/top_p/top_k [B], logits [B, V] → [B]
sample_tokens = jax.vmap(_sample_one, in_axes=(0, 0, 0, 0, 0, 0))


def sample_window(seeds, pos0, logits, temp, top_p, top_k):
    """Sample a verify/prefill window: logits [B, S, V] → tokens [B, S].

    Window column ``j`` of row ``b`` uses the lockstep key for output
    position ``pos0[b] + j`` — the verify window's samples are EXACTLY the
    tokens vanilla decode would draw at those positions, which is what
    turns greedy-prefix acceptance into proper rejection sampling for a
    deterministic drafter (docs/SERVING.md)."""
    s = logits.shape[1]
    pos = pos0[:, None] + jnp.arange(s, dtype=pos0.dtype)[None, :]  # [B, S]
    over_s = jax.vmap(_sample_one, in_axes=(None, 0, 0, None, None, None))
    return jax.vmap(over_s, in_axes=(0, 0, 0, 0, 0, 0))(
        seeds, pos, logits, temp, top_p, top_k
    )


def broadcast_params(n, seed, temp, top_p, top_k):
    """Broadcast one request's scalar sampling params (traced or not) to
    per-row arrays ``(seeds, temp, top_p, top_k)`` of length ``n`` — the
    oracle-side helper: ``generate(..., sampling=...)`` runs every batch
    row under the request's seed, with the scalars entering as TRACED jit
    arguments so one compiled program serves all sampling values."""
    return (
        jnp.full((n,), seed, jnp.int32),
        jnp.full((n,), temp, jnp.float32),
        jnp.full((n,), top_p, jnp.float32),
        jnp.full((n,), top_k, jnp.int32),
    )
