"""Model families exercising every parallel axis of the framework.

The reference is a comm library consumed by Megatron/vLLM/DeepEP models
(SURVEY.md §1 L6); this framework carries its own flagship models so the
collective/EP/sequence-parallel layers are exercised end-to-end the way those
applications exercise UCCL.
"""

from uccl_tpu.models.flagship import (
    FlagshipConfig,
    init_params,
    forward,
    loss_fn,
    make_train_step,
    param_specs,
)

__all__ = [
    "FlagshipConfig",
    "init_params",
    "forward",
    "loss_fn",
    "make_train_step",
    "param_specs",
]
