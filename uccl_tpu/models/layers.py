"""Shared model building blocks (per-shard functions for shard_map code):
RMSNorm, rotary embeddings, tensor-parallel cross-entropy.
"""

from __future__ import annotations

from typing import Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

Axis = Union[str, Tuple[str, ...]]


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-6) -> jax.Array:
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * lax.rsqrt(var + eps) * weight.astype(jnp.float32)).astype(x.dtype)


def rope(
    x: jax.Array, positions: jax.Array, theta: float = 10000.0
) -> jax.Array:
    """Rotary position embedding, split-half (Llama) convention.

    x: [B, S, H, D]; positions: [S] absolute positions (callers under sequence
    sharding pass ``cp_index * S_local + arange(S_local)``), or [B, S]
    per-sequence positions (the slot-pool serving path, where every slot sits
    at its own decode offset).
    """
    d = x.shape[-1]
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    ang = positions.astype(jnp.float32)[..., None] * freqs  # [(B,) S, half]
    cos = jnp.cos(ang)[..., None, :]  # [(B,) S, 1, half]
    sin = jnp.sin(ang)[..., None, :]
    if positions.ndim == 1:
        cos, sin = cos[None], sin[None]
    x1, x2 = x[..., :half], x[..., half:]
    x32_1, x32_2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out = jnp.concatenate(
        [x32_1 * cos - x32_2 * sin, x32_2 * cos + x32_1 * sin], axis=-1
    )
    return out.astype(x.dtype)


def tp_cross_entropy(
    logits_local: jax.Array,
    targets: jax.Array,
    vocab_offset: jax.Array,
    axis: Axis,
) -> jax.Array:
    """Cross-entropy with the vocab dimension sharded over ``axis``.

    logits_local: [T, V_local] this member's vocab slice (f32 recommended);
    targets: [T] global token ids; vocab_offset: scalar start of the local
    slice. Returns per-token loss [T] (replicated across the axis).

    The log-sum-exp runs distributed: global max via pmax, then psum of the
    local exp-sums — the standard Megatron vocab-parallel loss, expressed with
    XLA collectives.
    """
    logits_local = logits_local.astype(jnp.float32)
    v_local = logits_local.shape[-1]
    # the global max is a numerical-stability shift only — no gradient flows
    # through it (and pmax has no differentiation rule)
    m = lax.pmax(lax.stop_gradient(jnp.max(logits_local, axis=-1)), axis)  # [T]
    sumexp = jnp.sum(jnp.exp(logits_local - m[:, None]), axis=-1)
    lse = m + jnp.log(lax.psum(sumexp, axis))  # [T]
    # target logit: only the owning member contributes
    local_idx = targets - vocab_offset
    in_range = (local_idx >= 0) & (local_idx < v_local)
    safe_idx = jnp.clip(local_idx, 0, v_local - 1)
    tgt_local = jnp.take_along_axis(logits_local, safe_idx[:, None], axis=-1)[:, 0]
    tgt = lax.psum(jnp.where(in_range, tgt_local, 0.0), axis)
    return lse - tgt
