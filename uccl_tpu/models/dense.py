"""Dense (Llama-family) transformer: second model family.

Same parallel machinery as the flagship MoE (pp/dp/cp/tp via one shard_map;
GPipe microbatching; vocab-parallel embedding + CE) with a dense SwiGLU MLP in
place of the expert layer — the model class the reference's Megatron/DDP
workloads train over the NCCL plugin (SURVEY.md §2.6 DP/TP/PP rows).
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax

from uccl_tpu.utils.jaxcompat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from uccl_tpu.models import flagship as _fs
from uccl_tpu.models.layers import rms_norm, rope, tp_cross_entropy
from uccl_tpu.ops.attention import attention_reference
from uccl_tpu.parallel.mesh import AXIS
from uccl_tpu.parallel.pipeline import gpipe_spmd


@dataclasses.dataclass(frozen=True)
class DenseConfig:
    vocab: int = 1024
    dim: int = 256
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 4
    head_dim: int = 32
    ffn: int = 768
    rope_theta: float = 10000.0
    norm_eps: float = 1e-6
    n_microbatches: int = 1
    remat: str = "full"  # "full" | "dots" | "mlp" | "none" (flagship.
    # _remat_wrap; "mlp" is accepted but ≡ "dots" here — the dense FFN has
    # no MOE_CHECKPOINT_NAMES tags for the save-names half to match)
    seq_mode: str = "ring"
    attn_impl: str = "auto"
    dtype: Any = jnp.float32

    # flagship-compat fields consumed by the shared attention block
    @property
    def aux_loss_weight(self):
        return 0.0

    @property
    def z_loss_weight(self):
        return 0.0


def param_specs(cfg: DenseConfig) -> Dict[str, Any]:
    return {
        "embed": P(AXIS.TP, None),
        "blocks": {
            "ln1": P(AXIS.PP, None),
            "ln2": P(AXIS.PP, None),
            "wq": P(AXIS.PP, None, AXIS.TP),
            "wk": P(AXIS.PP, None, AXIS.TP),
            "wv": P(AXIS.PP, None, AXIS.TP),
            "wo": P(AXIS.PP, AXIS.TP, None),
            "w_gate": P(AXIS.PP, None, AXIS.TP),
            "w_up": P(AXIS.PP, None, AXIS.TP),
            "w_down": P(AXIS.PP, AXIS.TP, None),
        },
        "final_norm": P(None),
        "head": P(None, AXIS.TP),
    }


def init_params(key: jax.Array, cfg: DenseConfig) -> Dict[str, Any]:
    k = jax.random.split(key, 10)
    h, l, f = cfg.dim, cfg.n_layers, cfg.ffn
    qd, kvd = cfg.n_heads * cfg.head_dim, cfg.n_kv_heads * cfg.head_dim
    s_in, s_f = 1.0 / math.sqrt(h), 1.0 / math.sqrt(f)

    def rnd(kk, shape, scale):
        return jax.random.normal(kk, shape, jnp.float32) * scale

    return {
        "embed": rnd(k[0], (cfg.vocab, h), 0.02),
        "blocks": {
            "ln1": jnp.ones((l, h), jnp.float32),
            "ln2": jnp.ones((l, h), jnp.float32),
            "wq": rnd(k[1], (l, h, qd), s_in),
            "wk": rnd(k[2], (l, h, kvd), s_in),
            "wv": rnd(k[3], (l, h, kvd), s_in),
            "wo": rnd(k[4], (l, qd, h), 1.0 / math.sqrt(qd)),
            "w_gate": rnd(k[5], (l, h, f), s_in),
            "w_up": rnd(k[6], (l, h, f), s_in),
            "w_down": rnd(k[7], (l, f, h), s_f),
        },
        "final_norm": jnp.ones((h,), jnp.float32),
        "head": rnd(k[8], (h, cfg.vocab), s_in),
    }


def shard_params(params, mesh: Mesh, cfg: DenseConfig):
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params,
        param_specs(cfg),
    )


def _layer(x, lp, cfg: DenseConfig):
    b, s_loc, h = x.shape
    attn_out = _fs._attention(rms_norm(x, lp["ln1"], cfg.norm_eps), lp, cfg)
    x = x + lax.psum(attn_out, AXIS.TP)
    h2 = rms_norm(x, lp["ln2"], cfg.norm_eps)
    act = jax.nn.silu(h2 @ lp["w_gate"].astype(h2.dtype)) * (
        h2 @ lp["w_up"].astype(h2.dtype)
    )
    mlp = act @ lp["w_down"].astype(act.dtype)
    x = x + lax.psum(mlp, AXIS.TP)
    return x, jnp.zeros((), jnp.float32)


def _per_shard_logits(params, tokens, cfg: DenseConfig):
    b_loc, s_loc = tokens.shape
    m = cfg.n_microbatches
    if b_loc % m:
        raise ValueError(f"local batch {b_loc} not divisible by {m} microbatches")
    x = _fs._embed(tokens, params["embed"], cfg).astype(cfg.dtype)
    xmb = x.reshape(m, b_loc // m, s_loc, cfg.dim)
    layer_ckpt = _fs._remat_wrap(partial(_layer, cfg=cfg), cfg.remat)

    def stage_fn(xm):
        def body(carry, lp):
            y, aux = layer_ckpt(carry, lp)
            return y, aux

        y, auxs = lax.scan(body, xm, params["blocks"])
        return y, jnp.sum(auxs)

    out, _ = gpipe_spmd(stage_fn, xmb, AXIS.PP)
    x = out.reshape(b_loc, s_loc, cfg.dim)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    return x.astype(jnp.float32) @ params["head"]


def forward(params, tokens, cfg: DenseConfig, mesh: Mesh):
    def f(p, t):
        return _per_shard_logits(p, t, cfg)

    return shard_map(
        f,
        mesh=mesh,
        in_specs=(param_specs(cfg), P(AXIS.DP, AXIS.CP)),
        out_specs=P(AXIS.DP, AXIS.CP, AXIS.TP),
        check_vma=False,
    )(params, tokens)


def loss_fn(params, tokens, targets, cfg: DenseConfig, mesh: Mesh):
    def f(p, t, y):
        logits = _per_shard_logits(p, t, cfg)
        v_loc = logits.shape[-1]
        off = lax.axis_index(AXIS.TP) * v_loc
        per_token = tp_cross_entropy(
            logits.reshape(-1, v_loc), y.reshape(-1), off, AXIS.TP
        )
        return lax.pmean(jnp.mean(per_token), AXIS.EP)

    return shard_map(
        f,
        mesh=mesh,
        in_specs=(param_specs(cfg), P(AXIS.DP, AXIS.CP), P(AXIS.DP, AXIS.CP)),
        out_specs=P(),
        check_vma=False,
    )(params, tokens, targets)


def make_train_step(cfg: DenseConfig, mesh: Mesh, learning_rate: float = 3e-4):
    import optax

    tx = optax.adamw(learning_rate, weight_decay=0.01)

    def train_step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, tokens, targets, cfg, mesh)
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, {"loss": loss}

    return train_step, tx.init


def reference_forward(params, tokens, cfg: DenseConfig):
    """Unsharded oracle."""
    b, s = tokens.shape
    x = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)
    for i in range(cfg.n_layers):
        lp = jax.tree.map(lambda a: a[i], params["blocks"])
        h = rms_norm(x, lp["ln1"], cfg.norm_eps)
        d = cfg.head_dim
        q = (h @ lp["wq"]).reshape(b, s, cfg.n_heads, d)
        kk = (h @ lp["wk"]).reshape(b, s, cfg.n_kv_heads, d)
        v = (h @ lp["wv"]).reshape(b, s, cfg.n_kv_heads, d)
        pos = jnp.arange(s)
        q, kk = rope(q, pos, cfg.rope_theta), rope(kk, pos, cfg.rope_theta)
        attn = attention_reference(q, kk, v, causal=True)
        x = x + attn.reshape(b, s, -1) @ lp["wo"]
        h2 = rms_norm(x, lp["ln2"], cfg.norm_eps)
        act = jax.nn.silu(h2 @ lp["w_gate"]) * (h2 @ lp["w_up"])
        x = x + act @ lp["w_down"]
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    return x.astype(jnp.float32) @ params["head"]
