"""Flagship model: MoE transformer exercising every parallel axis (dp/pp/cp/tp + ep).

This is the framework's analog of the applications the reference serves
(Megatron TP/PP workloads, DeepSeek-style EP MoE, long-context CP — SURVEY.md
§2.6): a Mixtral/DeepSeek-class decoder written *manually sharded* in one
``shard_map`` over the 4-axis mesh, TPU-first:

* tensor parallel (``tp``): Megatron-style column/row splits on attention and
  expert FFNs; vocab-parallel embedding + cross-entropy.
* context parallel (``cp``): ring attention (default) or Ulysses over the
  sequence dimension — the long-context layer.
* expert parallel (``dp``×``cp``): capacity-bucketed all-to-all dispatch/combine
  from :mod:`uccl_tpu.ep.ops`.
* pipeline parallel (``pp``): GPipe microbatch schedule from
  :mod:`uccl_tpu.parallel.pipeline`, layers sharded over stages.
* data parallel (``dp``): batch sharding; gradient reduction falls out of
  shard_map's transpose (replicated params → psum'd cotangents).

Everything is static-shape, scan-based, and bfloat16-on-MXU friendly.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from uccl_tpu.utils.jaxcompat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from uccl_tpu.ep import ops as ep_ops
from uccl_tpu.models.layers import rms_norm, rope, tp_cross_entropy
from uccl_tpu.ops.attention import attention_reference, ring_attention, ulysses_attention
from uccl_tpu.parallel.mesh import AXIS
from uccl_tpu.parallel.pipeline import gpipe_spmd, pipeline_train


@dataclasses.dataclass(frozen=True)
class FlagshipConfig:
    vocab: int = 1024
    dim: int = 256
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 4
    head_dim: int = 32
    moe_experts: int = 8
    moe_topk: int = 2
    moe_ffn: int = 512
    capacity_factor: float = 1.5
    rope_theta: float = 10000.0
    norm_eps: float = 1e-6
    aux_loss_weight: float = 0.01
    z_loss_weight: float = 1e-3
    n_microbatches: int = 1
    pp_schedule: str = "gpipe"  # "gpipe" (autodiff+remat) | "1f1b" (manual)
    seq_mode: str = "ring"  # "ring" | "ulysses"
    attn_impl: str = "auto"  # "auto" | "flash" | "xla": kernel when cp == 1
    moe_impl: str = "sort"  # "sort" (ragged) | "dense" (oracle) | "ll" (packed
    # grouped-GEMM path, no padded FLOPs — ep/ll.py)
    moe_wire: str = "lax"  # "lax" | "pallas" (device-initiated remote-DMA
    # a2a; forward-only — the Pallas kernel has no vjp, so keep "lax" for
    # training paths)
    moe_chunks: int = 0  # pallas-wire chunk-pipeline depth (0 = auto: the
    # EP layer picks 2 double-buffered chunks when the budget allows,
    # overlapping expert GEMMs with the dispatch/combine wire; ignored on
    # the lax wire)
    wire_fp8: bool = False
    wire_dtype: Any = None  # None | "fp8" | "int8": block-quantized EP wire
    # payloads (shared ops.quant codec; wire_fp8=True is the legacy
    # spelling of "fp8" — an explicit wire_dtype wins)
    remat: str = "full"  # "full" | "dots" | "mlp" | "none" — see _remat_wrap
    dtype: Any = jnp.float32  # activation dtype (bfloat16 on TPU)


# ---------------------------------------------------------------------------
# Parameters


def param_specs(cfg: FlagshipConfig) -> Dict[str, Any]:
    """PartitionSpec tree matching :func:`init_params`' pytree."""
    ep_axes = AXIS.EP
    return {
        "embed": P(AXIS.TP, None),
        "blocks": {
            "ln1": P(AXIS.PP, None),
            "ln2": P(AXIS.PP, None),
            "wq": P(AXIS.PP, None, AXIS.TP),
            "wk": P(AXIS.PP, None, AXIS.TP),
            "wv": P(AXIS.PP, None, AXIS.TP),
            "wo": P(AXIS.PP, AXIS.TP, None),
            "router": P(AXIS.PP, None, None),
            "we_gate": P(AXIS.PP, ep_axes, None, AXIS.TP),
            "we_up": P(AXIS.PP, ep_axes, None, AXIS.TP),
            "we_down": P(AXIS.PP, ep_axes, AXIS.TP, None),
        },
        "final_norm": P(None),
        "head": P(None, AXIS.TP),
    }


def init_params(key: jax.Array, cfg: FlagshipConfig) -> Dict[str, Any]:
    """Initialize the full (global) parameter pytree on host."""
    k = jax.random.split(key, 10)
    h, l = cfg.dim, cfg.n_layers
    qd, kvd = cfg.n_heads * cfg.head_dim, cfg.n_kv_heads * cfg.head_dim
    e, f = cfg.moe_experts, cfg.moe_ffn
    s_in = 1.0 / math.sqrt(h)
    s_ffn = 1.0 / math.sqrt(f)

    def rnd(kk, shape, scale):
        return (jax.random.normal(kk, shape, jnp.float32) * scale).astype(jnp.float32)

    return {
        "embed": rnd(k[0], (cfg.vocab, h), 0.02),
        "blocks": {
            "ln1": jnp.ones((l, h), jnp.float32),
            "ln2": jnp.ones((l, h), jnp.float32),
            "wq": rnd(k[1], (l, h, qd), s_in),
            "wk": rnd(k[2], (l, h, kvd), s_in),
            "wv": rnd(k[3], (l, h, kvd), s_in),
            "wo": rnd(k[4], (l, qd, h), 1.0 / math.sqrt(qd)),
            "router": rnd(k[5], (l, h, e), s_in),
            "we_gate": rnd(k[6], (l, e, h, f), s_in),
            "we_up": rnd(k[7], (l, e, h, f), s_in),
            "we_down": rnd(k[8], (l, e, f, h), s_ffn),
        },
        "final_norm": jnp.ones((h,), jnp.float32),
        "head": rnd(k[9], (h, cfg.vocab), s_in),
    }


def shard_params(params, mesh: Mesh, cfg: FlagshipConfig):
    specs = param_specs(cfg)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


# ---------------------------------------------------------------------------
# Per-shard forward (inside shard_map)


def _attention(x, lp, cfg: FlagshipConfig):
    """x: [B, S_loc, H_model] -> [B, S_loc, H_model] (pre-psum over tp)."""
    b, s_loc, _ = x.shape
    d = cfg.head_dim
    nh_loc = lp["wq"].shape[-1] // d
    nkv_loc = lp["wk"].shape[-1] // d
    q = (x @ lp["wq"].astype(x.dtype)).reshape(b, s_loc, nh_loc, d)
    kk = (x @ lp["wk"].astype(x.dtype)).reshape(b, s_loc, nkv_loc, d)
    v = (x @ lp["wv"].astype(x.dtype)).reshape(b, s_loc, nkv_loc, d)
    cp_idx = lax.axis_index(AXIS.CP)
    positions = cp_idx * s_loc + jnp.arange(s_loc)
    q = rope(q, positions, cfg.rope_theta)
    kk = rope(kk, positions, cfg.rope_theta)
    from uccl_tpu.ops.attention import _auto_block
    from uccl_tpu.ops.pallas_attention import _is_tpu, flash_attention

    use_flash = cfg.attn_impl == "flash" or (
        cfg.attn_impl == "auto" and _is_tpu()
    )
    impl = "flash" if use_flash else "xla"
    if lax.axis_size(AXIS.CP) == 1:
        # No context parallelism: the single-shard Pallas flash kernel is the
        # fast path on TPU (MXU blockwise online softmax in VMEM).
        blk = _auto_block(s_loc)
        if use_flash and blk >= 8:
            attn = flash_attention(q, kk, v, True, blk, blk)
        elif cfg.attn_impl == "flash":
            raise ValueError(
                f"attn_impl='flash' requested but local seq {s_loc} has no "
                f"usable block size (largest power-of-two divisor {blk} < 8)"
            )
        else:
            # Direct single-shard attention, NOT ring_attention at n=1: the
            # math is identical, but the ring's self-ppermute would poison
            # manual-schedule vjps (ppermute's transpose silently drops
            # cotangents under check_vma=False when the vjp runs inside a
            # non-uniformly-predicated cond — the sharp edge check_vma=True
            # exists to catch).
            attn = attention_reference(q, kk, v, causal=True)
    elif cfg.seq_mode == "ulysses":
        # Flash feasibility is ulysses's own call: it attends over the
        # all-to-all-gathered full sequence, not the local shard.
        attn = ulysses_attention(q, kk, v, AXIS.CP, causal=True, impl=impl)
    else:
        attn = ring_attention(q, kk, v, AXIS.CP, causal=True, impl=impl)
    out = attn.reshape(b, s_loc, nh_loc * d) @ lp["wo"].astype(x.dtype)
    return out


def _layer(x, lp, cfg: FlagshipConfig):
    """One transformer block (per-shard). x: [B, S_loc, H]. Returns (x, aux)."""
    b, s_loc, h = x.shape
    attn_out = _attention(rms_norm(x, lp["ln1"], cfg.norm_eps), lp, cfg)
    x = x + lax.psum(attn_out, AXIS.TP)

    h2 = rms_norm(x, lp["ln2"], cfg.norm_eps)
    flat = h2.reshape(b * s_loc, h)
    router_logits = flat.astype(jnp.float32) @ lp["router"]
    moe_out, aux, z = ep_ops.moe_ffn(
        flat,
        router_logits,
        lp["we_gate"].astype(flat.dtype),
        lp["we_up"].astype(flat.dtype),
        lp["we_down"].astype(flat.dtype),
        AXIS.EP,
        num_selected=cfg.moe_topk,
        capacity_factor=cfg.capacity_factor,
        wire_fp8=cfg.wire_fp8,
        wire_dtype=cfg.wire_dtype,
        impl=cfg.moe_impl,
        wire=cfg.moe_wire,
        n_chunks=cfg.moe_chunks,
    )
    x = x + lax.psum(moe_out.reshape(b, s_loc, h), AXIS.TP)
    aux_scalar = cfg.aux_loss_weight * aux + cfg.z_loss_weight * z
    return x, aux_scalar


def _remat_wrap(f, mode: str):
    """Rematerialization wrapper for one transformer block under the
    per-stage ``lax.scan``. ``"full"`` recomputes the whole block in
    backward (minimum activation liveness — the conservative default);
    ``"dots"`` saves no-batch-dim matmul outputs (projections, router,
    vocab — NOT the expert einsums, which carry the ``e`` batch dim) and
    recomputes the rest; ``"mlp"`` additionally saves the expert-GEMM
    operands/results tagged in :mod:`uccl_tpu.ep.ops` / :mod:`~.ep.ll`
    (``MOE_CHECKPOINT_NAMES``) while still rematerializing the attention
    interior — the measured v5e sweet spot (backward re-runs NO forward
    GEMM; attention is HBM-bound on its [S,S] scores, so saving them
    costs more bandwidth than recomputing them); ``"none"`` disables
    remat (the scan saves every residual — fastest when activations
    fit). Gradients are bit-identical across modes; only the
    memory/recompute schedule changes."""
    if mode == "full":
        return jax.checkpoint(f)
    if mode == "dots":
        return jax.checkpoint(
            f, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        )
    if mode == "mlp":
        pol = jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            jax.checkpoint_policies.save_only_these_names(
                *ep_ops.MOE_CHECKPOINT_NAMES
            ),
        )
        return jax.checkpoint(f, policy=pol)
    if mode == "none":
        return f
    raise ValueError(
        f"unknown remat mode {mode!r} (want full|dots|mlp|none)"
    )


def _embed(tokens, embed_local, cfg: FlagshipConfig):
    """Vocab-parallel embedding lookup. tokens: [B, S_loc] -> [B, S_loc, H]."""
    v_loc = embed_local.shape[0]
    off = lax.axis_index(AXIS.TP) * v_loc
    local = tokens - off
    in_range = (local >= 0) & (local < v_loc)
    emb = jnp.take(embed_local, jnp.clip(local, 0, v_loc - 1), axis=0)
    emb = jnp.where(in_range[..., None], emb, jnp.zeros_like(emb))
    return lax.psum(emb, AXIS.TP)


def _per_shard_logits_aux(params, tokens, cfg: FlagshipConfig):
    """tokens: [B_loc, S_loc] -> (logits [B_loc, S_loc, V_loc], aux scalar)."""
    b_loc, s_loc = tokens.shape
    m = cfg.n_microbatches
    if b_loc % m:
        raise ValueError(f"local batch {b_loc} not divisible by {m} microbatches")

    x = _embed(tokens, params["embed"], cfg).astype(cfg.dtype)
    xmb = x.reshape(m, b_loc // m, s_loc, cfg.dim)

    layer_ckpt = _remat_wrap(partial(_layer, cfg=cfg), cfg.remat)

    def stage_fn(xm):
        def body(carry, lp):
            y, aux = layer_ckpt(carry, lp)
            return y, aux

        y, auxs = lax.scan(body, xm, params["blocks"])
        return y, jnp.sum(auxs)

    out, aux = gpipe_spmd(stage_fn, xmb, AXIS.PP)
    x = out.reshape(b_loc, s_loc, cfg.dim)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = x.astype(jnp.float32) @ params["head"]
    return logits, aux


def _per_shard_loss(params, tokens, targets, cfg: FlagshipConfig):
    logits, aux = _per_shard_logits_aux(params, tokens, cfg)
    v_loc = logits.shape[-1]
    off = lax.axis_index(AXIS.TP) * v_loc
    per_token = tp_cross_entropy(
        logits.reshape(-1, v_loc), targets.reshape(-1), off, AXIS.TP
    )
    loss = jnp.mean(per_token)
    loss = lax.pmean(loss, AXIS.EP)  # average over dp×cp data shards
    # aux is summed over layers and microbatches; normalize and average
    aux_norm = lax.pmean(aux, AXIS.EP) / (cfg.n_layers * cfg.n_microbatches)
    return loss + aux_norm, loss


# ---------------------------------------------------------------------------
# Manual-schedule training path (pp_schedule="1f1b")
#
# The gpipe path above differentiates THROUGH the pipeline scan (autodiff +
# remat: simple, but residual liveness grows with M). This path runs the
# hand-written 1F1B schedule (parallel/pipeline.py pipeline_train): bounded
# activation liveness, explicit boundary gradients — the embedding backward
# runs through the returned input cotangents, the loss head through its own
# gradient outputs, and the MoE aux/z losses ride the aux channel.


def _grad_sync_specs(cfg: FlagshipConfig):
    """Per-leaf mesh axes a manual gradient must be psum'd over: every axis
    the parameter is REPLICATED on — except pp, whose reduction
    pipeline_train already performed (loss params / input cotangents) or
    which shards the leaf (stage params)."""
    def axes_of(spec):
        used = set()
        for part in spec:
            if part is None:
                continue
            if isinstance(part, (tuple, list)):
                used.update(part)
            else:
                used.add(part)
        return tuple(
            a for a in AXIS.ALL if a not in used and a != AXIS.PP
        )

    return jax.tree.map(axes_of, param_specs(cfg))


def _per_shard_manual_grads(params, tokens, targets, cfg: FlagshipConfig):
    """Per-shard (total, ce, grads) on the manual 1F1B schedule. Gradient
    semantics match autodiff-of-pmean(loss over dp×cp): per-member partials,
    psum over each leaf's replicated axes, divided by the EP world."""
    # Ring/Ulysses CP rotate KV via lax.ppermute inside the stage. XLA's
    # collective-permute has no replica groups (its source-target pairs are
    # global), so a ppermute inside the schedule's per-slot lax.cond would
    # deadlock: members on stages whose predicate is false never post their
    # sends (root-caused round 3 — the round-2 "zeroed cotangents" were this
    # same unmatched-collective unsoundness). psum/all_to_all are safe under
    # cond because their replica groups never cross pp. Fix: run the
    # schedule in uniform (select-not-branch) mode whenever cp > 1 — the
    # same discipline gpipe_spmd always uses — at ~(P-1)/M extra masked
    # compute on the ramp slots.
    uniform = lax.axis_size(AXIS.CP) != 1
    b_loc, s_loc = tokens.shape
    m = cfg.n_microbatches
    if b_loc % m:
        raise ValueError(f"local batch {b_loc} not divisible by {m} microbatches")

    def embed_fn(emb):
        return _embed(tokens, emb, cfg).astype(cfg.dtype)

    x, embed_vjp = jax.vjp(embed_fn, params["embed"])
    xmb = x.reshape(m, b_loc // m, s_loc, cfg.dim)
    tmb = targets.reshape(m, b_loc // m, s_loc)

    layer_ckpt = _remat_wrap(partial(_layer, cfg=cfg), cfg.remat)

    def stage_fn(blocks, xm):
        def body(carry, lp):
            y, aux = layer_ckpt(carry, lp)
            return y, aux

        y, auxs = lax.scan(body, xm, blocks)
        return y, jnp.sum(auxs)

    n_tok = b_loc * s_loc  # per-shard tokens: summed mb losses == local mean

    def loss_head(lp, y, tgt):
        xln = rms_norm(y, lp["final_norm"], cfg.norm_eps)
        logits = xln.astype(jnp.float32) @ lp["head"]
        v_loc = logits.shape[-1]
        off = lax.axis_index(AXIS.TP) * v_loc
        per_token = tp_cross_entropy(
            logits.reshape(-1, v_loc), tgt.reshape(-1), off, AXIS.TP
        )
        return jnp.sum(per_token) / n_tok

    loss_params = {
        "final_norm": params["final_norm"], "head": params["head"]
    }
    total, ce, dblocks, dlp, dxmb = pipeline_train(
        stage_fn, loss_head, params["blocks"], loss_params, xmb, tmb,
        AXIS.PP, aux_weight=1.0 / (cfg.n_layers * m), uniform=uniform,
    )
    (d_embed,) = embed_vjp(dxmb.reshape(b_loc, s_loc, cfg.dim).astype(x.dtype))

    grads = {
        "embed": d_embed,
        "blocks": dblocks,
        "final_norm": dlp["final_norm"],
        "head": dlp["head"],
    }
    n_ep = lax.axis_size(AXIS.EP)
    # Seed redundancy: the loss value is replicated across tp, and seeding
    # every member's vjp with 1 differentiates n_tp copies of it (the psum
    # transposes under check_vma=False mix the redundant seeds) — every
    # partial comes out exactly n_tp too large, uniformly. One global
    # divide restores d(L)/dθ; the autodiff path never sees this because
    # shard_map's own transpose accounts for replicated outputs.
    n_tp = lax.axis_size(AXIS.TP)

    def sync(g, axes):
        if axes:
            g = lax.psum(g, tuple(axes))
        return g / (n_ep * n_tp)

    grads = jax.tree.map(sync, grads, _grad_sync_specs(cfg))
    return lax.pmean(total, AXIS.EP), lax.pmean(ce, AXIS.EP), grads


def manual_loss_and_grads(params, tokens, targets, cfg: FlagshipConfig, mesh: Mesh):
    """Global (total, ce, grads) on the manual 1F1B schedule — the
    grads-producing counterpart of value_and_grad over :func:`loss_fn`."""

    def f(p, t, y):
        return _per_shard_manual_grads(p, t, y, cfg)

    return shard_map(
        f,
        mesh=mesh,
        in_specs=(param_specs(cfg), _data_spec(), _data_spec()),
        out_specs=(P(), P(), param_specs(cfg)),
        check_vma=False,
    )(params, tokens, targets)


# ---------------------------------------------------------------------------
# Host API


def _data_spec() -> P:
    return P(AXIS.DP, AXIS.CP)


def forward(params, tokens, cfg: FlagshipConfig, mesh: Mesh):
    """Global forward: tokens [B, S] -> logits [B, S, V]. Jit-compatible."""

    def f(p, t):
        logits, _ = _per_shard_logits_aux(p, t, cfg)
        return logits

    return shard_map(
        f,
        mesh=mesh,
        in_specs=(param_specs(cfg), _data_spec()),
        out_specs=P(AXIS.DP, AXIS.CP, AXIS.TP),
        check_vma=False,
    )(params, tokens)


def loss_fn(params, tokens, targets, cfg: FlagshipConfig, mesh: Mesh):
    """Global mean loss (includes aux); returns (total_loss, ce_loss)."""

    def f(p, t, y):
        return _per_shard_loss(p, t, y, cfg)

    return shard_map(
        f,
        mesh=mesh,
        in_specs=(param_specs(cfg), _data_spec(), _data_spec()),
        out_specs=(P(), P()),
        check_vma=False,
    )(params, tokens, targets)


def make_train_step(cfg: FlagshipConfig, mesh: Mesh, learning_rate: float = 3e-4):
    """Returns (train_step, init_optimizer). train_step is jittable:
    (params, opt_state, tokens, targets) -> (params, opt_state, metrics)."""
    import optax

    tx = optax.adamw(learning_rate, weight_decay=0.01)

    def total_loss(p, t, y):
        total, ce = loss_fn(p, t, y, cfg, mesh)
        return total, ce

    def train_step(params, opt_state, tokens, targets):
        if cfg.pp_schedule == "1f1b":
            total, ce, grads = manual_loss_and_grads(
                params, tokens, targets, cfg, mesh
            )
        elif cfg.pp_schedule == "gpipe":
            (total, ce), grads = jax.value_and_grad(total_loss, has_aux=True)(
                params, tokens, targets
            )
        else:
            raise ValueError(
                f"unknown pp_schedule {cfg.pp_schedule!r}: expected 'gpipe' "
                "or '1f1b'"
            )
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, {"loss": total, "ce": ce}

    def init_optimizer(params):
        return tx.init(params)

    return train_step, init_optimizer


# ---------------------------------------------------------------------------
# Dense single-device reference (oracle for tests)


def reference_forward(params, tokens, cfg: FlagshipConfig):
    """Unsharded oracle implementing the same math (no mesh, no collectives).
    Capacity is computed from the *global* token count, so results match the
    sharded model only when capacity is large enough that nothing drops."""
    b, s = tokens.shape
    x = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)

    def one_layer(x, lp):
        h = rms_norm(x, lp["ln1"], cfg.norm_eps)
        d = cfg.head_dim
        q = (h @ lp["wq"]).reshape(b, s, cfg.n_heads, d)
        kk = (h @ lp["wk"]).reshape(b, s, cfg.n_kv_heads, d)
        v = (h @ lp["wv"]).reshape(b, s, cfg.n_kv_heads, d)
        pos = jnp.arange(s)
        q, kk = rope(q, pos, cfg.rope_theta), rope(kk, pos, cfg.rope_theta)
        attn = attention_reference(q, kk, v, causal=True)
        x = x + attn.reshape(b, s, -1) @ lp["wo"]

        h2 = rms_norm(x, lp["ln2"], cfg.norm_eps)
        flat = h2.reshape(b * s, cfg.dim)
        logits = flat.astype(jnp.float32) @ lp["router"]
        cap = max(
            1,
            int(
                cfg.capacity_factor * flat.shape[0] * cfg.moe_topk / cfg.moe_experts
            ),
        )
        r = ep_ops.route_topk(logits, cfg.moe_topk, cap)
        xe = jnp.einsum("tec,th->ech", r.dispatch_mask.astype(flat.dtype), flat)
        act = jax.nn.silu(jnp.einsum("ech,ehf->ecf", xe, lp["we_gate"])) * jnp.einsum(
            "ech,ehf->ecf", xe, lp["we_up"]
        )
        ye = jnp.einsum("ecf,efh->ech", act, lp["we_down"])
        moe = jnp.einsum("tec,ech->th", r.combine_weights.astype(ye.dtype), ye)
        x = x + moe.reshape(b, s, cfg.dim)
        return x, None

    for i in range(cfg.n_layers):
        lp = jax.tree.map(lambda a: a[i], params["blocks"])
        x, _ = one_layer(x, lp)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    return x.astype(jnp.float32) @ params["head"]
