"""KV-cache inference for the dense model: prefill / decode / generate.

This is the serving-side path the reference's P2P pillar exists to feed
(KV-cache transfer between prefill and decode workers — README.md:18,
ep/bench/vllm/disagg_proxy.py): the cache produced by :func:`prefill` is a
plain pytree of arrays, registered and moved by ``uccl_tpu.p2p`` (see
examples/disagg_kv.py), then consumed by :func:`decode_step` on another worker.

Single-device (per-replica) implementation with static-shape caches so every
decode step hits the same compiled executable.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from uccl_tpu.models.dense import DenseConfig
from uccl_tpu.models.layers import rms_norm, rope


class KVCache(NamedTuple):
    k: jax.Array  # [L, B, S_max, Hkv, D]
    v: jax.Array  # [L, B, S_max, Hkv, D]
    length: jax.Array  # [] int32 — valid prefix length

    @staticmethod
    def empty(cfg: DenseConfig, batch: int, max_seq: int, dtype=jnp.float32):
        shape = (cfg.n_layers, batch, max_seq, cfg.n_kv_heads, cfg.head_dim)
        return KVCache(
            jnp.zeros(shape, dtype), jnp.zeros(shape, dtype), jnp.zeros((), jnp.int32)
        )


def _attend_cached(q, k_cache, v_cache, length, cfg: DenseConfig):
    """q: [B, Sq, H, D] at positions [length, length+Sq); cache: [B, Smax, Hkv, D].
    Masked attention over the cache prefix + the new causal block."""
    b, sq, h, d = q.shape
    smax = k_cache.shape[1]
    n_rep = h // cfg.n_kv_heads
    kk = jnp.repeat(k_cache, n_rep, axis=2)
    vv = jnp.repeat(v_cache, n_rep, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, kk, preferred_element_type=jnp.float32)
    s = s / jnp.sqrt(jnp.float32(d))
    qpos = length + jnp.arange(sq)[:, None]  # [Sq, 1]
    kpos = jnp.arange(smax)[None, :]  # [1, Smax]
    mask = kpos <= qpos  # attend to everything at or before own position
    s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(vv.dtype), vv)


def _forward_cached(
    params, tokens, cache: KVCache, cfg, ffn=None
) -> Tuple[jax.Array, KVCache]:
    """Run tokens [B, S] starting at cache.length; returns (logits, cache').

    ``ffn(h2, layer_params) -> [B, S, H]`` overrides the dense SwiGLU block
    — the hook the MoE serving loop uses so the attention/KV-cache math
    exists exactly once (uccl_tpu/models/moe_inference.py)."""
    b, s = tokens.shape
    x = jnp.take(params["embed"], tokens, axis=0).astype(cache.k.dtype)
    positions = cache.length + jnp.arange(s)
    new_k, new_v = [], []
    for i in range(cfg.n_layers):
        lp = jax.tree.map(lambda a: a[i], params["blocks"])
        h = rms_norm(x, lp["ln1"], cfg.norm_eps)
        d = cfg.head_dim
        q = (h @ lp["wq"].astype(h.dtype)).reshape(b, s, cfg.n_heads, d)
        kk = (h @ lp["wk"].astype(h.dtype)).reshape(b, s, cfg.n_kv_heads, d)
        v = (h @ lp["wv"].astype(h.dtype)).reshape(b, s, cfg.n_kv_heads, d)
        q = rope(q, positions, cfg.rope_theta)
        kk = rope(kk, positions, cfg.rope_theta)
        k_cache = lax.dynamic_update_slice(
            cache.k[i], kk, (0, cache.length, 0, 0)
        )
        v_cache = lax.dynamic_update_slice(
            cache.v[i], v, (0, cache.length, 0, 0)
        )
        new_k.append(k_cache)
        new_v.append(v_cache)
        attn = _attend_cached(q, k_cache, v_cache, cache.length, cfg)
        x = x + attn.reshape(b, s, -1) @ lp["wo"].astype(attn.dtype)
        h2 = rms_norm(x, lp["ln2"], cfg.norm_eps)
        if ffn is None:
            act = jax.nn.silu(h2 @ lp["w_gate"].astype(h2.dtype)) * (
                h2 @ lp["w_up"].astype(h2.dtype)
            )
            x = x + act @ lp["w_down"].astype(act.dtype)
        else:
            x = x + ffn(h2, lp)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = x.astype(jnp.float32) @ params["head"]
    cache = KVCache(
        jnp.stack(new_k), jnp.stack(new_v), cache.length + s
    )
    return logits, cache


def prefill(params, tokens, cfg: DenseConfig, max_seq: int) -> Tuple[jax.Array, KVCache]:
    """Process the prompt; returns (last-position logits [B, V], warm cache)."""
    if tokens.shape[1] > max_seq:
        raise ValueError(
            f"prompt length {tokens.shape[1]} exceeds max_seq {max_seq}"
        )
    cache = KVCache.empty(cfg, tokens.shape[0], max_seq, params["embed"].dtype)
    logits, cache = _forward_cached(params, tokens, cache, cfg)
    return logits[:, -1], cache


def decode_step(params, token, cache: KVCache, cfg: DenseConfig):
    """token: [B] — one autoregressive step. Returns (logits [B, V], cache')."""
    logits, cache = _forward_cached(params, token[:, None], cache, cfg)
    return logits[:, 0], cache


def decode_step_elastic(params, token, ekv, cfg: DenseConfig):
    """One autoregressive step over an :class:`uccl_tpu.ep.elastic.ElasticKVCache`.

    Same contract as :func:`decode_step`, but the KV context comes from the
    elastic cache (hot blocks in HBM, cold blocks staged from host memory),
    so decode length is bounded by host memory, not HBM. Returns
    logits [B, V]; the cache is updated in place with the new token's KV.

    The gathered context is a dense [L, B, S_blocks, Hkv, D] view whose
    first ``length`` positions are valid — position ``length`` itself is the
    partial block's next empty slot, which is exactly where
    :func:`_forward_cached` writes the new token. The dense forward path is
    therefore reused verbatim (one compiled step per block-count bucket),
    so the elastic path inherits every dense-path improvement by
    construction.
    """
    k_ctx, v_ctx, length = ekv.kv()
    view = KVCache(k_ctx, v_ctx, jnp.asarray(length, jnp.int32))
    logits, view = _forward_cached(params, token[:, None], view, cfg)
    sl = (slice(None), slice(None), slice(length, length + 1))
    ekv.append_tokens(view.k[sl], view.v[sl])
    return logits[:, 0]


# Compiled-generate cache, LRU-bounded: a long-lived server sweeping shapes
# (batch buckets, growing new_tokens, several max_seq tiers) would otherwise
# retain a compiled executable per shape forever. 16 entries comfortably
# covers a serving process's steady-state shape set while bounding the
# executable memory; evicting the least-recently-used program lets XLA
# reclaim it.
_GEN_CACHE: OrderedDict = OrderedDict()
_GEN_CACHE_CAP = 16


def generate(
    params,
    prompt: jax.Array,
    cfg: DenseConfig,
    *,
    max_new_tokens: int = 32,
    max_seq: int = 256,
) -> jax.Array:
    """Greedy generation. prompt: [B, S] → [B, max_new_tokens].

    One jitted program (prefill + a decode ``lax.scan``), cached per
    (cfg, shapes, N): params enter as jit ARGUMENTS, so repeat calls at
    the same shapes are pure cache hits. The old form ran the scan
    eagerly — params were baked into the staged scan as constants, every
    call re-traced, and the constants could exceed a remote-compile
    request limit (PERF.md round-5 tunnel lessons)."""
    if prompt.shape[1] + max_new_tokens > max_seq:
        raise ValueError(
            f"prompt {prompt.shape[1]} + new {max_new_tokens} tokens exceed "
            f"max_seq {max_seq}: the cache would overflow"
        )
    key = (repr(cfg), prompt.shape, max_new_tokens, max_seq)
    fn = _GEN_CACHE.get(key)
    if fn is not None:
        _GEN_CACHE.move_to_end(key)  # LRU: a hit refreshes recency
    if fn is None:

        def run(p, t):
            logits, cache = prefill(p, t, cfg, max_seq)

            def body(carry, _):
                logits, cache = carry
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                logits, cache = decode_step(p, tok, cache, cfg)
                return (logits, cache), tok

            (_, _), toks = lax.scan(
                body, (logits, cache), None, length=max_new_tokens
            )
            return toks.T  # [B, T]

        fn = _GEN_CACHE[key] = jax.jit(run)
        while len(_GEN_CACHE) > _GEN_CACHE_CAP:
            _GEN_CACHE.popitem(last=False)
    return fn(params, prompt)
