"""KV-cache inference for the dense model: prefill / decode / generate.

This is the serving-side path the reference's P2P pillar exists to feed
(KV-cache transfer between prefill and decode workers — README.md:18,
ep/bench/vllm/disagg_proxy.py): the cache produced by :func:`prefill` is a
plain pytree of arrays, registered and moved by ``uccl_tpu.p2p`` (see
examples/disagg_kv.py), then consumed by :func:`decode_step` on another worker.

Single-device (per-replica) implementation with static-shape caches so every
decode step hits the same compiled executable.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from uccl_tpu.models.dense import DenseConfig
from uccl_tpu.models.layers import rms_norm, rope
from uccl_tpu.models.sampling import (
    broadcast_params, sample_tokens, sample_window,
)
from uccl_tpu.utils.lru import LRUFnCache


class KVCache(NamedTuple):
    k: jax.Array  # [L, B, S_max, Hkv, D]
    v: jax.Array  # [L, B, S_max, Hkv, D]
    length: jax.Array  # [] int32 — valid prefix length

    @staticmethod
    def empty(cfg: DenseConfig, batch: int, max_seq: int, dtype=jnp.float32):
        shape = (cfg.n_layers, batch, max_seq, cfg.n_kv_heads, cfg.head_dim)
        return KVCache(
            jnp.zeros(shape, dtype), jnp.zeros(shape, dtype), jnp.zeros((), jnp.int32)
        )


def _attend_cached(q, k_cache, v_cache, length, cfg: DenseConfig):
    """q: [B, Sq, H, D] at positions [length, length+Sq); cache: [B, Smax, Hkv, D].
    Masked attention over the cache prefix + the new causal block.

    ``length`` is a scalar (one shared prefix — the one-shot path) or [B]
    per-sequence prefixes (the slot-pool serving path): the mask math is the
    same, only its batch rank differs, so both paths produce bit-identical
    rows for equal per-row (length, prefix) — the serving engine's oracle
    guarantee rests on this."""
    b, sq, h, d = q.shape
    smax = k_cache.shape[1]
    n_rep = h // cfg.n_kv_heads
    kk = jnp.repeat(k_cache, n_rep, axis=2)
    vv = jnp.repeat(v_cache, n_rep, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, kk, preferred_element_type=jnp.float32)
    s = s / jnp.sqrt(jnp.float32(d))
    kpos = jnp.arange(smax)
    if jnp.ndim(length) == 0:
        qpos = length + jnp.arange(sq)[:, None]  # [Sq, 1]
        mask = kpos[None, :] <= qpos  # attend at or before own position
        s = jnp.where(mask[None, None], s, -1e30)
    else:
        qpos = length[:, None] + jnp.arange(sq)[None, :]  # [B, Sq]
        mask = kpos[None, None, :] <= qpos[:, :, None]  # [B, Sq, Smax]
        s = jnp.where(mask[:, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(vv.dtype), vv)


def _forward_cached(
    params, tokens, cache: KVCache, cfg, ffn=None
) -> Tuple[jax.Array, KVCache]:
    """Run tokens [B, S] starting at cache.length; returns (logits, cache').

    ``ffn(h2, layer_params) -> [B, S, H]`` overrides the dense SwiGLU block
    — the hook the MoE serving loop uses so the attention/KV-cache math
    exists exactly once (uccl_tpu/models/moe_inference.py)."""
    b, s = tokens.shape
    x = jnp.take(params["embed"], tokens, axis=0).astype(cache.k.dtype)
    positions = cache.length + jnp.arange(s)
    new_k, new_v = [], []
    for i in range(cfg.n_layers):
        lp = jax.tree.map(lambda a: a[i], params["blocks"])
        h = rms_norm(x, lp["ln1"], cfg.norm_eps)
        d = cfg.head_dim
        q = (h @ lp["wq"].astype(h.dtype)).reshape(b, s, cfg.n_heads, d)
        kk = (h @ lp["wk"].astype(h.dtype)).reshape(b, s, cfg.n_kv_heads, d)
        v = (h @ lp["wv"].astype(h.dtype)).reshape(b, s, cfg.n_kv_heads, d)
        q = rope(q, positions, cfg.rope_theta)
        kk = rope(kk, positions, cfg.rope_theta)
        k_cache = lax.dynamic_update_slice(
            cache.k[i], kk, (0, cache.length, 0, 0)
        )
        v_cache = lax.dynamic_update_slice(
            cache.v[i], v, (0, cache.length, 0, 0)
        )
        new_k.append(k_cache)
        new_v.append(v_cache)
        attn = _attend_cached(q, k_cache, v_cache, cache.length, cfg)
        x = x + attn.reshape(b, s, -1) @ lp["wo"].astype(attn.dtype)
        h2 = rms_norm(x, lp["ln2"], cfg.norm_eps)
        if ffn is None:
            act = jax.nn.silu(h2 @ lp["w_gate"].astype(h2.dtype)) * (
                h2 @ lp["w_up"].astype(h2.dtype)
            )
            x = x + act @ lp["w_down"].astype(act.dtype)
        else:
            x = x + ffn(h2, lp)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = x.astype(jnp.float32) @ params["head"]
    cache = KVCache(
        jnp.stack(new_k), jnp.stack(new_v), cache.length + s
    )
    return logits, cache


def prefill(params, tokens, cfg: DenseConfig, max_seq: int) -> Tuple[jax.Array, KVCache]:
    """Process the prompt; returns (last-position logits [B, V], warm cache)."""
    if tokens.shape[1] > max_seq:
        raise ValueError(
            f"prompt length {tokens.shape[1]} exceeds max_seq {max_seq}"
        )
    cache = KVCache.empty(cfg, tokens.shape[0], max_seq, params["embed"].dtype)
    logits, cache = _forward_cached(params, tokens, cache, cfg)
    return logits[:, -1], cache


def decode_step(params, token, cache: KVCache, cfg: DenseConfig):
    """token: [B] — one autoregressive step. Returns (logits [B, V], cache')."""
    logits, cache = _forward_cached(params, token[:, None], cache, cfg)
    return logits[:, 0], cache


def decode_step_elastic(params, token, ekv, cfg: DenseConfig):
    """One autoregressive step over an :class:`uccl_tpu.ep.elastic.ElasticKVCache`.

    Same contract as :func:`decode_step`, but the KV context comes from the
    elastic cache (hot blocks in HBM, cold blocks staged from host memory),
    so decode length is bounded by host memory, not HBM. Returns
    logits [B, V]; the cache is updated in place with the new token's KV.

    The gathered context is a dense [L, B, S_blocks, Hkv, D] view whose
    first ``length`` positions are valid — position ``length`` itself is the
    partial block's next empty slot, which is exactly where
    :func:`_forward_cached` writes the new token. The dense forward path is
    therefore reused verbatim (one compiled step per block-count bucket),
    so the elastic path inherits every dense-path improvement by
    construction.
    """
    k_ctx, v_ctx, length = ekv.kv()
    view = KVCache(k_ctx, v_ctx, jnp.asarray(length, jnp.int32))
    logits, view = _forward_cached(params, token[:, None], view, cfg)
    sl = (slice(None), slice(None), slice(length, length + 1))
    ekv.append_tokens(view.k[sl], view.v[sl])
    return logits[:, 0]


# -- slot-pool serving primitives ------------------------------------------
#
# The continuous-batching engine (uccl_tpu/serving) holds ONE fixed
# [B_slots, S_max] KV cache and reuses rows ("slots") across requests, so
# every sequence sits at its own length and joins/leaves the batch at its own
# time. The primitive that needs is a masked forward: tokens land at per-slot
# positions, cache writes are gated per slot (an inactive or padded slot's
# rows never change), and attention masks per slot. Everything else —
# attention math, rope, the layer stack — is the one-shot code above; rows
# with equal (prefix, length) are bit-identical between the two paths, which
# is what makes the engine's exact-oracle guarantee provable by test rather
# than by tolerance.


class SlotKVCache(NamedTuple):
    k: jax.Array  # [L, B_slots, S_max, Hkv, D]
    v: jax.Array  # [L, B_slots, S_max, Hkv, D]
    lengths: jax.Array  # [B_slots] int32 — per-slot valid prefix

    @staticmethod
    def empty(cfg: DenseConfig, n_slots: int, max_seq: int,
              dtype=jnp.float32) -> "SlotKVCache":
        shape = (cfg.n_layers, n_slots, max_seq, cfg.n_kv_heads, cfg.head_dim)
        return SlotKVCache(
            jnp.zeros(shape, dtype), jnp.zeros(shape, dtype),
            jnp.zeros((n_slots,), jnp.int32),
        )

    # -- slot KV export/import views (the disaggregation surface) ----------
    #
    # One slot's rows as host arrays, and the inverse: these are what
    # crosses the p2p wire between a prefill worker and a decode worker
    # (uccl_tpu/serving/disagg.py), and what the prefix-reuse cache copies
    # between slots. Raw float32 rows — bit-exact by construction, so the
    # disaggregated continuation is the oracle's continuation.
    #
    # All three go through module-level jitted helpers whose slot indices
    # and lengths are TRACED scalars, and whole slot rows move at the
    # fixed [L, S_max, Hkv, D] shape: one compiled program per pool shape,
    # instead of one per (slot, offset, length) combination that baked
    # constants would cost. Rows beyond the stamped length carry donor/
    # stale data and are dead by the masked-attention invariant (attention
    # stops at the slot's length; resumed prefill writes [start, start+C)
    # before attending to it).

    def export_rows(self, slot: int, lo: int, hi: int):
        """Host copies of rows [lo, hi): (k, v) each [L, hi-lo, Hkv, D]."""
        import numpy as np

        k_row, v_row = _slot_row_export(self.k, self.v, jnp.int32(slot))
        return (np.asarray(k_row[:, lo:hi]), np.asarray(v_row[:, lo:hi]))

    def import_rows(self, slot: int, k_rows, v_rows, *,
                    length: int) -> "SlotKVCache":
        """Rows [0, n) of ``slot`` replaced by ``k_rows``/``v_rows``
        ([L, n, Hkv, D]); the slot's length becomes ``length``. Callers on
        a hot path should pass full S_max rows (the decode worker's mirror
        does) so every import shares one compiled program."""
        import numpy as np

        smax = self.k.shape[2]
        n = k_rows.shape[1]
        if n < smax:  # pad to the row shape with dead rows
            pad = [(0, 0), (0, smax - n), (0, 0), (0, 0)]
            k_rows = np.pad(np.asarray(k_rows), pad)
            v_rows = np.pad(np.asarray(v_rows), pad)
        k, v, lengths = _slot_row_import(
            self.k, self.v, self.lengths, jnp.int32(slot),
            jnp.asarray(k_rows, self.k.dtype),
            jnp.asarray(v_rows, self.v.dtype), jnp.int32(length),
        )
        return SlotKVCache(k, v, lengths)

    def copy_prefix(self, dst: int, src: int, n: int) -> "SlotKVCache":
        """Copy slot ``src``'s row into slot ``dst`` and stamp dst's
        length to n (the prefix-cache hit path: dst resumes prefill at
        position n; src rows past n are dead weight in dst, never
        readable)."""
        k, v, lengths = _slot_row_copy(
            self.k, self.v, self.lengths, jnp.int32(dst), jnp.int32(src),
            jnp.int32(n),
        )
        return SlotKVCache(k, v, lengths)


@jax.jit
def _slot_row_export(k, v, slot):
    """One slot's full KV row [L, S_max, Hkv, D] (slot is traced: one
    compiled gather per pool shape)."""
    return (lax.dynamic_index_in_dim(k, slot, axis=1, keepdims=False),
            lax.dynamic_index_in_dim(v, slot, axis=1, keepdims=False))


@jax.jit
def _slot_row_import(k, v, lengths, slot, k_row, v_row, length):
    k = lax.dynamic_update_slice(k, k_row[:, None], (0, slot, 0, 0, 0))
    v = lax.dynamic_update_slice(v, v_row[:, None], (0, slot, 0, 0, 0))
    return k, v, lengths.at[slot].set(length)


@jax.jit
def _slot_row_copy(k, v, lengths, dst, src, n):
    k_row = lax.dynamic_index_in_dim(k, src, axis=1, keepdims=True)
    v_row = lax.dynamic_index_in_dim(v, src, axis=1, keepdims=True)
    k = lax.dynamic_update_slice(k, k_row, (0, dst, 0, 0, 0))
    v = lax.dynamic_update_slice(v, v_row, (0, dst, 0, 0, 0))
    return k, v, lengths.at[dst].set(n)


def _lora_delta(h, table, ids, layer):
    """Batched per-slot fused LoRA delta (ISSUE 18): gather each slot's
    rank-padded (A, B) pair from the stacked tables by adapter row id and
    add ``(h @ A) @ B`` beside the base matmul. ``table``: (A [L, T, H,
    R_max], B [L, T, R_max, out]); ``ids``: [B] int32 — row 0 is all
    zeros, so adapter-free slots compute an exact-0.0 delta (the zero-rank
    fast path sharing one compiled program with mixed-rank neighbors)."""
    a, bb = table
    al = a[layer][ids].astype(h.dtype)   # [B, H, R_max]
    bl = bb[layer][ids].astype(h.dtype)  # [B, R_max, out]
    return jnp.einsum("bsr,bro->bso", jnp.einsum("bsh,bhr->bsr", h, al), bl)


def _forward_slots(
    params, tokens, cache: SlotKVCache, start, write_mask, cfg, ffn=None,
    adapters=None, adapter_ids=None,
) -> Tuple[jax.Array, SlotKVCache]:
    """Masked batched forward: tokens [B, S] at positions [start_b, start_b+S).

    ``write_mask`` [B] bool gates every cache write — a masked slot's KV rows
    come back unchanged (its write positions are redirected out of bounds and
    dropped), so mid-decode neighbors are never corrupted by a prefill or by
    an idle slot's dummy token. Lengths are NOT advanced here; the callers
    own the per-slot length bookkeeping. ``ffn`` is the same dense-block
    override hook as :func:`_forward_cached` (the MoE serving loop uses it).

    ``adapters`` = ``{"wq": (A, B), "wv": (A, B)}`` stacked LoRA tables +
    ``adapter_ids`` [B] fuse a per-slot low-rank delta onto the query and
    value projections (:func:`_lora_delta`); None leaves the base program
    byte-identical to the pre-adapter form.
    """
    b, s = tokens.shape
    smax = cache.k.shape[2]
    x = jnp.take(params["embed"], tokens, axis=0).astype(cache.k.dtype)
    positions = start[:, None] + jnp.arange(s)[None, :]  # [B, S]
    # masked slots write at index smax → dropped by the scatter; rows beyond
    # the cache end (a bucket overhanging S_max) drop the same way
    pos_write = jnp.where(write_mask[:, None], positions, smax)
    bidx = jnp.arange(b)[:, None]
    new_k, new_v = [], []
    for i in range(cfg.n_layers):
        lp = jax.tree.map(lambda a: a[i], params["blocks"])
        h = rms_norm(x, lp["ln1"], cfg.norm_eps)
        d = cfg.head_dim
        q2 = h @ lp["wq"].astype(h.dtype)
        v2 = h @ lp["wv"].astype(h.dtype)
        if adapters is not None:
            q2 = q2 + _lora_delta(h, adapters["wq"], adapter_ids, i)
            v2 = v2 + _lora_delta(h, adapters["wv"], adapter_ids, i)
        q = q2.reshape(b, s, cfg.n_heads, d)
        kk = (h @ lp["wk"].astype(h.dtype)).reshape(b, s, cfg.n_kv_heads, d)
        v = v2.reshape(b, s, cfg.n_kv_heads, d)
        q = rope(q, positions, cfg.rope_theta)
        kk = rope(kk, positions, cfg.rope_theta)
        k_cache = cache.k[i].at[bidx, pos_write].set(kk, mode="drop")
        v_cache = cache.v[i].at[bidx, pos_write].set(v, mode="drop")
        new_k.append(k_cache)
        new_v.append(v_cache)
        attn = _attend_cached(q, k_cache, v_cache, start, cfg)
        x = x + attn.reshape(b, s, -1) @ lp["wo"].astype(attn.dtype)
        h2 = rms_norm(x, lp["ln2"], cfg.norm_eps)
        if ffn is None:
            act = jax.nn.silu(h2 @ lp["w_gate"].astype(h2.dtype)) * (
                h2 @ lp["w_up"].astype(h2.dtype)
            )
            x = x + act @ lp["w_down"].astype(act.dtype)
        else:
            x = x + ffn(h2, lp)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = x.astype(jnp.float32) @ params["head"]
    return logits, SlotKVCache(
        jnp.stack(new_k), jnp.stack(new_v), cache.lengths
    )


def prefill_slots(
    params, tokens, prompt_lens, new_mask, cache: SlotKVCache,
    cfg: DenseConfig, start=None, sampling=None, adapters=None,
    adapter_ids=None,
) -> Tuple[jax.Array, SlotKVCache]:
    """Masked batched prefill of newly admitted slots — resumable.

    tokens: [B_slots, S] prompt windows right-padded to S (rows of slots NOT
    in ``new_mask`` are ignored); prompt_lens: [B_slots] int32 FULL prompt
    lengths; new_mask: [B_slots] bool; start: [B_slots] int32 per-slot
    offsets (None = all zeros, the whole-prompt path). Row b carries prompt
    positions [start_b, start_b+S): KV is written only there, attention
    covers [0, start_b+S) causally — chunked prefill is the same math split
    along the sequence axis, so resuming in fixed-size chunks is bit-exact
    with the one-shot prefill. Admitted slots starting at 0 overwrite their
    previous occupant from position 0 — rows beyond the new prompt are dead
    (never readable: attention stops at the slot's length, and decode
    overwrites position L before any read of L). Garbage beyond a
    non-dividing final chunk's prompt end is dead the same way.

    Returns (next token [B_slots] — meaningful only for rows whose window
    reaches the prompt end, i.e. start + S >= prompt_lens; callers ignore
    the rest — and cache with lengths set to min(start+S, prompt_lens) on
    admitted slots). The token is the greedy argmax, or — with
    ``sampling`` = per-slot ``(seeds, pos0, temp, top_p, top_k)`` arrays —
    the lockstep-keyed sample at output position ``pos0`` (the engine
    passes zeros: the first token is output index 0; ``temp <= 0`` rows
    stay greedy).
    """
    if start is None:
        start = jnp.zeros_like(prompt_lens)
    logits, cache = _forward_slots(
        params, tokens, cache, start, new_mask, cfg,
        adapters=adapters, adapter_ids=adapter_ids,
    )
    # each slot's last valid prompt position WITHIN this window; clipped so
    # mid-prefill rows (prompt end beyond the window) gather in-bounds —
    # their token is garbage by contract and ignored by the engine
    s = tokens.shape[1]
    last_idx = jnp.clip(prompt_lens - 1 - start, 0, s - 1)
    last = jnp.take_along_axis(
        logits, last_idx[:, None, None], axis=1
    )[:, 0]  # [B, V]
    if sampling is None:
        tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
    else:
        seeds, pos0, temp, top_p, top_k = sampling
        tok = sample_tokens(seeds, pos0, last, temp, top_p, top_k)
    lengths = jnp.where(
        new_mask, jnp.minimum(start + s, prompt_lens), cache.lengths
    )
    return tok, SlotKVCache(cache.k, cache.v, lengths)


def verify_slots(
    params, tokens, active, cache: SlotKVCache, cfg: DenseConfig,
    sampling=None, adapters=None, adapter_ids=None,
) -> Tuple[jax.Array, jax.Array, SlotKVCache]:
    """Batched draft verification — the speculative-decoding primitive,
    generalizing :func:`decode_step_slots` from one token to a window.

    tokens: [B_slots, S] where column 0 is each slot's last committed token
    and columns 1..S-1 are its k = S-1 drafted continuation tokens; active:
    [B_slots] bool. The window runs at positions [length, length+S) — the
    same masked forward a prefill chunk uses, so per-row results are
    bit-identical to S sequential decode steps over the same tokens. Row j's
    greedy argmax is the target model's next token GIVEN the window prefix
    tokens[:j+1]; greedy acceptance is the longest draft prefix that matches
    those outputs: ``n_accepted[b] = max m such that tokens[b, 1..m] ==
    argmax[b, 0..m-1]``. Active slots advance their length by
    ``n_accepted + 1`` — the accepted draft tokens plus the one
    target-computed token (correction or bonus) every verify yields.

    KV written for rejected positions [length + n_accepted + 1, length + S)
    is dead by the chunked-prefill stale-KV argument: the next window starts
    at the new length and re-writes every stale position before attending to
    it, and attention never reads past its own query position. Rollback is
    the cursor, never a cache scrub.

    With ``sampling`` = per-slot ``(seeds, pos0, temp, top_p, top_k)``
    arrays, window column ``j`` is SAMPLED under the lockstep key for
    output position ``pos0 + j`` instead of argmaxed, and the same
    acceptance rule against the sampled targets IS proper rejection
    sampling for this engine's deterministic drafters: the proposal q is a
    point mass at the draft token d, so the accept probability
    min(1, p(d)/q(d)) = p(d) — exactly the probability the lockstep
    sample t_j equals d — and conditional on rejection the already-drawn
    t_j is distributed as the residual. Committing ``tok`` rows is
    therefore bit-identical to vanilla sampled decode at equal seeds
    (docs/SERVING.md spells out the math).

    Returns (target tokens [B_slots, S], n_accepted [B_slots], cache').
    """
    logits, out = _forward_slots(
        params, tokens, cache, cache.lengths, active, cfg,
        adapters=adapters, adapter_ids=adapter_ids,
    )
    if sampling is None:
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, S]
    else:
        seeds, pos0, temp, top_p, top_k = sampling
        tok = sample_window(seeds, pos0, logits, temp, top_p, top_k)
    n_acc = greedy_acceptance(tokens, tok)
    lengths = spec_advance(cache.lengths, active, n_acc)
    return tok, n_acc, SlotKVCache(out.k, out.v, lengths)


def greedy_acceptance(tokens, tok):
    """THE acceptance rule, shared by both stacks' verify primitives:
    per-row count of the longest draft prefix (``tokens[:, 1:]``) matching
    the window's own greedy argmaxes (``tok[:, :-1]``). Exactness hangs on
    this one definition — a divergence between the dense and MoE stacks
    would break their common oracle guarantee."""
    if tokens.shape[1] <= 1:
        return jnp.zeros((tokens.shape[0],), jnp.int32)
    match = (tokens[:, 1:] == tok[:, :-1]).astype(jnp.int32)
    return jnp.sum(jnp.cumprod(match, axis=1), axis=1).astype(jnp.int32)


def spec_advance(lengths, active, n_acc):
    """Post-verify cursor advance: active slots move by the accepted
    prefix plus the one target-computed token; inactive slots hold."""
    return lengths + jnp.where(active, n_acc + 1, 0).astype(jnp.int32)


def decode_step_slots(
    params, token, active, cache: SlotKVCache, cfg: DenseConfig,
    sampling=None, adapters=None, adapter_ids=None,
) -> Tuple[jax.Array, SlotKVCache]:
    """One masked autoregressive step over the slot pool — the S=1 case of
    :func:`verify_slots` (no draft: nothing to accept, advance by one).

    token: [B_slots] (inactive slots feed a dummy); active: [B_slots] bool.
    Active slots write their new KV at their own length and advance by one;
    inactive slots neither write nor advance. Returns (next greedy-or-
    sampled token [B_slots], cache'); ``sampling``'s ``pos0`` is each
    slot's output index for the token this step emits.
    """
    tok, _, cache = verify_slots(params, token[:, None], active, cache, cfg,
                                 sampling=sampling, adapters=adapters,
                                 adapter_ids=adapter_ids)
    return tok[:, 0], cache


# Compiled-generate cache — the shared LRU-bounded ``_fns`` pattern
# (utils/lru.py): 16 entries comfortably cover a serving process's
# steady-state shape set while letting XLA reclaim evicted programs.
_GEN_CACHE = LRUFnCache(16)


def generate(
    params,
    prompt: jax.Array,
    cfg: DenseConfig,
    *,
    max_new_tokens: int = 32,
    max_seq: int = 256,
    sampling=None,
) -> jax.Array:
    """Greedy (or, with ``sampling``, stochastic) generation.
    prompt: [B, S] → [B, max_new_tokens].

    One jitted program (prefill + a decode ``lax.scan``), cached per
    (cfg, shapes, N): params enter as jit ARGUMENTS, so repeat calls at
    the same shapes are pure cache hits. The old form ran the scan
    eagerly — params were baked into the staged scan as constants, every
    call re-traced, and the constants could exceed a remote-compile
    request limit (PERF.md round-5 tunnel lessons).

    ``sampling`` duck-types :class:`~uccl_tpu.serving.sampling.
    SamplingParams` (seed / temperature / top_p / top_k). The scalars
    enter as TRACED jit arguments — one compiled sampled program serves
    every parameter value — and every batch row runs under the request's
    seed with lockstep keys per output index, making this the vanilla
    sampled oracle the serving engine is bit-identical to. ``sampling is
    None`` keeps the greedy program byte-identical to before."""
    if prompt.shape[1] + max_new_tokens > max_seq:
        raise ValueError(
            f"prompt {prompt.shape[1]} + new {max_new_tokens} tokens exceed "
            f"max_seq {max_seq}: the cache would overflow"
        )
    key = (repr(cfg), prompt.shape, max_new_tokens, max_seq,
           sampling is not None)

    def build():
        if sampling is None:
            def run(p, t):
                logits, cache = prefill(p, t, cfg, max_seq)

                def body(carry, _):
                    logits, cache = carry
                    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    logits, cache = decode_step(p, tok, cache, cfg)
                    return (logits, cache), tok

                (_, _), toks = lax.scan(
                    body, (logits, cache), None, length=max_new_tokens
                )
                return toks.T  # [B, T]

            return jax.jit(run)

        def run(p, t, seed, temp, top_p, top_k):
            b = t.shape[0]
            seeds, temps, tps, tks = broadcast_params(
                b, seed, temp, top_p, top_k
            )
            logits, cache = prefill(p, t, cfg, max_seq)

            def body(carry, i):
                logits, cache = carry
                # scan step i emits output index i: the lockstep key is a
                # pure function of (seed, i), matching the engine exactly
                tok = sample_tokens(seeds, jnp.full((b,), i, jnp.int32),
                                    logits, temps, tps, tks)
                logits, cache = decode_step(p, tok, cache, cfg)
                return (logits, cache), tok

            (_, _), toks = lax.scan(
                body, (logits, cache),
                jnp.arange(max_new_tokens, dtype=jnp.int32),
            )
            return toks.T  # [B, T]

        return jax.jit(run)

    fn = _GEN_CACHE.get(key, build)
    if sampling is None:
        return fn(params, prompt)
    return fn(params, prompt, jnp.int32(int(sampling.seed)),
              jnp.float32(sampling.temperature),
              jnp.float32(sampling.top_p), jnp.int32(sampling.top_k))
