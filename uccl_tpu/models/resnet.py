"""ResNet family (v1.5 bottleneck) — the reference's DDP benchmark workload.

The reference's data-parallel example trains torchvision ResNet-50 under DDP
over its NCCL plugin (examples/ddp_train.py; experimental/misc/resnet_ddp*.py
hand-rolled per-layer allreduce variants); the driver's baseline configs name
"DDP ResNet-50" explicitly. This is the TPU-native counterpart: NHWC layout
(the TPU conv sweet spot), ``lax.conv_general_dilated`` on the MXU,
batch-norm with tracked running statistics carried in an explicit state
pytree (functional, donation-friendly), and a pure ``(params, state, x) ->
(logits, state')`` step that drops straight into the DDP example's explicit
gradient-allreduce loop.

Depths: 18/34 (basic blocks), 50/101/152 (bottleneck).
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
from jax import lax

_DEPTHS: Dict[int, Tuple[str, List[int]]] = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    depth: int = 50
    num_classes: int = 1000
    width: int = 64  # stem channels; stages use width * (1, 2, 4, 8)
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5
    dtype: Any = jnp.float32

    def __post_init__(self):
        if self.depth not in _DEPTHS:
            raise ValueError(
                f"depth {self.depth} not supported (choose {sorted(_DEPTHS)})"
            )

    @property
    def block_kind(self) -> str:
        return _DEPTHS[self.depth][0]

    @property
    def stage_sizes(self) -> List[int]:
        return _DEPTHS[self.depth][1]


# ---------------------------------------------------------------------------
# Layers


def _conv(x, w, stride=1):
    """NHWC conv, SAME padding, HWIO kernel."""
    return lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _bn_apply(x, p, s, train: bool, momentum: float, eps: float):
    """Batch norm over N,H,W. Returns (y, new_state_entry)."""
    if train:
        mean = jnp.mean(x, axis=(0, 1, 2))
        var = jnp.var(x, axis=(0, 1, 2))
        new_s = {
            "mean": momentum * s["mean"] + (1 - momentum) * mean,
            "var": momentum * s["var"] + (1 - momentum) * var,
        }
    else:
        mean, var = s["mean"], s["var"]
        new_s = s
    inv = lax.rsqrt(var.astype(jnp.float32) + eps).astype(x.dtype)
    y = (x - mean.astype(x.dtype)) * inv * p["scale"].astype(x.dtype) + p[
        "bias"
    ].astype(x.dtype)
    return y, new_s


def _init_conv(key, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    std = math.sqrt(2.0 / fan_in)  # He init for ReLU nets
    return jax.random.normal(key, (kh, kw, cin, cout), jnp.float32) * std


def _init_bn(c, zero_scale=False):
    return {
        "scale": jnp.zeros((c,), jnp.float32)
        if zero_scale
        else jnp.ones((c,), jnp.float32),
        "bias": jnp.zeros((c,), jnp.float32),
    }


def _bn_state(c):
    return {"mean": jnp.zeros((c,), jnp.float32), "var": jnp.ones((c,), jnp.float32)}


# ---------------------------------------------------------------------------
# Blocks


def _init_block(key, kind, cin, cmid, stride):
    """One residual block's params + state. Output channels: cmid*4
    (bottleneck) or cmid (basic). The last BN's scale starts at zero
    (zero-init residual: each block begins as identity, the standard
    large-batch trick)."""
    ks = jax.random.split(key, 4)
    cout = cmid * 4 if kind == "bottleneck" else cmid
    if kind == "bottleneck":
        p = {
            "conv1": _init_conv(ks[0], 1, 1, cin, cmid),
            "bn1": _init_bn(cmid),
            "conv2": _init_conv(ks[1], 3, 3, cmid, cmid),
            "bn2": _init_bn(cmid),
            "conv3": _init_conv(ks[2], 1, 1, cmid, cout),
            "bn3": _init_bn(cout, zero_scale=True),
        }
        s = {"bn1": _bn_state(cmid), "bn2": _bn_state(cmid), "bn3": _bn_state(cout)}
    else:
        p = {
            "conv1": _init_conv(ks[0], 3, 3, cin, cmid),
            "bn1": _init_bn(cmid),
            "conv2": _init_conv(ks[1], 3, 3, cmid, cout),
            "bn2": _init_bn(cout, zero_scale=True),
        }
        s = {"bn1": _bn_state(cmid), "bn2": _bn_state(cout)}
    if stride != 1 or cin != cout:
        p["proj"] = _init_conv(ks[3], 1, 1, cin, cout)
        p["bn_proj"] = _init_bn(cout)
        s["bn_proj"] = _bn_state(cout)
    return p, s, cout


def _block_apply(x, p, s, kind, stride, train, cfg: ResNetConfig):
    bn = partial(_bn_apply, train=train, momentum=cfg.bn_momentum, eps=cfg.bn_eps)
    new_s = {}
    if "proj" in p:
        shortcut = _conv(x, p["proj"], stride)
        shortcut, new_s["bn_proj"] = bn(shortcut, p["bn_proj"], s["bn_proj"])
    else:
        shortcut = x
    if kind == "bottleneck":
        y = _conv(x, p["conv1"], 1)
        y, new_s["bn1"] = bn(y, p["bn1"], s["bn1"])
        y = jax.nn.relu(y)
        y = _conv(y, p["conv2"], stride)
        y, new_s["bn2"] = bn(y, p["bn2"], s["bn2"])
        y = jax.nn.relu(y)
        y = _conv(y, p["conv3"], 1)
        y, new_s["bn3"] = bn(y, p["bn3"], s["bn3"])
    else:
        y = _conv(x, p["conv1"], stride)
        y, new_s["bn1"] = bn(y, p["bn1"], s["bn1"])
        y = jax.nn.relu(y)
        y = _conv(y, p["conv2"], 1)
        y, new_s["bn2"] = bn(y, p["bn2"], s["bn2"])
    return jax.nn.relu(y + shortcut), new_s


# ---------------------------------------------------------------------------
# Model


def init_params(key, cfg: ResNetConfig):
    """Returns (params, state): state carries the BN running statistics."""
    keys = jax.random.split(key, 2 + sum(cfg.stage_sizes))
    kind = cfg.block_kind
    params: Dict[str, Any] = {
        "stem": _init_conv(keys[0], 7, 7, 3, cfg.width),
        "bn_stem": _init_bn(cfg.width),
    }
    state: Dict[str, Any] = {"bn_stem": _bn_state(cfg.width)}
    cin = cfg.width
    ki = 1
    blocks_p, blocks_s = [], []
    for stage, n_blocks in enumerate(cfg.stage_sizes):
        cmid = cfg.width * (2**stage)
        for b in range(n_blocks):
            stride = 2 if (b == 0 and stage > 0) else 1
            p, s, cin = _init_block(keys[ki], kind, cin, cmid, stride)
            blocks_p.append(p)
            blocks_s.append(s)
            ki += 1
    params["blocks"] = blocks_p
    params["head"] = (
        jax.random.normal(keys[ki], (cin, cfg.num_classes), jnp.float32)
        / math.sqrt(cin)
    )
    params["head_bias"] = jnp.zeros((cfg.num_classes,), jnp.float32)
    state["blocks"] = blocks_s
    return params, state


def forward(params, state, x, cfg: ResNetConfig, train: bool = True):
    """x: [N, H, W, 3] NHWC float -> (logits [N, classes], new_state)."""
    x = x.astype(cfg.dtype)
    y = _conv(x, params["stem"], 2)
    y, bn_stem = _bn_apply(
        y, params["bn_stem"], state["bn_stem"], train, cfg.bn_momentum, cfg.bn_eps
    )
    y = jax.nn.relu(y)
    y = lax.reduce_window(
        y, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
    )
    new_state: Dict[str, Any] = {"bn_stem": bn_stem, "blocks": []}
    bi = 0
    kind = cfg.block_kind
    for stage, n_blocks in enumerate(cfg.stage_sizes):
        for b in range(n_blocks):
            stride = 2 if (b == 0 and stage > 0) else 1
            y, s_new = _block_apply(
                y, params["blocks"][bi], state["blocks"][bi], kind, stride,
                train, cfg,
            )
            new_state["blocks"].append(s_new)
            bi += 1
    y = jnp.mean(y, axis=(1, 2))  # global average pool
    logits = (
        y.astype(jnp.float32) @ params["head"] + params["head_bias"]
    )
    return logits, new_state


def num_params(params) -> int:
    return sum(int(p.size) for p in jax.tree.leaves(params))


def loss_fn(params, state, x, labels, cfg: ResNetConfig):
    """Mean softmax cross-entropy; returns (loss, new_state)."""
    logits, new_state = forward(params, state, x, cfg, train=True)
    lse = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(lse - tgt), new_state
