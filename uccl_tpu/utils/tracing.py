"""Profiling / tracing hooks.

The TPU answer to the reference's observability stack (SURVEY.md §5: STATS
engine counters, latency histograms, NPKit GPU event tracing, nsys wrappers):
``jax.profiler`` XPlane traces plus lightweight named annotations that show up
on the TPU timeline, and a wall-clock scope timer feeding LatencyHistograms.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator, Optional

import jax

from uccl_tpu.utils.latency import LatencyHistogram
from uccl_tpu.utils.logging import get_logger

_log = get_logger("UTIL")

_scope_hists: Dict[str, LatencyHistogram] = {}


def start_trace(log_dir: str) -> None:
    """Begin an XPlane profiler capture (view with xprof/tensorboard)."""
    jax.profiler.start_trace(log_dir)


def stop_trace() -> None:
    jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region on the device timeline (jax.profiler.TraceAnnotation)."""
    with jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def timed_scope(name: str, log: bool = False) -> Iterator[None]:
    """Wall-clock scope timer; samples land in a per-name LatencyHistogram
    (uccl_tpu.utils.latency) retrievable via :func:`scope_stats`."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        us = (time.perf_counter() - t0) * 1e6
        hist = _scope_hists.get(name)
        if hist is None:
            hist = _scope_hists.setdefault(name, LatencyHistogram())
        hist.record(us)
        if log:
            _log.info("%s: %.1f us", name, us)


def scope_stats(name: str) -> Optional[Dict[str, float]]:
    h = _scope_hists.get(name)
    return h.summary() if h else None


def reset_scopes() -> None:
    _scope_hists.clear()
