"""Profiling / tracing hooks.

The TPU answer to the reference's observability stack (SURVEY.md §5: STATS
engine counters, latency histograms, NPKit GPU event tracing, nsys wrappers):
``jax.profiler`` XPlane traces plus lightweight named annotations that show up
on the TPU timeline, and a wall-clock scope timer feeding LatencyHistograms.

.. deprecated:: the host-side event layer lives in :mod:`uccl_tpu.obs`
   (docs/OBSERVABILITY.md). ``timed_scope`` keeps its histogram contract
   (``scope_stats``/``reset_scopes`` work unchanged) and is re-pointed at
   the obs spine: every scope sample also lands as a span on the obs
   tracer (when enabled), and the per-scope summaries are registered as
   the ``scopes`` pull source on :data:`uccl_tpu.obs.REGISTRY`, so they
   ride the ``/metrics`` + ``/snapshot`` exports. New code should use
   ``obs.span`` directly.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Iterator, Optional

import jax

from uccl_tpu.obs import counters as _obsc
from uccl_tpu.obs import tracer as _obst
from uccl_tpu.utils.latency import LatencyHistogram
from uccl_tpu.utils.logging import get_logger

_log = get_logger("UTIL")

# scope histograms: mutated from arbitrary runtime threads, so every access
# goes through the lock (the old get-then-setdefault pair raced two threads
# into distinct histograms, silently dropping one side's samples)
_scope_hists: Dict[str, LatencyHistogram] = {}
_scope_lock = threading.Lock()


def _scopes_source() -> Dict[str, Dict[str, float]]:
    """Per-scope summaries for the obs registry (the ``scopes`` source)."""
    with _scope_lock:
        hists = dict(_scope_hists)
    return {name: h.summary() for name, h in hists.items()}


_obsc.REGISTRY.register_source("scopes", _scopes_source)


def start_trace(log_dir: str) -> None:
    """Begin an XPlane profiler capture (view with xprof/tensorboard)."""
    jax.profiler.start_trace(log_dir)


def stop_trace() -> None:
    jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region on the device timeline (jax.profiler.TraceAnnotation)."""
    with jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def timed_scope(name: str, log: bool = False) -> Iterator[None]:
    """Wall-clock scope timer; samples land in a per-name LatencyHistogram
    (uccl_tpu.utils.latency) retrievable via :func:`scope_stats`, and as a
    span on the obs tracer when tracing is enabled."""
    tr = _obst.get_tracer()
    ts0 = tr.now_us() if tr is not None else 0.0
    t0 = time.perf_counter()
    try:
        yield
    finally:
        us = (time.perf_counter() - t0) * 1e6
        with _scope_lock:
            hist = _scope_hists.get(name)
            if hist is None:
                hist = _scope_hists[name] = LatencyHistogram()
        hist.record(us)
        if tr is not None:
            tr.complete(name, ts0, tr.now_us() - ts0)
        if log:
            _log.info("%s: %.1f us", name, us)


def scope_stats(name: str) -> Optional[Dict[str, float]]:
    with _scope_lock:
        h = _scope_hists.get(name)
    return h.summary() if h else None


def reset_scopes() -> None:
    with _scope_lock:
        _scope_hists.clear()
