"""Leveled, subsystem-scoped logging + runtime checks.

TPU-native equivalent of the reference's ``include/util/debug.h:1-60``:
``UCCL_LOG(level)`` / ``UCCL_LOG(INFO, subsys)`` with levels FATAL/ERROR/WARN/INFO,
env-controlled subsystem filtering, plus ``UCCL_CHECK``/``UCCL_DCHECK`` assertions.

Env controls (mirroring UCCL_DEBUG / UCCL_DEBUG_SUBSYS):

* ``UCCL_TPU_DEBUG``        — minimum level name (FATAL|ERROR|WARN|INFO|DEBUG).
* ``UCCL_TPU_DEBUG_SUBSYS`` — comma list of subsystems to enable, or ``ALL``.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
from typing import Any, Optional

SUBSYSTEMS = (
    "INIT",
    "COLL",
    "P2P",
    "EP",
    "PARALLEL",
    "OPS",
    "MODEL",
    "NATIVE",
    "UTIL",
)

_LEVELS = {
    "FATAL": logging.CRITICAL,
    "ERROR": logging.ERROR,
    "WARN": logging.WARNING,
    "INFO": logging.INFO,
    "DEBUG": logging.DEBUG,
}

_lock = threading.Lock()
_configured = False
_enabled_subsys: Optional[set] = None  # None => ALL


def _configure() -> None:
    global _configured, _enabled_subsys
    with _lock:
        if _configured:
            return
        level_name = os.environ.get("UCCL_TPU_DEBUG", "WARN").upper()
        level = _LEVELS.get(level_name, logging.WARNING)
        root = logging.getLogger("uccl_tpu")
        root.setLevel(level)
        if not root.handlers:
            h = logging.StreamHandler(sys.stderr)
            h.setFormatter(
                logging.Formatter(
                    "[%(asctime)s %(levelname)s %(name)s] %(message)s", "%H:%M:%S"
                )
            )
            root.addHandler(h)
        root.propagate = False
        subsys = os.environ.get("UCCL_TPU_DEBUG_SUBSYS", "ALL").upper()
        _enabled_subsys = (
            None if subsys == "ALL" else {s.strip() for s in subsys.split(",")}
        )
        _configured = True


def get_logger(subsys: str = "UTIL") -> logging.Logger:
    _configure()
    if subsys not in SUBSYSTEMS:
        raise ValueError(f"unknown subsystem {subsys!r}; one of {SUBSYSTEMS}")
    logger = logging.getLogger(f"uccl_tpu.{subsys}")
    if _enabled_subsys is not None and subsys not in _enabled_subsys:
        logger.setLevel(logging.CRITICAL)  # effectively silenced except FATAL
    return logger


def log(level: str, msg: str, *args: Any, subsys: str = "UTIL") -> None:
    """UCCL_LOG(level, subsys)-style one-shot logging."""
    lvl = _LEVELS.get(level.upper())
    if lvl is None:
        raise ValueError(f"unknown level {level!r}")
    get_logger(subsys).log(lvl, msg, *args)
    if level.upper() == "FATAL":
        raise RuntimeError(f"FATAL[{subsys}]: {msg % args if args else msg}")


class CheckError(AssertionError):
    pass


def CHECK(cond: Any, msg: str = "CHECK failed") -> None:
    """Always-on invariant check (reference UCCL_CHECK)."""
    if not cond:
        raise CheckError(msg)


_DCHECK_ON = os.environ.get("UCCL_TPU_DCHECK", "1") not in ("0", "false", "off")


def DCHECK(cond: Any, msg: str = "DCHECK failed") -> None:
    """Debug-only check (reference UCCL_DCHECK); disable with UCCL_TPU_DCHECK=0."""
    if _DCHECK_ON and not cond:
        raise CheckError(msg)
