"""Version-bridging aliases for the jax APIs this codebase targets.

The code is written against current jax (``jax.shard_map`` with the
``check_vma`` knob, ``pltpu.CompilerParams``, the faithful
``pltpu.InterpretParams`` TPU interpreter). Pinned-toolchain containers can
ship an older jax (0.4.x) that exposes the same machinery under earlier
names — ``jax.experimental.shard_map`` with ``check_rep``, and
``TPUCompilerParams`` — and whose TPU interpret mode is the discharge-based
one: remote DMAs are rewritten into synchronous cross-device gathers (data
movement is faithful, per-DMA global ordering is implied) but remote
semaphore signals are not implemented and only single-named-axis meshes are
supported. This module prefers the modern surface and falls back, so one
codebase imports everywhere; Pallas kernels consult
:data:`FAITHFUL_PALLAS_INTERPRET` to decide whether barrier/credit semaphore
traffic is real under interpret mode or must be elided (see
:mod:`uccl_tpu.collective.dma`).
"""

from __future__ import annotations

from jax import lax
from jax.experimental.pallas import tpu as pltpu

if not hasattr(lax, "axis_size"):
    # Polyfill (jax 0.4.x): the static size of a (possibly tuple) named
    # axis inside shard_map. Installed on jax.lax itself so the many call
    # sites across the codebase need no edits; modern jax is untouched.
    def _axis_size(axis):
        from jax._src.core import get_axis_env

        sizes = get_axis_env().axis_sizes
        if isinstance(axis, (tuple, list)):
            out = 1
            for a in axis:
                out *= sizes[a]
            return out
        return sizes[axis]

    lax.axis_size = _axis_size

try:  # modern: jax.shard_map, check_vma
    from jax import shard_map as _shard_map

    _CHECK_KW = "check_vma"
except ImportError:  # jax<=0.4.x: experimental module, check_rep
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"

# True when pltpu.InterpretParams exists — the faithful multi-device TPU
# interpreter that simulates remote DMAs AND semaphores/barriers. False on
# the legacy discharge interpreter (jax 0.4.x).
FAITHFUL_PALLAS_INTERPRET = hasattr(pltpu, "InterpretParams")

# True when this jax ships the modern jax.shard_map. The 0.4.x experimental
# shard_map's partial-eval gives rank-0 residuals dim-0 out_names and raises
# a _SpecError when a shard_mapped program with scalar residuals is
# differentiated from OUTSIDE the shard_map (value_and_grad over loss_fn) —
# tests of those grad paths skip on legacy rather than fail.
MODERN_SHARD_MAP = _CHECK_KW == "check_vma"


def shard_map(f, mesh, in_specs, out_specs, check_vma=False):
    """``jax.shard_map`` on modern jax; the experimental one (with
    ``check_vma`` mapped onto ``check_rep``) on 0.4.x."""
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{_CHECK_KW: check_vma},
    )


import jax as _jax

if not hasattr(_jax, "shard_map"):
    # Polyfill `jax.shard_map` (and `from jax import shard_map`) on 0.4.x
    # so the many call sites across the codebase and tests need no edits;
    # modern jax is untouched.
    _jax.shard_map = shard_map


def tpu_compiler_params(collective_id: int = 0):
    """``pltpu.CompilerParams(has_side_effects=True, collective_id=...)`` on
    modern jax; the ``TPUCompilerParams`` spelling (which has no
    ``has_side_effects`` knob) on 0.4.x."""
    if hasattr(pltpu, "CompilerParams"):
        return pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id
        )
    return pltpu.TPUCompilerParams(collective_id=collective_id)


def tpu_interpret_params(interpret: bool):
    """Value for ``pl.pallas_call(interpret=...)``: ``InterpretParams()``
    where the faithful interpreter exists, plain ``True`` on the legacy
    discharge interpreter, ``False`` for real lowering."""
    if not interpret:
        return False
    return pltpu.InterpretParams() if FAITHFUL_PALLAS_INTERPRET else True
