"""ICI torus topology helpers.

The reference's multipath "packet spraying" picks among 32 QP paths per flow
(reference: collective/rdma/transport_config.h:40 PORT_ENTROPY, transport.cc:2186
EventOnSelectPath). On TPU the fabric is the ICI torus driven by XLA, so the analog
is *ring/path selection over torus axes*: which device orderings a chunk-graph
collective schedule rotates around, and how many independent rings (one per torus
direction) a collective can spray chunks across.

Pure-python; used by the chunk-graph planner (uccl_tpu.collective.plan) and by
ring-attention schedules (uccl_tpu.parallel.ring).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Tuple


@dataclass(frozen=True)
class TorusAxis:
    """One axis of a (possibly multi-dim) torus of devices."""

    name: str
    size: int


def ring_order(n: int, offset: int = 0, reverse: bool = False) -> List[int]:
    """Device ordering for a logical ring of n members.

    offset rotates the starting point; reverse flips direction. Two rings with
    reverse=False/True spray chunks over both torus directions simultaneously —
    the ICI analog of UCCL's dual-direction path diversity.
    """
    order = [(i + offset) % n for i in range(n)]
    if reverse:
        order = [order[0]] + order[1:][::-1]
    return order


def ring_neighbors(rank: int, n: int, reverse: bool = False) -> Tuple[int, int]:
    """(prev, next) neighbors of `rank` on the ring."""
    step = -1 if reverse else 1
    return ((rank - step) % n, (rank + step) % n)


def ppermute_pairs(n: int, shift: int = 1) -> List[Tuple[int, int]]:
    """(src, dst) pairs for jax.lax.ppermute implementing a ring rotation by shift."""
    return [(i, (i + shift) % n) for i in range(n)]


def bidirectional_rings(n: int) -> List[List[int]]:
    """The two directed rings available on a 1-D torus axis."""
    return [ring_order(n), ring_order(n, reverse=True)]


def factor_2d(n: int) -> Tuple[int, int]:
    """Factor n into the most-square (rows, cols) grid — used to lay a logical
    2-D torus over a flat device list when the physical topology is unknown."""
    best = (1, n)
    r = 1
    while r * r <= n:
        if n % r == 0:
            best = (r, n // r)
        r += 1
    return best


def bcast_tree_rounds(n: int, root: int = 0) -> List[List[Tuple[int, int]]]:
    """Binomial-tree broadcast schedule: per round, the (src, dst) member
    pairs (absolute indices on a ring of ``n`` rooted at ``root``). Round t
    doubles the holder set — members at virtual rank < 2^t forward to
    virtual rank + 2^t — so the whole tree is ceil(log2 n) rounds and every
    member sends at most log2(n) copies.

    THE one tree-edge arithmetic: the lax lowering
    (``collective.plan.tree_broadcast``), the host-side DCN broadcast
    (``collective.hierarchical.DcnGroup.broadcast``) and the planner's
    tree cost features all derive their schedule from this list, so the
    three surfaces cannot drift."""
    rounds: List[List[Tuple[int, int]]] = []
    mask = 1
    while mask < n:
        rounds.append(
            [((v + root) % n, (v + mask + root) % n)
             for v in range(mask) if v + mask < n]
        )
        mask <<= 1
    return rounds


def recursive_halving_peers(rank: int, n: int) -> List[int]:
    """Peer schedule for recursive-halving/doubling collectives (n power of two)."""
    if n & (n - 1):
        raise ValueError(f"recursive halving needs power-of-two size, got {n}")
    peers = []
    d = n // 2
    while d >= 1:
        peers.append(rank ^ d)
        d //= 2
    return peers
