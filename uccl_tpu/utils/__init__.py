"""Shared substrate: config, logging, latency histograms, topology.

The TPU-native equivalent of the reference's ``include/util`` + ``param.h`` layer
(reference: collective/rdma/param.h:16-29, include/util/debug.h:1-60,
include/util/latency.h). Built first per SURVEY.md §7 step 1.
"""

from uccl_tpu.utils.config import param, set_env_file, Param
from uccl_tpu.utils.logging import get_logger, log, CHECK, DCHECK
from uccl_tpu.utils.latency import LatencyHistogram

__all__ = [
    "param",
    "set_env_file",
    "Param",
    "get_logger",
    "log",
    "CHECK",
    "DCHECK",
    "LatencyHistogram",
]
