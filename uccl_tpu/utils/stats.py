"""Periodic stats reporting.

The analog of the reference's engine stats thread (collective/rdma
transport.cc:1797 ``stats_thread_fn`` — 2 s interval, silenced by
``UCCL_ENGINE_QUIET``): components register counter callbacks; a daemon thread
logs a snapshot every interval. Silence with ``UCCL_TPU_STATS_QUIET=1``.

.. deprecated:: the registration surface is absorbed by
   :data:`uccl_tpu.obs.REGISTRY` (docs/OBSERVABILITY.md). The module-level
   ``registry`` here now mirrors every register/unregister into the obs
   registry's pull sources, so anything registered through the old surface
   is also exported via ``/metrics`` + ``/snapshot`` and the obs JSON
   snapshot. Existing callers keep working unchanged; new code should
   register on ``uccl_tpu.obs.REGISTRY`` (``register_source``) directly.
   The reporter thread itself stays — it is the log-file face of the same
   sources.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from uccl_tpu.obs import counters as _obsc
from uccl_tpu.utils.config import param
from uccl_tpu.utils.logging import get_logger

_log = get_logger("UTIL")

_quiet = param("stats_quiet", False, help="silence the periodic stats thread")
_interval = param("stats_interval_s", 2.0, help="stats reporting interval")


class StatsRegistry:
    """Named counter sources; snapshot() pulls every registered callback.

    When constructed with ``obs_registry``, every source is mirrored into
    that registry's pull sources (the deprecation shim: the module-level
    ``registry`` below mirrors into :data:`uccl_tpu.obs.REGISTRY`).
    Standalone instances (tests) stay self-contained."""

    def __init__(self, obs_registry: Optional[_obsc.Registry] = None):
        self._sources: Dict[str, Callable[[], Dict[str, float]]] = {}
        self._lock = threading.Lock()
        self._obs = obs_registry

    def register(self, name: str, fn: Callable[[], Dict[str, float]]) -> None:
        with self._lock:
            self._sources[name] = fn
        if self._obs is not None:
            self._obs.register_source(name, fn)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)
        if self._obs is not None:
            self._obs.unregister_source(name)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            sources = dict(self._sources)
        out = {}
        for name, fn in sources.items():
            try:
                out[name] = fn()
            except Exception as e:  # a broken source must not kill the thread
                out[name] = {"error": repr(e)}
        return out


registry = StatsRegistry(obs_registry=_obsc.REGISTRY)


class StatsThread:
    """Daemon thread logging registry snapshots every interval."""

    def __init__(self, reg: Optional[StatsRegistry] = None):
        self._reg = reg if reg is not None else registry
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(float(_interval.get())):
            if _quiet.get():
                continue
            snap = self._reg.snapshot()
            if snap:
                _log.info("stats: %s", snap)
