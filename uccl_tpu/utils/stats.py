"""Periodic stats reporting.

The analog of the reference's engine stats thread (collective/rdma
transport.cc:1797 ``stats_thread_fn`` — 2 s interval, silenced by
``UCCL_ENGINE_QUIET``): components register counter callbacks; a daemon thread
logs a snapshot every interval. Silence with ``UCCL_TPU_STATS_QUIET=1``.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from uccl_tpu.utils.config import param
from uccl_tpu.utils.logging import get_logger

_log = get_logger("UTIL")

_quiet = param("stats_quiet", False, help="silence the periodic stats thread")
_interval = param("stats_interval_s", 2.0, help="stats reporting interval")


class StatsRegistry:
    """Named counter sources; snapshot() pulls every registered callback."""

    def __init__(self):
        self._sources: Dict[str, Callable[[], Dict[str, float]]] = {}
        self._lock = threading.Lock()

    def register(self, name: str, fn: Callable[[], Dict[str, float]]) -> None:
        with self._lock:
            self._sources[name] = fn

    def unregister(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            sources = dict(self._sources)
        out = {}
        for name, fn in sources.items():
            try:
                out[name] = fn()
            except Exception as e:  # a broken source must not kill the thread
                out[name] = {"error": repr(e)}
        return out


registry = StatsRegistry()


class StatsThread:
    """Daemon thread logging registry snapshots every interval."""

    def __init__(self, reg: Optional[StatsRegistry] = None):
        self._reg = reg if reg is not None else registry
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(float(_interval.get())):
            if _quiet.get():
                continue
            snap = self._reg.snapshot()
            if snap:
                _log.info("stats: %s", snap)
