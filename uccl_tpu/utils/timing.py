"""Chained-fori_loop timing harnesses — the round-5 "Harness lesson"
(PERF.md) in ONE place, shared by the probe scripts (via scripts/_timing)
and the benchmarks:

  * the loop body must be CHAINED to the carry — a body whose inputs are
    all loop-invariant is hoisted out by XLA's LICM and the loop times
    nothing (measured: "fwd+bwd" 1.6 ms < fwd 3.4 ms);
  * consume outputs with a full reduction, never a one-element read that
    XLA can narrow/DCE through (measured: flattered XLA attention 3x vs
    the un-trimmable pallas kernel);
  * pass arrays as jit ARGUMENTS, not closures — baked-in constants can
    exceed the axon tunnel's remote-compile request limit (HTTP 413);
  * sync via a host scalar read — block_until_ready does not synchronize
    under the axon tunnel.

Two estimators:
  chained_timeit — per-iteration time of fn(a0, *rest, c) -> carry; use
    for ms-scale probes where one dispatch's fixed cost amortizes away.
  slope_timeit — per-op = (t(base+n) - t(base)) / n over a pytree of
    args; the differencing cancels the fixed dispatch + host-read RTT
    exactly, which µs-scale ops need (a per-call loop over the tunnel
    measures only its own ~10 ms dispatch floor).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
from jax import lax


def perturb(a, c):
    """Couple array `a` to the carry so the loop body is not hoistable.
    Float: + c*1e-12 (negligible). Int: + min(|c|, 0) cast — PROVABLY zero
    for any carry value, yet data-dependent, so values are bit-unchanged
    and XLA still cannot prove loop invariance. (The earlier min(c, 0)
    coupling assumed a non-negative carry; a slope carry that drifts
    negative — reductions of signed outputs do — silently mutated every
    int leaf it touched.)"""
    if jnp.issubdtype(a.dtype, jnp.floating):
        return a + (c * 1e-12).astype(a.dtype)
    return a + jnp.minimum(jnp.abs(c), 0.0).astype(a.dtype)


def chained_timeit(name, fn, *args, iters=10, flops=None, width=34):
    """Time fn over `iters` chained iterations in ONE jitted dispatch.
    fn(a0, *rest, c) -> new carry scalar; a0 is perturbed by the carry.
    Returns seconds per iteration; prints `name`, ms, and TF/s if `flops`
    (per-iteration FLOPs) is given."""
    def body(i, state):
        c, arrs = state
        return fn(perturb(arrs[0], c), *arrs[1:], c), arrs

    f = jax.jit(lambda n, c0, *a: lax.fori_loop(0, n, body, (c0, a)))
    c0 = jnp.zeros((), jnp.float32)
    t0 = time.perf_counter()
    float(f(2, c0, *args)[0])  # compile + warm
    tc = time.perf_counter() - t0
    t0 = time.perf_counter()
    float(f(iters, c0, *args)[0])
    dt = (time.perf_counter() - t0) / iters
    tf = f"  {flops / dt / 1e12:6.1f} TF/s" if flops else ""
    print(f"{name:{width}s} {dt * 1e3:8.3f} ms{tf}  (compile {tc:.0f}s)",
          flush=True)
    return dt


def slope_timeit(fn, args, iters, signal_floor=0.02, n_cap=20000):
    """Per-op seconds for fn(*args) via the SLOPE of two chained fori_loop
    runs: (t(base+n) - t(base)) / n, median of 3 pairs. The first leaf of
    `args` (float or int — see perturb) is carry-coupled each iteration;
    every output leaf is consumed by a full reduction. n escalates ×10
    until the differenced signal (slope × n) clears `signal_floor`
    seconds or n reaches `n_cap` — µs-scale ops need thousands of chained
    iterations to rise above run-to-run jitter."""
    flat, treedef = jax.tree.flatten(tuple(args))
    pi = next(
        (i for i, l in enumerate(flat) if hasattr(l, "dtype")), None
    )

    def body(i, state):
        c, leaves = state
        leaves = list(leaves)
        if pi is not None:
            leaves[pi] = perturb(leaves[pi], c)
        out = fn(*jax.tree.unflatten(treedef, leaves))
        s = sum(
            l.astype(jnp.float32).sum()
            for l in jax.tree.leaves(out)
            if hasattr(l, "astype")
        )
        return c + s * 1e-9, tuple(state[1])

    run = jax.jit(
        lambda n, c0, leaves: lax.fori_loop(0, n, body, (c0, leaves))
    )
    c0 = jnp.zeros((), jnp.float32)
    leaves = tuple(flat)
    float(run(2, c0, leaves)[0])  # compile + warm, host-scalar sync

    def timed(n):
        t0 = time.perf_counter()
        float(run(n, c0, leaves)[0])
        return time.perf_counter() - t0

    base, n = 3, max(1, iters)
    while True:
        slopes = sorted(
            (timed(base + n) - timed(base)) / n for _ in range(3)
        )
        if slopes[1] * n > signal_floor or n >= n_cap:
            break
        n = min(n * 10, n_cap)
    return max(slopes[1], 1e-9)  # clamp: noise can make a tiny op negative
