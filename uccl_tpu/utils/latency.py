"""Percentile latency histograms.

TPU-native equivalent of the reference's ``include/util/latency.h`` (log-bucketed
percentile histograms used by every engine stats thread). Pure numpy so it is usable
from host runtime threads without touching JAX.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Sequence

import numpy as np


class LatencyHistogram:
    """Log-scale bucketed histogram over microsecond samples.

    Buckets are exponential: bucket i covers [base**i, base**(i+1)) microseconds,
    giving ~5% resolution with base=1.05 across ns..minutes like the reference's
    fixed 1..2^k bucket ladder but with finer grain.
    """

    def __init__(self, base: float = 1.05, max_us: float = 60e6):
        self._base = base
        self._log_base = math.log(base)
        self._nbuckets = int(math.log(max_us) / self._log_base) + 2
        self._counts = np.zeros(self._nbuckets, dtype=np.int64)
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = 0.0
        self._lock = threading.Lock()

    def _bucket(self, us: float) -> int:
        if us < 1.0:
            return 0
        idx = int(math.log(us) / self._log_base) + 1
        return min(idx, self._nbuckets - 1)

    def record(self, us: float) -> None:
        with self._lock:
            self._counts[self._bucket(us)] += 1
            self._count += 1
            self._sum += us
            self._min = min(self._min, us)
            self._max = max(self._max, us)

    def record_many(self, samples: Sequence[float]) -> None:
        for s in samples:
            self.record(s)

    @property
    def count(self) -> int:
        return self._count

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0

    def percentile(self, p: float) -> float:
        """p in [0, 100]; returns the bucket upper-bound latency in us."""
        with self._lock:
            if self._count == 0:
                return 0.0
            target = max(1, math.ceil(self._count * p / 100.0))
            cum = np.cumsum(self._counts)
            idx = int(np.searchsorted(cum, target))
            upper = self._base ** idx
            return min(max(upper, self._min), self._max)

    def summary(self) -> Dict[str, float]:
        return {
            "count": float(self.count),
            "mean_us": self.mean,
            "min_us": 0.0 if self._count == 0 else self._min,
            "p50_us": self.percentile(50),
            "p90_us": self.percentile(90),
            "p99_us": self.percentile(99),
            "max_us": self._max,
        }

    def __str__(self) -> str:  # pragma: no cover - debugging aid
        s = self.summary()
        return (
            f"n={s['count']:.0f} mean={s['mean_us']:.1f}us p50={s['p50_us']:.1f}us "
            f"p90={s['p90_us']:.1f}us p99={s['p99_us']:.1f}us max={s['max_us']:.1f}us"
        )
