"""LRU-bounded compiled-function cache — THE ``_fns`` pattern.

One implementation for every per-shape jit cache in the serving stacks
(inference's generate cache, MoEServer._fns, the serving backends): a
long-lived process sweeping shapes (batch buckets, growing scan lengths,
several max_seq tiers) would otherwise retain a compiled executable per
shape forever. A small cap comfortably covers a server's steady-state
shape set while letting XLA reclaim evicted programs.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable


class LRUFnCache:
    """``get(key, build)``: return the cached value or build+insert it,
    evicting least-recently-used entries beyond ``cap``."""

    def __init__(self, cap: int = 16):
        if cap < 1:
            raise ValueError(f"cap must be >= 1, got {cap}")
        self.cap = cap
        self._d: OrderedDict = OrderedDict()

    def get(self, key, build: Callable):
        val = self._d.get(key)
        if val is None:
            val = self._d[key] = build()
            while len(self._d) > self.cap:
                self._d.popitem(last=False)
        else:
            self._d.move_to_end(key)  # LRU: a hit refreshes recency
        return val

    def __len__(self) -> int:
        return len(self._d)

    def __contains__(self, key) -> bool:
        return key in self._d
