"""Environment-driven configuration parameters.

TPU-native equivalent of the reference's ``UCCL_PARAM`` macro system
(reference: collective/rdma/param.{h,cc} — lazily-cached ``UCCL_*`` env lookups with an
optional env file loaded via ``setEnvFile``). Semantics preserved:

* A param is named once, reads ``UCCL_TPU_<ENV>`` lazily on first access, caches the
  value, and can be overridden programmatically (tests) or via an env file.
* Typed: int / float / bool / str, with a declared default.
* ``dump_params()`` prints every registered param for observability (the analog of the
  reference's startup param logging).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional

_ENV_PREFIX = "UCCL_TPU_"

_registry: Dict[str, "Param"] = {}
_registry_lock = threading.Lock()

# Extra key/value pairs loaded from an env file; consulted before os.environ so a file
# can pin a config for a whole job (reference param.h `setEnvFile`).
_env_file_values: Dict[str, str] = {}


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on", "y")


_PARSERS: Dict[type, Callable[[str], Any]] = {
    int: lambda s: int(s, 0),
    float: float,
    bool: _parse_bool,
    str: lambda s: s,
}


class Param:
    """A lazily-cached, env-overridable configuration value."""

    def __init__(self, name: str, default: Any, type_: type = None, help: str = ""):
        self.name = name
        self.env = _ENV_PREFIX + name.upper()
        self.default = default
        self.type = type_ or type(default)
        self.help = help
        self._cached: Optional[Any] = None
        self._resolved = False
        self._override: Optional[Any] = None
        if self.type not in _PARSERS:
            raise TypeError(f"unsupported param type {self.type} for {name}")

    def get(self) -> Any:
        if self._override is not None:
            return self._override
        if not self._resolved:
            raw = _env_file_values.get(self.env, os.environ.get(self.env))
            if raw is None:
                self._cached = self.default
            else:
                self._cached = _PARSERS[self.type](raw)
            self._resolved = True
        return self._cached

    def set(self, value: Any) -> None:
        """Programmatic override (wins over env); pass None to clear."""
        self._override = value

    def reset(self) -> None:
        """Drop the cache so the next get() re-reads the environment."""
        self._cached = None
        self._resolved = False
        self._override = None

    def __call__(self) -> Any:
        return self.get()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Param({self.name}={self.get()!r} env={self.env})"


def param(name: str, default: Any, type_: type = None, help: str = "") -> Param:
    """Declare (or fetch) a named config param. Idempotent per name."""
    with _registry_lock:
        existing = _registry.get(name)
        if existing is not None:
            return existing
        p = Param(name, default, type_, help)
        _registry[name] = p
        return p


def set_env_file(path: str) -> None:
    """Load KEY=VALUE lines; those values take precedence over os.environ."""
    values: Dict[str, str] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#") or "=" not in line:
                continue
            k, v = line.split("=", 1)
            values[k.strip()] = v.strip()
    _env_file_values.update(values)
    with _registry_lock:
        for p in _registry.values():
            p.reset()


def reset_all() -> None:
    """Test helper: drop every cached value."""
    with _registry_lock:
        for p in _registry.values():
            p.reset()
    _env_file_values.clear()


def dump_params() -> Dict[str, Any]:
    with _registry_lock:
        return {name: p.get() for name, p in sorted(_registry.items())}
