"""Tiered KV cache: prefix reuse far beyond device slots (ISSUE 17).

The prefix cache (PR 8) lives in parked device slots, so its capacity is
``n_slots`` — nowhere near a fleet of users' shared system prompts. This
module grows it into a **device → host → remote** hierarchy behind the same
:class:`~uccl_tpu.serving.prefix_cache.PrefixCache` trie, the TPU
reproduction of UCCL's P2P pillar (NIXL-style registered-memory KV transfer
with optional DietGPU float compression, PAPER.md §0.2):

* **T0** — parked device slots: today's behavior, byte-for-byte unchanged
  semantics (the trie's ``int`` residents);
* **T1** — a bounded host-memory pool (:class:`HostKVTier`) fed by the
  PR 8/10 slot-row export programs (``SlotKVCache.export_rows`` /
  ``import_rows``, MoE mirrors);
* **T2** — a remote peer (:class:`KvTierServer`) advertising capacity over
  the PR 13 windowed SACK transport (``Channel.writev``), reusing the
  weight-push MAGIC+JSON control framing and per-entry CRC discipline.

**Demotion is the new eviction path**: a T0 LRU victim's rows export to T1
instead of being dropped (``TieredKVCache.demote``, the ``demote=`` hook of
``PrefixCache.evict_lru``); a full T1 spills ITS LRU entry to T2 — or drops
it, counted, when no remote tier is attached. Demotion never blocks
admission: an entry too large for the host pool is dropped immediately.
**Promotion is a hit at depth**: a T1/T2 donor's entry is fetched, decoded,
and imported into the admitted request's own slot, which then resumes at
``prefill_pos = matched_len`` — bit-exact by the PR 4 start-offset argument
when the tier is lossless.

**Exactness contract per tier** (surfaced in the trie entry, so hits are
never silently lossy): the default ``wire_dtype=None`` stores raw f32 rows —
promotions are BIT-EXACT and the engine's oracle guarantee extends across
demote→promote cycles. Opting into ``wire_dtype="fp8"|"int8"`` stores
entries block-scale compressed at rest via the shared :mod:`uccl_tpu.ops.
quant` codec (~4x/4x smaller than f32 — the same host bytes hold ~4x the
entries); each round trip is error-bounded by the codec's documented
``amax / ROUND_TRIP_DIVISOR`` contract (pinned by tests), every ref carries
``exact=False``, and the engine stamps ``Request.cache_hit_exact`` so the
divergence is attributable per request.

Counters/gauges (docs/OBSERVABILITY.md): ``kv_tier_hits_total{tier}``,
``kv_tier_promotions_total{tier}``, ``kv_tier_demotions_total{tier}``,
``kv_tier_drops_total{tier}``, ``kv_tier_resident_tokens{tier}``,
``kv_tier_resident_bytes{tier}`` (T1/T2; T0's residency is the existing
``prefix_cache_resident_{slots,tokens}``), plus ``kv_tier.promote`` /
``kv_tier.demote`` trace spans and ``p2p_bytes_total{verb="kv_tier"}`` for
the remote tier's ingress bytes.
"""

from __future__ import annotations

import json
import threading
import zlib
from collections import OrderedDict
from typing import Dict, Optional, Tuple

import numpy as np

from uccl_tpu import obs
from uccl_tpu.utils.logging import get_logger

_log = get_logger("P2P")

_TIER_HITS = obs.counter(
    "kv_tier_hits_total",
    "prefix-cache hits served by tier (t0 = parked-slot copy, t1/t2 = "
    "promotion from the host pool / a remote peer)",
)
_PROMOTIONS = obs.counter(
    "kv_tier_promotions_total",
    "tier entries imported back into a device slot to serve a hit, by "
    "source tier",
)
_DEMOTIONS = obs.counter(
    "kv_tier_demotions_total",
    "entries moved DOWN a tier under capacity pressure (t1 = device slot "
    "exported to the host pool, t2 = host entry spilled to the remote peer)",
)
_DROPS = obs.counter(
    "kv_tier_drops_total",
    "tier entries dropped instead of demoted (no deeper tier, oversize, or "
    "a stale remote ref) — the counted never-blocks-admission escape hatch",
)
_RES_TOKENS = obs.gauge(
    "kv_tier_resident_tokens",
    "prompt tokens resident per deep tier (sum of entry token counts)",
)
_RES_BYTES = obs.gauge(
    "kv_tier_resident_bytes",
    "at-rest bytes resident per deep tier (encoded blobs, scales included)",
)
# the one shared p2p byte family (p2p/endpoint.py declares it): the remote
# tier's service-level ingress verb, beside weight_push/write/read
_P2P_BYTES = obs.counter(
    "p2p_bytes_total",
    "bytes moved through p2p endpoints by verb",
)

_MAGIC = b"UKT1"


class TierRef:
    """One deep-tier trie resident: names WHERE an entry's bytes live
    (``tier`` ∈ {"t1", "t2"}, store key ``key``), how many prompt-prefix
    token rows it holds (``tokens``), whether a promotion reproduces the
    donor rows bit-exactly (``exact`` — False for quantized-at-rest
    entries), and its at-rest size (``nbytes``). Hashed by identity: the
    trie treats it as an opaque non-int resident."""

    __slots__ = ("tier", "key", "tokens", "exact", "nbytes")

    def __init__(self, tier: str, key: int, tokens: int, exact: bool,
                 nbytes: int):
        self.tier = tier
        self.key = key
        self.tokens = tokens
        self.exact = exact
        self.nbytes = nbytes

    def __repr__(self):
        return (f"TierRef({self.tier}, key={self.key}, "
                f"tokens={self.tokens}, exact={self.exact})")


# -- the at-rest codec --------------------------------------------------------
#
# One entry = the victim slot's exported (k, v) rows, each [L, n, Hkv, D]
# f32. Lossless mode concatenates the raw bytes (bit-exact round trip);
# quantized mode block-scales each tensor along D through the shared
# ops/quant codec and stores payload + f32 scale sidecar. The blob is one
# flat uint8 array (what crosses the T2 wire in one windowed writev), the
# meta dict is its self-description (what rides the JSON control frame).


def encode_entry(k_rows: np.ndarray, v_rows: np.ndarray,
                 wire_dtype: Optional[str] = None,
                 block: int = 32) -> Tuple[np.ndarray, dict]:
    """Encode one entry's KV rows for at-rest storage.

    Returns ``(blob, meta)``: a flat uint8 array and the dict that decodes
    it. ``wire_dtype=None`` stores raw f32 (bit-exact); "fp8"/"int8" stores
    block-scaled payloads (+ per-block f32 scales) along the head dim.
    """
    from uccl_tpu.ops import quant

    k_rows = np.ascontiguousarray(np.asarray(k_rows, np.float32))
    v_rows = np.ascontiguousarray(np.asarray(v_rows, np.float32))
    if k_rows.shape != v_rows.shape:
        raise ValueError(
            f"k/v row shapes differ: {k_rows.shape} vs {v_rows.shape}"
        )
    shape = list(k_rows.shape)
    wire = quant.resolve_wire_dtype(wire_dtype)
    if wire is None:
        blob = np.concatenate([k_rows.reshape(-1).view(np.uint8),
                               v_rows.reshape(-1).view(np.uint8)])
        return blob, {"enc": "raw", "shape": shape}
    g = quant.adapt_block(shape[-1], block)
    import jax.numpy as jnp

    parts = []
    for t in (k_rows, v_rows):
        q, scale = quant.quantize_block(jnp.asarray(t), wire, g)
        parts.append(np.asarray(q).reshape(-1).view(np.uint8))
        parts.append(np.asarray(scale, np.float32).reshape(-1)
                     .view(np.uint8))
    nb = shape[-1] // g  # adapt_block returns a divisor: exact block count
    return np.concatenate(parts), {
        "enc": wire, "shape": shape, "block": g, "nblocks": nb,
    }


def decode_entry(blob: np.ndarray, meta: dict
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Inverse of :func:`encode_entry`: ``(k_rows, v_rows)`` f32, each of
    ``meta["shape"]``. Raw entries are bit-exact; quantized entries carry
    the codec's documented round-trip error."""
    from uccl_tpu.ops import quant

    blob = np.asarray(blob, np.uint8)
    shape = tuple(int(s) for s in meta["shape"])
    n = int(np.prod(shape))
    if meta["enc"] == "raw":
        if blob.nbytes != 2 * n * 4:
            raise ValueError(
                f"raw entry blob {blob.nbytes}B != 2x{n} f32"
            )
        half = n * 4
        k = blob[:half].view(np.float32).reshape(shape)
        v = blob[half:].view(np.float32).reshape(shape)
        return k.copy(), v.copy()
    import jax.numpy as jnp

    g = int(meta["block"])
    nb = int(meta["nblocks"])
    pdt = np.dtype(quant.wire_payload_dtype(meta["enc"]))
    scale_shape = shape[:-1] + (nb,)
    sn = int(np.prod(scale_shape))
    per = n * pdt.itemsize + sn * 4
    if blob.nbytes != 2 * per:
        raise ValueError(
            f"{meta['enc']} entry blob {blob.nbytes}B != 2x{per}B"
        )
    out = []
    for i in range(2):
        seg = blob[i * per:(i + 1) * per]
        q = seg[:n * pdt.itemsize].view(pdt).reshape(shape)
        scale = seg[n * pdt.itemsize:].view(np.float32).reshape(scale_shape)
        out.append(np.asarray(quant.dequantize_block(
            jnp.asarray(q), jnp.asarray(scale), g, dtype=jnp.float32
        )))
    return out[0], out[1]


# -- T1: the bounded host pool ------------------------------------------------


class HostKVTier:
    """Bounded host-memory entry store with LRU order — the T1 tier.

    Pure storage + accounting; the demote/spill/promote POLICY lives in
    :class:`TieredKVCache` (and the LRU *authority* for trie entries stays
    the trie's seq stamps — this order only breaks ties for spill victims,
    and the two agree by construction: demotions insert in eviction order
    and gets touch both)."""

    def __init__(self, capacity_bytes: int):
        if capacity_bytes < 1:
            raise ValueError(
                f"capacity_bytes must be >= 1, got {capacity_bytes}"
            )
        self.capacity_bytes = int(capacity_bytes)
        self.used_bytes = 0
        self.used_tokens = 0
        # key -> (blob, meta, ref); insertion/touch order = LRU order
        self._store: "OrderedDict[int, tuple]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._store)

    def __contains__(self, key: int) -> bool:
        return key in self._store

    def put(self, key: int, blob: np.ndarray, meta: dict, ref) -> None:
        if key in self._store:
            raise ValueError(f"t1 key {key} already stored")
        self._store[key] = (blob, meta, ref)
        self.used_bytes += int(blob.nbytes)
        self.used_tokens += int(ref.tokens)

    def get(self, key: int):
        ent = self._store.get(key)
        if ent is not None:
            self._store.move_to_end(key)
        return ent

    def pop(self, key: int):
        ent = self._store.pop(key, None)
        if ent is not None:
            self.used_bytes -= int(ent[0].nbytes)
            self.used_tokens -= int(ent[2].tokens)
        return ent

    def lru_key(self) -> Optional[int]:
        return next(iter(self._store), None)


# -- T2: the remote peer over the SACK channel --------------------------------
#
# Control plane: MAGIC + JSON on the channel's ordered path-0 send/recv
# (the weight_push framing); data plane: one windowed writev per entry blob
# into an advertised FifoItem window, CRC-verified before accept. Ops:
#
#   put:  c -> {op:put, key, nbytes, crc, meta}   s -> {op:win, fifo}
#         c writev(blob)  c -> {op:sent}          s -> {op:ok, evicted:[..]}
#   get:  c -> {op:get, key, fifo, max}           s -> {op:miss}
#                                     | s writev(blob) -> {op:hit, nbytes,
#                                                          crc, meta}
#   del:  c -> {op:del, key}                      s -> {op:ok}
#
# The server advertises capacity_bytes and enforces it by evicting ITS LRU
# entries on put; evicted keys ride back in the put response so the client
# invalidates their (now stale) trie refs eagerly instead of discovering
# the miss at promotion time.


def _send_msg(chan, msg: dict) -> None:
    chan.send(_MAGIC + json.dumps(msg).encode())


def _recv_msg(chan, timeout_ms: int) -> dict:
    raw = chan.recv(timeout_ms=timeout_ms)
    if not raw.startswith(_MAGIC):
        raise IOError(f"kv_tier: bad control frame {raw[:8]!r}")
    return json.loads(raw[len(_MAGIC):].decode())


class KvTierServer:
    """A remote KV tier peer: advertises ``capacity_bytes`` of entry
    storage over a :class:`~uccl_tpu.p2p.channel.Channel` and serves
    put/get/del requests until the channel dies (the WeightPublisher
    serve_forever pattern)."""

    def __init__(self, capacity_bytes: int):
        if capacity_bytes < 1:
            raise ValueError(
                f"capacity_bytes must be >= 1, got {capacity_bytes}"
            )
        self.capacity_bytes = int(capacity_bytes)
        self.used_bytes = 0
        self._lock = threading.Lock()
        # key -> (blob, meta); insertion/touch order = LRU order
        self._store: "OrderedDict[int, tuple]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._store)

    # -- storage (lock-guarded: serve loop + tests may race) ---------------
    def _reserve(self, nbytes: int):
        """Make room for an ``nbytes`` entry; returns the evicted keys."""
        evicted = []
        with self._lock:
            while (self._store
                   and self.used_bytes + nbytes > self.capacity_bytes):
                k, (blob, _m) = self._store.popitem(last=False)
                self.used_bytes -= blob.nbytes
                evicted.append(int(k))
        return evicted

    def _put(self, key: int, blob: np.ndarray, meta: dict):
        with self._lock:
            self._store[key] = (blob, meta)
            self.used_bytes += blob.nbytes

    def _get(self, key: int):
        with self._lock:
            ent = self._store.get(key)
            if ent is not None:
                self._store.move_to_end(key)
            return ent

    def _del(self, key: int):
        with self._lock:
            ent = self._store.pop(key, None)
            if ent is not None:
                self.used_bytes -= ent[0].nbytes

    # -- the serve loop ----------------------------------------------------
    def serve(self, chan, timeout_ms: int = 60000) -> str:
        """Handle ONE request on ``chan`` (blocking). Returns the op."""
        req = _recv_msg(chan, timeout_ms)
        op = req.get("op")
        if op == "put":
            nbytes = int(req["nbytes"])
            if nbytes > self.capacity_bytes:
                _send_msg(chan, {"op": "err",
                                 "msg": f"entry {nbytes}B > capacity "
                                        f"{self.capacity_bytes}B"})
                return op
            evicted = self._reserve(nbytes)
            buf = np.zeros(nbytes, np.uint8)
            ep = chan.ep
            mr = ep.reg(buf)
            try:
                _send_msg(chan, {"op": "win",
                                 "fifo": ep.advertise(mr).hex()})
                sent = _recv_msg(chan, timeout_ms)
                if sent.get("op") != "sent":
                    raise IOError(f"kv_tier: expected sent, got {sent}")
                if zlib.crc32(buf) != int(req["crc"]):
                    _send_msg(chan, {"op": "err", "msg": "CRC mismatch"})
                    return op
            finally:
                ep.dereg(mr)
            self._put(int(req["key"]), buf, req["meta"])
            _P2P_BYTES.inc(nbytes, verb="kv_tier")
            _send_msg(chan, {"op": "ok", "evicted": evicted})
            return op
        if op == "get":
            ent = self._get(int(req["key"]))
            limit = int(req.get("max", 0))
            if ent is None or (limit and ent[0].nbytes > limit):
                # unknown key, or an entry too large for this client's
                # advertised window (the writev would overrun its
                # registration): both are a miss to this client
                _send_msg(chan, {"op": "miss"})
                return op
            blob, meta = ent
            chan.writev([blob], [bytes.fromhex(req["fifo"])],
                        timeout_ms=timeout_ms)
            _send_msg(chan, {"op": "hit", "nbytes": int(blob.nbytes),
                             "crc": zlib.crc32(blob), "meta": meta})
            return op
        if op == "del":
            self._del(int(req["key"]))
            _send_msg(chan, {"op": "ok"})
            return op
        raise IOError(f"kv_tier: unknown op {req}")

    def serve_forever(self, chan, timeout_ms: int = 60000):
        """Daemon helper: serve requests on ``chan`` until it dies. A
        dying loop is never silent (the Channel CC-probe rule): the
        terminating exception is counted on
        ``kv_tier_serve_errors_total{reason}``; a timed-out idle recv is
        the one quiet exit."""

        def loop():
            while True:
                try:
                    self.serve(chan, timeout_ms)
                except TimeoutError:
                    return  # idle channel: no request within the window
                except Exception as e:
                    obs.counter(
                        "kv_tier_serve_errors_total",
                        "kv-tier serve loops terminated by an exception, "
                        "by exception class",
                    ).inc(reason=type(e).__name__)
                    _log.warning("kv_tier: serve loop terminating (%s: %s)",
                                 type(e).__name__, e)
                    return

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        return t


class RemoteKVTier:
    """Client side of the T2 tier: put/get/del against a
    :class:`KvTierServer` over one channel. Maintains a registered scratch
    window of ``max_entry_bytes`` for gets (one registration per client,
    not per fetch) and byte/token accounting for the t2 gauges."""

    def __init__(self, chan, max_entry_bytes: int,
                 timeout_ms: int = 60000):
        self.chan = chan
        self.timeout_ms = timeout_ms
        self.max_entry_bytes = int(max_entry_bytes)
        self._buf = np.zeros(self.max_entry_bytes, np.uint8)
        self._mr = chan.ep.reg(self._buf)
        self.used_bytes = 0
        self.used_tokens = 0

    def put(self, key: int, blob: np.ndarray, meta: dict):
        """Ship one entry; returns the server's evicted-key list (stale
        refs the caller must invalidate), or None when the entry is
        refused — larger than the server's capacity, or larger than this
        client's ``max_entry_bytes`` scratch window (stored, it could
        never be fetched back without the server writing past the
        window's registration)."""
        blob = np.ascontiguousarray(np.asarray(blob, np.uint8))
        if blob.nbytes > self.max_entry_bytes:
            return None
        _send_msg(self.chan, {"op": "put", "key": int(key),
                              "nbytes": int(blob.nbytes),
                              "crc": zlib.crc32(blob), "meta": meta})
        win = _recv_msg(self.chan, self.timeout_ms)
        if win.get("op") == "err":
            return None
        if win.get("op") != "win":
            raise IOError(f"kv_tier: expected win, got {win}")
        self.chan.writev([blob], [bytes.fromhex(win["fifo"])],
                         timeout_ms=self.timeout_ms)
        _send_msg(self.chan, {"op": "sent"})
        ok = _recv_msg(self.chan, self.timeout_ms)
        if ok.get("op") != "ok":
            raise IOError(f"kv_tier: put rejected: {ok}")
        return [int(k) for k in ok.get("evicted", [])]

    def get(self, key: int) -> Optional[Tuple[np.ndarray, dict]]:
        """Fetch one entry into the scratch window; CRC-verified. None on
        a miss (the server LRU-dropped it — a stale ref)."""
        fifo = self.chan.ep.advertise(self._mr)
        _send_msg(self.chan, {"op": "get", "key": int(key),
                              "fifo": fifo.hex(),
                              "max": self.max_entry_bytes})
        resp = _recv_msg(self.chan, self.timeout_ms)
        if resp.get("op") == "miss":
            return None
        if resp.get("op") != "hit":
            raise IOError(f"kv_tier: expected hit, got {resp}")
        nbytes = int(resp["nbytes"])
        if nbytes > self.max_entry_bytes:
            raise IOError(
                f"kv_tier: peer claims a {nbytes}B entry landed in a "
                f"{self.max_entry_bytes}B window"
            )
        blob = self._buf[:nbytes].copy()
        if zlib.crc32(blob) != int(resp["crc"]):
            raise IOError("kv_tier: get CRC mismatch (wire corruption "
                          "past the SACK layer)")
        _P2P_BYTES.inc(nbytes, verb="kv_tier")
        return blob, resp["meta"]

    def delete(self, key: int) -> None:
        _send_msg(self.chan, {"op": "del", "key": int(key)})
        ok = _recv_msg(self.chan, self.timeout_ms)
        if ok.get("op") != "ok":
            raise IOError(f"kv_tier: del rejected: {ok}")

    def close(self) -> None:
        self.chan.ep.dereg(self._mr)


# -- the tier manager ---------------------------------------------------------


class TieredKVCache:
    """Demotion/promotion policy over {T1 host pool, optional T2 remote},
    attached behind one engine's :class:`PrefixCache`.

    The engine calls :meth:`demote` from its eviction path (via
    ``PrefixCache.evict_lru(demote=...)``) and :meth:`promote` from its
    hit path; the trie calls :meth:`release` whenever it drops a tier-ref
    resident. Invariants (tested): an entry lives in exactly one tier;
    demotion never blocks admission (a full T1 spills to T2 or DROPS,
    counted); promotion writes only the admitted request's own slot, never
    evicting the donor entry it serves.
    """

    def __init__(self, host_bytes: int, *,
                 wire_dtype: Optional[str] = None, block: int = 32,
                 remote: Optional[RemoteKVTier] = None,
                 remote_fail_limit: int = 3):
        from uccl_tpu.ops import quant

        self.wire_dtype = quant.resolve_wire_dtype(wire_dtype)
        self.block = int(block)
        self.t1 = HostKVTier(host_bytes)
        self.remote = remote
        self.remote_fail_limit = int(remote_fail_limit)
        self.backend = None
        self.cache = None
        self._next_key = 0
        self._remote_failures = 0  # consecutive comms failures
        self._remote_dead = False  # latched after remote_fail_limit
        # our view of what lives on the remote peer: key -> ref (pruned on
        # eviction notices, deletes, and discovered-stale gets)
        self._t2_refs: Dict[int, TierRef] = {}

    @property
    def exact(self) -> bool:
        """Whether at-rest entries round-trip bit-exactly (lossless f32)."""
        return self.wire_dtype is None

    def attach(self, backend, cache) -> None:
        """Bind the engine's backend (the KV byte mover) and trie (the
        index). Called by ``ServingEngine.__init__``."""
        self.backend = backend
        self.cache = cache
        cache.attach_tiers(self)

    # -- gauges ------------------------------------------------------------
    def _stamp(self) -> None:
        _RES_TOKENS.set(self.t1.used_tokens, tier="t1")
        _RES_BYTES.set(self.t1.used_bytes, tier="t1")
        if self.remote is not None:
            _RES_TOKENS.set(self.remote.used_tokens, tier="t2")
            _RES_BYTES.set(self.remote.used_bytes, tier="t2")

    def count_hit(self, tier: str) -> None:
        """Per-tier hit accounting (the engine calls this for t0 hits too,
        so the tier split sums to ``prefix_cache_hits_total``)."""
        _TIER_HITS.inc(tier=tier)

    # -- demotion (the eviction path) --------------------------------------
    def demote(self, slot: int, n_tokens: int) -> Optional[TierRef]:
        """Export a T0 eviction victim's rows [0, n_tokens) into T1 and
        return the tier ref to splice into the trie — or None when the
        entry cannot be kept (empty, or larger than the whole host pool:
        counted on ``kv_tier_drops_total{tier="t1"}``). Never blocks: a
        full T1 spills its LRU entries down (or out) first."""
        if n_tokens < 1 or self.backend is None:
            return None
        with obs.span("kv_tier.demote", track="engine", tier="t1",
                      slot=slot, tokens=n_tokens):
            k_rows, v_rows = self.backend.export_slot_kv(slot, 0, n_tokens)
            blob, meta = encode_entry(k_rows, v_rows, self.wire_dtype,
                                      self.block)
            if blob.nbytes > self.t1.capacity_bytes:
                _DROPS.inc(tier="t1")
                return None
            while (self.t1.used_bytes + blob.nbytes
                   > self.t1.capacity_bytes):
                self._spill_lru()
            key = self._next_key
            self._next_key += 1
            ref = TierRef("t1", key, n_tokens, self.exact,
                          int(blob.nbytes))
            self.t1.put(key, blob, meta, ref)
        _DEMOTIONS.inc(tier="t1")
        self._stamp()
        return ref

    def _remote_failure(self, verb: str, exc: Exception) -> None:
        """Count one remote-tier comms failure. After ``remote_fail_limit``
        CONSECUTIVE failures the tier latches dead: spills drop (counted)
        and T2 hits degrade to misses without touching the channel again —
        a dying peer costs at most ``remote_fail_limit`` timeouts."""
        self._remote_failures += 1
        if self._remote_failures >= self.remote_fail_limit:
            self._remote_dead = True
        _log.warning(
            "kv_tier: t2 %s failed (%s: %s) — failure %d/%d%s", verb,
            type(exc).__name__, exc, self._remote_failures,
            self.remote_fail_limit,
            "; remote tier latched dead" if self._remote_dead else "",
        )

    def _spill_lru(self) -> None:
        """Move T1's LRU entry down to T2 (or drop it, counted) — the
        trie's resident swaps via ``replace_ref`` at the SAME path and LRU
        stamp, so the entry keeps its identity and recency. A remote-tier
        failure (channel timeout, refused put) degrades to the same
        counted drop: demotion never raises into the admission path."""
        key = self.t1.lru_key()
        blob, meta, ref = self.t1.pop(key)
        new_ref = None
        if self.remote is not None and not self._remote_dead:
            try:
                evicted = self.remote.put(key, blob, meta)
            except Exception as e:  # entry already out of T1: drop it
                evicted = None
                self._remote_failure("put", e)
            else:
                self._remote_failures = 0
            if evicted is not None:
                new_ref = TierRef("t2", key, ref.tokens, ref.exact,
                                  int(blob.nbytes))
                self._t2_refs[key] = new_ref
                self.remote.used_bytes += int(blob.nbytes)
                self.remote.used_tokens += int(ref.tokens)
                _DEMOTIONS.inc(tier="t2")
                # the peer made room by LRU-dropping: invalidate those
                # entries' refs NOW instead of missing at promotion time
                for ek in evicted:
                    self._invalidate_t2(ek)
        if new_ref is None:
            _DROPS.inc(tier="t1")
        self.cache.replace_ref(ref, new_ref)
        self._stamp()

    def _invalidate_t2(self, key: int, drop_trie: bool = True) -> None:
        """Forget a remote entry (eviction notice, discovered-stale get).
        ``drop_trie=False`` releases only this side's accounting and
        leaves the trie resident to the caller — :meth:`promote`'s miss
        path, whose contract already hands the trie drop to the engine
        (dropping here too would double-remove and KeyError)."""
        stale = self._t2_refs.pop(key, None)
        if stale is None:
            return
        self.remote.used_bytes -= stale.nbytes
        self.remote.used_tokens -= stale.tokens
        _DROPS.inc(tier="t2")
        if drop_trie and stale in self.cache._resident:
            self.cache.replace_ref(stale, None)

    # -- promotion (the hit path) ------------------------------------------
    def promote(self, ref: TierRef, slot: int, n_tokens: int) -> bool:
        """Serve a deep-tier hit: fetch ``ref``'s entry, decode, and import
        rows [0, n_tokens) into the admitted request's own ``slot`` (which
        then resumes prefill at ``n_tokens``). The donor entry is read,
        never moved — promotion cannot evict what it serves. Returns False
        on a stale ref (the caller treats the admission as a cold miss and
        drops the ref)."""
        if n_tokens > ref.tokens:
            raise ValueError(
                f"promote of {n_tokens} tokens from a {ref.tokens}-token "
                f"entry ({ref})"
            )
        with obs.span("kv_tier.promote", track="engine", tier=ref.tier,
                      slot=slot, tokens=n_tokens, exact=ref.exact):
            if ref.tier == "t1":
                ent = self.t1.get(ref.key)
                if ent is None:
                    return False
                blob, meta, _ = ent
            else:
                got = None
                if self.remote is not None and not self._remote_dead:
                    try:
                        got = self.remote.get(ref.key)
                    except Exception as e:  # degrade to a stale miss
                        self._remote_failure("get", e)
                    else:
                        self._remote_failures = 0
                if got is None:
                    # release OUR accounting only: the caller is the
                    # single owner of the trie drop on a stale ref
                    self._invalidate_t2(ref.key, drop_trie=False)
                    return False
                blob, meta = got
            k_rows, v_rows = decode_entry(blob, meta)
            self.backend.import_slot_kv(
                slot, k_rows[:, :n_tokens], v_rows[:, :n_tokens],
                length=n_tokens,
            )
        _PROMOTIONS.inc(tier=ref.tier)
        _TIER_HITS.inc(tier=ref.tier)
        return True

    # -- release (the trie dropped a ref) ----------------------------------
    def release(self, ref: TierRef) -> None:
        """Free a dropped trie entry's store bytes. Idempotent — the
        spill/invalidate paths move bytes BEFORE swapping the resident, so
        the release embedded in ``PrefixCache._remove`` is a no-op for
        them."""
        if ref.tier == "t1":
            if self.t1.pop(ref.key) is not None:
                self._stamp()
            return
        if ref.key in self._t2_refs:
            del self._t2_refs[ref.key]
            self.remote.used_bytes -= ref.nbytes
            self.remote.used_tokens -= ref.tokens
            if not self._remote_dead:
                try:
                    self.remote.delete(ref.key)
                except Exception:
                    pass  # best-effort: the peer's LRU reclaims it anyway
            self._stamp()
