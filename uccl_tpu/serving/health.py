"""Fleet failure detection: heartbeat/lease liveness for serving peers.

Until now every layer of the serving fleet assumed its peers were
immortal: a dead replica stranded its queued and active requests, a
prefill worker that died after GRANT leaked a decode slot forever, and
``drain()`` loops just timed out and raised. This module is the missing
control-plane primitive — a :class:`FailureDetector` running the classic
per-peer **HEALTHY → SUSPECT → DEAD** state machine off heartbeats
(docs/SERVING.md):

* **remote peers** (disagg workers over the p2p plane) are tracked by
  heartbeat notifs (``{"t": "hb"}`` riding the same notif plane as
  BEGIN/GRANT/FINAL — the prefill worker's pump sends them, the decode
  worker's poll feeds them in via :meth:`FailureDetector.heartbeat`);
* **in-process replicas** (the Router's engines) get a liveness-probe
  equivalent: a callable checked at every :meth:`tick` whose ``True``
  counts as a heartbeat — so the Router covers both kinds of replica
  with one detector.

A peer whose last heartbeat is older than ``suspect_after_s`` becomes
SUSPECT (excluded from new routing but not yet recovered — the grace
window absorbs GC pauses and compile stalls without flapping); older than
``dead_after_s`` it becomes DEAD, which is **terminal for the
registration** (a late heartbeat from a dead peer must not resurrect
state the fleet already recovered — re-admit a returning peer by
re-registering it, the elastic up-scale path). A SUSPECT peer that
heartbeats returns to HEALTHY — the tested no-flap property.

Telemetry (docs/OBSERVABILITY.md): ``fleet_peer_state{peer}`` gauge
(0 = healthy, 1 = suspect, 2 = dead), ``fleet_heartbeats_total{peer}``,
and ``peer_suspect`` / ``peer_dead`` trace instants on every transition.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from uccl_tpu import obs
from uccl_tpu.utils.logging import get_logger

_log = get_logger("UTIL")

HEALTHY = "healthy"
SUSPECT = "suspect"
DEAD = "dead"

_STATE_CODE = {HEALTHY: 0, SUSPECT: 1, DEAD: 2}

_PEER_STATE = obs.gauge(
    "fleet_peer_state",
    "failure-detector state per peer (0=healthy, 1=suspect, 2=dead)",
)
_HEARTBEATS = obs.counter(
    "fleet_heartbeats_total",
    "heartbeats observed per peer (notif-borne hb messages, or "
    "in-process liveness probes returning alive)",
)
_RECOVERED = obs.counter(
    "serving_recovered_total",
    "requests recovered off a DEAD replica by outcome: "
    "resubmitted (was queued — re-queued on a survivor under the same "
    "trace_id), restarted (was active — re-run from scratch on a "
    "survivor), lost (no survivor could take it, counted into the "
    "conservation invariant's `lost` term)",
)


@dataclass
class _Peer:
    name: str
    t_last: float
    state: str = HEALTHY
    probe: Optional[Callable[[], bool]] = None
    transitions: List[Tuple[str, float]] = field(default_factory=list)


class FailureDetector:
    """Per-peer HEALTHY→SUSPECT→DEAD liveness off heartbeats or probes.

    ``suspect_after_s`` is the silence that makes a peer SUSPECT (routing
    exclusion), ``dead_after_s`` the silence that makes it DEAD (recovery
    fires). The gap between the two is the **suspect grace window**: a
    peer that resumes heartbeating inside it returns to HEALTHY with no
    recovery churn. ``clock`` is injectable (monotonic seconds) so tests
    drive transitions without sleeping.
    """

    def __init__(self, *, suspect_after_s: float = 0.5,
                 dead_after_s: float = 1.5,
                 clock: Callable[[], float] = time.monotonic):
        if suspect_after_s <= 0:
            raise ValueError(
                f"suspect_after_s must be > 0, got {suspect_after_s}"
            )
        if dead_after_s <= suspect_after_s:
            raise ValueError(
                f"dead_after_s ({dead_after_s}) must exceed "
                f"suspect_after_s ({suspect_after_s}): the grace window "
                "is what keeps a slow peer from flapping straight to DEAD"
            )
        self.suspect_after_s = suspect_after_s
        self.dead_after_s = dead_after_s
        self._clock = clock
        self._peers: Dict[str, _Peer] = {}

    # -- membership ----------------------------------------------------
    def register(self, peer, probe: Optional[Callable[[], bool]] = None,
                 ) -> None:
        """Start tracking ``peer`` (any hashable — str()'d for labels),
        initially HEALTHY with a fresh heartbeat. ``probe`` makes it an
        in-process peer: each :meth:`tick` calls it, and ``True`` counts
        as a heartbeat (the Router's replica liveness equivalent).
        Re-registering an existing peer resets it to HEALTHY — the
        explicit resurrection path for a returning peer."""
        name = str(peer)
        self._peers[name] = _Peer(name, self._clock(), probe=probe)
        _PEER_STATE.set(0, peer=name)

    def deregister(self, peer) -> None:
        self._peers.pop(str(peer), None)

    def peers(self) -> List[str]:
        return list(self._peers)

    def state(self, peer) -> str:
        return self._peers[str(peer)].state

    def is_routable(self, peer) -> bool:
        """Only HEALTHY peers take new work (SUSPECT is excluded but not
        yet recovered; DEAD is gone)."""
        p = self._peers.get(str(peer))
        return p is not None and p.state == HEALTHY

    # -- liveness feeds ------------------------------------------------
    def heartbeat(self, peer, t: Optional[float] = None) -> None:
        """Record one heartbeat from ``peer`` (a notif-borne hb, or any
        control message proving liveness). A SUSPECT peer returns to
        HEALTHY; a DEAD peer stays dead (terminal per registration —
        its state was already recovered elsewhere)."""
        p = self._peers.get(str(peer))
        if p is None:
            return  # unknown peer: late hb after deregistration
        _HEARTBEATS.inc(peer=p.name)
        if p.state == DEAD:
            return
        p.t_last = t if t is not None else self._clock()
        if p.state == SUSPECT:
            p.state = HEALTHY
            p.transitions.append((HEALTHY, p.t_last))
            _PEER_STATE.set(0, peer=p.name)

    def tick(self, t: Optional[float] = None) -> List[Tuple[str, str]]:
        """Advance every peer's state at time ``t`` (default: the clock).
        Probed peers are probed first (alive == heartbeat). Returns the
        transitions fired this tick as ``(peer, new_state)`` pairs — the
        Router consumes the DEAD ones to trigger recovery."""
        now = t if t is not None else self._clock()
        fired: List[Tuple[str, str]] = []
        for p in self._peers.values():
            if p.state == DEAD:
                continue
            if p.probe is not None:
                alive = False
                try:
                    alive = bool(p.probe())
                except Exception:
                    pass  # a raising probe is a dead peer
                if alive:
                    self.heartbeat(p.name, t=now)
                    continue
            age = now - p.t_last
            if age > self.dead_after_s:
                p.state = DEAD
                p.transitions.append((DEAD, now))
                _PEER_STATE.set(2, peer=p.name)
                obs.instant("peer_dead", track="health", peer=p.name,
                            silent_s=round(age, 4))
                # terminal transition = post-mortem moment: freeze the
                # ring + registry before recovery churns them (one
                # bundle per peer — the recorder dedupes on the key)
                obs.flight_trigger(
                    "peer_dead",
                    # the detector's identity is part of the key: two
                    # detectors (router + disagg) may both track a peer
                    # named "0", and each death deserves its own bundle
                    key=f"health:{id(self):x}:{p.name}", peer=p.name,
                    source="health", silent_s=round(age, 4),
                    suspect_after_s=self.suspect_after_s,
                    dead_after_s=self.dead_after_s,
                    transitions=[(s, round(ts, 4))
                                 for s, ts in p.transitions])
                _log.warning("peer %s DEAD after %.3fs silence",
                             p.name, age)
                fired.append((p.name, DEAD))
            elif age > self.suspect_after_s and p.state == HEALTHY:
                p.state = SUSPECT
                p.transitions.append((SUSPECT, now))
                _PEER_STATE.set(1, peer=p.name)
                obs.instant("peer_suspect", track="health", peer=p.name,
                            silent_s=round(age, 4))
                fired.append((p.name, SUSPECT))
        return fired


def abandon_engine(engine) -> List:
    """Strip every queued and in-slot request off a dead engine and count
    ALL of them lost (``serving_recovered_total{outcome="lost"}`` + the
    dead engine's ``lost`` metric — the conservation invariant's sink
    term). This is the no-survivors recovery (a standalone worker dying
    with nobody to resubmit to); the Router's recovery instead evacuates
    and re-routes (uccl_tpu/serving/router.py). Returns the abandoned
    requests."""
    from uccl_tpu.serving.request import RequestState

    queued, active = engine.evacuate()
    for req in queued + active:
        req.state = RequestState.LOST
        req.finish_reason = "replica_dead"
        engine.metrics.on_lost(req)
        _RECOVERED.inc(outcome="lost")
        obs.instant("recover", track="health", rid=req.rid,
                    outcome="lost", trace_id=req.trace_id)
    return queued + active
