"""Per-request sampling policy for the serving engine (ISSUE 18).

:class:`SamplingParams` is the request-side knob set — temperature,
top-p / top-k truncation, and a seed. The math lives in
:mod:`uccl_tpu.models.sampling` (beside the models that execute it, so
both stacks import it without a package cycle); this module owns the
policy object, its validation, and the host-side batching the engine uses
to build per-slot parameter arrays for the slot primitives.

Determinism contract: a request's sample at output position ``i`` depends
ONLY on (seed, i, the logits row) — ``fold_in(PRNGKey(seed), i)`` is the
key, whatever path (chunked prefill, slot reuse, preemption/resume,
speculative verify) produced the row. Two requests with equal prompts,
params and seeds emit identical tokens; the engine is bit-identical to
the sampled one-shot ``generate`` oracle at equal seeds (tested, not
tolerated — docs/SERVING.md).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

# re-exported: the one sampling definition both stacks and the oracles use
from uccl_tpu.models.sampling import (  # noqa: F401
    broadcast_params, fold_key, sample_tokens, sample_window,
)


@dataclass(frozen=True)
class SamplingParams:
    """One request's sampling policy.

    ``temperature <= 0`` means greedy (the per-row rule the compiled
    sampler applies, so mixed greedy/sampled batches share one program);
    ``top_k <= 0`` disables top-k; ``top_p >= 1`` disables nucleus
    truncation. ``seed`` is the request's whole entropy source.
    """

    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0
    seed: int = 0

    def __post_init__(self):
        if not np.isfinite(self.temperature):
            raise ValueError(f"temperature must be finite, got "
                             f"{self.temperature}")
        if not (0.0 < self.top_p <= 1.0):
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if not (-(2 ** 31) <= int(self.seed) < 2 ** 31):
            raise ValueError(f"seed must fit int32, got {self.seed}")


#: the arrays a slot batch feeds the sampled primitives, in order
FIELDS = ("seeds", "pos0", "temp", "top_p", "top_k")


def slot_arrays(n_slots: int):
    """Fresh host-side per-slot sampling arrays, all greedy (temp=0) —
    the engine mutates rows at admit/retire and ships copies per call."""
    return {
        "seeds": np.zeros(n_slots, np.int32),
        "pos0": np.zeros(n_slots, np.int32),
        "temp": np.zeros(n_slots, np.float32),
        "top_p": np.ones(n_slots, np.float32),
        "top_k": np.zeros(n_slots, np.int32),
    }


def stamp_slot(arrays, slot: int, params: "SamplingParams | None") -> None:
    """Write one request's params into its slot row (None → greedy row)."""
    if params is None:
        arrays["seeds"][slot] = 0
        arrays["temp"][slot] = 0.0
        arrays["top_p"][slot] = 1.0
        arrays["top_k"][slot] = 0
    else:
        arrays["seeds"][slot] = np.int32(int(params.seed))
        arrays["temp"][slot] = np.float32(params.temperature)
        arrays["top_p"][slot] = np.float32(params.top_p)
        arrays["top_k"][slot] = np.int32(params.top_k)


def pack(arrays, pos0) -> tuple:
    """The positional tuple the backends accept: (seeds, pos0, temp,
    top_p, top_k), with ``pos0`` supplied per call (each slot's output
    index for the first token this call emits)."""
    return (arrays["seeds"].copy(), np.asarray(pos0, np.int32),
            arrays["temp"].copy(), arrays["top_p"].copy(),
            arrays["top_k"].copy())
