"""Synthetic load generation + the Poisson arrival drive loop.

One implementation shared by ``python -m uccl_tpu.serve --server`` (the CI
serving smoke tier) and ``benchmarks/serving_bench.py`` — both must
measure the SAME loop, or a warmup/arrival-timing fix would land in only
one of them.
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

import numpy as np

from uccl_tpu.serving.engine import ServingEngine, _bucket
from uccl_tpu.serving.request import Request, now


def synth_workload(rng: np.random.Generator, n: int, prompt_len: int,
                   vocab: int, arrival_rate: float):
    """Mixed-length prompts (lengths in [max(1, L/2), L]) with Poisson
    arrival offsets (all at t=0 when rate is 0). Returns
    (prompts, lens, arrivals)."""
    lo = max(1, prompt_len // 2)
    lens = rng.integers(lo, prompt_len + 1, n)
    prompts = [rng.integers(0, vocab, l).astype(np.int32) for l in lens]
    if arrival_rate > 0:
        arrivals = np.cumsum(rng.exponential(1.0 / arrival_rate, n))
    else:
        arrivals = np.zeros(n)
    return prompts, lens, arrivals


def synth_shared_workload(rng: np.random.Generator, n: int, prompt_len: int,
                          vocab: int, arrival_rate: float, hit_rate: float,
                          shared_len: int):
    """Mixed workload with a shared "system prompt": with probability
    ``hit_rate`` a request's prompt is the fixed ``shared_len``-token
    prefix plus a random tail (prefix-cache fodder); otherwise a plain
    mixed-length random prompt as in :func:`synth_workload`. Returns
    (prompts, lens, arrivals)."""
    if not (0 < shared_len < prompt_len):
        raise ValueError(
            f"shared_len must be in (0, prompt_len), got {shared_len} of "
            f"{prompt_len}"
        )
    # arrivals FIRST: every hit-rate arm at the same seed then faces the
    # identical arrival stream, so TTFT/goodput deltas are cache effects,
    # not Poisson-sample luck
    if arrival_rate > 0:
        arrivals = np.cumsum(rng.exponential(1.0 / arrival_rate, n))
    else:
        arrivals = np.zeros(n)
    shared = rng.integers(0, vocab, shared_len).astype(np.int32)
    prompts = []
    for _ in range(n):
        if rng.random() < hit_rate:
            tail = rng.integers(1, prompt_len - shared_len + 1)
            prompts.append(np.concatenate(
                [shared, rng.integers(0, vocab, tail).astype(np.int32)]
            ))
        else:
            lo = max(1, prompt_len // 2)
            prompts.append(rng.integers(
                0, vocab, rng.integers(lo, prompt_len + 1)
            ).astype(np.int32))
    lens = np.asarray([p.size for p in prompts])
    return prompts, lens, arrivals


def synth_multi_prefix_workload(rng: np.random.Generator, n: int,
                                prompt_len: int, vocab: int,
                                arrival_rate: float, n_prefixes: int,
                                shared_len: int):
    """Working-set workload for the tiered KV cache: ``n_prefixes``
    distinct fixed ``shared_len``-token prefixes (a fleet of tenants'
    system prompts), request ``i`` using prefix ``i % n_prefixes`` plus a
    random tail. The deterministic round-robin is the point: with a
    working set larger than the device slot count, every prefix's donor is
    LRU-evicted (demoted, with tiers attached) before its next use, so the
    stream forces demote→promote cycles instead of lucky T0 re-hits.
    ``n_prefixes`` IS the working set — sweep it against ``n_slots`` for
    the 10–100× capacity axis. Arrivals are drawn FIRST so every tier
    config at the same seed faces the identical arrival stream (the
    synth_shared_workload rule). Returns (prompts, lens, arrivals)."""
    if n_prefixes < 1:
        raise ValueError(f"n_prefixes must be >= 1, got {n_prefixes}")
    if not (0 < shared_len < prompt_len):
        raise ValueError(
            f"shared_len must be in (0, prompt_len), got {shared_len} of "
            f"{prompt_len}"
        )
    if arrival_rate > 0:
        arrivals = np.cumsum(rng.exponential(1.0 / arrival_rate, n))
    else:
        arrivals = np.zeros(n)
    prefixes = [rng.integers(0, vocab, shared_len).astype(np.int32)
                for _ in range(n_prefixes)]
    prompts = []
    for i in range(n):
        tail = int(rng.integers(1, prompt_len - shared_len + 1))
        prompts.append(np.concatenate(
            [prefixes[i % n_prefixes],
             rng.integers(0, vocab, tail).astype(np.int32)]
        ))
    lens = np.asarray([p.size for p in prompts])
    return prompts, lens, arrivals


def synth_repeat_workload(rng: np.random.Generator, n: int, prompt_len: int,
                          vocab: int, arrival_rate: float,
                          motif_max: int = 2):
    """Repetitive-prompt workload — the regime a prompt-lookup drafter
    (serving/spec.py) targets: template/boilerplate-heavy traffic whose
    greedy continuations settle into short cycles. Each prompt tiles a
    random 1..``motif_max``-token motif to a mixed length in
    [max(1, L/2), L]; :func:`synth_workload`'s random prompts bound the
    other end of the acceptance spectrum (novel text, near-zero
    acceptance). Arrivals are drawn FIRST so every arm at the same seed
    faces the identical arrival stream (the synth_shared_workload rule).
    Returns (prompts, lens, arrivals)."""
    if motif_max < 1:
        raise ValueError(f"motif_max must be >= 1, got {motif_max}")
    if arrival_rate > 0:
        arrivals = np.cumsum(rng.exponential(1.0 / arrival_rate, n))
    else:
        arrivals = np.zeros(n)
    lo = max(1, prompt_len // 2)
    prompts = []
    for _ in range(n):
        ml = int(rng.integers(1, motif_max + 1))
        motif = rng.integers(0, vocab, ml).astype(np.int32)
        length = int(rng.integers(lo, prompt_len + 1))
        prompts.append(np.tile(motif, (length + ml - 1) // ml)[:length])
    lens = np.asarray([p.size for p in prompts])
    return prompts, lens, arrivals


def assign_classes(rng: np.random.Generator, n: int,
                   interactive_frac: float,
                   pattern: str = "bernoulli"):
    """Priority-class labels for a workload. ``bernoulli`` draws each
    request ``interactive`` with probability ``interactive_frac`` (class
    arrivals interleave the way mixed traffic really does); ``batch-first``
    puts every batch request at the FRONT of the arrival order — the
    deterministic preemption fixture: batch work occupies the slots before
    any interactive request arrives, so each interactive arrival must
    preempt (the qa/ci smoke arm's guarantee). Call AFTER drawing the
    arrival stream in callers that share arrivals across arms, so the mix
    knob never perturbs timing."""
    if not (0.0 <= interactive_frac <= 1.0):
        raise ValueError(
            f"interactive_frac must be in [0, 1], got {interactive_frac}"
        )
    if pattern == "bernoulli":
        return ["interactive" if rng.random() < interactive_frac
                else "batch" for _ in range(n)]
    if pattern == "batch-first":
        n_int = round(n * interactive_frac)
        return ["batch"] * (n - n_int) + ["interactive"] * n_int
    raise ValueError(f"unknown class pattern {pattern!r}")


def warm_engine(engine: ServingEngine, lens, max_seq: int,
                new_tokens: int) -> None:
    """Compile every prefill program the sampled lengths can hit plus the
    decode program, then zero the metrics: compiles are a one-time cost a
    long-lived server never pays again, and folding them into TTFT
    percentiles would report compile time, not serving time.

    Whole-prompt mode compiles one program per pow2 bucket (one
    representative length each). Chunked mode has exactly ONE prefill
    program — [n_slots, C] regardless of prompt length — so a single
    longest-length request covers it (and exercises the multi-chunk
    resume path while it's at it). Min 2 tokens either way — a 1-token
    warmup retires at prefill and would leave the decode program cold."""
    if engine.prefill_chunk is not None:
        longest = max((int(l) for l in lens), default=1)
        engine.submit(np.zeros(max(1, longest), np.int32),
                      max_new_tokens=min(2, new_tokens))
        engine.drain()
        if engine.prefix_cache is not None:
            # a second identical prompt HITS the parked warmup donor,
            # compiling the slot-copy program the hit path runs through —
            # then the cache is emptied (warmup prompts must not stay
            # resident as reuse donors)
            engine.submit(np.zeros(max(1, longest), np.int32),
                          max_new_tokens=min(2, new_tokens))
            engine.drain()
            engine.prefix_cache.clear(engine.pool)
        engine.reset_metrics()
        _clear_warmup_trace()
        return
    by_bucket = {}
    for l in lens:
        by_bucket[_bucket(int(l), max_seq)] = int(l)
    for _, l in sorted(by_bucket.items()):
        engine.submit(np.zeros(l, np.int32),
                      max_new_tokens=min(2, new_tokens))
        engine.drain()
    engine.reset_metrics()
    _clear_warmup_trace()


def _clear_warmup_trace() -> None:
    """Warmup requests are synthetic compile fodder — their lifecycle
    events would sit at the front of every exported trace, so the tracer
    resets with the metrics."""
    from uccl_tpu import obs

    t = obs.get_tracer()
    if t is not None:
        t.clear()


def warm_replicas(router, lens, max_seq: int, new_tokens: int) -> None:
    """Compile warmup for every engine behind a Router (each replica owns
    its own jit caches and KV pool), then zero the router's routed counts
    — warmup submissions must not skew the routed distribution benches
    label arms from."""
    for eng in router.engines:
        warm_engine(eng, lens, max_seq, new_tokens)
    router.routed = [0] * len(router.replicas)


def drive(engine, prompts, arrivals, max_new_tokens,
          eos_id: Optional[int] = None, priorities=None,
          deadlines_ms=None, tenants=None, samplings=None,
          adapters=None) -> Tuple[List[Request], float]:
    """Run the arrival stream to completion: submit requests as their
    arrival offsets come due (wall clock), stepping the engine whenever it
    has work. ``engine`` is a ServingEngine or a Router (same submit/step/
    has_work surface). ``max_new_tokens`` is one budget for every request
    or a per-request list (mixed workloads: short interactive turns over
    long batch jobs). ``priorities`` / ``deadlines_ms`` / ``tenants`` /
    ``samplings`` / ``adapters`` are optional per-request lists (None
    entries = the submit defaults). Returns
    (accepted requests, wall seconds); rejected submissions (bounded
    queue) are counted in the engine's metrics but not returned — expired
    requests ARE returned (they were accepted) and finish as EXPIRED."""
    reqs: List[Request] = []
    i, n = 0, len(prompts)
    t0 = now()
    while i < n or engine.has_work():
        t = now() - t0
        while i < n and arrivals[i] <= t:
            kw = {}
            if priorities is not None and priorities[i] is not None:
                kw["priority"] = priorities[i]
            if deadlines_ms is not None and deadlines_ms[i] is not None:
                kw["deadline_ms"] = deadlines_ms[i]
            if tenants is not None and tenants[i] is not None:
                kw["tenant"] = tenants[i]
            if samplings is not None and samplings[i] is not None:
                kw["sampling"] = samplings[i]
            if adapters is not None and adapters[i] is not None:
                kw["adapter"] = adapters[i]
            mnt = (max_new_tokens[i]
                   if isinstance(max_new_tokens, (list, tuple))
                   else max_new_tokens)
            r = engine.submit(prompts[i], max_new_tokens=mnt,
                              eos_id=eos_id, **kw)
            if r is not None:
                reqs.append(r)
            i += 1
        if engine.has_work():
            engine.step()
        elif i < n:
            time.sleep(min(0.005, max(arrivals[i] - (now() - t0), 0.0)))
    return reqs, now() - t0
