"""Request lifecycle for the continuous-batching engine.

A request is one sequence: a prompt, a token budget, and an optional EOS id.
It moves QUEUED → ACTIVE (admitted to a KV slot) → FINISHED (EOS or budget),
or is REJECTED at submit when the queue is full (backpressure). Under
chunked prefill (``ServingEngine(prefill_chunk=C)``) admission enters
PARTIAL_PREFILL first: the request occupies its slot while its prefill
cursor (``prefill_pos``) advances one fixed-size chunk per engine step, and
it becomes ACTIVE when the cursor reaches the prompt end and the first
token is emitted. Timing marks are taken at every transition so the serving
metrics (TTFT, TPOT, queue wait, latency — docs/SERVING.md) fall out of the
lifecycle instead of being instrumented around it.
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np


def now() -> float:
    """The engine's clock (monotonic seconds). One symbol so every timing
    window — engine, metrics, serve.py's one-shot percentiles — measures
    with the same clock."""
    return time.perf_counter()


class RequestState(enum.Enum):
    QUEUED = "queued"
    PARTIAL_PREFILL = "partial_prefill"  # in a slot, prefill cursor mid-prompt
    ACTIVE = "active"
    PREEMPTED = "preempted"  # paused at a chunk boundary, KV saved, re-queued
    FINISHED = "finished"
    REJECTED = "rejected"
    EXPIRED = "expired"  # left the queue on deadline expiry or cancel()
    LOST = "lost"  # stranded on a dead replica (recovery re-runs a NEW
    # Request under the same trace_id on a survivor; this copy is done)


@dataclass
class Request:
    """One serving request and its measured lifecycle.

    ``out_tokens`` is the greedy continuation, element-for-element the
    prefix of what the one-shot ``generate`` oracle would emit for the same
    prompt (exactness is the engine's tested contract, not a tolerance).
    """

    rid: int
    prompt: np.ndarray  # 1-D int32 token ids
    max_new_tokens: int
    eos_id: Optional[int] = None
    state: RequestState = RequestState.QUEUED
    slot: Optional[int] = None
    admit_seq: Optional[int] = None  # admission order (FIFO is testable)
    prefill_pos: int = 0  # chunked-prefill cursor: prompt[:prefill_pos] is in KV
    cache_hit_len: int = 0  # prompt tokens reused from the prefix cache
    # whether the reused rows are bit-exact w.r.t. recomputation: True for
    # T0 slot copies and lossless-tier promotions; False when the serving
    # tier stored them quantized at rest (bounded error, never silent —
    # the kv_tiers exactness contract)
    cache_hit_exact: bool = True
    adopted: bool = False  # entered via adopt() (disagg decode side), not submit()
    priority: str = "interactive"  # SLO class: "interactive" | "batch"
    tenant: str = "default"  # multi-tenant identity: fair-scheduling queue,
    # per-tenant metrics label, prefix-cache namespace (ISSUE 18)
    # per-request sampling policy (serving/sampling.py SamplingParams);
    # None = greedy — the engine's exactness oracle is then the sampled
    # one-shot generate at the same seed instead of the argmax one
    sampling: Optional[object] = None
    adapter: Optional[str] = None  # LoRA adapter tenant name (AdapterStore)
    _adapter_row: int = field(default=0, repr=False, compare=False)
    # device table row pinned at admit (0 = the zero-rank fast path)
    # prefix-cache namespace captured at FIRST admission (engine._ns):
    # the adapter version the KV was actually computed under, so a
    # republish while this request is in flight can never park its rows
    # into the new version's namespace (cross-version contamination)
    _cache_ns: Optional[str] = field(default=None, repr=False,
                                     compare=False)
    # set by TenantFairScheduler when this request's token cost is charged
    # (first admission); a requeued copy — preemption resume, engine
    # adapter-deferral — is never re-billed
    billed: bool = field(default=False, repr=False, compare=False)
    deadline_ms: Optional[float] = None  # admission deadline after submit
    # distributed-tracing identity (obs/context.py): trace_id is minted
    # once at ingress (submit / Router.submit) and carried VERBATIM across
    # the disagg stream, so one request is one timeline fleet-wide;
    # span_id is the minting side's root span
    trace_id: Optional[str] = None
    span_id: Optional[str] = None
    preemptions: int = 0  # times this request was paused for a higher class
    out_tokens: List[int] = field(default_factory=list)
    finish_reason: Optional[str] = None  # "eos" | "length" | "deadline" |
    # "cancel" | "oversized" (cost > token-bucket burst, rejected at
    # submit) | "adapter_lost" (adapter archive-evicted while queued)
    t_submit: float = 0.0
    t_admit: Optional[float] = None
    t_first_token: Optional[float] = None
    t_finish: Optional[float] = None
    # drafting-context buffer: prompt + committed tokens, grown in place
    # (capacity = prompt + max_new_tokens is the request's hard ceiling)
    # so the per-step speculative draft never re-concatenates O(len)
    _ctx: Optional[np.ndarray] = field(default=None, repr=False,
                                       compare=False)
    _ctx_len: int = 0
    # preemption save state: host copies of the slot's full KV rows plus
    # the last emitted token, taken at the chunk boundary where the engine
    # paused this request (None while not preempted)
    _saved_kv: Optional[tuple] = field(default=None, repr=False,
                                       compare=False)
    _saved_last_tok: Optional[int] = field(default=None, repr=False,
                                           compare=False)

    @property
    def track(self) -> str:
        """The request's trace track (one Chrome-trace row per request —
        the engine emits its submit→admit→prefill→first-token→finish
        lifecycle marks here, docs/OBSERVABILITY.md)."""
        return f"req-{self.rid}"

    @property
    def n_generated(self) -> int:
        return len(self.out_tokens)

    def context(self) -> np.ndarray:
        """Prompt + committed continuation — what a speculative drafter
        conditions on (never includes uncommitted draft tokens). Returns a
        READ-ONLY view of an amortized buffer: tokens committed since the
        last call are appended in place (decode calls this every step, so
        re-concatenating the whole context would cost O(len) per step)."""
        if not self.out_tokens:
            return self.prompt
        n = self.prompt.size + len(self.out_tokens)
        if self._ctx is None:
            self._ctx = np.empty(self.prompt.size + self.max_new_tokens,
                                 np.int32)
            self._ctx[:self.prompt.size] = self.prompt
            self._ctx_len = self.prompt.size
        while self._ctx_len < n:
            self._ctx[self._ctx_len] = \
                self.out_tokens[self._ctx_len - self.prompt.size]
            self._ctx_len += 1
        return self._ctx[:n]

    @property
    def queue_wait(self) -> Optional[float]:
        """Submit → admission into a KV slot: the scheduling delay alone
        (TTFT minus this is pure compute/prefill time)."""
        if self.t_admit is None:
            return None
        return self.t_admit - self.t_submit

    @property
    def ttft(self) -> Optional[float]:
        """Time to first token: submit (queue wait included) → first token."""
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.t_submit

    @property
    def tpot(self) -> Optional[float]:
        """Time per output token after the first (decode steady state)."""
        if (self.t_finish is None or self.t_first_token is None
                or self.n_generated < 2):
            return None
        return (self.t_finish - self.t_first_token) / (self.n_generated - 1)

    @property
    def latency(self) -> Optional[float]:
        if self.t_finish is None:
            return None
        return self.t_finish - self.t_submit

    @property
    def kv_len(self) -> int:
        """Rows of this request's KV that are live on device: the prefill
        cursor plus one row per decode step taken (the first token comes
        from prefill logits and writes no row; each decode/verify commit
        advances the device length by its committed count). This is the
        exact window preemption must save to resume bit-identically."""
        return self.prefill_pos + max(0, self.n_generated - 1)

    def deadline_passed(self, t: float) -> bool:
        """Whether the admission deadline expired at engine-clock ``t``."""
        return (self.deadline_ms is not None
                and (t - self.t_submit) * 1e3 > self.deadline_ms)

    def is_done(self) -> bool:
        return self.state in (RequestState.FINISHED, RequestState.REJECTED,
                              RequestState.EXPIRED, RequestState.LOST)
