"""KV slot pool: the fixed [n_slots, S_max] cache managed as reusable rows.

The device cache is allocated ONCE (the backend owns the arrays); this class
owns the host-side bookkeeping — which rows are free, which request occupies
which row, occupancy history. Freeing a slot does not touch device memory:
a stale row is dead by construction (attention stops at the slot's length,
and a new occupant prefills from position 0, re-writing every position
before any read of it — see models/inference.py `prefill_slots`).
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Optional


class SlotPool:
    """Free-list of KV cache rows with admit/free/occupancy tracking.

    Slots exist in three states: **free** (on the heap), **occupied** (a
    live request's KV), or **parked** (a retired request's KV kept resident
    for the prefix-reuse cache — still charged against the pool, but not a
    live occupant: ``leaked()`` excludes parked slots, and only
    ``reclaim()`` — the cache's eviction — returns them to the free list).
    """

    def __init__(self, n_slots: int):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        self.n_slots = n_slots
        # min-heap: admissions always take the LOWEST free slot id, so the
        # pool packs low rows under partial load (a deque here would hand
        # out slots in FIFO-of-frees order, not lowest-first — tested)
        self._free = list(range(n_slots))
        heapq.heapify(self._free)
        self._occupant: Dict[int, int] = {}  # slot -> rid
        self._parked: Dict[int, int] = {}  # slot -> rid of the retiree
        self.total_admits = 0
        self.total_frees = 0
        self.high_water = 0  # max concurrent occupancy observed

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_active(self) -> int:
        return self.n_slots - len(self._free)

    @property
    def n_parked(self) -> int:
        return len(self._parked)

    @property
    def occupancy(self) -> float:
        return self.n_active / self.n_slots

    def occupant(self, slot: int) -> Optional[int]:
        return self._occupant.get(slot)

    def active_slots(self) -> List[int]:
        return sorted(self._occupant)

    def admit(self, rid: int) -> Optional[int]:
        """Claim a free slot for ``rid``; None when the pool is full."""
        if not self._free:
            return None
        slot = heapq.heappop(self._free)
        self._occupant[slot] = rid
        self.total_admits += 1
        self.high_water = max(self.high_water, self.n_active)
        return slot

    def free(self, slot: int) -> None:
        if slot not in self._occupant:
            raise ValueError(f"slot {slot} is not occupied")
        del self._occupant[slot]
        heapq.heappush(self._free, slot)
        self.total_frees += 1

    def park(self, slot: int) -> None:
        """Retire an occupied slot into the parked (cache-resident) state:
        its KV stays readable as a prefix-reuse donor, but no request owns
        it and admissions cannot claim it until :meth:`reclaim`."""
        if slot not in self._occupant:
            raise ValueError(f"slot {slot} is not occupied")
        self._parked[slot] = self._occupant.pop(slot)

    def reclaim(self, slot: int) -> None:
        """Return a parked slot to the free list (prefix-cache eviction)."""
        if slot not in self._parked:
            raise ValueError(f"slot {slot} is not parked")
        del self._parked[slot]
        heapq.heappush(self._free, slot)
        self.total_frees += 1

    def is_parked(self, slot: int) -> bool:
        """Whether ``slot`` is in the parked (cache-resident) state — the
        tier manager's sanity check that a demotion victim really is cache
        residency and not a live request's KV."""
        return slot in self._parked

    def parked_slots(self) -> List[int]:
        return sorted(self._parked)

    def leaked(self) -> int:
        """Live-occupied slots — must be 0 after a full drain (tested).
        Parked slots are cache residency, not leaks: the prefix cache owns
        their lifecycle (LRU eviction under admission pressure)."""
        return len(self._occupant)
