"""Continuous-batching serving engine over the KV slot pool.

Orca/vLLM-shape iteration-level scheduling on the repo's serving stacks:
``submit()`` queues a request, each ``step()`` (1) admits queue-head
requests into free KV slots and batch-prefills exactly those slots (masked —
mid-decode neighbors untouched), (2) runs ONE masked batched decode step
over every active slot, (3) retires sequences on EOS or token budget and
frees their slots for the next admission. ``drain()`` steps until idle.

**Chunked prefill** (``prefill_chunk=C``) bounds decode stalls: instead of
prefilling a whole bucketed prompt before the step's decode pass — one long
arriving prompt then stalls every in-flight decode for the full prefill —
each admitted request advances a prefill cursor by ONE fixed-size chunk of
C tokens per step, and the step still runs its single decode pass. A decode
therefore never waits behind more than one chunk (the stall bound, tested),
and the prefill program compiles ONCE at [n_slots, C] instead of once per
pow2 bucket. ``step_tokens`` adds a per-step token budget (decode token =
1, prefill chunk = C): admission is deferred while the step's committed
spend would exceed it. ``prefill_chunk=None`` (default) is the PR 3
whole-prompt path, unchanged.

The engine is exact, not approximate: each request's emitted tokens are
bit-identical to the one-shot ``generate`` oracle for the same prompt
(greedy decode over the same per-row math — chunked prefill is that math
split along the sequence axis; tests/test_serving.py proves both modes on
both stacks). Model programs are jitted once per shape via the same
LRU-bounded ``_fns`` pattern the one-shot servers use.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional

import numpy as np

from uccl_tpu import obs
from uccl_tpu.serving.metrics import ServingMetrics
from uccl_tpu.serving.request import Request, RequestState, now
from uccl_tpu.serving.sampling import (
    SamplingParams, pack as pack_sampling, slot_arrays, stamp_slot,
)
from uccl_tpu.serving.scheduler import (
    PRIORITY_CLASSES, FIFOScheduler, PriorityScheduler,
    TenantFairScheduler,
)
from uccl_tpu.serving.slots import SlotPool
from uccl_tpu.serving.spec import (
    SPEC_ACCEPTED_LEN as _SPEC_ACCEPTED_LEN,
    SPEC_TOKENS as _SPEC_TOKENS,
)
from uccl_tpu.utils.lru import LRUFnCache

# serving telemetry on the obs registry (docs/OBSERVABILITY.md): the
# admission-rejection counter and slot-pool gauges are always live (dict
# adds); trace events additionally light up under --trace-out /
# obs.enable_tracing() and cost one bool check otherwise.
_REJECTS = obs.counter(
    "serving_admission_rejected_total",
    "requests rejected at submit: queue backpressure, or a token-bucket "
    "cost that exceeds the tenant's burst (could never be admitted)",
)
_OCCUPANCY = obs.gauge(
    "serving_slot_occupancy", "KV slot-pool occupancy after the last step"
)
_HIGH_WATER = obs.gauge(
    "serving_slot_high_water", "max concurrent KV slot occupancy observed"
)
_PREFILL_TOKENS = obs.counter(
    "serving_prefill_tokens_total",
    "prompt tokens per prefill path: kind=computed ran the model, "
    "kind=skipped were reused from the prefix cache (the auditable cut)",
)
_DROPPED = obs.counter(
    "serving_rejected_total",
    "queued requests dropped before admission: reason=deadline (aged out "
    "of the queue), reason=cancel (caller withdrew it), or "
    "reason=adapter_lost (the adapter was archive-evicted while queued)",
)
_PREEMPTS = obs.counter(
    "serving_preempted_total",
    "batch-class requests paused at a chunk boundary (KV saved, slot "
    "handed to an interactive arrival)",
)
_RESUMES = obs.counter(
    "serving_resumed_total",
    "preempted requests re-admitted with their KV restored (bit-exact "
    "continuation at the saved cursor)",
)
_SPEC_RESAMPLE = obs.counter(
    "spec_resample_total",
    "sampled verify windows with a rejected draft: the committed token at "
    "the first rejection is the residual-distribution resample (the "
    "rejection-sampling correction, docs/SERVING.md)",
)
_TENANT_REQS = obs.counter(
    "serving_tenant_requests_total",
    "requests finished per tenant (labels: tenant)",
)
_TENANT_TOKS = obs.counter(
    "serving_tenant_tokens_total",
    "generated tokens delivered per tenant (labels: tenant)",
)


def _flat_extra(sampling, adapters) -> list:
    """Flatten the optional sampled/adapted arguments into positional jit
    args of fixed count: 5 per-slot sampling arrays, then 4 adapter tables
    + per-slot row ids. The compiled-fn cache keys carry the two presence
    flags, so the argmax/-adapter-free programs stay byte-identical."""
    extra = []
    if sampling is not None:
        extra.extend(sampling)
    if adapters is not None:
        tables, ids = adapters
        extra.extend([tables["wq"][0], tables["wq"][1],
                      tables["wv"][0], tables["wv"][1], ids])
    return extra


def _split_extra(rest, sampled: bool, adapted: bool):
    """Inverse of :func:`_flat_extra` inside a jitted run fn: returns
    (sampling tuple | None, adapter tables | None, adapter ids | None)."""
    rest = list(rest)
    samp = None
    if sampled:
        samp = tuple(rest[:5])
        rest = rest[5:]
    adp = ids = None
    if adapted:
        adp = {"wq": (rest[0], rest[1]), "wv": (rest[2], rest[3])}
        ids = rest[4]
    return samp, adp, ids


@dataclass
class ChunkEvent:
    """One slot's KV rows [lo, hi) became valid during this engine step —
    either computed by a prefill chunk (``reused=False``) or copied from a
    prefix-cache donor at admission (``reused=True``). The engine hands
    these to its ``chunk_sink`` (the disagg prefill worker's streaming
    hook) BEFORE any retirement in the same step, so a sink can export the
    rows while the slot still holds them."""

    req: Request
    slot: int
    lo: int
    hi: int
    done: bool  # this event completes the request's prefill
    first_token: Optional[int]  # set iff done
    reused: bool


def _bucket(n: int, cap: int) -> int:
    """Prefill bucket length: next power of two (bounded compile count —
    at most log2(max_seq) distinct prefill programs), clipped to cap."""
    b = 1
    while b < n:
        b *= 2
    return min(b, cap)


class DenseBackend:
    """Slot-pool serving over the dense KV stack (models/inference.py).

    ``fns`` shares another backend's compiled-program cache: the jitted
    programs are pure in params/cache (nothing baked but shapes), so N
    replica backends of the same (cfg, n_slots, max_seq) can reuse ONE
    compile set — a replica set costs one warmup, not N."""

    def __init__(self, params, cfg, *, n_slots: int, max_seq: int,
                 fns: Optional[LRUFnCache] = None):
        import jax

        from uccl_tpu.models.inference import SlotKVCache

        self.params = params
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_seq = max_seq
        self.cache = SlotKVCache.empty(cfg, n_slots, max_seq)
        self._fns = fns if fns is not None else LRUFnCache(16)
        self._jax = jax

    def _prefill_fn(self, s: int, sampled: bool, adapted: bool):
        jax = self._jax
        cfg = self.cfg

        def build():
            from uccl_tpu.models.inference import SlotKVCache, prefill_slots

            def run(p, tok, lens, mask, off, kc, vc, ln, *rest):
                samp, adp, ids = _split_extra(rest, sampled, adapted)
                t, cache = prefill_slots(
                    p, tok, lens, mask, SlotKVCache(kc, vc, ln), cfg,
                    start=off, sampling=samp, adapters=adp,
                    adapter_ids=ids,
                )
                return t, cache.k, cache.v, cache.lengths

            return jax.jit(run)

        return self._fns.get(("prefill", s, sampled, adapted), build)

    def _decode_fn(self, sampled: bool, adapted: bool):
        jax = self._jax
        cfg = self.cfg

        def build():
            from uccl_tpu.models.inference import (
                SlotKVCache, decode_step_slots,
            )

            def run(p, tok, mask, kc, vc, ln, *rest):
                samp, adp, ids = _split_extra(rest, sampled, adapted)
                t, cache = decode_step_slots(
                    p, tok, mask, SlotKVCache(kc, vc, ln), cfg,
                    sampling=samp, adapters=adp, adapter_ids=ids,
                )
                return t, cache.k, cache.v, cache.lengths

            return jax.jit(run)

        return self._fns.get(("decode", sampled, adapted), build)

    def _verify_fn(self, s: int, sampled: bool, adapted: bool):
        jax = self._jax
        cfg = self.cfg

        def build():
            from uccl_tpu.models.inference import SlotKVCache, verify_slots

            def run(p, tok, mask, kc, vc, ln, *rest):
                samp, adp, ids = _split_extra(rest, sampled, adapted)
                t, n_acc, cache = verify_slots(
                    p, tok, mask, SlotKVCache(kc, vc, ln), cfg,
                    sampling=samp, adapters=adp, adapter_ids=ids,
                )
                return t, n_acc, cache.k, cache.v, cache.lengths

            return jax.jit(run)

        return self._fns.get(("verify", s, sampled, adapted), build)

    def prefill(self, tokens: np.ndarray, lens: np.ndarray,
                mask: np.ndarray,
                start: Optional[np.ndarray] = None,
                sampling=None, adapters=None) -> np.ndarray:
        from uccl_tpu.models.inference import SlotKVCache

        if start is None:
            start = np.zeros(tokens.shape[0], np.int32)
        fn = self._prefill_fn(tokens.shape[1], sampling is not None,
                              adapters is not None)
        t, k, v, ln = fn(self.params, tokens, lens, mask, start,
                         self.cache.k, self.cache.v, self.cache.lengths,
                         *_flat_extra(sampling, adapters))
        self.cache = SlotKVCache(k, v, ln)
        return np.asarray(t)

    def decode(self, tokens: np.ndarray, active: np.ndarray,
               sampling=None, adapters=None) -> np.ndarray:
        from uccl_tpu.models.inference import SlotKVCache

        fn = self._decode_fn(sampling is not None, adapters is not None)
        t, k, v, ln = fn(self.params, tokens, active,
                         self.cache.k, self.cache.v, self.cache.lengths,
                         *_flat_extra(sampling, adapters))
        self.cache = SlotKVCache(k, v, ln)
        return np.asarray(t)

    def verify(self, tokens: np.ndarray, active: np.ndarray,
               sampling=None, adapters=None):
        """One batched [n_slots, k+1] draft-verify window (spec decode):
        returns (target tokens [n_slots, k+1], n_accepted [n_slots]) —
        greedy argmaxes, or lockstep-keyed samples under ``sampling``."""
        from uccl_tpu.models.inference import SlotKVCache

        fn = self._verify_fn(tokens.shape[1], sampling is not None,
                             adapters is not None)
        t, n_acc, k, v, ln = fn(self.params, tokens, active,
                                self.cache.k, self.cache.v,
                                self.cache.lengths,
                                *_flat_extra(sampling, adapters))
        self.cache = SlotKVCache(k, v, ln)
        return np.asarray(t), np.asarray(n_acc)

    # slot KV movement (prefix-cache hits + the disagg p2p stream) — thin
    # shims over the cache's export/import views (models/inference.py)
    def export_slot_kv(self, slot: int, lo: int, hi: int):
        return self.cache.export_rows(slot, lo, hi)

    def import_slot_kv(self, slot: int, k_rows, v_rows, *,
                       length: int) -> None:
        self.cache = self.cache.import_rows(slot, k_rows, v_rows,
                                            length=length)

    def copy_slot_prefix(self, dst: int, src: int, n: int) -> None:
        self.cache = self.cache.copy_prefix(dst, src, n)


class MoEBackend:
    """Slot-pool serving over the EP-sharded MoE stack: slots are the
    [W, B_loc] rows of the server's cache (slot s ↔ shard s // B_loc, row
    s % B_loc); prefill routes through the sorted EP path, decode through
    the packed LL path (the DeepEP decode regime) by default."""

    def __init__(self, server, params, *, batch_local: int, max_seq: int,
                 decode_impl: str = "ll"):
        self.server = server
        self.params = params
        self.world = server.world
        self.b_loc = batch_local
        self.n_slots = self.world * batch_local
        self.max_seq = max_seq
        self.decode_impl = decode_impl
        self.cache = server.slot_cache(batch_local, max_seq)

    def _grid(self, flat: np.ndarray, dtype) -> "np.ndarray":
        import jax.numpy as jnp

        return jnp.asarray(
            np.asarray(flat).reshape((self.world, self.b_loc)
                                     + flat.shape[1:]).astype(dtype)
        )

    def _extra(self, sampling, adapters):
        """Grid the flat per-slot sampled/adapted arguments onto the
        [W, B_loc] shard layout: sampling arrays and adapter ids grid like
        tokens; the stacked adapter tables broadcast a leading [W] dim
        (every shard applies the same tables to its local rows)."""
        import jax.numpy as jnp

        samp = adp = ids = None
        if sampling is not None:
            seeds, pos0, temp, top_p, top_k = sampling
            samp = (self._grid(seeds, np.int32),
                    self._grid(pos0, np.int32),
                    self._grid(temp, np.float32),
                    self._grid(top_p, np.float32),
                    self._grid(top_k, np.int32))
        if adapters is not None:
            tables, flat_ids = adapters
            adp = {t: (jnp.broadcast_to(a, (self.world,) + a.shape),
                       jnp.broadcast_to(b, (self.world,) + b.shape))
                   for t, (a, b) in tables.items()}
            ids = self._grid(flat_ids, np.int32)
        return samp, adp, ids

    def prefill(self, tokens: np.ndarray, lens: np.ndarray,
                mask: np.ndarray,
                start: Optional[np.ndarray] = None,
                sampling=None, adapters=None) -> np.ndarray:
        if start is None:
            start = np.zeros(tokens.shape[0], np.int32)
        samp, adp, ids = self._extra(sampling, adapters)
        t, self.cache = self.server.prefill_slots(
            self.params, self._grid(tokens, np.int32),
            self._grid(lens, np.int32), self._grid(mask, bool), self.cache,
            start=self._grid(start, np.int32),
            sampling=samp, adapters=adp, adapter_ids=ids,
        )
        return np.asarray(t).reshape(self.n_slots)

    def decode(self, tokens: np.ndarray, active: np.ndarray,
               sampling=None, adapters=None) -> np.ndarray:
        samp, adp, ids = self._extra(sampling, adapters)
        t, self.cache = self.server.decode_step_slots(
            self.params, self._grid(tokens, np.int32),
            self._grid(active, bool), self.cache, impl=self.decode_impl,
            sampling=samp, adapters=adp, adapter_ids=ids,
        )
        return np.asarray(t).reshape(self.n_slots)

    def verify(self, tokens: np.ndarray, active: np.ndarray,
               sampling=None, adapters=None):
        """One batched [n_slots, k+1] draft-verify window (spec decode),
        through the sorted EP path — the multi-token regime, like prefill.
        Returns (target tokens [n_slots, k+1], n_accepted [n_slots])."""
        samp, adp, ids = self._extra(sampling, adapters)
        t, n_acc, self.cache = self.server.verify_slots(
            self.params, self._grid(tokens, np.int32),
            self._grid(active, bool), self.cache,
            sampling=samp, adapters=adp, adapter_ids=ids,
        )
        s = tokens.shape[1]
        return (np.asarray(t).reshape(self.n_slots, s),
                np.asarray(n_acc).reshape(self.n_slots))

    # slot KV movement — MoESlotCache maps flat slot ids to its [W, B_loc]
    # grid internally, so the engine-facing surface matches DenseBackend's
    def export_slot_kv(self, slot: int, lo: int, hi: int):
        return self.cache.export_rows(slot, lo, hi)

    def import_slot_kv(self, slot: int, k_rows, v_rows, *,
                       length: int) -> None:
        self.cache = self.cache.import_rows(slot, k_rows, v_rows,
                                            length=length)

    def copy_slot_prefix(self, dst: int, src: int, n: int) -> None:
        self.cache = self.cache.copy_prefix(dst, src, n)


def replicate_backend(backend, n: int, weights=None) -> List:
    """``n`` replica backends from one prototype — THE sharing rule for a
    replica set (serve.py and serving_bench both build through here, so
    it can't drift): every replica owns its KV pool, but dense replicas
    share the prototype's compiled-program cache (the jitted fns are pure
    in params/cache) and MoE replicas share its server (and therefore its
    compiled programs) — N replicas cost one warmup.

    ``weights``: a fetched weight-push snapshot
    (:class:`uccl_tpu.p2p.weight_push.WeightSnapshot`) or a param pytree
    — every replica INCLUDING the prototype serves these params instead
    of the prototype's in-memory ones. This is the fleet spin-up path:
    replicas import the published version off the p2p wire (its bytes
    already counted on ``p2p_bytes_total{verb="weight_push"}``) rather
    than cloning untracked host references. The tree structure must
    match the prototype's params (same leaf paths/shapes) — mismatches
    fail loudly before any replica serves a stale mix."""
    if n < 1:
        raise ValueError(f"need n >= 1 replicas, got {n}")
    if weights is not None:
        backend = _reweight_backend(backend, weights)
    out = [backend]
    for _ in range(1, n):
        if isinstance(backend, MoEBackend):
            out.append(MoEBackend(
                backend.server, backend.params,
                batch_local=backend.b_loc, max_seq=backend.max_seq,
                decode_impl=backend.decode_impl,
            ))
        else:
            out.append(DenseBackend(
                backend.params, backend.cfg, n_slots=backend.n_slots,
                max_seq=backend.max_seq, fns=backend._fns,
            ))
    return out


def _reweight_backend(backend, weights):
    """A same-shape backend serving ``weights`` (a WeightSnapshot or a
    param pytree) — compiled-fn caches are reused (the jitted programs
    are pure in params), so swapping a pushed version in costs zero new
    compiles."""
    import jax
    import numpy as np

    tree = weights.tree() if hasattr(weights, "tree") else weights
    want, want_def = jax.tree_util.tree_flatten(backend.params)
    got, got_def = jax.tree_util.tree_flatten(tree)
    if want_def != got_def or len(want) != len(got):
        raise ValueError(
            f"pushed weight tree does not match the prototype's params "
            f"(treedef {got_def} vs {want_def})"
        )
    for w, g in zip(want, got):
        if tuple(np.shape(w)) != tuple(np.shape(g)):
            raise ValueError(
                f"pushed weight leaf shape {np.shape(g)} != prototype "
                f"{np.shape(w)}"
            )
    params = jax.tree_util.tree_map(
        lambda w, g: jax.numpy.asarray(g, dtype=w.dtype), backend.params,
        tree,
    )
    if isinstance(backend, MoEBackend):
        return MoEBackend(backend.server, params,
                          batch_local=backend.b_loc,
                          max_seq=backend.max_seq,
                          decode_impl=backend.decode_impl)
    return DenseBackend(params, backend.cfg, n_slots=backend.n_slots,
                        max_seq=backend.max_seq, fns=backend._fns)


class ServingEngine:
    """submit()/step()/drain() over a backend (Dense or MoE).

    ``prefill_chunk=C`` enables chunked prefill: admitted requests advance
    their prefill cursor by one C-token chunk per step (one compiled
    prefill program at [n_slots, C]) and in-flight decodes run every step —
    no decode ever waits behind more than one chunk. ``step_tokens`` caps a
    step's committed token spend (decode slot = 1 token, or 1+k under
    speculation; prefill chunk = C) by deferring admission; it requires
    ``prefill_chunk`` (the whole-prompt path has no sub-step unit to budget
    with). Decodes are never budget-gated — they are the latency the
    budget protects.

    ``spec_k=K`` enables speculative decoding (serving/spec.py,
    docs/SERVING.md): each step's decode pass becomes one batched
    [n_slots, K+1] draft-verify window — the ``drafter`` (default
    :class:`~uccl_tpu.serving.spec.NGramDrafter`, no second model)
    proposes K tokens per decoding slot, greedy acceptance commits each
    slot's matched draft prefix plus one target-computed token, and
    rejected-position KV is dead by cursor rollback (never a cache scrub).
    Composes with chunked prefill (a prompt finishing its last chunk joins
    the same step's verify), ``adopt()``, and prefix-cache hits; output
    stays bit-identical to vanilla greedy decode.
    """

    _stats_seq = 0  # distinct registry source name per registered engine

    def __init__(self, backend, *, max_queue: Optional[int] = None,
                 register_stats: bool = False,
                 prefill_chunk: Optional[int] = None,
                 step_tokens: Optional[int] = None,
                 prefix_cache=None,
                 kv_tiers=None,
                 chunk_sink: Optional[Callable[[List[ChunkEvent]], None]]
                 = None,
                 spec_k: Optional[int] = None,
                 drafter=None,
                 priority_classes: bool = False,
                 preempt: bool = False,
                 adapters=None,
                 tenant_fair=None,
                 step_stall_s: Optional[float] = None):
        if spec_k is not None:
            if spec_k < 1:
                raise ValueError(f"spec_k must be >= 1, got {spec_k}")
            if drafter is None:
                from uccl_tpu.serving.spec import NGramDrafter

                drafter = NGramDrafter()
        elif drafter is not None:
            raise ValueError(
                "drafter requires spec_k: without a draft width there is "
                "no verify window to fill"
            )
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}"
            )
        if step_tokens is not None:
            if prefill_chunk is None:
                raise ValueError(
                    "step_tokens requires prefill_chunk: the whole-prompt "
                    "path has no sub-step unit to budget with"
                )
            if step_tokens < prefill_chunk:
                raise ValueError(
                    f"step_tokens ({step_tokens}) must be >= prefill_chunk "
                    f"({prefill_chunk}), or no request could ever be "
                    "admitted"
                )
        if prefix_cache is not None:
            if prefill_chunk is None:
                raise ValueError(
                    "prefix_cache requires prefill_chunk: matches are "
                    "chunk-granular and resume via the chunked program"
                )
            if prefix_cache.chunk != prefill_chunk:
                raise ValueError(
                    f"prefix_cache.chunk ({prefix_cache.chunk}) must equal "
                    f"prefill_chunk ({prefill_chunk}): a match boundary "
                    "must be a resumable prefill position"
                )
        if kv_tiers is not None and prefix_cache is None:
            raise ValueError(
                "kv_tiers requires prefix_cache: the trie is the one index "
                "over every tier — without it there is nothing to demote "
                "from or promote into"
            )
        if chunk_sink is not None and prefill_chunk is None:
            raise ValueError(
                "chunk_sink requires prefill_chunk: the whole-prompt path "
                "emits no per-chunk availability events"
            )
        if tenant_fair and priority_classes:
            raise ValueError(
                "tenant_fair and priority_classes are mutually exclusive "
                "admission policies: per-tenant DRR has no class ladder "
                "(within a tenant, order is FIFO)"
            )
        if adapters is not None and not hasattr(adapters, "acquire"):
            raise ValueError(
                "adapters must be an AdapterStore "
                "(uccl_tpu.serving.adapters)"
            )
        if preempt:
            if not priority_classes:
                raise ValueError(
                    "preempt requires priority_classes: without classes "
                    "there is no higher-priority arrival to preempt for"
                )
            if prefill_chunk is None:
                raise ValueError(
                    "preempt requires prefill_chunk: preemption pauses at "
                    "chunk boundaries and resumes via the chunked "
                    "start-offset program"
                )
        self.backend = backend
        self.spec_k = spec_k
        self.drafter = drafter
        self.prefill_chunk = prefill_chunk
        self.step_tokens = step_tokens
        self.prefix_cache = prefix_cache
        self.kv_tiers = kv_tiers
        if kv_tiers is not None:
            kv_tiers.attach(backend, prefix_cache)
        self.chunk_sink = chunk_sink
        self.fleet = None  # FleetWorker once attach_fleet() is called
        self.priority_classes = priority_classes
        self.preempt = preempt
        self.adapters = adapters
        self.tenant_fair = bool(tenant_fair)
        self.pool = SlotPool(backend.n_slots)
        if tenant_fair:
            kw = dict(tenant_fair) if isinstance(tenant_fair, dict) else {}
            self.sched = TenantFairScheduler(max_queue=max_queue, **kw)
        elif priority_classes:
            self.sched = PriorityScheduler(max_queue=max_queue)
        else:
            self.sched = FIFOScheduler(max_queue=max_queue)
        self.metrics = ServingMetrics()
        # per-slot sampling rows + adapter table row ids: stamped at
        # admission, cleared at retire/preempt — the batched calls ship
        # copies so a mid-step mutation can never race a device program
        self._sampling = slot_arrays(backend.n_slots)
        self._adapter_ids = np.zeros(backend.n_slots, np.int32)
        self._by_slot = {}  # slot -> Request (every occupied slot)
        self._prefilling = {}  # slot -> Request mid-prefill (chunked mode)
        self.dead = False  # killed (chaos / failure injection): step() raises
        self._last_tok = np.zeros(backend.n_slots, np.int32)
        self._next_rid = 0
        if step_stall_s is not None and step_stall_s <= 0:
            raise ValueError(
                f"step_stall_s must be > 0, got {step_stall_s}"
            )
        self.step_stall_s = step_stall_s  # flight step_stall budget (off=None)
        self._conservation_fired = False
        # flight-bundle face: slot/scheduler occupancy at dump time (a
        # no-op unless a recorder is armed when the engine is built)
        self._flight_name = f"engine:{id(self):x}"
        obs.flight_provider(self._flight_name, self._flight_state)
        self._stats_name: Optional[str] = None
        if register_stats:
            # unique per engine: a second registered engine must not
            # silently replace the first's export (registry.register
            # overwrites by name), nor unhook it on close()
            n = ServingEngine._stats_seq
            ServingEngine._stats_seq += 1
            self._stats_name = "serving" if n == 0 else f"serving-{n}"
            self.metrics.register(self, self._stats_name)

    def attach_fleet(self, fleet) -> None:
        """Bind this engine to the fleet prefix-cache plane
        (``serving/fleet.py``, ISSUE 19): ``fleet.fetch`` is consulted
        when an admission misses the local trie, and the fleet's
        publisher (when it carries one) becomes the trie's residency
        listener so parked entries are advertised in the shared
        directory. Requires a chunked engine with a prefix cache — the
        fleet is an extension of the trie, not a replacement."""
        if self.prefix_cache is None or self.prefill_chunk is None:
            raise ValueError(
                "attach_fleet requires prefill_chunk + prefix_cache: the "
                "fleet directory indexes chunk-aligned trie entries"
            )
        self.fleet = fleet
        pub = getattr(fleet, "publisher", None)
        if pub is not None:
            if pub.backend is None:
                pub.backend = self.backend
            if pub.tiers is None:
                pub.tiers = self.kv_tiers
            self.prefix_cache.listener = pub

    # -- submission ---------------------------------------------------------
    def submit(self, prompt, *, max_new_tokens: int = 16,
               eos_id: Optional[int] = None,
               priority: str = "interactive",
               deadline_ms: Optional[float] = None,
               tenant: str = "default",
               sampling: Optional[SamplingParams] = None,
               adapter: Optional[str] = None,
               trace=None) -> Optional[Request]:
        """Queue one request. Returns the Request, or None when rejected by
        backpressure (bounded queue full). ``priority`` picks the SLO class
        (``interactive`` admits before ``batch``; only meaningful on a
        ``priority_classes`` engine — a FIFO engine records the label but
        schedules by arrival order). ``deadline_ms`` is an ADMISSION
        deadline: still queued that many ms after submit, the request
        leaves as ``RequestState.EXPIRED`` instead of aging in place.
        ``trace`` carries an upstream :class:`~uccl_tpu.obs.TraceContext`
        (the Router, or a disagg prefill worker relaying its own ingress
        mint); None mints a fresh one here — either way every request owns
        a fleet-unique trace_id stamped on its lifecycle events.

        ``tenant`` is the request's isolation identity (ISSUE 18): its
        fair-scheduling queue under ``tenant_fair``, its metrics label,
        and its prefix-cache namespace — two tenants never share cached
        KV. ``sampling`` (a :class:`SamplingParams`) switches the request
        from greedy to lockstep-seeded stochastic decoding; ``adapter``
        names a published LoRA adapter in the engine's
        :class:`~uccl_tpu.serving.adapters.AdapterStore` to fuse onto
        this request's slot."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must be non-empty")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if prompt.size + max_new_tokens > self.backend.max_seq:
            raise ValueError(
                f"prompt {prompt.size} + new {max_new_tokens} tokens exceed "
                f"max_seq {self.backend.max_seq}: the slot would overflow"
            )
        if priority not in PRIORITY_CLASSES:
            raise ValueError(
                f"unknown priority {priority!r} (classes: "
                f"{PRIORITY_CLASSES})"
            )
        if deadline_ms is not None and deadline_ms <= 0:
            raise ValueError(f"deadline_ms must be > 0, got {deadline_ms}")
        if not tenant or not isinstance(tenant, str):
            raise ValueError(f"tenant must be a non-empty string, got "
                             f"{tenant!r}")
        if sampling is not None and not isinstance(sampling,
                                                   SamplingParams):
            raise ValueError(
                f"sampling must be a SamplingParams, got "
                f"{type(sampling).__name__}"
            )
        if adapter is not None:
            if self.adapters is None:
                raise ValueError(
                    "adapter requires an engine AdapterStore "
                    "(ServingEngine(adapters=...))"
                )
            if not self.adapters.has(adapter):
                raise ValueError(
                    f"no published adapter for {adapter!r} (publish or "
                    f"ingest it first)"
                )
        ctx = trace if trace is not None else obs.new_context()
        req = Request(
            rid=self._next_rid, prompt=prompt,
            max_new_tokens=max_new_tokens, eos_id=eos_id, t_submit=now(),
            priority=priority, deadline_ms=deadline_ms, tenant=tenant,
            sampling=sampling, adapter=adapter,
            trace_id=ctx.trace_id, span_id=ctx.span_id,
        )
        self._next_rid += 1
        self.metrics.on_submit(req)
        obs.instant("submit", track=req.track, rid=req.rid,
                    prompt_len=int(prompt.size),
                    max_new_tokens=max_new_tokens, cls=priority,
                    tenant=tenant, trace_id=req.trace_id)
        if not self.sched.submit(req):
            self.metrics.on_reject(req)
            _REJECTS.inc()
            obs.instant("reject", track=req.track, rid=req.rid)
            return None
        return req

    def cancel(self, rid: int) -> bool:
        """Withdraw a still-QUEUED request: it leaves the queue as
        ``RequestState.EXPIRED`` with ``finish_reason="cancel"``, counted
        on ``serving_rejected_total{reason="cancel"}``. Returns False when
        ``rid`` is not queued (already admitted, finished, or unknown) —
        in-slot requests run to completion."""
        req = self.sched.cancel(rid)
        if req is None:
            return False
        self.metrics.on_expire(req)
        _DROPPED.inc(reason="cancel")
        obs.instant("cancel", track=req.track, rid=req.rid)
        return True

    def pending_tokens(self) -> int:
        """Outstanding token work across queue and slots: every request's
        remaining prefill tokens plus its remaining decode budget — the
        router's per-replica step-debt signal (uccl_tpu/serving/router.py).
        A queued fresh request counts in full; a queued PREEMPTED request
        only its unfinished remainder; an in-slot request its unprefilled
        tail plus undelivered tokens."""
        debt = 0
        for r in self.sched.queued_requests():
            debt += max(0, int(r.prompt.size) - r.prefill_pos)
            debt += max(0, r.max_new_tokens - r.n_generated)
        for r in self._by_slot.values():
            debt += max(0, int(r.prompt.size) - r.prefill_pos)
            debt += max(0, r.max_new_tokens - r.n_generated)
        return debt

    def adopt(self, prompt, first_token, *, max_new_tokens: int = 16,
              eos_id: Optional[int] = None, slot: Optional[int] = None,
              priority: str = "interactive",
              tenant: str = "default",
              sampling: Optional[SamplingParams] = None,
              queue_s: Optional[float] = None,
              prefill_s: Optional[float] = None,
              transfer_s: Optional[float] = None,
              trace=None) -> Request:
        """Admit a request whose prefill happened ELSEWHERE — the disagg
        decode side. The caller must already have imported the prompt's KV
        into ``slot`` (``backend.import_slot_kv`` with length =
        ``len(prompt)``) and supplies the first generated token the prefill
        fleet computed; the request enters ACTIVE directly and decodes from
        the next ``step()`` on. ``slot=None`` claims a free slot here;
        passing a slot means the caller reserved it (``pool.admit``) when
        the KV stream opened. ``priority`` keeps the request's SLO-class
        label (it rode the BEGIN message) so per-class metrics stay
        truthful — adopted requests are ACTIVE at once, so the class never
        queues here. The ``*_s`` wall-clock splits (queue on the prefill
        fleet, prefill compute, transfer tail) land on the metrics'
        disaggregated-TTFT series. ``trace`` is the context the request
        was minted with at the PREFILL fleet's ingress (it rode the BEGIN
        notif verbatim) — passing it keeps the adopted request on the same
        fleet-wide timeline; None mints a local one. Returns the Request
        (already FINISHED when ``max_new_tokens == 1`` or the first token
        is EOS)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must be non-empty")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if prompt.size + max_new_tokens > self.backend.max_seq:
            raise ValueError(
                f"prompt {prompt.size} + new {max_new_tokens} tokens exceed "
                f"max_seq {self.backend.max_seq}: the slot would overflow"
            )
        if priority not in PRIORITY_CLASSES:
            raise ValueError(
                f"unknown priority {priority!r} (classes: "
                f"{PRIORITY_CLASSES})"
            )
        t = now()
        ctx = trace if trace is not None else obs.new_context()
        req = Request(
            rid=self._next_rid, prompt=prompt,
            max_new_tokens=max_new_tokens, eos_id=eos_id, t_submit=t,
            priority=priority, tenant=tenant, sampling=sampling,
            trace_id=ctx.trace_id, span_id=ctx.span_id,
        )
        self._next_rid += 1
        if slot is None:
            slot = self.pool.admit(req.rid)
            if slot is None:
                raise RuntimeError(
                    "adopt: no free slot (reserve one at stream-open time "
                    "or size the decode pool for the stream fan-in)"
                )
        req.slot = slot
        req.adopted = True
        req.state = RequestState.ACTIVE
        req.prefill_pos = prompt.size
        req.t_admit = t
        self._stamp_admit(slot, req)
        self.metrics.on_submit(req)
        self.metrics.on_admit(req)
        self.metrics.on_adopt(req, queue_s=queue_s, prefill_s=prefill_s,
                              transfer_s=transfer_s)
        self._by_slot[slot] = req
        obs.instant("adopt", track=req.track, rid=req.rid, slot=slot,
                    prompt_len=int(prompt.size), trace_id=req.trace_id)
        finished: List[Request] = []
        self._emit_first_token(slot, req, np.int32(first_token), now(),
                               finished)
        return req

    # -- failure injection + recovery ---------------------------------------
    def kill(self) -> None:
        """Simulate this replica's process dying (the chaos harness /
        failure-detector testbed): the engine stops serving — ``step()``
        raises, the Router's liveness probe sees it dead — but its
        bookkeeping stays frozen until recovery :meth:`evacuate`s it.
        There is no un-kill: a returning process is a NEW replica
        (``Router.attach``), exactly as in a real fleet."""
        self.dead = True

    def evacuate(self):
        """Strip every queued and in-slot request out of this engine —
        the dead-replica recovery feed (uccl_tpu/serving/router.py): the
        requests will be re-run elsewhere (or counted lost), and THIS
        engine's queue/slot bookkeeping is zeroed so fleet aggregates
        (qsize, n_active, leaked) stop counting phantom state that died
        with the process. Parked prefix-cache donors are reclaimed too —
        a dead replica's cache is gone. Returns ``(queued, active)``
        request lists; metrics accounting is the CALLER's job (the
        router counts each on the dead engine's ``lost`` term)."""
        queued = self.sched.take_all()
        active = list(self._by_slot.values())
        for slot, r in list(self._by_slot.items()):
            self._release_slot(slot, r)
            self.pool.free(slot)
        self._by_slot.clear()
        self._prefilling.clear()
        if self.prefix_cache is not None:
            self.prefix_cache.clear(self.pool)
        return queued, active

    # -- the engine iteration ----------------------------------------------
    def has_work(self) -> bool:
        return bool(self.sched.qsize or self._by_slot)

    def step(self) -> List[Request]:
        """One iteration: admit + prefill work, one masked decode, retire.
        Whole-prompt mode prefills admitted prompts in full; chunked mode
        advances every mid-prefill request by one chunk (budget-gated
        admission). Returns requests finished during this step."""
        if self.dead:
            raise RuntimeError(
                "engine is dead (killed): a dead replica cannot step — "
                "recover its requests via Router health handling"
            )
        t0 = now()
        tr = obs.get_tracer()
        ts0 = tr.now_us() if tr is not None else 0.0
        finished: List[Request] = []
        # queue aging first: an expired request must not take this step's
        # admission (its deadline already passed at the step boundary)
        for req in self.sched.expire(t0):
            self.metrics.on_expire(req)
            _DROPPED.inc(reason="deadline")
            obs.instant("expire", track=req.track, rid=req.rid,
                        deadline_ms=req.deadline_ms)
        if self.prefill_chunk is None:
            newly, _ = self._gate_admitted(self.sched.admit(self.pool))
            if newly:
                self._prefill(newly, finished)
            if self._by_slot:
                self._decode(finished)
        else:
            self._step_chunked(finished)
        dt = now() - t0
        self.metrics.on_step(dt)
        if tr is not None:
            tr.complete("engine.step", ts0, tr.now_us() - ts0, "engine",
                        active=len(self._by_slot), queued=self.sched.qsize,
                        finished=len(finished))
        _OCCUPANCY.set(self.pool.occupancy)
        _HIGH_WATER.set(self.pool.high_water)
        if self.step_stall_s is not None and dt > self.step_stall_s:
            obs.flight_trigger(
                "step_stall", key=self._flight_name, dur_s=round(dt, 6),
                budget_s=self.step_stall_s,
                occupancy=round(self.pool.occupancy, 4),
                queued=self.sched.qsize, active=len(self._by_slot))
        self._check_conservation()
        return finished

    def _step_chunked(self, finished) -> None:
        """Chunked-mode iteration: budget-gated admission (evicting LRU
        prefix-cache donors when the pool is full), one batched chunk over
        every mid-prefill slot, then the step's single decode pass
        (requests whose cursor just reached the prompt end join it
        immediately — same step, like the whole-prompt path)."""
        c = self.prefill_chunk
        limit = None
        if self.step_tokens is not None:
            # committed spend this step: 1 token per decoding slot (1+k
            # when speculating — the verify window really runs k+1 rows),
            # C per mid-prefill slot; admit only what fits the remainder
            per_decode = 1 if self.spec_k is None else 1 + self.spec_k
            spend = ((len(self._by_slot) - len(self._prefilling))
                     * per_decode + len(self._prefilling) * c)
            limit = max(0, (self.step_tokens - spend) // c)
        events: List[ChunkEvent] = []
        # admit ONE at a time: each admission's prefix-cache match (and
        # donor copy) must land before the NEXT admission's make_room can
        # evict that donor — a batch admit would let admission k+1 reclaim
        # the very slot admission k is about to copy from
        while limit is None or limit > 0:
            batch = self.sched.admit(self.pool, limit=1,
                                     make_room=self._make_room)
            if not batch:
                break
            batch, deferred = self._gate_admitted(batch)
            if not batch:
                if deferred:
                    break  # adapter rows exhausted: retry next step
                continue  # adapter-lost rejection: try the next head
            if limit is not None:
                limit -= 1
            slot, req = batch[0]
            if req._saved_last_tok is not None:
                # a preemption victim coming back: restore its saved KV and
                # cursor instead of prefilling from scratch (no cache
                # match — its rows are already exact). The restored prompt
                # rows re-announce to the chunk sink: a victim preempted
                # in the same step as its admission had its original event
                # dropped (see the stale-event filter below), so the
                # stream re-ships [0, cursor) — duplicate one-sided writes
                # of identical rows are idempotent
                self._resume(slot, req)
                pos = min(req.prefill_pos, int(req.prompt.size))
                if self.chunk_sink is not None and pos > 0:
                    events.append(ChunkEvent(req, slot, 0, pos, False,
                                             None, True))
                continue
            req.state = RequestState.PARTIAL_PREFILL
            req.prefill_pos = 0
            self._stamp_admit(slot, req)
            if self.prefix_cache is not None:
                hit_exact, hit_tag = True, None
                matched, donor = self.prefix_cache.match(req.prompt,
                                                         self._ns(req))
                if matched > 0:
                    # resume at the cached boundary: land the donor's KV
                    # rows [0, matched) in the fresh slot — a device-to-
                    # device copy for a parked-slot (T0) donor, a tier
                    # promotion (fetch + decode + import) for a T1/T2 ref —
                    # then the chunked program continues from
                    # start=matched, bit-exact by the PR 4 resumability
                    # contract when the serving tier is lossless
                    if isinstance(donor, (int, np.integer)):
                        self.backend.copy_slot_prefix(slot, donor, matched)
                        if self.kv_tiers is not None:
                            self.kv_tiers.count_hit("t0")
                    elif self.kv_tiers.promote(donor, slot, matched):
                        # the deferred deep-tier hit: match() leaves
                        # counting to this commit so a stale ref never
                        # inflates the reuse ledger
                        self.prefix_cache.commit_hit(matched)
                    else:
                        # stale ref (entry lost under the trie): drop it
                        # — promote() released the tier accounting and
                        # left the trie drop to this caller — and
                        # prefill cold, counted as the miss it became
                        self.prefix_cache.replace_ref(donor, None)
                        self.prefix_cache.count_stale_miss()
                        matched = 0
                    if matched > 0:
                        hit_exact = getattr(donor, "exact", True)
                        hit_tag = (int(donor)
                                   if isinstance(donor, (int, np.integer))
                                   else repr(donor))
                if matched == 0 and self.fleet is not None:
                    # local miss (already counted): consult the fleet
                    # directory — a peer may hold this prefix, in which
                    # case its entry is fetched over the T2 wire path
                    # into THIS request's slot (fleet.py; a stale owner
                    # degrades back to the cold miss, never wrong bytes)
                    matched, hit_exact = self.fleet.fetch(
                        req.prompt, self._ns(req), slot, self.backend)
                    if matched > 0:
                        hit_tag = f"fleet:{matched}"
                if matched > 0:
                    req.prefill_pos = matched
                    req.cache_hit_len = matched
                    req.cache_hit_exact = hit_exact
                    _PREFILL_TOKENS.inc(matched, kind="skipped")
                    obs.instant("prefix_hit", track=req.track, slot=slot,
                                donor=hit_tag, matched=matched)
                    events.append(ChunkEvent(req, slot, 0, matched,
                                             False, None, True))
            self._by_slot[slot] = req
            self._prefilling[slot] = req
            self.metrics.on_admit(req)
            obs.instant("admit", track=req.track, slot=slot)
        if self._prefilling:
            self._prefill_chunk_step(finished, events)
        if len(self._by_slot) > len(self._prefilling):
            self._decode(finished)

    def _make_room(self) -> bool:
        """Admission's last resort when no slot is free: evict the LRU
        prefix-cache donor; failing that, preempt a running batch-class
        request when the queue head is interactive (``preempt=True``)."""
        return self._evict_cache_donor() or self._preempt_one()

    def _evict_cache_donor(self) -> bool:
        """Evict the LRU prefix-cache donor. Live requests' slots are never
        candidates — only parked (retired, cache-resident) slots are in the
        cache. The donor the queue-head request would match is protected:
        evicting it would trade that admission's cache hit for its slot
        (when it is the ONLY parked slot, admission waits instead — a live
        retire parks or frees a slot within a bounded number of steps)."""
        if self.prefix_cache is None:
            return False
        demote = (self.kv_tiers.demote if self.kv_tiers is not None
                  else None)
        protect = None
        head = self.sched.peek()
        if head is not None:
            protect = self.prefix_cache.peek_donor(head.prompt,
                                                   self._ns(head))
        if self.prefix_cache.evict_lru(self.pool, protect=protect,
                                       demote=demote) is not None:
            return True
        # the protected donor was the ONLY candidate: with live requests
        # in flight a retire will park/free a slot within bounded steps, so
        # defer; with none, nothing can ever free a slot — evict the donor
        # (trading the head's cache hit for forward progress — though with
        # tiers attached the demotion keeps the ENTRY alive, so the head
        # still hits, just via a promotion)
        if protect is not None and not self._by_slot:
            return self.prefix_cache.evict_lru(
                self.pool, demote=demote) is not None
        return False

    def _preempt_one(self) -> bool:
        """Pause the most recently admitted batch-class request so the
        interactive queue head can take its slot. The victim's live KV rows
        are exported to host through the slot-row view (the PR 8 disagg/
        prefix-cache machinery — raw f32 rows, so restore is bitwise), its
        cursor (``prefill_pos``) and last emitted token are saved on the
        request, the slot is freed with NO cache scrub (stale rows are dead
        by the masked-attention argument), and the victim re-queues at the
        HEAD of the batch class. Resume (:meth:`_resume`) imports the rows
        into whatever slot frees up and continues mid-prefill via the
        PR 4 ``start`` offset or mid-decode from the restored last token —
        output bit-identical to the unpreempted run (tested).

        Newest-first victim selection (max ``admit_seq``) preempts the
        request with the least sunk work, so older batch requests keep
        draining — preemption reorders *within* the batch class as little
        as possible. Adopted (disagg) requests have no admit_seq and are
        never victims: their KV provenance is the remote stream."""
        if not self.preempt:
            return False
        head = self.sched.peek()
        if head is None or head.priority != PRIORITY_CLASSES[0]:
            return False
        victims = [r for r in self._by_slot.values()
                   if r.priority == "batch" and r.admit_seq is not None]
        if not victims:
            return False
        victim = max(victims, key=lambda r: r.admit_seq)
        slot = victim.slot
        kv_len = victim.kv_len
        if kv_len > 0:
            # full S_max rows: one compiled export program per pool shape
            # (the import side pads to S_max anyway); the live window
            # [0, kv_len) is what resume stamps back as the length
            k_rows, v_rows = self.backend.export_slot_kv(
                slot, 0, self.backend.max_seq
            )
            victim._saved_kv = (k_rows, v_rows, kv_len)
        victim._saved_last_tok = int(self._last_tok[slot])
        self._by_slot.pop(slot)
        self._prefilling.pop(slot, None)
        self._release_slot(slot, victim)
        self.pool.free(slot)
        victim.slot = None
        victim.state = RequestState.PREEMPTED
        victim.preemptions += 1
        self.sched.requeue(victim)
        self.metrics.on_preempt(victim)
        _PREEMPTS.inc()
        obs.instant("preempt", track=victim.track, slot=slot,
                    pos=victim.prefill_pos, generated=victim.n_generated,
                    for_rid=head.rid)
        return True

    def _resume(self, slot: int, req: Request) -> None:
        """Re-enter a preempted request: import its saved KV rows into the
        newly granted slot (possibly a different one — the rows carry the
        state, not the slot id), restore the decode input token, and rejoin
        at the saved cursor: mid-prefill victims continue chunking at
        ``start=prefill_pos``, finished-prefill victims join this step's
        decode pass directly."""
        saved = req._saved_kv
        if saved is not None:
            k_rows, v_rows, kv_len = saved
            self.backend.import_slot_kv(slot, k_rows, v_rows,
                                        length=kv_len)
            req._saved_kv = None
        self._last_tok[slot] = np.int32(req._saved_last_tok)
        req._saved_last_tok = None
        # re-stamp sampling + adapter: the adapter may land on a DIFFERENT
        # table row than before preemption — row contents are the same
        # published weights, so the fused math is unchanged
        self._stamp_admit(slot, req)
        self._by_slot[slot] = req
        if req.prefill_pos < req.prompt.size:
            req.state = RequestState.PARTIAL_PREFILL
            self._prefilling[slot] = req
        # else: sched.admit already stamped ACTIVE — it decodes this step
        self.metrics.on_resume(req)
        _RESUMES.inc()
        obs.instant("resume", track=req.track, slot=slot,
                    pos=req.prefill_pos, generated=req.n_generated)

    def drain(self, max_steps: int = 100000) -> List[Request]:
        """Step until queue and slots are empty; returns all finished."""
        done: List[Request] = []
        steps = 0
        while self.has_work():
            done.extend(self.step())
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"drain exceeded {max_steps} steps with work remaining "
                    f"(queued={self.sched.qsize}, active={len(self._by_slot)})"
                )
        return done

    def snapshot(self) -> dict:
        return self.metrics.snapshot(
            queued=self.sched.qsize, active=len(self._by_slot),
            n_slots=self.pool.n_slots, occupancy=self.pool.occupancy,
        )

    def reset_metrics(self) -> None:
        """Zero counters/samples (e.g. after compile warmup) — the slot
        pool, queue and compiled programs are untouched. Also zeroes the
        process-wide serving latency HISTOGRAMS (serving/metrics.py):
        warmups reset every engine in the process before the measured
        window, so the histogram- and sample-derived percentiles keep
        describing the same observation set."""
        from uccl_tpu.serving.metrics import reset_latency_histograms

        self.metrics = ServingMetrics()
        reset_latency_histograms()

    def _flight_state(self) -> dict:
        """What a post-mortem bundle captures of this engine: the slot
        and queue occupancy the scheduler-facing narrative needs, never
        request payloads."""
        return {
            "dead": self.dead,
            "n_slots": self.pool.n_slots,
            "occupancy": round(self.pool.occupancy, 4),
            "high_water": self.pool.high_water,
            "active": len(self._by_slot),
            "prefilling": len(self._prefilling),
            "queued": self.sched.qsize,
            "scheduler": self.sched.debug_state(),
            "conservation": self._conservation_terms(),
        }

    def _conservation_terms(self) -> dict:
        m = self.metrics
        return {"submitted": m.submitted, "completed": m.completed,
                "active": len(self._by_slot), "queued": self.sched.qsize,
                "rejected": m.rejected, "expired": m.expired,
                "lost": m.lost}

    def _check_conservation(self) -> None:
        """The serving invariant, re-asserted at every step boundary:
        submitted == completed + active + queued + rejected + expired +
        lost. A violation is unrecoverable accounting damage — freeze
        the evidence ONCE (the first broken step is the interesting one;
        later steps inherit the same corruption)."""
        if self._conservation_fired:
            return
        t = self._conservation_terms()
        rhs = sum(v for k, v in t.items() if k != "submitted")
        if t["submitted"] != rhs:
            self._conservation_fired = True
            obs.flight_trigger("conservation", key=self._flight_name,
                               terms=t, rhs=rhs)

    def close(self) -> None:
        # only tear down the stats export THIS engine registered — a
        # second engine with register_stats=False must not unhook the
        # first one's source
        if self._stats_name is not None:
            self.metrics.unregister(self._stats_name)
            self._stats_name = None
        obs.flight_unregister(self._flight_name)

    # -- internals ----------------------------------------------------------
    def _ns(self, req: Request) -> str:
        """The request's prefix-cache namespace: tenant, plus adapter
        identity AND version when one is fused — adapter deltas land on
        ``wv``, so cached KV rows are adapter-dependent and a re-published
        adapter must never hit its predecessor's rows. The default tenant
        with no adapter maps to the root namespace (single-tenant engines
        are unchanged).

        The namespace is CAPTURED at first admission (``_stamp_admit``)
        and reused verbatim for the retire-time park: a request's KV was
        computed under the adapter version pinned when it entered its
        slot, so a republish while it is in flight must not relabel the
        rows with the NEW version — that would hand v1-derived KV to v2
        requests, the exact contamination the versioning exists to stop.
        Before admission (queued peek/match) the current version is the
        right answer — that IS the version admission would pin."""
        if req._cache_ns is not None:
            return req._cache_ns
        if req.adapter is not None:
            return (f"{req.tenant}|{req.adapter}"
                    f"@{self.adapters.version(req.adapter)}")
        if req.tenant != "default":
            return req.tenant
        return ""

    def _gate_admitted(self, batch):
        """Re-validate adapters for a just-admitted batch, BEFORE any slot
        is stamped. Submit-time validation can go stale while a request
        queues: an adapter archive-evicted under ``max_published`` can
        never run again (the request exits REJECTED, ``adapter_lost``),
        and a batch needing more fresh table rows than are free or
        evictable must wait (DEFERRED back to the queue head — a retire
        will unpin a row — together with every later admission of the
        batch, so FIFO order within a tenant is preserved). Without this
        gate ``adapters.acquire`` raises inside ``step()`` AFTER the
        scheduler popped the request and the pool granted the slot,
        crashing the engine with inconsistent queue/pool state.

        The row budget is batch-aware: resident adapters the batch will
        pin are excluded from the available count (``n_available_rows``),
        so one batch can never plan a staging that evicts a row a later
        admission of the same batch needs. Returns ``(survivors,
        deferred_any)``; the scheduler never re-bills a requeued request
        (``req.billed``), so deferral retries cost the tenant nothing."""
        if self.adapters is None:
            return batch, False
        batch_resident = {r.adapter for _, r in batch
                          if r.adapter is not None
                          and self.adapters.is_resident(r.adapter)}
        avail = self.adapters.n_available_rows(exclude=batch_resident)
        staged = set()  # fresh (non-resident) adapters this batch stages
        ok, deferred = [], []
        for slot, req in batch:
            gate = None
            if deferred:
                gate = "defer"
            elif req.adapter is not None:
                if not self.adapters.has(req.adapter):
                    gate = "lost"
                elif (not self.adapters.is_resident(req.adapter)
                        and req.adapter not in staged):
                    if len(staged) >= avail:
                        gate = "defer"
                    else:
                        staged.add(req.adapter)
            if gate is None:
                ok.append((slot, req))
                continue
            self.pool.free(slot)
            if gate == "lost":
                req.state = RequestState.REJECTED
                req.slot = None
                req.finish_reason = "adapter_lost"
                self.metrics.on_expire(req)
                _DROPPED.inc(reason="adapter_lost")
                obs.instant("reject", track=req.track, rid=req.rid,
                            reason="adapter_lost")
            else:
                deferred.append(req)
        for req in reversed(deferred):
            self.sched.defer(req)
        return ok, bool(deferred)

    def _stamp_admit(self, slot: int, req: Request) -> None:
        """Slot-entry bookkeeping for sampling + adapters: write the
        request's sampling row and pin its adapter into a device table
        row (0 = the zero-rank fast path). Runs at every slot grant —
        fresh admission, preemption resume, adopt."""
        stamp_slot(self._sampling, slot, req.sampling)
        row = 0
        if req.adapter is not None:
            row = self.adapters.acquire(req.adapter)
        req._adapter_row = row
        self._adapter_ids[slot] = row
        if req._cache_ns is None:
            # first slot grant: freeze the namespace under the adapter
            # version just pinned (resume/adopt re-grants keep the
            # original — their KV predates any later republish)
            req._cache_ns = self._ns(req)

    def _release_slot(self, slot: int, req: Request) -> None:
        """Undo :meth:`_stamp_admit` when the request leaves its slot
        (retire or preemption): greedy the sampling row, zero the adapter
        id, unpin the adapter table row."""
        stamp_slot(self._sampling, slot, None)
        self._adapter_ids[slot] = 0
        if req._adapter_row:
            self.adapters.release(req._adapter_row)
            req._adapter_row = 0

    def _sampling_for(self, rows, pos0=None):
        """The packed per-slot sampling tuple for a batched call covering
        ``rows`` ((slot, req) pairs) — None when every covered request is
        greedy, so the argmax programs stay byte-identical to the
        pre-sampling engine. ``pos0`` is each slot's output index for the
        first token the call emits (None = zeros: prefill's first token
        is output index 0)."""
        if not any(r.sampling is not None for _, r in rows):
            return None
        if pos0 is None:
            pos0 = np.zeros(self.backend.n_slots, np.int32)
        return pack_sampling(self._sampling, pos0)

    def _adapters_for(self, rows):
        """The (device tables, per-slot row ids) pair for a batched call —
        None when no covered request fused an adapter (id-0 rows would
        compute an exact-0.0 delta, but skipping keeps the adapter-free
        programs byte-identical)."""
        if self.adapters is None or not any(r._adapter_row
                                            for _, r in rows):
            return None
        return (self.adapters.device_tables(), self._adapter_ids.copy())

    def _extra_kw(self, rows, pos0=None) -> dict:
        """Backend-call kwargs for ``rows`` — sampling/adapters keys only
        when actually needed, so greedy adapter-free engines keep calling
        backends (including the test stubs and any external backend
        implementation) with the pre-sampling signature."""
        kw = {}
        samp = self._sampling_for(rows, pos0)
        if samp is not None:
            kw["sampling"] = samp
        adp = self._adapters_for(rows)
        if adp is not None:
            kw["adapters"] = adp
        return kw

    def _prefill(self, newly, finished) -> None:
        n = self.backend.n_slots
        s_bucket = _bucket(max(r.prompt.size for _, r in newly),
                           self.backend.max_seq)
        tokens = np.zeros((n, s_bucket), np.int32)
        lens = np.ones(n, np.int32)  # 1 (not 0): the -1 logit gather stays
        mask = np.zeros(n, bool)     # in bounds on non-admitted rows
        for slot, req in newly:
            tokens[slot, :req.prompt.size] = req.prompt
            lens[slot] = req.prompt.size
            mask[slot] = True
            self._stamp_admit(slot, req)
            self.metrics.on_admit(req)
            obs.instant("admit", track=req.track, slot=slot)
        _PREFILL_TOKENS.inc(sum(int(r.prompt.size) for _, r in newly),
                            kind="computed")
        tr = obs.get_tracer()
        ts0 = tr.now_us() if tr is not None else 0.0
        t0 = now()
        tok = self.backend.prefill(tokens, lens, mask,
                                   **self._extra_kw(newly))
        self.metrics.on_prefill(now() - t0, len(newly))
        t_done = now()
        if tr is not None:
            # one measured window, spans on every covered track: the wire
            # row shows the batched device call, each request row its share
            dur = tr.now_us() - ts0
            tr.complete("wire.prefill", ts0, dur, "wire",
                        n=len(newly), bucket=s_bucket)
            for slot, req in newly:
                tr.complete("prefill", ts0, dur, req.track, slot=slot)
        for slot, req in newly:
            self._by_slot[slot] = req
            # the whole prompt is in KV now — keep the cursor truthful so
            # pending_tokens() (the router's debt signal) never counts an
            # already-prefilled prompt as outstanding work
            req.prefill_pos = req.prompt.size
            self._emit_first_token(slot, req, tok[slot], t_done, finished)

    def _prefill_chunk_step(self, finished,
                            events: Optional[List[ChunkEvent]] = None,
                            ) -> None:
        """Advance every mid-prefill slot by one C-token chunk (ONE batched
        call, one compiled program at [n_slots, C]). Rows whose cursor
        reaches the prompt end emit their first token and leave
        PARTIAL_PREFILL; other rows' returned tokens are garbage by the
        model contract and ignored here. ``events`` carries this step's
        admission-time prefix-cache copies; the chunk advances are appended
        and the whole batch goes to ``chunk_sink`` BEFORE any retirement,
        so a sink can export rows while slots still hold them."""
        c = self.prefill_chunk
        n = self.backend.n_slots
        tokens = np.zeros((n, c), np.int32)
        lens = np.ones(n, np.int32)  # 1 (not 0): the gather index
        start = np.zeros(n, np.int32)  # clip stays in bounds on idle rows
        mask = np.zeros(n, bool)
        for slot, req in self._prefilling.items():
            chunk = req.prompt[req.prefill_pos:req.prefill_pos + c]
            tokens[slot, :chunk.size] = chunk
            lens[slot] = req.prompt.size
            start[slot] = req.prefill_pos
            mask[slot] = True
        tr = obs.get_tracer()
        ts0 = tr.now_us() if tr is not None else 0.0
        t0 = now()
        rows = list(self._prefilling.items())
        tok = self.backend.prefill(tokens, lens, mask, start=start,
                                   **self._extra_kw(rows))
        self.metrics.on_prefill(now() - t0, len(self._prefilling),
                                chunked=True)
        t_done = now()
        if tr is not None:
            dur = tr.now_us() - ts0
            tr.complete("wire.prefill", ts0, dur, "wire",
                        n=len(self._prefilling), chunk=c)
            for slot, req in self._prefilling.items():
                tr.complete("prefill_chunk", ts0, dur, req.track,
                            slot=slot, offset=req.prefill_pos)
        if events is None:
            events = []
        computed = 0
        advanced = []
        for slot, req in self._prefilling.items():
            old = req.prefill_pos
            req.prefill_pos = min(old + c, req.prompt.size)
            done = req.prefill_pos >= req.prompt.size
            computed += req.prefill_pos - old
            events.append(ChunkEvent(
                req, slot, old, req.prefill_pos, done,
                int(tok[slot]) if done else None, False,
            ))
            advanced.append((slot, req, done))
        _PREFILL_TOKENS.inc(computed, kind="computed")
        if self.chunk_sink is not None:
            # drop events whose slot changed hands since they were queued:
            # an admission-time prefix-copy event whose request was
            # preempted later in the SAME admission loop would otherwise
            # export rows now owned by the request that took the slot
            self.chunk_sink([ev for ev in events
                             if self._by_slot.get(ev.slot) is ev.req])
        for slot, req, done in advanced:
            if not done:
                continue  # more chunks to go — next step
            del self._prefilling[slot]
            req.state = RequestState.ACTIVE
            self._emit_first_token(slot, req, tok[slot], t_done, finished)

    def _decode(self, finished) -> None:
        decoding = {s: r for s, r in self._by_slot.items()
                    if s not in self._prefilling}
        if self.spec_k is not None:
            self._spec_decode(decoding, finished)
            return
        active = np.zeros(self.backend.n_slots, bool)
        pos0 = np.zeros(self.backend.n_slots, np.int32)
        for slot, req in decoding.items():
            active[slot] = True
            pos0[slot] = req.n_generated  # this step's output index
        rows = list(decoding.items())
        tr = obs.get_tracer()
        ts0 = tr.now_us() if tr is not None else 0.0
        t0 = now()
        tok = self.backend.decode(self._last_tok.copy(), active,
                                  **self._extra_kw(rows, pos0))
        self.metrics.on_decode_step(now() - t0, len(decoding),
                                    tokens=len(decoding))
        t_done = now()
        if tr is not None:
            tr.complete("wire.decode", ts0, tr.now_us() - ts0, "wire",
                        n=len(decoding))
        for slot, req in list(decoding.items()):
            self._last_tok[slot] = tok[slot]
            req.out_tokens.append(int(tok[slot]))
            self._maybe_retire(slot, req, t_done, finished)

    def _spec_decode(self, decoding, finished) -> None:
        """One speculative decode iteration: draft k tokens per decoding
        slot (host-side, jax-free), verify every slot in ONE batched
        [n_slots, k+1] window, commit each slot's accepted prefix plus the
        target-computed correction/bonus token. Commits stop early at EOS
        or the token budget (both retire the request, so the over-advanced
        device cursor is dead with the slot). Drafters may propose fewer
        than k tokens — the window pads with zeros, and a pad that happens
        to match still commits a correct token (acceptance only ever
        commits the target's own argmaxes)."""
        k = self.spec_k
        n = self.backend.n_slots
        tokens = np.zeros((n, k + 1), np.int32)
        active = np.zeros(n, bool)
        proposed = np.zeros(n, np.int32)
        pos0 = np.zeros(n, np.int32)
        for slot, req in decoding.items():
            tokens[slot, 0] = self._last_tok[slot]
            d = np.asarray(self.drafter.draft(req.context(), k),
                           np.int32).reshape(-1)[:k]
            if d.size:
                tokens[slot, 1:1 + d.size] = d
            proposed[slot] = d.size
            active[slot] = True
            pos0[slot] = req.n_generated  # window column j → pos0 + j
        rows = list(decoding.items())
        tr = obs.get_tracer()
        ts0 = tr.now_us() if tr is not None else 0.0
        t0 = now()
        tok, n_acc = self.backend.verify(tokens, active,
                                         **self._extra_kw(rows, pos0))
        dt = now() - t0
        t_done = now()
        if tr is not None:
            # the device window only — the host commit loop below must not
            # inflate the span (same placement as _decode's wire.decode)
            tr.complete("wire.verify", ts0, tr.now_us() - ts0, "wire",
                        n=len(decoding), k=k)
        committed_total = 0
        for slot, req in list(decoding.items()):
            m = int(n_acc[slot])
            committed = 0
            for j in range(m + 1):
                t = int(tok[slot, j])
                self._last_tok[slot] = tok[slot, j]
                req.out_tokens.append(t)
                committed += 1
                if ((req.eos_id is not None and t == req.eos_id)
                        or req.n_generated >= req.max_new_tokens):
                    break
            committed_total += committed
            # telemetry meters DRAFTED tokens only: the window pads
            # undrafted positions with zeros, and a pad that coincidentally
            # matches the argmax still COMMITS (it is the target's own
            # token) but must not count as an accepted speculation — nor an
            # abstention as k rejections
            p = int(proposed[slot])
            acc = min(m, p)
            if (acc < p and req.sampling is not None
                    and req.sampling.temperature > 0):
                # a sampled window hit a rejection: the committed token at
                # the rejection position IS the residual resample (the
                # deterministic-drafter rejection-sampling coupling —
                # docs/SERVING.md), so meter the correction
                _SPEC_RESAMPLE.inc()
            _SPEC_TOKENS.inc(acc, outcome="accepted")
            _SPEC_TOKENS.inc(p - acc, outcome="rejected")
            _SPEC_TOKENS.inc(1, outcome="bonus")
            _SPEC_ACCEPTED_LEN.inc(1, len=str(acc))
            self.metrics.on_spec(proposed=p, accepted=acc)
            self._maybe_retire(slot, req, t_done, finished)
        self.metrics.on_decode_step(dt, len(decoding),
                                    tokens=committed_total)

    def _emit_first_token(self, slot: int, req: Request, tok_val, t: float,
                          finished) -> None:
        """Record a request's first generated token (prefill completion in
        either mode): seed the decode input, stamp TTFT, maybe retire."""
        self._last_tok[slot] = tok_val
        req.out_tokens.append(int(tok_val))
        req.t_first_token = t
        self.metrics.on_first_token(req)
        obs.instant("first_token", track=req.track,
                    ttft_ms=round(req.ttft * 1e3, 3),
                    trace_id=req.trace_id)
        self._maybe_retire(slot, req, t, finished)

    def _maybe_retire(self, slot: int, req: Request, t: float,
                      finished) -> None:
        if req.eos_id is not None and req.out_tokens[-1] == req.eos_id:
            req.finish_reason = "eos"
        elif req.n_generated >= req.max_new_tokens:
            req.finish_reason = "length"
        else:
            return
        req.state = RequestState.FINISHED
        req.t_finish = t
        self._release_slot(slot, req)
        # park-on-retire: with a prefix cache, the retiring slot's prompt
        # KV stays resident as a reuse donor (LRU-evicted under admission
        # pressure) instead of being freed — under the request's tenant/
        # adapter namespace, so a cross-tenant prompt never hits these rows
        parked = (self.prefix_cache is not None
                  and self.prefix_cache.park(self.pool, slot, req.prompt,
                                             self._ns(req)))
        if not parked:
            self.pool.free(slot)
        self._by_slot.pop(slot, None)
        self.metrics.on_finish(req)
        _TENANT_REQS.inc(tenant=req.tenant)
        _TENANT_TOKS.inc(req.n_generated, tenant=req.tenant)
        obs.instant("finish", track=req.track, reason=req.finish_reason,
                    tokens=req.n_generated, parked=parked,
                    trace_id=req.trace_id)
        finished.append(req)
