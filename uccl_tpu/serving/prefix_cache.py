"""Prefix-reuse KV cache: a token trie at chunk granularity over parked slots.

The radix/prefix-cache idea (vLLM automatic prefix caching, SGLang RadixAttention)
on this repo's slot pool: a retired request's KV rows stay **resident** — its
slot is *parked*, not freed — and its prompt is inserted into a token trie
keyed by fixed-size chunks of ``prefill_chunk`` tokens. A later request whose
prompt shares a cached chunk-aligned prefix resumes prefill at
``prefill_pos = matched_len`` after a slot-to-slot KV copy: the PR 4 resumable
prefill primitive (``prefill_slots(..., start=off)``) makes the continuation
bit-exact, so shared system prompts are computed ONCE and every skipped token
is still oracle-identical.

Chunk granularity is deliberate: it matches the engine's prefill chunk, so a
match boundary is always a position the chunked prefill program can resume
from, and trie keys are the raw bytes of one chunk's tokens (no hashing
collisions to reason about).

Residency is **tier-tagged** (ISSUE 17): a resident is either an ``int`` —
a parked device slot, tier 0, charged against the slot pool exactly as
before — or an opaque tier reference (``serving/kv_tiers.py``'s
:class:`TierRef`) naming an entry demoted to the host pool (T1) or a remote
peer (T2). The trie is the ONE index over all tiers: lookup walks the same
nodes whatever tier the donor lives in, eviction demotes T0 victims through
the ``demote=`` hook instead of dropping them, and a deep-tier hit promotes
through :class:`~uccl_tpu.serving.kv_tiers.TieredKVCache`. This module stays
host-only and jax-free — it never touches KV bytes, only names them.

Each resident's chunk-key path is recorded at insert time, so ``_remove``
walks ONLY the victim's branch (O(depth), not O(total trie nodes) — the
pre-17 implementation pruned the entire trie on every eviction).

Counters (obs registry, docs/OBSERVABILITY.md): ``prefix_cache_hits_total``,
``prefix_cache_misses_total``, ``prefix_cache_evictions_total``,
``prefix_cache_tokens_reused_total``, gauges ``prefix_cache_resident_slots``
and ``prefix_cache_resident_tokens`` (both device-tier: parked slots and
their depth×chunk token sum — deeper tiers report on the
``kv_tier_resident_{tokens,bytes}`` families).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from uccl_tpu import obs

_HITS = obs.counter(
    "prefix_cache_hits_total",
    "admissions that resumed prefill from a cached chunk-aligned prefix",
)
_MISSES = obs.counter(
    "prefix_cache_misses_total",
    "admissions with no usable cached prefix (cold prefill from 0)",
)
_EVICTIONS = obs.counter(
    "prefix_cache_evictions_total",
    "parked donor slots reclaimed LRU-first under admission pressure",
)
_TOKENS_REUSED = obs.counter(
    "prefix_cache_tokens_reused_total",
    "prompt tokens whose prefill compute was skipped via a cached prefix",
)
_RESIDENT = obs.gauge(
    "prefix_cache_resident_slots",
    "slots currently parked as prefix-cache donors",
)
_RESIDENT_TOKENS = obs.gauge(
    "prefix_cache_resident_tokens",
    "prompt tokens held by parked prefix-cache donors (depth x chunk summed "
    "over device-tier residents) — the cache-pressure axis capacity sweeps "
    "read in tokens rather than slots",
)


class _Node:
    """One trie node: children keyed by the raw bytes of a C-token chunk;
    ``slots`` is every resident (parked slot id or tier ref) whose cached
    prompt passes through this node (i.e. whose KV holds at least this
    node's depth in chunks)."""

    __slots__ = ("children", "slots")

    def __init__(self):
        self.children: Dict[bytes, _Node] = {}
        self.slots: Set = set()


class PrefixCache:
    """Chunk-granular prefix trie over parked KV slots + demoted tier
    entries, LRU-evicted.

    The engine owns the pool and the KV copies; this class owns WHICH
    resident holds WHICH prefix and for how long. Invariant: every ``int``
    resident referenced anywhere in the trie is parked in the engine's pool
    (never a live request's slot), so eviction can only ever reclaim cache
    residency; every non-int resident is a tier ref whose bytes live in the
    attached :class:`~uccl_tpu.serving.kv_tiers.TieredKVCache` — and each
    logical entry lives in EXACTLY ONE tier (a demotion moves the resident,
    never copies it).
    """

    def __init__(self, chunk: int):
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        self.chunk = chunk
        self._root = _Node()
        # resident -> (depth in chunks, last-use sequence number). Depth is
        # how many full chunks of the resident's prompt are keyed in the
        # trie. Keys are slot ints (T0) or TierRefs (T1/T2).
        self._resident: Dict = {}
        # resident -> its chunk-key path, recorded at insert time so
        # removal walks only this branch (never the whole trie)
        self._paths: Dict = {}
        self._seq = 0
        self._t0_tokens = 0  # running depth*chunk sum over int residents
        self._tiers = None  # TieredKVCache once attach_tiers() is called
        # fleet listener (ISSUE 19): mirrors residency into the shared
        # directory — on_insert(resident, path) / on_remove(resident),
        # both called on the engine step thread, both fail-soft
        self.listener = None

    # -- inspection -------------------------------------------------------
    @property
    def n_resident(self) -> int:
        """Device-tier (parked-slot) residents — the pre-tier meaning."""
        return sum(1 for r in self._resident if isinstance(r, int))

    @property
    def n_tier_refs(self) -> int:
        """Deep-tier (T1/T2) residents."""
        return len(self._resident) - self.n_resident

    def resident_slots(self) -> List[int]:
        return sorted(r for r in self._resident if isinstance(r, int))

    def tier_refs(self) -> List:
        return [r for r in self._resident if not isinstance(r, int)]

    def attach_tiers(self, tiers) -> None:
        """Bind the tier manager: ``_remove`` of a tier-ref resident then
        releases its store bytes (``tiers.release(ref)``, idempotent) so
        dropping a trie entry can never strand tier capacity."""
        self._tiers = tiers

    def _touch(self, resident) -> None:
        depth, _ = self._resident[resident]
        self._seq += 1
        self._resident[resident] = (depth, self._seq)

    def _chunks(self, prompt: np.ndarray, n: int, ns: str = ""):
        """Chunk keys of ``prompt``, namespaced (ISSUE 18): every key is
        prefixed with ``ns`` bytes, so two tenants (or two adapter
        versions of one tenant) sharing a system prompt occupy DISJOINT
        trie branches — adapter-divergent KV can never cross-hit. A
        cross-namespace lookup walks into the other namespace's branch
        root, finds nothing, and counts an ordinary miss."""
        c = self.chunk
        tag = ns.encode() + b"\x00" if ns else b""
        p = np.ascontiguousarray(np.asarray(prompt, np.int32))
        for i in range(n):
            yield tag + p[i * c:(i + 1) * c].tobytes()

    def _stamp_gauges(self) -> None:
        _RESIDENT.set(self.n_resident)
        _RESIDENT_TOKENS.set(self._t0_tokens)

    # -- lookup -----------------------------------------------------------
    def _lookup(self, prompt, ns: str = "") -> Tuple[int, Optional[object]]:
        """Side-effect-free deepest-usable-prefix walk (no counters, no
        LRU refresh) — shared by :meth:`match` and :meth:`peek_donor`."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        usable = (prompt.size - 1) // self.chunk  # ≥1 token must remain
        node, best = self._root, None
        depth = 0
        for key in self._chunks(prompt, usable, ns):
            node = node.children.get(key)
            if node is None:
                break
            depth += 1
            if node.slots:
                best = (depth, node)
        if best is None:
            return 0, None
        depth, node = best
        # prefer a device-tier donor over a deep-tier ref at equal depth
        # (a slot copy beats a decode+import promotion), then the most
        # recently used among equals (keeps hot shared prompts hot)
        donor = max(node.slots,
                    key=lambda s: (isinstance(s, int),
                                   self._resident[s][1]))
        return depth * self.chunk, donor

    def match(self, prompt, ns: str = "") -> Tuple[int, Optional[object]]:
        """Deepest cached chunk-aligned prefix of ``prompt`` that is usable
        for resumption, WITHIN namespace ``ns`` (the engine passes the
        request's tenant + adapter version — a cross-tenant or
        cross-adapter-version attempt counts a miss, never a hit).
        Returns ``(matched_len, donor)`` with
        ``matched_len`` a multiple of ``chunk`` and ``donor`` a parked slot
        id (int, tier 0) or a tier ref; ``(0, None)`` on a miss.

        A match is capped at the largest chunk multiple ≤ ``len(prompt)-1``:
        at least one prompt position must remain to prefill, because the
        first generated token comes from the final position's logits — a
        fully cached prompt still recomputes its last partial/full chunk.
        Refreshes the donor's LRU stamp.

        Counting: a miss counts here; so does a device-tier (``int``
        donor) hit — its slot-to-slot copy cannot fail. A DEEP-tier
        donor's hit (+ reused tokens) is counted only by
        :meth:`commit_hit` once the promotion actually lands KV rows in
        the slot; a stale ref instead counts a :meth:`count_stale_miss`
        cold miss. The ledger never credits skipped compute that was not
        skipped, and the per-tier split of ``kv_tier_hits_total`` keeps
        summing to ``prefix_cache_hits_total``.
        """
        matched, donor = self._lookup(prompt, ns)
        if donor is None:
            _MISSES.inc()
            return 0, None
        self._touch(donor)
        if isinstance(donor, int):
            _HITS.inc()
            _TOKENS_REUSED.inc(matched)
        return matched, donor

    def commit_hit(self, matched: int) -> None:
        """Count a deep-tier hit deferred by :meth:`match` — the engine
        calls this after ``TieredKVCache.promote`` returned True, i.e.
        after the donor's rows really landed in the admitted slot."""
        _HITS.inc()
        _TOKENS_REUSED.inc(matched)

    def count_stale_miss(self) -> None:
        """Count the cold miss a stale deep-tier ref degraded to (the
        promotion found the entry gone): the admission prefills from 0,
        so it is a miss in every ledger that matters."""
        _MISSES.inc()

    def peek_donor(self, prompt, ns: str = "") -> Optional[object]:
        """The resident :meth:`match` would reuse for ``prompt``, with no
        counter or LRU side effects — the engine protects it from being
        its own admission's eviction victim."""
        return self._lookup(prompt, ns)[1]

    def covered(self, prompt, ns: str = "") -> Optional[object]:
        """If the trie already caches ``prompt``'s full-chunk prefix at
        maximal depth in namespace ``ns``, return a resident holding it
        (parking another copy would waste a slot); else None."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        k = prompt.size // self.chunk
        if k < 1:
            return None
        node = self._root
        for key in self._chunks(prompt, k, ns):
            node = node.children.get(key)
            if node is None:
                return None
        if not node.slots:
            return None
        return max(node.slots, key=lambda s: self._resident[s][1])

    # -- residency --------------------------------------------------------
    def _insert(self, resident, path: List[bytes],
                seq: Optional[int] = None) -> None:
        """Add ``resident`` along ``path`` (a list of chunk keys) and
        record the path for O(depth) removal. ``seq`` pins the LRU stamp —
        a demotion re-inserts at the victim's OLD stamp, because moving an
        entry down a tier must not refresh its recency."""
        node = self._root
        for key in path:
            node = node.children.setdefault(key, _Node())
            node.slots.add(resident)
        if seq is None:
            self._seq += 1
            seq = self._seq
        self._resident[resident] = (len(path), seq)
        self._paths[resident] = list(path)
        if isinstance(resident, int):
            self._t0_tokens += len(path) * self.chunk
        self._stamp_gauges()
        if self.listener is not None:
            self.listener.on_insert(resident, list(path))

    def park(self, pool, slot: int, prompt, ns: str = "") -> bool:
        """Try to keep a retiring request's slot resident as a donor,
        keyed in namespace ``ns`` (the retiring request's tenant +
        adapter version — its KV is only ever a donor within it).

        Returns True when the slot was parked (caller must NOT free it);
        False when caching is useless — prompt shorter than one chunk, or
        its full-chunk prefix is already cached (the existing donor's LRU
        stamp is refreshed instead) — and the caller should free the slot.

        One tier-crossing rule: when the covering resident is a DEEP-tier
        ref at exactly this prompt's full-chunk depth, the fresh slot
        supersedes it — the entry moves back to tier 0 (the slot parks,
        the ref is dropped and its store bytes released), because serving
        future hits from a device slot beats re-promoting the same bytes
        every time. A ref covering a DEEPER prefix is a different entry
        and blocks nothing.
        """
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        k = prompt.size // self.chunk
        if k < 1:
            return False
        existing = self.covered(prompt, ns)
        if existing is not None:
            if isinstance(existing, int) or self._resident[existing][0] > k:
                self._touch(existing)
                return False
            # deep-tier ref at exactly depth k: supersede it with the slot
            self._remove(existing)
        self._insert(slot, list(self._chunks(prompt, k, ns)))
        pool.park(slot)
        return True

    def _remove(self, resident) -> None:
        """Drop every trie reference to ``resident`` by walking ONLY its
        recorded chunk-key path (pruning nodes left empty, deepest-first).
        A removed tier ref also releases its store bytes through the
        attached tier manager."""
        depth, _ = self._resident.pop(resident)
        path = self._paths.pop(resident)
        nodes = [self._root]
        node = self._root
        for key in path:
            node = node.children[key]
            node.slots.discard(resident)
            nodes.append(node)
        for i in range(len(path) - 1, -1, -1):
            child = nodes[i + 1]
            if child.slots or child.children:
                break
            del nodes[i].children[path[i]]
        if isinstance(resident, int):
            self._t0_tokens -= depth * self.chunk
        elif self._tiers is not None:
            self._tiers.release(resident)
        self._stamp_gauges()
        if self.listener is not None:
            self.listener.on_remove(resident)

    def replace_ref(self, old_ref, new_ref) -> None:
        """Swap a deep-tier resident for another AT THE SAME PATH AND LRU
        STAMP (or drop it when ``new_ref`` is None) — the tier manager's
        hook for T1→T2 spills and stale-ref invalidation. The manager has
        already moved/freed the store bytes, so the embedded release is a
        no-op by idempotence."""
        _, seq = self._resident[old_ref]
        path = self._paths[old_ref]
        self._remove(old_ref)
        if new_ref is not None:
            self._insert(new_ref, path, seq=seq)

    def evict_lru(self, pool, protect: Optional[int] = None,
                  demote=None) -> Optional[int]:
        """Reclaim the least-recently-used parked slot for admission: the
        slot returns to the pool's free list and every trie entry for it is
        dropped — or, with a ``demote`` hook, MOVED: ``demote(slot,
        n_tokens)`` may export the victim's rows to a deeper tier and
        return a tier ref, which is re-inserted at the victim's exact path
        and LRU stamp (the entry keeps its identity and recency, only its
        bytes change tier). Only parked slots are candidates (live
        requests are never resident, tier refs hold no slot).
        ``protect`` exempts one resident — the donor the admission
        triggering this eviction is about to match (evicting it would
        trade the hit for the slot). Returns the evicted slot id, or None
        when no candidate remains."""
        candidates = [s for s in self._resident
                      if isinstance(s, int) and s != protect]
        if not candidates:
            return None
        slot = min(candidates, key=lambda s: self._resident[s][1])
        depth, seq = self._resident[slot]
        path = self._paths[slot]
        ref = demote(slot, depth * self.chunk) if demote is not None else None
        self._remove(slot)
        if ref is not None:
            self._insert(ref, path, seq=seq)
        pool.reclaim(slot)
        _EVICTIONS.inc()
        return slot

    def clear(self, pool) -> None:
        """Reclaim every parked slot, release every tier ref, and empty the
        trie (e.g. after compile warmup, whose synthetic prompts must not
        act as donors). Counters are untouched — benches isolate arms by
        delta."""
        for resident in list(self._resident):
            self._remove(resident)
            if isinstance(resident, int):
                pool.reclaim(resident)
        self._root = _Node()
        self._paths.clear()
        self._t0_tokens = 0
        _RESIDENT.set(0)
        _RESIDENT_TOKENS.set(0)
