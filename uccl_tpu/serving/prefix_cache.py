"""Prefix-reuse KV cache: a token trie at chunk granularity over parked slots.

The radix/prefix-cache idea (vLLM automatic prefix caching, SGLang RadixAttention)
on this repo's slot pool: a retired request's KV rows stay **resident** — its
slot is *parked*, not freed — and its prompt is inserted into a token trie
keyed by fixed-size chunks of ``prefill_chunk`` tokens. A later request whose
prompt shares a cached chunk-aligned prefix resumes prefill at
``prefill_pos = matched_len`` after a slot-to-slot KV copy: the PR 4 resumable
prefill primitive (``prefill_slots(..., start=off)``) makes the continuation
bit-exact, so shared system prompts are computed ONCE and every skipped token
is still oracle-identical.

Chunk granularity is deliberate: it matches the engine's prefill chunk, so a
match boundary is always a position the chunked prefill program can resume
from, and trie keys are the raw bytes of one chunk's tokens (no hashing
collisions to reason about).

Residency is charged against the slot pool (``SlotPool.park``): parked donors
occupy real KV rows, and admission pressure evicts them LRU-first via the
scheduler's ``make_room`` hook — a live request's slot is never evicted
because live slots are, by construction, never *in* the cache (only retire
parks). Everything is host-only and jax-free; KV bytes move in the backend.

Counters (obs registry, docs/OBSERVABILITY.md): ``prefix_cache_hits_total``,
``prefix_cache_misses_total``, ``prefix_cache_evictions_total``,
``prefix_cache_tokens_reused_total``, gauge ``prefix_cache_resident_slots``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from uccl_tpu import obs

_HITS = obs.counter(
    "prefix_cache_hits_total",
    "admissions that resumed prefill from a cached chunk-aligned prefix",
)
_MISSES = obs.counter(
    "prefix_cache_misses_total",
    "admissions with no usable cached prefix (cold prefill from 0)",
)
_EVICTIONS = obs.counter(
    "prefix_cache_evictions_total",
    "parked donor slots reclaimed LRU-first under admission pressure",
)
_TOKENS_REUSED = obs.counter(
    "prefix_cache_tokens_reused_total",
    "prompt tokens whose prefill compute was skipped via a cached prefix",
)
_RESIDENT = obs.gauge(
    "prefix_cache_resident_slots",
    "slots currently parked as prefix-cache donors",
)


class _Node:
    """One trie node: children keyed by the raw bytes of a C-token chunk;
    ``slots`` is every parked slot whose cached prompt passes through this
    node (i.e. whose KV holds at least this node's depth in chunks)."""

    __slots__ = ("children", "slots")

    def __init__(self):
        self.children: Dict[bytes, _Node] = {}
        self.slots: Set[int] = set()


class PrefixCache:
    """Chunk-granular prefix trie over parked KV slots, LRU-evicted.

    The engine owns the pool and the KV copies; this class owns WHICH slot
    holds WHICH prefix and for how long. Invariant: every slot referenced
    anywhere in the trie is parked in the engine's pool (never a live
    request's slot), so eviction can only ever reclaim cache residency.
    """

    def __init__(self, chunk: int):
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        self.chunk = chunk
        self._root = _Node()
        # slot -> (depth in chunks, last-use sequence number). Depth is how
        # many full chunks of the slot's prompt are keyed in the trie.
        self._resident: Dict[int, Tuple[int, int]] = {}
        self._seq = 0

    # -- inspection -------------------------------------------------------
    @property
    def n_resident(self) -> int:
        return len(self._resident)

    def resident_slots(self) -> List[int]:
        return sorted(self._resident)

    def _touch(self, slot: int) -> None:
        depth, _ = self._resident[slot]
        self._seq += 1
        self._resident[slot] = (depth, self._seq)

    def _chunks(self, prompt: np.ndarray, n: int):
        c = self.chunk
        p = np.ascontiguousarray(np.asarray(prompt, np.int32))
        for i in range(n):
            yield p[i * c:(i + 1) * c].tobytes()

    # -- lookup -----------------------------------------------------------
    def _lookup(self, prompt) -> Tuple[int, Optional[int]]:
        """Side-effect-free deepest-usable-prefix walk (no counters, no
        LRU refresh) — shared by :meth:`match` and :meth:`peek_donor`."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        usable = (prompt.size - 1) // self.chunk  # ≥1 token must remain
        node, best = self._root, None
        depth = 0
        for key in self._chunks(prompt, usable):
            node = node.children.get(key)
            if node is None:
                break
            depth += 1
            if node.slots:
                best = (depth, node)
        if best is None:
            return 0, None
        depth, node = best
        # prefer the most recently used donor among equals (keeps hot
        # shared prompts hot)
        donor = max(node.slots, key=lambda s: self._resident[s][1])
        return depth * self.chunk, donor

    def match(self, prompt) -> Tuple[int, Optional[int]]:
        """Deepest cached chunk-aligned prefix of ``prompt`` that is usable
        for resumption. Returns ``(matched_len, donor_slot)`` with
        ``matched_len`` a multiple of ``chunk``; ``(0, None)`` on a miss.

        A match is capped at the largest chunk multiple ≤ ``len(prompt)-1``:
        at least one prompt position must remain to prefill, because the
        first generated token comes from the final position's logits — a
        fully cached prompt still recomputes its last partial/full chunk.
        Counts one hit (+ reused tokens) or one miss, and refreshes the
        donor's LRU stamp.
        """
        matched, donor = self._lookup(prompt)
        if donor is None:
            _MISSES.inc()
            return 0, None
        self._touch(donor)
        _HITS.inc()
        _TOKENS_REUSED.inc(matched)
        return matched, donor

    def peek_donor(self, prompt) -> Optional[int]:
        """The slot :meth:`match` would reuse for ``prompt``, with no
        counter or LRU side effects — the engine protects it from being
        its own admission's eviction victim."""
        return self._lookup(prompt)[1]

    def covered(self, prompt) -> Optional[int]:
        """If the trie already caches ``prompt``'s full-chunk prefix at
        maximal depth, return a slot holding it (parking another copy would
        waste a slot); else None."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        k = prompt.size // self.chunk
        if k < 1:
            return None
        node = self._root
        for key in self._chunks(prompt, k):
            node = node.children.get(key)
            if node is None:
                return None
        if not node.slots:
            return None
        return max(node.slots, key=lambda s: self._resident[s][1])

    # -- residency --------------------------------------------------------
    def park(self, pool, slot: int, prompt) -> bool:
        """Try to keep a retiring request's slot resident as a donor.

        Returns True when the slot was parked (caller must NOT free it);
        False when caching is useless — prompt shorter than one chunk, or
        its full-chunk prefix is already cached (the existing donor's LRU
        stamp is refreshed instead) — and the caller should free the slot.
        """
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        k = prompt.size // self.chunk
        if k < 1:
            return False
        existing = self.covered(prompt)
        if existing is not None:
            self._touch(existing)
            return False
        node = self._root
        for key in self._chunks(prompt, k):
            node = node.children.setdefault(key, _Node())
            node.slots.add(slot)
        self._seq += 1
        self._resident[slot] = (k, self._seq)
        pool.park(slot)
        _RESIDENT.set(len(self._resident))
        return True

    def _remove(self, slot: int) -> None:
        """Drop every trie reference to ``slot`` (prune empty branches)."""
        del self._resident[slot]

        def prune(node: _Node) -> None:
            dead = []
            for key, child in node.children.items():
                child.slots.discard(slot)
                prune(child)
                if not child.slots and not child.children:
                    dead.append(key)
            for key in dead:
                del node.children[key]

        prune(self._root)
        _RESIDENT.set(len(self._resident))

    def evict_lru(self, pool, protect: Optional[int] = None) -> Optional[int]:
        """Reclaim the least-recently-used parked slot for admission: the
        slot returns to the pool's free list and every trie entry for it is
        dropped. Only parked slots are candidates (live requests are never
        resident), so a pinned/live slot can never be freed here.
        ``protect`` exempts one slot — the donor the admission triggering
        this eviction is about to match (evicting it would trade the hit
        for the slot). Returns the evicted slot id, or None when no
        candidate remains."""
        candidates = [s for s in self._resident if s != protect]
        if not candidates:
            return None
        slot = min(candidates, key=lambda s: self._resident[s][1])
        self._remove(slot)
        pool.reclaim(slot)
        _EVICTIONS.inc()
        return slot

    def clear(self, pool) -> None:
        """Reclaim every parked slot and empty the trie (e.g. after compile
        warmup, whose synthetic prompts must not act as donors). Counters
        are untouched — benches isolate arms by delta."""
        for slot in list(self._resident):
            self._remove(slot)
            pool.reclaim(slot)
        self._root = _Node()
        _RESIDENT.set(0)
