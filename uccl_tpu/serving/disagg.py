"""Disaggregated prefill/decode serving: chunk-streamed KV handoff over p2p.

The P2P pillar's reason to exist (PAPER.md §0.2: a NIXL-style
initiator-target KV-cache transfer engine), promoted from the one-shot
``examples/disagg_kv.py`` proof into a serving architecture: a
**PrefillWorker** runs a chunked-prefill ``ServingEngine`` and, as each
C-token chunk of a prompt lands in its KV slot, one-sided-writes that
``[off, off+C)`` KV slab into the decode worker's advertised slot pool via
``Endpoint.writev_async`` — transfer of chunk *i* overlaps prefill compute
of chunk *i+1*, so when the last chunk's logits produce the first token,
only ONE chunk (plus the control notif) remains in flight. The
**DecodeWorker** reserved its slot when the stream opened (BEGIN→GRANT),
imports the streamed rows, and ``adopt()``s the request into its own
engine: TTFT is bounded by prefill + one chunk's transfer, not prefill +
whole-cache transfer. Add the prefill side's prefix-reuse cache
(``serving/prefix_cache.py``) and shared system prompts are computed once:
a hit resumes at ``prefill_pos = matched_len`` — still shipping every
chunk (the decode side needs all rows), but skipping their compute.

Exactness: KV slabs cross the wire as raw float32 rows, the first token is
computed by the (oracle-exact, tested) prefill engine, and the decode
engine continues through the same masked decode primitive — so the
disaggregated output is bit-identical to one-shot ``generate``, cold or
cache-hit, on both stacks (tests/test_prefix_cache.py,
tests/test_disagg_kv.py).

Wire format (docs/SERVING.md): the decode side advertises its ENTIRE host
KV mirror (one FifoItem for K, one for V, exchanged in HELLO); the prefill
side derives per-(layer, chunk) windows by descriptor slicing
(``FifoItem.slice``), so the steady-state control plane is three small
JSON notifs per request — BEGIN (prompt + timing), GRANT (slot), FINAL
(length + first token + timing) — and ALL KV bytes move one-sided.

Control-plane timestamps are wall-clock (``time.time()``): the TTFT split
(queue / prefill / transfer) spans two processes, where the engines'
monotonic clocks share no epoch.

Distributed tracing (docs/OBSERVABILITY.md): every submitted request's
:class:`~uccl_tpu.obs.TraceContext` rides the BEGIN notif verbatim, the
decode side stamps it onto its GRANT/adopt/import events, and a
Chrome-trace flow pair (``s`` inside the first ``kv_stream.tx`` span,
``f`` inside ``kv_stream.import``, ids derived from the trace_id) binds
the two processes' spans into one Perfetto arrow once
``scripts/trace_merge.py`` merges the per-role dumps. The HELLO handshake
is followed by a notif-borne clock exchange (``clock_ping`` →
``clock_pong`` → ``clock_sync``): the prefill side estimates the wall
offset to its decode peer by the RTT midpoint
(:func:`uccl_tpu.obs.estimate_clock_offset`) and hands the decode process
its offset from the reference (prefill) clock, which lands in that
process's trace metadata for merge-time alignment.
"""

from __future__ import annotations

import base64
import json
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from uccl_tpu import obs
from uccl_tpu.serving.engine import ChunkEvent, ServingEngine
from uccl_tpu.serving.health import DEAD as _PEER_DEAD
from uccl_tpu.serving.request import Request, now

KV_DTYPE = np.float32

_STREAM_CHUNKS = obs.counter(
    "kv_stream_chunks_total",
    "KV slabs streamed between prefill and decode workers (role=tx|rx)",
)
_STREAM_REQS = obs.counter(
    "kv_stream_requests_total",
    "requests whose KV crossed the disagg stream (role=tx|rx)",
)
_LEASES_EXPIRED = obs.counter(
    "disagg_leases_expired_total",
    "GRANT leases reclaimed on the decode side: the reserved slot's KV "
    "never completed before expiry (reason=timeout) or its prefill peer "
    "was declared dead (reason=peer_dead) — the slot returns to the "
    "pool instead of leaking forever",
)
_STALE_FINALS = obs.counter(
    "disagg_stale_finals_total",
    "FINALs arriving for a stream whose lease already expired — dropped "
    "(the slot was reclaimed; importing would corrupt its new occupant)",
)
_CTRL_RETRIES = obs.counter(
    "disagg_ctrl_retries_total",
    "control-plane retransmissions by message (msg=begin: no GRANT "
    "within the retry window; msg=grant: a duplicate BEGIN re-answered "
    "idempotently; msg=final: no FINAL-ack within the window)",
)
_CTRL_DROPPED = obs.counter(
    "disagg_ctrl_dropped_total",
    "control notifs dropped by the Python-level chaos injector "
    "(set_ctrl_drop) — the notif plane's fault-injection face",
)
_DRAIN_TIMEOUTS = obs.counter(
    "disagg_drain_timeouts_total",
    "drain/serve deadlines that expired with work outstanding, by role "
    "— the structured-timeout counter (the raise names the stuck "
    "rids/conns)",
)

# -- control-plane fault injection ------------------------------------------
# The native injector (Endpoint.set_drop_rate / set_conn_fault) faults the
# one-sided DATA plane only — notifs ride the reliable control path by
# design (p2p/endpoint.py). Chaos runs that want control-plane loss
# (dropped GRANTs, lost FINALs) inject it HERE, at the send site, with a
# seeded RNG so runs reproduce. HELLO/clock/bye are exempt: they are
# handshake/teardown, not the retried steady-state plane under test.
_CTRL_DROP: Dict[str, object] = {"rate": 0.0, "rng": None}
_DROPPABLE = ("begin", "grant", "final", "final_ack", "hb")


def set_ctrl_drop(rate: float, seed: int = 0) -> None:
    """Drop each outgoing steady-state control notif (BEGIN/GRANT/FINAL/
    final-ack/heartbeat) with probability ``rate``, process-wide —
    counted on ``disagg_ctrl_dropped_total{msg}``. 0 disables."""
    import random

    _CTRL_DROP["rate"] = float(rate)
    _CTRL_DROP["rng"] = random.Random(seed)


# Flight-recorder arming for the notif plane: past ``storm_after``
# process-wide control retransmissions, ONE ``ctrl_storm`` bundle fires
# (docs/OBSERVABILITY.md) — a retry or two is the idempotent plane doing
# its job; a storm means the plane is lossy or the peer unresponsive.
_CTRL_FLIGHT: Dict[str, object] = {"storm_after": None, "fired": False,
                                   "retries": 0}


def arm_ctrl_flight(storm_after: Optional[int] = None) -> None:
    _CTRL_FLIGHT["storm_after"] = storm_after
    _CTRL_FLIGHT["fired"] = False
    _CTRL_FLIGHT["retries"] = 0


def _note_ctrl_retry(msg: str) -> None:
    _CTRL_RETRIES.inc(msg=msg)
    _CTRL_FLIGHT["retries"] += 1
    storm = _CTRL_FLIGHT["storm_after"]
    if (storm is not None and not _CTRL_FLIGHT["fired"]
            and _CTRL_FLIGHT["retries"] >= storm):
        _CTRL_FLIGHT["fired"] = True
        obs.flight_trigger("ctrl_storm", key="disagg:ctrl",
                           retries=_CTRL_FLIGHT["retries"],
                           storm_after=storm, last_msg=msg)


# -- wire format ------------------------------------------------------------
@dataclass(frozen=True)
class KVWireFormat:
    """Byte layout of a decode worker's host KV mirror — the contract both
    ends slice against. The mirror is the CANONICAL dense slot layout
    ``[L, n_slots, S_max, Hkv, D]`` float32 regardless of model stack (the
    MoE cache maps its [W, B_loc] grid to flat slot ids at import), so
    prefill and decode stacks only need matching model dims, not matching
    cache layouts. Pure host math — numpy-only, unit-tested without jax."""

    n_layers: int
    n_slots: int
    max_seq: int
    n_kv_heads: int
    head_dim: int
    itemsize: int = 4

    @property
    def row_bytes(self) -> int:
        return self.n_kv_heads * self.head_dim * self.itemsize

    def pool_shape(self) -> Tuple[int, ...]:
        return (self.n_layers, self.n_slots, self.max_seq,
                self.n_kv_heads, self.head_dim)

    def pool_nbytes(self) -> int:
        n = 1
        for d in self.pool_shape():
            n *= d
        return n * self.itemsize

    def spans(self, slot: int, lo: int, hi: int) -> List[Tuple[int, int]]:
        """Per-layer ``(offset_bytes, length_bytes)`` of rows [lo, hi) of
        ``slot`` inside one pool array (K or V — same layout)."""
        if not (0 <= lo < hi <= self.max_seq):
            raise ValueError(f"rows [{lo}, {hi}) outside [0, {self.max_seq})")
        if not (0 <= slot < self.n_slots):
            raise ValueError(f"slot {slot} outside pool of {self.n_slots}")
        out = []
        for layer in range(self.n_layers):
            base = ((layer * self.n_slots + slot) * self.max_seq + lo)
            out.append((base * self.row_bytes, (hi - lo) * self.row_bytes))
        return out

    def to_meta(self) -> Dict:
        return {
            "n_layers": self.n_layers, "n_slots": self.n_slots,
            "max_seq": self.max_seq, "n_kv_heads": self.n_kv_heads,
            "head_dim": self.head_dim, "itemsize": self.itemsize,
        }

    @staticmethod
    def from_meta(meta: Dict) -> "KVWireFormat":
        return KVWireFormat(**{k: int(v) for k, v in meta.items()})


def _model_dims(backend) -> Dict[str, int]:
    """(n_layers, n_kv_heads, head_dim) of a serving backend — DenseBackend
    carries its config, MoEBackend's lives on its server."""
    cfg = getattr(backend, "cfg", None)
    if cfg is None:
        cfg = backend.server.cfg
    return {"n_layers": cfg.n_layers, "n_kv_heads": cfg.n_kv_heads,
            "head_dim": cfg.head_dim}


def wire_format_for(backend) -> KVWireFormat:
    """The wire format describing ``backend``'s slot pool as a mirror."""
    return KVWireFormat(n_slots=backend.n_slots, max_seq=backend.max_seq,
                        itemsize=np.dtype(KV_DTYPE).itemsize,
                        **_model_dims(backend))


# -- control plane ----------------------------------------------------------
def _send_msg(ep, conn: int, msg: Dict) -> None:
    rate = _CTRL_DROP["rate"]
    if rate and msg.get("t") in _DROPPABLE \
            and _CTRL_DROP["rng"].random() < rate:
        _CTRL_DROPPED.inc(msg=str(msg.get("t")))
        return
    ep.send_notif(conn, json.dumps(msg).encode())


def _drain_msgs(ep) -> List[Tuple[int, Dict]]:
    return [(conn, json.loads(raw.decode()))
            for conn, raw in ep.get_notifs()]


def _b64(raw: bytes) -> str:
    return base64.b64encode(raw).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s.encode())


# -- prefill side -----------------------------------------------------------
@dataclass
class _TxStream:
    """Prefill-side per-request stream state."""

    rid: int
    prompt: np.ndarray
    max_new_tokens: int
    eos_id: Optional[int]
    t_submit_wall: float
    trace: Optional["obs.TraceContext"] = None  # rides BEGIN verbatim
    begin_msg: Optional[Dict] = None  # resent verbatim until GRANTed
    t_begin_sent: float = 0.0  # monotonic mark of the last BEGIN tx
    t_admit_wall: Optional[float] = None
    t_done_wall: Optional[float] = None
    slabs: List[Tuple[int, int, np.ndarray, np.ndarray]] = field(
        default_factory=list)  # (lo, hi, k, v) exported, awaiting ship
    remote_slot: Optional[int] = None  # GRANTed decode-side slot
    xids: List[int] = field(default_factory=list)
    n_shipped: int = 0
    flow_emitted: bool = False  # the one flow-start per request went out
    first_token: Optional[int] = None
    done: bool = False  # prefill finished (first token known)
    cache_hit_len: int = 0  # rows reused from the prefix cache


class _ChunkFanout:
    """One prefill engine's chunk sink shared by several
    :class:`PrefillWorker` bonds — the N×M plane (ISSUE 19): each bond
    streams to a DIFFERENT decode worker over its own conn. Every bond
    sees every event and picks up only the rids it opened (``_on_chunks``
    drops unknown rids; a rid is submitted through exactly one bond), so
    no slab is ever exported or shipped twice."""

    def __init__(self):
        self.sinks: List = []

    def __call__(self, events) -> None:
        for s in self.sinks:
            s(events)


class PrefillWorker:
    """The prefill-fleet role: a chunked-prefill ``ServingEngine`` whose
    per-chunk KV output streams to one decode worker as it is computed.

    The engine must run ``prefill_chunk=C`` (the streaming granularity) and
    may carry a ``PrefixCache`` — cache-hit slabs ship without having been
    recomputed. Submissions go through :meth:`submit` (which opens the
    stream); drive the loop with :meth:`step` until :meth:`idle`.
    """

    def __init__(self, engine: ServingEngine, ep, ip: str, port: int,
                 *, timeout_ms: int = 30000,
                 heartbeat_s: Optional[float] = 0.5,
                 ctrl_retry_s: float = 0.5):
        _init_prefill_worker(self, engine, ep, ep.connect(ip, port),
                             timeout_ms=timeout_ms,
                             heartbeat_s=heartbeat_s,
                             ctrl_retry_s=ctrl_retry_s)

    # -- submission ----------------------------------------------------
    def submit(self, prompt, *, max_new_tokens: int = 16,
               eos_id: Optional[int] = None,
               priority: str = "interactive",
               tenant: str = "default",
               trace=None) -> Optional[Request]:
        """Open a KV stream and queue the prompt on the prefill engine
        (``max_new_tokens=1`` locally — this fleet never decodes; the
        requested budget rides the BEGIN message to the decode side).
        ``priority`` orders this fleet's own prefill queue (when its
        engine runs priority classes) and rides BEGIN so the adopted
        request keeps its class label decode-side. ``tenant`` rides the
        same way: it namespaces this fleet's prefix cache AND labels the
        decode side's adoption, so fleet-merged per-tenant series stay
        truthful across the process split. ``trace`` carries a
        router-minted :class:`~uccl_tpu.obs.TraceContext` (None mints one
        here); it rides BEGIN verbatim so the decode side's spans join the
        same fleet-wide timeline. Returns the local Request, or None on
        queue backpressure."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        ctx = trace if trace is not None else obs.new_context()
        req = self.engine.submit(prompt, max_new_tokens=1,
                                 priority=priority, tenant=tenant,
                                 trace=ctx)
        if req is None:
            return None
        st = _TxStream(req.rid, prompt, max_new_tokens, eos_id,
                       t_submit_wall=time.time(), trace=ctx)
        self._streams[req.rid] = st
        st.begin_msg = {
            "t": "begin", "rid": req.rid, "prompt": prompt.tolist(),
            "max_new_tokens": max_new_tokens, "eos_id": eos_id,
            "priority": priority, "tenant": tenant,
            "t_submit": st.t_submit_wall,
            "trace": ctx.to_wire(),
        }
        st.t_begin_sent = time.monotonic()
        _send_msg(self.ep, self.conn, st.begin_msg)
        return req

    # -- engine hook ---------------------------------------------------
    def _on_chunks(self, events: List[ChunkEvent]) -> None:
        """Export every newly valid KV slab to host NOW (the slot may be
        freed/parked at this step's retirement) and queue it for the wire;
        cache-hit copies (``reused=True``) ship exactly like computed
        chunks — the decode side needs all rows either way."""
        for ev in events:
            st = self._streams.get(ev.req.rid)
            if st is None:
                continue  # warmup / non-streamed submission
            if st.t_admit_wall is None and ev.req.t_admit is not None:
                # back-date to the engine's admission mark (the first
                # event arrives AFTER the first chunk's compute — stamping
                # now() would misfile that compute under queue time)
                st.t_admit_wall = time.time() - max(
                    0.0, now() - ev.req.t_admit
                )
            with obs.span("kv_stream.export", track="wire", slot=ev.slot,
                          lo=ev.lo, hi=ev.hi, reused=ev.reused):
                k, v = self.engine.backend.export_slot_kv(
                    ev.slot, ev.lo, ev.hi
                )
            st.slabs.append((ev.lo, ev.hi, k, v))
            if ev.reused:
                st.cache_hit_len = max(st.cache_hit_len, ev.hi)
            if ev.done:
                st.done = True
                st.first_token = ev.first_token
                st.t_done_wall = time.time()

    # -- the pump ------------------------------------------------------
    def _ship(self, st: _TxStream) -> None:
        fifos_k, fifos_v = self._fifo_k, self._fifo_v
        for lo, hi, k, v in st.slabs:
            spans = self.fmt.spans(st.remote_slot, lo, hi)
            srcs = ([np.ascontiguousarray(k[layer])
                     for layer in range(self.fmt.n_layers)]
                    + [np.ascontiguousarray(v[layer])
                       for layer in range(self.fmt.n_layers)])
            fifos = ([fifos_k.slice(off, ln).pack() for off, ln in spans]
                     + [fifos_v.slice(off, ln).pack() for off, ln in spans])
            tr = obs.get_tracer()
            t0 = tr.now_us() if tr is not None else 0.0
            if self.chan is not None:
                # windowed SACK transport: the whole slab batch is ONE
                # selective-repeat transfer (loss recovered inside, pull
                # credit gates issue) — delivered when this returns, so
                # FINAL needs no per-xid waits for these slabs
                self.chan.writev(srcs, fifos, timeout_ms=self._timeout_ms)
            else:
                st.xids.extend(
                    self.ep.writev_async(self.conn, srcs, fifos)
                )
            if tr is not None:
                dur = tr.now_us() - t0
                tr.complete("kv_stream.tx", t0, dur, "wire", rid=st.rid,
                            slot=st.remote_slot, lo=lo, hi=hi,
                            bytes=sum(s.nbytes for s in srcs),
                            trace_id=(st.trace.trace_id
                                      if st.trace else None))
                if st.trace is not None and not st.flow_emitted:
                    # ONE flow-start per request, timestamped INSIDE the
                    # first tx span so Perfetto binds the arrow to it; the
                    # decode side's matching flow-finish sits inside its
                    # kv_stream.import span (same derived id, no extra
                    # coordination — the id IS the trace_id)
                    tr.flow("kv_handoff", "s",
                            obs.flow_id(st.trace.trace_id), "wire",
                            ts_us=t0 + dur / 2.0)
                    st.flow_emitted = True
            st.n_shipped += 1
            _STREAM_CHUNKS.inc(role="tx")
        st.slabs.clear()

    def adoption_backpressure(self) -> int:
        """Requests stuck waiting for decode-side capacity, as this worker
        can best estimate it: streams whose BEGIN has no GRANT yet (local,
        always current) vs the decode peer's own reported pending depth as
        of the last GRANT (covers OTHER prefill workers sharing the peer
        under fan-in) — the larger of the two, since each is a lower bound
        on the same backlog. 0 means the peer grants as fast as we BEGIN —
        the router's steering signal (uccl_tpu/serving/router.py)."""
        ungranted = sum(1 for st in self._streams.values()
                        if st.remote_slot is None)
        hinted = (self.decode_hint["queued"]
                  if self.decode_hint is not None else 0)
        return max(ungranted, hinted)

    def pump(self) -> None:
        """Drain GRANTs/acks, retry unanswered control messages, ship
        queued slabs, close finished streams (wait for every slab's
        completion, then send FINAL — writes and notifs share the conn,
        so the decode side sees all rows before FINAL).

        The control plane is LOSS-TOLERANT (docs/SERVING.md): a BEGIN
        with no GRANT inside ``ctrl_retry_s`` is resent verbatim (the
        decode side's rid-keyed dedup makes the retry idempotent — a
        lost GRANT never double-reserves), and a FINAL waits for an
        explicit ``final_ack`` and is resent until it lands (the decode
        side re-acks an already-adopted rid without re-adopting). Both
        retries count on ``disagg_ctrl_retries_total{msg}``."""
        now_m = time.monotonic()
        for _, msg in _drain_msgs(self.ep):
            if msg.get("t") == "grant":
                st = self._streams.get(msg["rid"])
                if st is not None:
                    st.remote_slot = int(msg["slot"])
                if "free" in msg:
                    self.decode_hint = {"free": int(msg["free"]),
                                        "queued": int(msg["queued"])}
            elif msg.get("t") == "final_ack":
                self._finaled.pop(int(msg["rid"]), None)
            elif msg.get("t") == "clock_pong":
                self._on_clock_pong(msg)
        if self.heartbeat_s is not None \
                and now_m - self._last_hb > self.heartbeat_s:
            self._last_hb = now_m
            _send_msg(self.ep, self.conn, {"t": "hb"})
        for st in self._streams.values():
            if (st.remote_slot is None
                    and now_m - st.t_begin_sent > self._ctrl_retry_s):
                # GRANT (or the BEGIN itself) lost: resend, idempotent
                st.t_begin_sent = now_m
                _note_ctrl_retry("begin")
                _send_msg(self.ep, self.conn, st.begin_msg)
            if st.remote_slot is not None and st.slabs:
                self._ship(st)
        for rid, st in list(self._streams.items()):
            if not (st.done and st.remote_slot is not None
                    and not st.slabs):
                continue
            for xid in st.xids:
                if not self.ep.wait(xid, self._timeout_ms):
                    obs.counter("p2p_transfer_failures_total").inc(
                        reason="kv_slab")
                    obs.instant("p2p_transfer_failed", track="wire",
                                reason="kv_slab", rid=rid)
                    raise IOError(
                        f"kv stream rid={rid}: slab write undelivered"
                    )
            final = {
                "t": "final", "rid": rid,
                "length": int(st.prompt.size),
                "first_token": int(st.first_token),
                "chunks": st.n_shipped,
                "cache_hit_len": st.cache_hit_len,
                "t_submit": st.t_submit_wall,
                "t_admit": st.t_admit_wall,
                "t_done": st.t_done_wall,
            }
            _send_msg(self.ep, self.conn, final)
            _STREAM_REQS.inc(role="tx")
            # await the decode side's final_ack; resent until it lands
            self._finaled[rid] = {"msg": final, "t_sent": now_m}
            del self._streams[rid]
        for rid, ent in self._finaled.items():
            if now_m - ent["t_sent"] > self._ctrl_retry_s:
                ent["t_sent"] = now_m
                _note_ctrl_retry("final")
                _send_msg(self.ep, self.conn, ent["msg"])

    def _send_clock_ping(self) -> None:
        self._clock_pings_left -= 1
        _send_msg(self.ep, self.conn, {
            "t": "clock_ping", "t0": time.time(),
            "mono_us": time.perf_counter() * 1e6,
        })

    def _on_clock_pong(self, msg: Dict) -> None:
        """Second leg of the HELLO clock exchange: the pong carries our
        ping's send time (t0) plus the peer's receive/send wall marks
        (t1/t2); with our receive time (t3) the RTT midpoint estimates the
        peer's wall-clock offset (obs/context.py). One round is not
        enough: the first ping can sit in the peer's notif queue across
        its compile warmup, inflating the RTT and (with it) the offset
        error bound of rtt/2 — so the exchange repeats a few rounds and
        keeps the MINIMUM-RTT estimate (the classic NTP clock filter).
        Each improvement goes BACK to the peer as ``clock_sync`` so the
        DECODE process records its own offset from the reference
        (prefill) clock in its trace metadata — scripts/trace_merge.py
        aligns on exactly that field."""
        t3 = time.time()
        offset_s, rtt_s = obs.estimate_clock_offset(
            float(msg["t0"]), float(msg["t1"]), float(msg["t2"]), t3
        )
        if self.clock_rtt_s is None or rtt_s < self.clock_rtt_s:
            self.clock_offset_s = offset_s
            self.clock_rtt_s = rtt_s
            # the reference process's own offset is 0 by definition;
            # record the measurement's provenance in this side's trace
            # metadata too
            obs.set_clock_offset(0.0, rtt_us=round(rtt_s * 1e6, 3),
                                 peer="decode", role="reference")
            _send_msg(self.ep, self.conn, {
                "t": "clock_sync",
                "offset_us": offset_s * 1e6,
                "rtt_us": rtt_s * 1e6,
            })
        if self._clock_pings_left > 0:
            self._send_clock_ping()

    def step(self) -> None:
        """One loop iteration: advance the engine (chunks export through
        the sink) then pump the wire."""
        if self.engine.has_work():
            self.engine.step()
        self.pump()

    def idle(self) -> bool:
        return (not self.engine.has_work() and not self._streams
                and not self._finaled)

    def outstanding(self) -> Dict[str, List[int]]:
        """What this worker is still waiting on, by kind — the structured
        face of a stuck drain (``ungranted`` BEGINs with no GRANT,
        ``granted`` streams mid-ship, ``unacked_final`` FINALs with no
        ack): a timeout names these instead of raising context-free."""
        return {
            "ungranted": sorted(rid for rid, st in self._streams.items()
                                if st.remote_slot is None),
            "granted": sorted(rid for rid, st in self._streams.items()
                              if st.remote_slot is not None),
            "unacked_final": sorted(self._finaled),
        }

    def drain(self, timeout_s: float = 120.0) -> None:
        deadline = time.monotonic() + timeout_s
        while not self.idle():
            if time.monotonic() > deadline:
                _DRAIN_TIMEOUTS.inc(role="prefill")
                out = self.outstanding()
                obs.instant("drain_timeout", track="wire", role="prefill",
                            **{k: len(v) for k, v in out.items()})
                raise TimeoutError(
                    f"prefill drain stalled after {timeout_s}s: "
                    f"ungranted BEGINs rid={out['ungranted']}, "
                    f"granted streams mid-ship rid={out['granted']}, "
                    f"unacked FINALs rid={out['unacked_final']}, "
                    f"engine queued={self.engine.sched.qsize} "
                    f"active={len(self.engine._by_slot)}"
                )
            self.step()
            if not self.engine.has_work():
                time.sleep(0.001)  # waiting on grants/completions only

    def close(self) -> None:
        _send_msg(self.ep, self.conn, {"t": "bye"})


# -- decode side ------------------------------------------------------------
class DecodeWorker:
    """The decode-fleet role: a ``ServingEngine`` whose requests arrive as
    KV streams. BEGIN reserves a slot (deferred under a full pool — the
    GRANT is the admission backpressure), streamed slabs land one-sided in
    the registered host mirror, FINAL imports rows [0, plen) into the
    engine's device cache and ``adopt()``s the request.

    **Lease-guarded grants** (docs/SERVING.md): with ``grant_lease_s``
    a GRANT is a *lease*, not a gift — if the stream's FINAL does not
    land before expiry (the prefill peer died post-GRANT, or its FINAL
    is lost forever), the reserved slot is reclaimed into the pool,
    counted on ``disagg_leases_expired_total{reason}``, and a late
    FINAL for the expired stream is dropped (``disagg_stale_finals_
    total``) instead of importing into the slot's new occupant. BEGINs
    are **idempotent** by (conn, rid): a retried BEGIN whose GRANT was
    lost re-answers with the SAME slot (counted ``disagg_ctrl_retries_
    total{msg="grant"}``) and never double-reserves; a retried FINAL
    after adoption re-acks without re-adopting. ``detector`` plugs a
    :class:`~uccl_tpu.serving.health.FailureDetector` under the conn
    set — every control notif counts as a heartbeat (plus explicit hb
    messages from a ``heartbeat_s`` prefill worker), and a conn going
    DEAD expires its leases immediately (reason="peer_dead").
    """

    def __init__(self, engine: ServingEngine, ep,
                 pull_rate_bps: Optional[float] = None,
                 grant_lease_s: Optional[float] = None,
                 detector=None):
        self.engine = engine
        self.ep = ep
        self.grant_lease_s = grant_lease_s
        self.detector = detector
        self._pending_keys: set = set()  # (conn, rid) of queued BEGINs
        # settled-stream dedup windows, insertion-ordered and BOUNDED: a
        # retried BEGIN/FINAL only arrives within the sender's retry
        # horizon, so a long-lived decode worker must not accumulate one
        # key per request forever — past the cap the oldest settles for
        # good (a duplicate for an evicted key would raise as unknown,
        # which by then is the right answer)
        self._adopted_keys: Dict[Tuple[int, int], None] = {}
        self._expired_leases: Dict[Tuple[int, int], None] = {}
        self._settled_cap = 4096
        self.fmt = wire_format_for(engine.backend)
        self.mirror_k = np.zeros(self.fmt.pool_shape(), KV_DTYPE)
        self.mirror_v = np.zeros(self.fmt.pool_shape(), KV_DTYPE)
        self._mr_k = ep.reg(self.mirror_k)
        self._mr_v = ep.reg(self.mirror_v)
        # EQDS receiver-driven credit at disagg fan-in (docs/EQDS.md): the
        # GRANT already bounds concurrent inbound streams (slot admission
        # — "half of EQDS"); pull_rate_bps adds the other half, a
        # PullPacer granting byte credit across ALL attached inbound
        # channels at this decode worker's known drain rate, so N prefill
        # workers cannot burst past the fan-in link. Only active for
        # prefill workers attached over the channel transport with
        # pull=True (add_local_prefill).
        self.channels: List[object] = []
        self._pacer = None
        if pull_rate_bps:
            from uccl_tpu.p2p.eqds import PullPacer

            self._pacer = PullPacer(pull_rate_bps)
        self._pending: Deque[Tuple[int, Dict]] = deque()
        self._granted: Dict[Tuple[int, int], Dict] = {}  # (conn, rid) -> st
        self._finished: List[Request] = []
        self.origin: Dict[int, Tuple[int, int]] = {}  # local rid -> (conn, remote rid)
        # closed = EVERY attached prefill conn said BYE (per-conn counting:
        # under N-to-1 fan-in one worker closing must not strand the rest)
        self.closed = False
        self._n_conns = 0
        self._n_byes = 0
        # this process's wall offset from the reference (prefill) clock,
        # as estimated by the peer's clock exchange (None until synced;
        # under fan-in the last sync wins — all peers measure the same
        # two clocks)
        self.clock_offset_us: Optional[float] = None
        self.clock_rtt_us: Optional[float] = None

    @property
    def port(self) -> int:
        return self.ep.port

    def attach(self, timeout_ms: int = 30000) -> int:
        """Accept one prefill worker and hand it the pool descriptors."""
        conn = self.ep.accept(timeout_ms=timeout_ms)
        return self._finish_attach(conn)

    def attach_channel(self, timeout_ms: int = 30000,
                       chunk_bytes: Optional[int] = None):
        """Accept one prefill worker dialing over a multipath
        :class:`~uccl_tpu.p2p.channel.Channel` (the windowed SACK
        transport): KV slabs arrive as windowed chunk sprays instead of
        raw writev, control notifs ride the channel's path-0 conn, and —
        when this worker was built with ``pull_rate_bps`` — the channel
        attaches to the receiver-driven credit pacer, making the decode
        side the incast actuator. Returns the server-side Channel."""
        from uccl_tpu.p2p.channel import Channel

        chan = Channel.accept(self.ep, timeout_ms=timeout_ms,
                              chunk_bytes=chunk_bytes)
        self.channels.append(chan)
        if self._pacer is not None:
            self._pacer.attach(chan)
            self._pacer.start()
        self._finish_attach(chan.conns[0])
        return chan

    def _finish_attach(self, conn: int) -> int:
        self._n_conns += 1
        # a conn attaching AFTER earlier conns all said BYE re-opens the
        # decoder (sequential fan-in must not inherit a stale closed flag)
        self.closed = self._n_byes >= self._n_conns
        if self.detector is not None:
            self.detector.register(conn)
        self.ep.send(conn, json.dumps({
            "t": "hello", "fmt": self.fmt.to_meta(),
            "k_fifo": _b64(self.ep.advertise(self._mr_k)),
            "v_fifo": _b64(self.ep.advertise(self._mr_v)),
        }).encode())
        return conn

    def close(self) -> None:
        """Stop the credit pacer (with a final flush so in-flight senders
        finish) and close attached channels (their conns + probe/credit
        registrations on this worker's endpoint). The endpoint itself
        stays open — it was handed in by the caller, who owns it."""
        if self._pacer is not None:
            self._pacer.stop(flush_bytes=self.fmt.pool_nbytes())
            self._pacer = None
        for chan in self.channels:
            try:
                chan.close()
            except Exception:
                pass  # peer already gone
        self.channels = []

    def _settle(self, window: Dict, key: Tuple[int, int]) -> None:
        window[key] = None
        while len(window) > self._settled_cap:
            window.pop(next(iter(window)))

    # -- control-plane handling ----------------------------------------
    def poll(self) -> None:
        for conn, msg in _drain_msgs(self.ep):
            kind = msg.get("t")
            if self.detector is not None:
                # ANY control traffic proves the peer alive; hb messages
                # exist so an idle peer still proves it
                self.detector.heartbeat(conn)
            if kind == "hb":
                continue
            if kind == "begin":
                key = (conn, int(msg["rid"]))
                granted = self._granted.get(key)
                if granted is not None:
                    # retried BEGIN whose GRANT was lost: idempotent —
                    # re-answer with the SAME slot, never re-reserve.
                    # Contact also RENEWS the lease (and lifts any
                    # quarantine): the retry proves the sender never had
                    # a grant, so nothing was ever shipped at this slot
                    # — the lease clock restarts from a real exchange,
                    # not from the first (lost) GRANT
                    granted["t_grant"] = time.monotonic()
                    granted.pop("expired", None)
                    _note_ctrl_retry("grant")
                    _send_msg(self.ep, conn, {
                        "t": "grant", "rid": key[1],
                        "slot": granted["slot"],
                        "free": self.engine.pool.n_free,
                        "queued": len(self._pending),
                    })
                    continue
                if key in self._expired_leases:
                    # the old incarnation was reclaimed, yet the sender
                    # is STILL asking to begin — it never held a grant
                    # (it only retries while ungranted), so nothing of
                    # the old stream was ever shipped: treat it as a
                    # fresh stream instead of wedging the retry loop
                    self._expired_leases.pop(key, None)
                if (key in self._pending_keys
                        or key in self._adopted_keys):
                    continue  # duplicate of a queued/settled stream
                self._pending_keys.add(key)
                self._pending.append((conn, msg))
            elif kind == "final":
                self._on_final(conn, msg)
            elif kind == "clock_ping":
                # timestamp on arrival AND on reply: the gap between the
                # two is the peer-side processing time the RTT-midpoint
                # formula subtracts out
                t1 = time.time()
                _send_msg(self.ep, conn, {
                    "t": "clock_pong", "t0": msg["t0"], "t1": t1,
                    "t2": time.time(),
                    "mono_us": time.perf_counter() * 1e6,
                    "wall_us": t1 * 1e6,
                })
            elif kind == "clock_sync":
                self.clock_offset_us = float(msg["offset_us"])
                self.clock_rtt_us = float(msg["rtt_us"])
                obs.set_clock_offset(self.clock_offset_us,
                                     rtt_us=round(self.clock_rtt_us, 3),
                                     peer="prefill", role="synced")
            elif kind == "bye":
                self._n_byes += 1
                self.closed = self._n_byes >= self._n_conns
        if self.detector is not None:
            self.detector.tick()
        self._expire_leases()
        self._try_grant()

    def _expire_leases(self) -> None:
        """Reclaim GRANTed slots whose stream never FINALed: past the
        lease (reason=timeout), or the moment the granting conn's peer
        is declared DEAD by the failure detector (reason=peer_dead).
        The reclaimed slot returns to the pool — the decode side never
        leaks capacity to a dead prefill worker — and the stream key is
        remembered so a late FINAL is dropped, not imported.

        One hazard needs care: a peer that is provably ALIVE (still
        heartbeating) but stalled mid-ship may still be one-sided-
        writing slabs into the slot's mirror rows — freeing the slot now
        would hand those rows to a new occupant mid-write. So with a
        detector attached, a timed-out lease on a live conn is
        **quarantined** instead: the expiry is counted (the lease DID
        lapse) but the slot stays reserved until the stream terminates
        (its FINAL arrives and is dropped as stale), the peer dies, or a
        retried BEGIN renews the lease (nothing was ever shipped — the
        poll handler's renewal path). Without a detector the decode side
        cannot tell alive from dead and frees at timeout — size
        ``grant_lease_s`` above the worst-case ship stall there, or run
        heartbeats + a detector (the default pairing)."""
        if self.grant_lease_s is None and self.detector is None:
            return
        now_m = time.monotonic()
        for key, st in list(self._granted.items()):
            dead_peer = False
            if self.detector is not None:
                try:
                    dead_peer = self.detector.state(key[0]) == _PEER_DEAD
                except KeyError:
                    pass
            overdue = (self.grant_lease_s is not None
                       and now_m - st["t_grant"] > self.grant_lease_s)
            if dead_peer:
                self._reclaim(key, st, "peer_dead")
            elif overdue:
                if self.detector is not None:
                    if not st.get("expired"):
                        st["expired"] = True
                        _LEASES_EXPIRED.inc(reason="timeout")
                        trace = st.get("trace")
                        obs.instant(
                            "lease_expired", track="wire", conn=key[0],
                            rid=key[1], slot=st["slot"],
                            reason="timeout", quarantined=True,
                            trace_id=(trace.trace_id if trace
                                      else None))
                else:
                    self._reclaim(key, st, "timeout")

    def _reclaim(self, key, st, reason: str) -> None:
        """Actually free a granted slot and settle the stream key (late
        FINALs drop). Counts the expiry unless quarantine already did."""
        del self._granted[key]
        self._settle(self._expired_leases, key)
        self.engine.pool.free(st["slot"])
        if not st.get("expired"):
            _LEASES_EXPIRED.inc(reason=reason)
        trace = st.get("trace")
        obs.instant("lease_reclaimed", track="wire", conn=key[0],
                    rid=key[1], slot=st["slot"], reason=reason,
                    trace_id=trace.trace_id if trace else None)

    def _try_grant(self) -> None:
        while self._pending:
            conn, msg = self._pending[0]
            slot = self.engine.pool.admit(int(msg["rid"]))
            if slot is None:
                break  # pool full: BEGINs wait (admission backpressure)
            self._pending.popleft()
            self._pending_keys.discard((conn, int(msg["rid"])))
            trace = obs.TraceContext.from_wire(msg.get("trace"))
            self._granted[(conn, int(msg["rid"]))] = {
                # monotonic: the lease is a purely LOCAL interval (never
                # crosses the wire), and a wall-clock step (NTP, VM
                # resume) must not spuriously expire every live lease
                "slot": slot, "msg": msg, "t_grant": time.monotonic(),
                "trace": trace,
            }
            obs.instant("grant", track="wire", rid=int(msg["rid"]),
                        slot=slot,
                        trace_id=trace.trace_id if trace else None)
            # capacity hints ride every GRANT (the adoption-backpressure
            # feed, docs/SERVING.md): free decode slots AFTER this grant
            # and the BEGINs still waiting for one — the prefill side
            # surfaces them so a router steers new prompts away from a
            # saturated decode peer
            _send_msg(self.ep, conn, {
                "t": "grant", "rid": int(msg["rid"]), "slot": slot,
                "free": self.engine.pool.n_free,
                "queued": len(self._pending),
            })

    def _on_final(self, conn: int, final: Dict) -> None:
        key = (conn, int(final["rid"]))
        if key in self._adopted_keys:
            # retried FINAL (our ack was lost): re-ack, never re-adopt
            _send_msg(self.ep, conn, {"t": "final_ack", "rid": key[1]})
            return
        if key in self._expired_leases:
            # the lease already reclaimed this stream's slot — importing
            # now would corrupt the slot's new occupant. Ack it anyway so
            # the sender stops retrying a stream the fleet gave up on.
            _STALE_FINALS.inc()
            obs.instant("stale_final", track="wire", conn=conn,
                        rid=key[1])
            _send_msg(self.ep, conn, {"t": "final_ack", "rid": key[1]})
            return
        quarantined = self._granted.get(key)
        if quarantined is not None and quarantined.get("expired"):
            # a QUARANTINED lease's stream just terminated: this FINAL is
            # the last thing the stream will ever write, so the slot is
            # finally safe to free — but the lease lapsed long ago, so
            # the request itself is dropped as stale, never adopted
            _STALE_FINALS.inc()
            obs.instant("stale_final", track="wire", conn=conn,
                        rid=key[1], quarantined=True)
            self._reclaim(key, quarantined, "timeout")
            _send_msg(self.ep, conn, {"t": "final_ack", "rid": key[1]})
            return
        st = self._granted.pop(key, None)
        if st is None:
            raise KeyError(
                f"FINAL for unknown stream rid={final['rid']} (no BEGIN "
                "grant recorded)"
            )
        slot, begin, trace = st["slot"], st["msg"], st["trace"]
        plen = int(final["length"])
        # full S_max rows: rows past plen are dead (masked attention), and
        # the fixed shape keeps every import on one compiled program
        k_rows = self.mirror_k[:, slot, :]
        v_rows = self.mirror_v[:, slot, :]
        tr = obs.get_tracer()
        ts0 = tr.now_us() if tr is not None else 0.0
        self.engine.backend.import_slot_kv(
            slot, k_rows, v_rows, length=plen
        )
        if tr is not None:
            dur = tr.now_us() - ts0
            tr.complete("kv_stream.import", ts0, dur, "wire", slot=slot,
                        rows=plen, chunks=int(final["chunks"]),
                        trace_id=trace.trace_id if trace else None)
            if trace is not None:
                # the flow-finish matching the prefill side's flow-start:
                # same derived id, timestamped inside this import span so
                # the merged trace renders one arrow tx -> import
                tr.flow("kv_handoff", "f", obs.flow_id(trace.trace_id),
                        "wire", ts_us=ts0 + dur / 2.0)
        _STREAM_CHUNKS.inc(int(final["chunks"]), role="rx")
        _STREAM_REQS.inc(role="rx")
        t_adopt = time.time()
        t_submit, t_admit, t_done = (final["t_submit"], final["t_admit"],
                                     final["t_done"])
        req = self.engine.adopt(
            np.asarray(begin["prompt"], np.int32),
            int(final["first_token"]),
            max_new_tokens=int(begin["max_new_tokens"]),
            eos_id=begin["eos_id"], slot=slot,
            priority=begin.get("priority", "interactive"),
            tenant=begin.get("tenant", "default"),
            queue_s=t_admit - t_submit, prefill_s=t_done - t_admit,
            transfer_s=t_adopt - t_done,
            trace=trace,
        )
        req.cache_hit_len = int(final.get("cache_hit_len", 0))
        self.origin[req.rid] = (conn, int(final["rid"]))
        self._settle(self._adopted_keys, key)
        _send_msg(self.ep, conn, {"t": "final_ack", "rid": key[1]})
        if req.is_done():  # max_new_tokens == 1 or EOS at the first token
            self._finished.append(req)

    def step(self) -> List[Request]:
        """One loop iteration: drain control messages, run one engine
        step when there is decode work. Returns requests finished now."""
        self.poll()
        out, self._finished = self._finished, []
        if self.engine.has_work():
            out.extend(self.engine.step())
        return out

    def serve(self, n_requests: Optional[int] = None,
              timeout_s: float = 300.0) -> List[Request]:
        """Loop until ``n_requests`` finished (or the peer said BYE and
        everything drained). The example/bench decode processes run this."""
        done: List[Request] = []
        deadline = time.monotonic() + timeout_s
        while True:
            done.extend(self.step())
            if n_requests is not None and len(done) >= n_requests:
                return done
            if (self.closed and not self.engine.has_work()
                    and not self._pending and not self._granted):
                return done
            if not self.engine.has_work():
                time.sleep(0.001)
            if time.monotonic() > deadline:
                _DRAIN_TIMEOUTS.inc(role="decode")
                open_keys = sorted(self._granted)
                pending = sorted((c, int(m["rid"]))
                                 for c, m in self._pending)
                obs.instant("drain_timeout", track="wire", role="decode",
                            granted=len(open_keys), pending=len(pending))
                raise TimeoutError(
                    f"decode serve stalled after {timeout_s}s at "
                    f"{len(done)} finished: granted-unFINALed "
                    f"(conn,rid)={open_keys}, queued BEGINs "
                    f"(conn,rid)={pending}, engine "
                    f"active={len(self.engine._by_slot)}"
                )


# -- shared one-shot reference + in-process pair helpers --------------------
def oneshot_reference(params, cfg, prompt, new_tokens: int, max_seq: int):
    """The single-worker greedy continuation both disagg examples check
    against (prefill + decode_step loop — one implementation, two
    consumers: examples/disagg_kv.py and examples/disagg_proxy.py)."""
    import jax.numpy as jnp

    from uccl_tpu.models.inference import prefill

    logits, cache = prefill(params, jnp.asarray(prompt), cfg, max_seq)
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return decode_continue(params, cfg, cache, tok, new_tokens)


def decode_continue(params, cfg, cache, first_tok, new_tokens: int):
    """Continue ``new_tokens`` greedy steps from a warm cache + first
    token (the decode leg shared by the legacy one-shot examples)."""
    import jax.numpy as jnp

    from uccl_tpu.models.inference import decode_step

    tok = jnp.asarray(first_tok)
    toks = [np.asarray(tok)]
    for _ in range(new_tokens - 1):
        logits, cache = decode_step(params, tok, cache, cfg)
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        toks.append(np.asarray(tok))
    return np.stack(toks, axis=1)


def make_local_pair(prefill_engine: ServingEngine,
                    decode_engine: ServingEngine,
                    *,
                    transport: str = "ep",
                    pull_rate_bps: Optional[float] = None,
                    grant_lease_s: Optional[float] = None,
                    detector=None,
                    **transport_kw) -> Tuple[PrefillWorker, DecodeWorker]:
    """Both roles in ONE process over loopback endpoints — the in-process
    harness tests and benches drive (the example runs the same classes in
    two real processes). ``transport``/``pull_rate_bps``/extras route the
    KV plane over the windowed Channel transport (add_local_prefill);
    ``grant_lease_s``/``detector`` arm the decode side's lease guard and
    failure detector (docs/SERVING.md fault tolerance)."""
    from uccl_tpu.p2p import Endpoint

    dw = DecodeWorker(decode_engine, Endpoint(),
                      pull_rate_bps=pull_rate_bps,
                      grant_lease_s=grant_lease_s, detector=detector)
    return add_local_prefill(dw, prefill_engine, transport=transport,
                             **transport_kw), dw


def add_local_prefill(dw: DecodeWorker,
                      prefill_engine: ServingEngine,
                      *,
                      transport: str = "ep",
                      n_paths: int = 2,
                      chunk_bytes: Optional[int] = None,
                      pull: bool = False,
                      window_cc: Optional[str] = None,
                      heartbeat_s: Optional[float] = 0.5,
                      ctrl_retry_s: float = 0.5) -> PrefillWorker:
    """Attach one more in-process prefill worker to ``dw`` — the loopback
    fan-in arrangement (N prefill engines streaming into one decode pool;
    each stream is its own conn, so GRANT/FINAL bookkeeping stays
    per-(conn, rid) and workers never see each other's slots).

    ``transport="channel"`` dials a multipath
    :class:`~uccl_tpu.p2p.channel.Channel` instead of a bare conn: KV
    slabs ride the windowed SACK transport (selective repeat, per-path
    quality steering, loss/reorder-proof), ``pull=True`` gates slab issue
    on the decode worker's receiver-driven credit (requires ``dw`` built
    with ``pull_rate_bps``), and ``window_cc`` ("timely"|"swift") runs
    sender-side window CC off per-chunk completion RTTs."""
    from uccl_tpu.p2p import Endpoint

    ep_p = Endpoint()
    pw = PrefillWorker.__new__(PrefillWorker)
    if transport == "channel":
        import threading

        from uccl_tpu.p2p.channel import Channel

        res: Dict[str, object] = {}

        def _accept():
            try:
                res["chan"] = dw.attach_channel(chunk_bytes=chunk_bytes)
            except Exception as e:  # surfaced below, not swallowed
                res["err"] = e

        t = threading.Thread(target=_accept)
        t.start()
        chan = Channel.connect(ep_p, "127.0.0.1", dw.ep.port,
                               n_paths=n_paths, chunk_bytes=chunk_bytes)
        t.join(timeout=30)
        if "err" in res:
            raise res["err"]  # the real accept-side failure, with traceback
        if "chan" not in res:
            raise TimeoutError("decode side never accepted the channel")
        if pull:
            if dw._pacer is None:
                raise ValueError(
                    "pull=True needs a DecodeWorker(pull_rate_bps=...)"
                )
            chan.enable_pull_sender()
        if window_cc:
            chan.enable_window_cc(window_cc)
        _init_prefill_worker(pw, prefill_engine, ep_p, chan.conns[0],
                             chan=chan, heartbeat_s=heartbeat_s,
                             ctrl_retry_s=ctrl_retry_s)
    elif transport == "ep":
        # loopback: connect() completes against the listening endpoint
        # before accept() is called (the test_p2p pair idiom)
        conn_p = ep_p.connect("127.0.0.1", dw.ep.port)
        dw.attach()
        _init_prefill_worker(pw, prefill_engine, ep_p, conn_p,
                             heartbeat_s=heartbeat_s,
                             ctrl_retry_s=ctrl_retry_s)
    else:
        raise ValueError(f"unknown transport {transport!r}")
    return pw


def _init_prefill_worker(pw: PrefillWorker, engine: ServingEngine, ep,
                         conn: int, timeout_ms: int = 30000,
                         chan=None, heartbeat_s: Optional[float] = 0.5,
                         ctrl_retry_s: float = 0.5) -> None:
    """PrefillWorker init against an already-open conn (the local-pair
    path, where connect must precede the peer's accept). ``chan`` routes
    KV slabs over the windowed multipath Channel transport (conn must be
    its path-0 conn — the notif/control path). ``heartbeat_s`` sends a
    liveness hb notif at that interval (the decode side's failure
    detector feeds off it; ON by default — a detector-armed decode peer
    would otherwise age an idle-but-healthy conn to DEAD, and one tiny
    notif per interval is free; None disables); ``ctrl_retry_s`` is the
    control-plane retransmission window (BEGIN without GRANT, FINAL
    without ack)."""
    if engine.prefill_chunk is None:
        raise ValueError("PrefillWorker needs a chunked engine")
    sink = engine.chunk_sink
    if sink is None:
        sink = _ChunkFanout()
    elif not isinstance(sink, _ChunkFanout):
        raise ValueError("engine already has a chunk_sink")
    hello = json.loads(ep.recv(conn, timeout_ms=timeout_ms))
    assert hello.get("t") == "hello", hello
    from uccl_tpu.p2p.channel import FifoItem

    pw.engine = engine
    pw.ep = ep
    pw.conn = conn
    pw.chan = chan
    pw.fmt = KVWireFormat.from_meta(hello["fmt"])
    dims = _model_dims(engine.backend)
    dims["max_seq"] = engine.backend.max_seq
    for k, v in dims.items():
        if getattr(pw.fmt, k) != v:
            raise ValueError(
                f"decode pool {k}={getattr(pw.fmt, k)} != prefill "
                f"backend {k}={v}: the KV slabs would not line up"
            )
    pw._fifo_k = FifoItem.unpack(_unb64(hello["k_fifo"]))
    pw._fifo_v = FifoItem.unpack(_unb64(hello["v_fifo"]))
    pw._streams = {}
    pw._finaled = {}  # rid -> FINAL awaiting the decode side's ack
    pw._timeout_ms = timeout_ms
    pw._ctrl_retry_s = ctrl_retry_s
    pw.heartbeat_s = heartbeat_s
    pw._last_hb = time.monotonic()
    # decode-peer capacity as of the last GRANT (free slots + pending
    # BEGIN depth) — feeds adoption_backpressure() / the replica router
    pw.decode_hint = None
    # clock exchange (docs/OBSERVABILITY.md): the first ping rides a
    # notif right after HELLO and its pong comes back through the regular
    # pump, so the exchange needs no extra blocking recv (the in-process
    # loopback pair pumps both sides from one thread); follow-up rounds
    # refine the estimate by minimum RTT (_on_clock_pong). None until the
    # first pong lands.
    pw.clock_offset_s = None  # estimated decode_wall - prefill_wall
    pw.clock_rtt_s = None
    pw._clock_pings_left = 8
    pw._send_clock_ping()
    sink.sinks.append(pw._on_chunks)
    engine.chunk_sink = sink


def drive_pair(pw: PrefillWorker, dw: DecodeWorker, prompts, arrivals,
               max_new_tokens: int, eos_id: Optional[int] = None,
               timeout_s: float = 300.0) -> Tuple[List[Request], float]:
    """Submit ``prompts`` at their Poisson ``arrivals`` offsets and step
    both workers until every accepted request finishes on the decode side.
    Returns (decode-side finished Requests, wall seconds) — the disagg
    analog of ``loadgen.drive``."""
    finished: List[Request] = []
    i, n = 0, len(prompts)
    accepted = 0
    t0 = now()
    deadline = time.monotonic() + timeout_s
    while i < n or not pw.idle() or len(finished) < accepted:
        t = now() - t0
        while i < n and arrivals[i] <= t:
            if pw.submit(prompts[i], max_new_tokens=max_new_tokens,
                         eos_id=eos_id) is not None:
                accepted += 1
            i += 1
        pw.step()
        finished.extend(dw.step())
        if not pw.engine.has_work() and not dw.engine.has_work():
            time.sleep(0.0005)
        if time.monotonic() > deadline:
            _DRAIN_TIMEOUTS.inc(role="pair")
            out = pw.outstanding()
            raise TimeoutError(
                f"disagg drive stalled after {timeout_s}s: "
                f"{len(finished)}/{accepted} finished; prefill side "
                f"ungranted rid={out['ungranted']} granted "
                f"rid={out['granted']} unacked-final "
                f"rid={out['unacked_final']}; decode side granted "
                f"(conn,rid)={sorted(dw._granted)}"
            )
    return finished, now() - t0


def warm_pair(pw: PrefillWorker, dw: DecodeWorker, prompt_len: int,
              new_tokens: int = 2) -> None:
    """One dummy request through the whole stream: compiles the prefill
    chunk program, the decode program, and touches every wire path — then
    zeroes both engines' metrics and clears the prefix cache (warmup
    prompts must not act as donors). Counters stay cumulative; benches
    snapshot deltas around each arm."""
    reps = 2 if pw.engine.prefix_cache is not None else 1
    for _ in range(reps):  # rep 2 hits the parked rep-1 donor: compiles
        pw.submit(np.zeros(max(1, prompt_len), np.int32),  # the copy path
                  max_new_tokens=max(2, new_tokens))
        got: List[Request] = []
        deadline = time.monotonic() + 120.0
        while len(got) < 1:
            pw.step()
            got.extend(dw.step())
            if time.monotonic() > deadline:
                _DRAIN_TIMEOUTS.inc(role="pair")
                out = pw.outstanding()
                raise TimeoutError(
                    f"disagg warmup stalled after 120s: prefill "
                    f"ungranted rid={out['ungranted']} granted "
                    f"rid={out['granted']} unacked-final "
                    f"rid={out['unacked_final']}; decode granted "
                    f"(conn,rid)={sorted(dw._granted)}"
                )
    pw.drain()
    if pw.engine.prefix_cache is not None:
        pw.engine.prefix_cache.clear(pw.engine.pool)
    pw.engine.reset_metrics()
    dw.engine.reset_metrics()
    from uccl_tpu.serving.loadgen import _clear_warmup_trace

    _clear_warmup_trace()
