"""Speculative decoding for the serving engine: drafters + spec telemetry.

Decode is the engine's steady-state cost and moves ONE token per slot per
step — each step pays a full forward over the weights to commit a single
token. Speculative decoding overlaps cheap guesswork with that expensive
pass (the UCCL chunk-pipelining idea applied to compute instead of wire):
a **drafter** proposes k continuation tokens per active slot, the target
model scores all slots' windows in ONE compiled ``[n_slots, k+1]`` verify
program (``inference.verify_slots`` / ``MoEServer.verify_slots``), and
greedy acceptance commits each slot's longest draft prefix that matches
the target's own argmaxes, plus one target-computed token (the correction
when a draft missed, the bonus when all k hit). A step therefore commits
1..k+1 tokens per slot for roughly one step's latency, and the output is
**bit-identical to vanilla greedy decode** — acceptance only ever commits
tokens the target model itself would have emitted (docs/SERVING.md spells
out the rule and the KV-rollback-by-cursor argument).

Drafters are host-side and jax-free. The default needs no second model:

* :class:`NGramDrafter` — prompt-lookup decoding (the Leviathan-style
  draft-then-verify line surveyed in PAPERS.md, with the drafter replaced
  by context self-lookup): find the most recent earlier occurrence of the
  context's suffix n-gram and propose the tokens that followed it.
  Repetitive continuations (shared boilerplate, code, the loops greedy
  decode falls into) verify at high acceptance; novel text degrades to
  vanilla pace, never to wrong tokens.

Custom drafters (a truncated-stack model, a distilled head) implement
:class:`Drafter.draft` and plug into ``ServingEngine(spec_k=K,
drafter=...)``.
"""

from __future__ import annotations

import numpy as np

from uccl_tpu import obs

# verification outcomes, counted per verify window (docs/OBSERVABILITY.md):
# accepted/rejected partition the tokens the drafter actually PROPOSED
# (window pads are excluded — a pad that coincidentally matches still
# commits a correct token but is not a speculation), bonus is the one
# target-computed token every window yields. Commit truncation (EOS or
# token budget inside an accepted prefix) does not un-count an acceptance —
# these series record what verification proved, the engine's decode_tokens
# metric records what was committed.
SPEC_TOKENS = obs.counter(
    "spec_tokens_total",
    "speculative tokens by verification outcome: outcome=accepted drafts "
    "matched the target's greedy output, outcome=rejected drafts missed, "
    "outcome=bonus is the per-window target-computed token",
)
SPEC_ACCEPTED_LEN = obs.counter(
    "spec_accepted_len_total",
    "verify windows by accepted-prefix length (len=0..k): the acceptance "
    "histogram behind the spec_tokens_total rates",
)


class Drafter:
    """Proposes up to ``k`` continuation tokens for one slot's context."""

    def draft(self, context: np.ndarray, k: int) -> np.ndarray:
        """context: 1-D int32 (prompt + committed tokens). Return up to
        ``k`` proposed next tokens (int32, may be empty — the engine pads
        the verify window; a padded position that happens to match the
        target still commits a correct token, so abstaining is always
        safe)."""
        raise NotImplementedError


class NGramDrafter(Drafter):
    """Prompt-lookup drafting: propose the continuation of the context's
    own most recent suffix match.

    The longest suffix n-gram (``max_ngram`` down to ``min_ngram``) with an
    earlier occurrence in the context wins; ties between occurrences go to
    the most recent one (local context predicts local continuation best).
    Deterministic, O(context) per call, no model."""

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if not (1 <= min_ngram <= max_ngram):
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"({min_ngram}, {max_ngram})"
            )
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def draft(self, context: np.ndarray, k: int) -> np.ndarray:
        ctx = np.asarray(context, np.int32).reshape(-1)
        n_hi = min(self.max_ngram, ctx.size - 1)
        if k < 1 or n_hi < self.min_ngram:
            return np.zeros(0, np.int32)
        for n in range(n_hi, self.min_ngram - 1, -1):
            suffix = ctx[ctx.size - n:]
            # candidate windows start at i in [0, L-n-1] — the window at
            # L-n is the suffix itself
            windows = np.lib.stride_tricks.sliding_window_view(ctx, n)
            hits = np.flatnonzero(
                (windows[: ctx.size - n] == suffix).all(axis=1)
            )
            if hits.size:
                # prefer the most recent match whose continuation has all
                # k tokens in-context: inside a repeating run the very
                # latest match sits one step back and its continuation is
                # cut short by the context end, which would cap every
                # proposal at a fraction of k
                full = hits[hits + n + k <= ctx.size]
                i = int(full[-1]) if full.size else int(hits[-1])
                return ctx[i + n: i + n + k].copy()
        return np.zeros(0, np.int32)
