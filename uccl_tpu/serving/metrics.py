"""Serving metrics: queue depth, slot occupancy, goodput, TTFT/TPOT.

Definitions (shared with serve.py's one-shot percentiles and
benchmarks/serving_bench.py — docs/SERVING.md spells them out):

* **TTFT** — submit → first generated token, queue wait included.
* **queue wait** — submit → admission into a KV slot: the scheduling delay
  alone, reported as its own series so scheduling and compute delays are
  separable (TTFT − queue wait ≈ prefill/compute time).
* **TPOT** — per-request mean seconds per output token AFTER the first
  (decode steady state): (t_finish - t_first) / (n_out - 1).
* **decode step latency** — wall time of one masked batched decode call.
* **engine step latency** — wall time of one full ``step()`` (admission +
  prefill work + decode); its MAX is the decode-stall bound chunked
  prefill exists to shrink (docs/SERVING.md).
* **goodput** — completed requests' output tokens per second of serving
  wall time (first submit → last finish). Tokens of in-flight or rejected
  requests never count: goodput is *useful delivered* throughput.

The snapshot is JSON-ready and also exported through the repo-wide stats
thread (`uccl_tpu.utils.stats.registry`) under the ``serving`` source, the
same channel every other subsystem reports on.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

from uccl_tpu import obs
from uccl_tpu.serving.request import Request, now

# Merge-safe latency histograms (docs/OBSERVABILITY.md): the sample lists
# below stay the exact in-process percentile source, but sample lists
# cannot be combined across processes — these log-bucketed families SUM,
# so obs/aggregate.py can federate N workers' /metrics into one fleet
# distribution. Observed by the SAME lifecycle hooks that append the
# samples, so the two derivations agree to a bucket width by construction
# (check_obs --fleet asserts it; serving_bench stamps both).
TTFT_HIST = obs.histogram(
    "serving_ttft_seconds", "submit -> first token, queue wait included"
)
QUEUE_WAIT_HIST = obs.histogram(
    "serving_queue_wait_seconds", "submit -> admission into a KV slot"
)
TPOT_HIST = obs.histogram(
    "serving_tpot_seconds", "per-token decode steady state after the first"
)
STEP_HIST = obs.histogram(
    "serving_step_seconds", "one full engine step() wall time"
)
TRANSFER_HIST = obs.histogram(
    "serving_transfer_seconds",
    "disagg KV transfer tail: prefill-done -> adopt (decode side)",
)
DISAGG_TTFT_HIST = obs.histogram(
    "serving_disagg_ttft_seconds",
    "disaggregated end-to-end TTFT: queue + prefill + transfer "
    "(wall-clock marks carried in the stream's control messages)",
)

_LATENCY_HISTS = (TTFT_HIST, QUEUE_WAIT_HIST, TPOT_HIST, STEP_HIST,
                  TRANSFER_HIST, DISAGG_TTFT_HIST)


def reset_latency_histograms() -> None:
    """Zero the process-wide serving latency histograms — called with
    ``ServingEngine.reset_metrics`` so compile-warmup observations never
    pollute the recorded distributions (warmups reset every engine in the
    process before the measured window, so clearing the shared families
    there is exact)."""
    for fam in _LATENCY_HISTS:
        fam.clear()


def percentile(xs: List[float], q: float) -> Optional[float]:
    """Linear-interpolation percentile (numpy's default), None when empty."""
    if not xs:
        return None
    s = sorted(xs)
    if len(s) == 1:
        return float(s[0])
    pos = (len(s) - 1) * q / 100.0
    lo = math.floor(pos)
    frac = pos - lo
    if lo + 1 >= len(s):
        return float(s[-1])
    return float(s[lo] * (1.0 - frac) + s[lo + 1] * frac)


def percentiles_ms(xs: List[float], qs=(50, 95)) -> Dict[str, float]:
    """{'p50': ..., 'p95': ...} in milliseconds (empty dict when no samples)."""
    out = {}
    for q in qs:
        v = percentile(xs, q)
        if v is not None:
            out[f"p{q}"] = round(v * 1e3, 3)
    return out


def dist(xs: List[float], qs=(50, 95)) -> Dict[str, float]:
    """Percentiles + mean/max in the samples' OWN units (token counts,
    ratios — anything that is not a duration; durations go through
    :func:`percentiles_ms`). Empty dict when no samples."""
    out = {}
    for q in qs:
        v = percentile(xs, q)
        if v is not None:
            out[f"p{q}"] = round(v, 3)
    if xs:
        out["mean"] = round(sum(xs) / len(xs), 3)
        out["max"] = round(float(max(xs)), 3)
    return out


class ServingMetrics:
    """Counters + latency samples for one engine; host-only, jax-free."""

    def __init__(self):
        self.submitted = 0
        self.rejected = 0
        self.expired = 0  # queued requests dropped by deadline or cancel()
        # requests stranded on THIS engine when its replica died (the
        # fault-recovery sink term): a recovered request's resubmission on
        # a survivor is a NEW submission there, so the dead copy must
        # leave through `lost` for fleet conservation to stay exact —
        # submitted == completed+active+queued+rejected+expired+lost
        self.lost = 0
        self.admitted = 0
        self.adopted = 0  # requests entering via adopt() (disagg decode)
        self.preempted = 0  # pauses of a lower-class request at a chunk boundary
        self.resumed = 0  # preempted requests re-admitted (KV restored)
        self.completed = 0
        self.output_tokens = 0  # completed requests only (goodput numerator)
        self.prefill_calls = 0
        self.prefill_chunks = 0  # chunked-prefill calls (subset of prefill_calls)
        self.decode_calls = 0
        # tokens COMMITTED by decode/verify calls — under speculative
        # decoding a step commits 0..k+1 tokens per slot, so throughput
        # derives from this count, never from an assumed 1 token per call
        # (the PR 1 "1-token-delta window" assumption, generalized)
        self.decode_tokens = 0
        # speculative decoding (per active slot per verify window):
        # proposed = tokens the drafter actually proposed (window pads
        # from abstentions are excluded), accepted = its matched prefix
        self.spec_windows = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.accepted_len: List[int] = []
        self.ttft_s: List[float] = []
        self.queue_wait_s: List[float] = []
        self.tpot_s: List[float] = []
        self.latency_s: List[float] = []
        self.prefill_s: List[float] = []
        self.decode_step_s: List[float] = []
        self.step_s: List[float] = []
        # disaggregated TTFT split (decode side, wall-clock seconds carried
        # in the stream's control messages — docs/SERVING.md): submit→admit
        # on the prefill fleet, admit→prefill-done, prefill-done→adopt
        # (the transfer tail), and the end-to-end sum per adopted request.
        self.disagg_queue_s: List[float] = []
        self.disagg_prefill_s: List[float] = []
        self.disagg_transfer_s: List[float] = []
        self.disagg_ttft_s: List[float] = []
        # per-priority-class series (SLO attainment is judged per class —
        # docs/SERVING.md): every request lands in exactly one class bucket
        self.class_submitted: Dict[str, int] = {}
        self.class_completed: Dict[str, int] = {}
        self.class_ttft_s: Dict[str, List[float]] = {}
        self.class_tpot_s: Dict[str, List[float]] = {}
        self.class_queue_wait_s: Dict[str, List[float]] = {}
        # per-tenant series (ISSUE 18): isolation is judged per tenant —
        # the multi-tenant bench derives each tenant's SLO attainment and
        # goodput share from these, so an overloading neighbor's damage
        # (or the fair scheduler's lack thereof) is directly visible
        self.tenant_submitted: Dict[str, int] = {}
        self.tenant_completed: Dict[str, int] = {}
        self.tenant_output_tokens: Dict[str, int] = {}
        self.tenant_ttft_s: Dict[str, List[float]] = {}
        self.tenant_tpot_s: Dict[str, List[float]] = {}
        self.tenant_queue_wait_s: Dict[str, List[float]] = {}
        self.t_first_submit: Optional[float] = None
        self.t_last_finish: Optional[float] = None

    # -- lifecycle hooks (the engine calls these) ---------------------------
    def on_submit(self, req: Request) -> None:
        self.submitted += 1
        self.class_submitted[req.priority] = \
            self.class_submitted.get(req.priority, 0) + 1
        self.tenant_submitted[req.tenant] = \
            self.tenant_submitted.get(req.tenant, 0) + 1
        if self.t_first_submit is None:
            self.t_first_submit = req.t_submit

    def on_reject(self, req: Request) -> None:
        self.rejected += 1

    def on_expire(self, req: Request) -> None:
        """A queued request left by deadline expiry or cancellation."""
        self.expired += 1

    def on_lost(self, req: Request) -> None:
        """A request stranded on this (dead) engine left the fleet — or
        was re-run on a survivor as a metrically-new submission. Either
        way THIS engine's copy exits through the `lost` term (the
        conservation invariant's recovery sink, docs/SERVING.md)."""
        self.lost += 1

    def on_admit(self, req: Request) -> None:
        self.admitted += 1
        if req.queue_wait is not None:
            self.queue_wait_s.append(req.queue_wait)
            QUEUE_WAIT_HIST.observe(req.queue_wait)
            self.class_queue_wait_s.setdefault(req.priority, []).append(
                req.queue_wait
            )
            self.tenant_queue_wait_s.setdefault(req.tenant, []).append(
                req.queue_wait
            )

    def on_preempt(self, req: Request) -> None:
        """A lower-class request was paused at a chunk boundary (its KV
        saved, its slot handed to an interactive arrival)."""
        self.preempted += 1

    def on_resume(self, req: Request) -> None:
        """A preempted request re-entered a slot (KV restored) — NOT a new
        admission: its queue-wait and admitted count were recorded at its
        first admission, so conservation stays exact."""
        self.resumed += 1

    def on_first_token(self, req: Request) -> None:
        if req.ttft is not None:
            self.ttft_s.append(req.ttft)
            TTFT_HIST.observe(req.ttft)
            self.class_ttft_s.setdefault(req.priority, []).append(req.ttft)
            self.tenant_ttft_s.setdefault(req.tenant, []).append(req.ttft)

    def on_adopt(self, req: Request, *, queue_s: Optional[float] = None,
                 prefill_s: Optional[float] = None,
                 transfer_s: Optional[float] = None) -> None:
        """A request adopted mid-stream (disagg decode side): its KV and
        first token arrived over the wire, so TTFT decomposes into the
        prefill fleet's queue + prefill time plus the transfer tail."""
        self.adopted += 1
        if queue_s is not None:
            self.disagg_queue_s.append(max(0.0, queue_s))
        if prefill_s is not None:
            self.disagg_prefill_s.append(max(0.0, prefill_s))
        if transfer_s is not None:
            self.disagg_transfer_s.append(max(0.0, transfer_s))
            TRANSFER_HIST.observe(max(0.0, transfer_s))
        if None not in (queue_s, prefill_s, transfer_s):
            ttft = (max(0.0, queue_s) + max(0.0, prefill_s)
                    + max(0.0, transfer_s))
            self.disagg_ttft_s.append(ttft)
            DISAGG_TTFT_HIST.observe(ttft)

    def on_finish(self, req: Request) -> None:
        self.completed += 1
        self.class_completed[req.priority] = \
            self.class_completed.get(req.priority, 0) + 1
        self.tenant_completed[req.tenant] = \
            self.tenant_completed.get(req.tenant, 0) + 1
        self.output_tokens += req.n_generated
        self.tenant_output_tokens[req.tenant] = \
            self.tenant_output_tokens.get(req.tenant, 0) + req.n_generated
        self.t_last_finish = req.t_finish
        if req.tpot is not None:
            self.tpot_s.append(req.tpot)
            TPOT_HIST.observe(req.tpot)
            self.class_tpot_s.setdefault(req.priority, []).append(req.tpot)
            self.tenant_tpot_s.setdefault(req.tenant, []).append(req.tpot)
        if req.latency is not None:
            self.latency_s.append(req.latency)

    def on_prefill(self, dt: float, n_new: int, *,
                   chunked: bool = False) -> None:
        self.prefill_calls += 1
        if chunked:
            self.prefill_chunks += 1
        self.prefill_s.append(dt)

    def on_decode_step(self, dt: float, n_active: int,
                       tokens: Optional[int] = None) -> None:
        """One masked decode/verify call over ``n_active`` slots that
        committed ``tokens`` output tokens (None = the vanilla 1 token per
        active slot; speculative steps pass their actual commit count)."""
        self.decode_calls += 1
        self.decode_step_s.append(dt)
        self.decode_tokens += n_active if tokens is None else tokens

    def on_spec(self, *, proposed: int, accepted: int) -> None:
        """One slot's verify outcome: ``proposed`` drafted tokens entered
        the window, ``accepted`` matched the target's greedy output."""
        self.spec_windows += 1
        self.spec_proposed += proposed
        self.spec_accepted += accepted
        self.accepted_len.append(accepted)

    def on_step(self, dt: float) -> None:
        self.step_s.append(dt)
        STEP_HIST.observe(dt)

    # -- derived ------------------------------------------------------------
    def goodput(self) -> Optional[float]:
        """Completed output tokens / serving wall seconds."""
        if self.t_last_finish is None or self.t_first_submit is None:
            return None
        dt = self.t_last_finish - self.t_first_submit
        if dt <= 0:
            return None
        return self.output_tokens / dt

    def snapshot(self, *, queued: int = 0, active: int = 0,
                 n_slots: int = 0, occupancy: float = 0.0) -> Dict:
        """JSON-ready state. Conservation invariant (tested):
        submitted == completed + active + queued + rejected + expired
        + lost (preemptions move requests between active and queued,
        never out; `lost` is the fault-recovery sink — a request
        stranded on a dead replica leaves here, and its survivor-side
        re-run is a new submission there)."""
        snap = {
            "submitted": self.submitted,
            "rejected": self.rejected,
            "expired": self.expired,
            "lost": self.lost,
            "admitted": self.admitted,
            "completed": self.completed,
            "queued": queued,
            "active": active,
            "n_slots": n_slots,
            "occupancy": round(occupancy, 4),
            "output_tokens": self.output_tokens,
            "prefill_calls": self.prefill_calls,
            "prefill_chunks": self.prefill_chunks,
            "decode_calls": self.decode_calls,
            "decode_tokens": self.decode_tokens,
            "ttft_ms": percentiles_ms(self.ttft_s),
            "queue_wait_ms": percentiles_ms(self.queue_wait_s),
            "tpot_ms": percentiles_ms(self.tpot_s),
            "latency_ms": percentiles_ms(self.latency_s),
            "prefill_ms": percentiles_ms(self.prefill_s),
            "decode_step_ms": percentiles_ms(self.decode_step_s),
            "step_ms": percentiles_ms(self.step_s),
        }
        if self.step_s:
            snap["max_step_ms"] = round(max(self.step_s) * 1e3, 3)
        # decode throughput off the COMMITTED token count over decode-call
        # wall time — honest whether a call commits n_active tokens
        # (vanilla) or up to (k+1) * n_active (speculative)
        decode_wall = sum(self.decode_step_s)
        if decode_wall > 0 and self.decode_tokens:
            snap["decode_tok_s"] = round(self.decode_tokens / decode_wall, 1)
        if self.spec_windows:
            snap["spec_windows"] = self.spec_windows
            snap["spec_proposed"] = self.spec_proposed
            snap["spec_accepted"] = self.spec_accepted
            if self.spec_proposed:
                snap["spec_acceptance_rate"] = round(
                    self.spec_accepted / self.spec_proposed, 4
                )
            snap["accepted_len"] = dist(self.accepted_len)
        if self.preempted or self.resumed:
            snap["preempted"] = self.preempted
            snap["resumed"] = self.resumed
        # per-class SLO surfaces, emitted once a second class shows up (a
        # single-class engine's snapshot stays byte-compatible with PR 3's)
        if len(self.class_submitted) > 1:
            snap["per_class"] = {
                cls: {
                    "submitted": n,
                    "completed": self.class_completed.get(cls, 0),
                    "ttft_ms": percentiles_ms(
                        self.class_ttft_s.get(cls, [])
                    ),
                    "tpot_ms": percentiles_ms(
                        self.class_tpot_s.get(cls, [])
                    ),
                    "queue_wait_ms": percentiles_ms(
                        self.class_queue_wait_s.get(cls, [])
                    ),
                }
                for cls, n in sorted(self.class_submitted.items())
            }
        # per-tenant SLO surfaces, same emission rule: a single-tenant
        # engine's snapshot stays byte-compatible with the pre-tenancy one
        if len(self.tenant_submitted) > 1:
            snap["per_tenant"] = {
                t: {
                    "submitted": n,
                    "completed": self.tenant_completed.get(t, 0),
                    "output_tokens": self.tenant_output_tokens.get(t, 0),
                    "ttft_ms": percentiles_ms(
                        self.tenant_ttft_s.get(t, [])
                    ),
                    "tpot_ms": percentiles_ms(
                        self.tenant_tpot_s.get(t, [])
                    ),
                    "queue_wait_ms": percentiles_ms(
                        self.tenant_queue_wait_s.get(t, [])
                    ),
                }
                for t, n in sorted(self.tenant_submitted.items())
            }
        if self.adopted:
            snap["adopted"] = self.adopted
            snap["disagg_queue_ms"] = percentiles_ms(self.disagg_queue_s)
            snap["disagg_prefill_ms"] = percentiles_ms(self.disagg_prefill_s)
            snap["disagg_transfer_ms"] = percentiles_ms(
                self.disagg_transfer_s
            )
            snap["disagg_ttft_ms"] = percentiles_ms(self.disagg_ttft_s)
        gp = self.goodput()
        if gp is not None:
            snap["goodput_tok_s"] = round(gp, 1)
        return snap

    @staticmethod
    def merged(parts: List["ServingMetrics"]) -> "ServingMetrics":
        """One metrics object spanning N replica engines (the router's
        aggregate snapshot): counts add, sample lists concatenate — so the
        merged percentiles are computed over the REAL union of samples, not
        averaged per-replica percentiles (which would be meaningless) —
        and the goodput window spans first submit to last finish across
        the whole replica set."""
        out = ServingMetrics()
        for m in parts:
            for attr, v in vars(m).items():
                cur = getattr(out, attr)
                if attr in ("t_first_submit", "t_last_finish"):
                    continue  # merged below (min/max, not sum)
                if isinstance(v, bool):
                    continue
                if isinstance(v, (int, float)):
                    setattr(out, attr, cur + v)
                elif isinstance(v, list):
                    cur.extend(v)
                elif isinstance(v, dict):
                    for k2, v2 in v.items():
                        if isinstance(v2, list):
                            cur.setdefault(k2, []).extend(v2)
                        else:
                            cur[k2] = cur.get(k2, 0) + v2
            if m.t_first_submit is not None:
                out.t_first_submit = (m.t_first_submit
                                      if out.t_first_submit is None
                                      else min(out.t_first_submit,
                                               m.t_first_submit))
            if m.t_last_finish is not None:
                out.t_last_finish = (m.t_last_finish
                                     if out.t_last_finish is None
                                     else max(out.t_last_finish,
                                              m.t_last_finish))
        return out

    # -- repo-wide stats thread export --------------------------------------
    @staticmethod
    def prometheus_lines(snapshot: Dict,
                         prefix: str = "uccl_serving") -> List[str]:
        """The snapshot as Prometheus text lines (the ``/metrics`` face of
        the same numbers — names through the shared obs sanitizer so this
        exporter and :func:`uccl_tpu.obs.prometheus_text` cannot drift).
        Percentile sub-dicts become one series per quantile, labeled
        ``{q="p50"}``; booleans and strings are skipped."""
        from uccl_tpu.obs import escape_label_value, sanitize_name

        lines: List[str] = []
        for k, v in snapshot.items():
            name = sanitize_name(f"{prefix}_{k}")
            if k == "per_class" and isinstance(v, dict):
                # one series per (class, metric[, quantile]) — the SLO
                # surfaces check_obs --router greps for
                for cls, metrics in v.items():
                    c = escape_label_value(str(cls))
                    for mk, mv in metrics.items():
                        mname = sanitize_name(f"{prefix}_class_{mk}")
                        if isinstance(mv, dict):
                            for q, qv in mv.items():
                                if isinstance(qv, (int, float)) \
                                        and not isinstance(qv, bool):
                                    lines.append(
                                        f'{mname}{{cls="{c}",'
                                        f'q="{escape_label_value(str(q))}"'
                                        f"}} {qv}"
                                    )
                        elif isinstance(mv, (int, float)) \
                                and not isinstance(mv, bool):
                            lines.append(f'{mname}{{cls="{c}"}} {mv}')
                continue
            if k == "per_tenant" and isinstance(v, dict):
                # one series per (tenant, metric[, quantile]) — the
                # isolation surfaces check_obs --tenants greps for
                for ten, metrics in v.items():
                    tl = escape_label_value(str(ten))
                    for mk, mv in metrics.items():
                        mname = sanitize_name(f"{prefix}_tenant_{mk}")
                        if isinstance(mv, dict):
                            for q, qv in mv.items():
                                if isinstance(qv, (int, float)) \
                                        and not isinstance(qv, bool):
                                    lines.append(
                                        f'{mname}{{tenant="{tl}",'
                                        f'q="{escape_label_value(str(q))}"'
                                        f"}} {qv}"
                                    )
                        elif isinstance(mv, (int, float)) \
                                and not isinstance(mv, bool):
                            lines.append(
                                f'{mname}{{tenant="{tl}"}} {mv}'
                            )
                continue
            if isinstance(v, dict):
                for q, qv in v.items():
                    if isinstance(qv, (int, float)) \
                            and not isinstance(qv, bool):
                        lines.append(
                            f'{name}{{q="{escape_label_value(str(q))}"}} '
                            f"{qv}"
                        )
            elif isinstance(v, (int, float)) and not isinstance(v, bool):
                lines.append(f"{name} {v}")
        return lines

    def register(self, engine, name: str = "serving") -> None:
        """Export through uccl_tpu.utils.stats — the same periodic snapshot
        channel the transport engines report on."""
        from uccl_tpu.utils.stats import registry

        def source() -> Dict[str, float]:
            s = engine.snapshot()
            return {
                k: float(v) for k, v in s.items()
                if isinstance(v, (int, float))
            }

        registry.register(name, source)

    def unregister(self, name: str = "serving") -> None:
        from uccl_tpu.utils.stats import registry

        registry.unregister(name)
