"""Fleet prefix-cache directory: N per-worker tries become one cache.

Every fleet ingredient already exists in-process — the windowed SACK
channel (PR 13), the tiered KV movers (PR 17), the OOB store (PR 6), the
namespaced prefix trie (PR 18) — but each worker's trie is a private
cache: a system prompt computed on worker A is recomputed on worker B.
This module is the cross-worker layer (ISSUE 19), UCCL's P2P pillar
(NIXL-style initiator-target KV transfer) graduated from example to
architecture:

* :class:`FleetDirectory` — a **directory of resident prefixes** over the
  p2p :class:`~uccl_tpu.p2p.store.StoreClient`. Each worker registers
  every chunk-aligned prefix depth of every entry its
  :class:`~uccl_tpu.serving.prefix_cache.PrefixCache` parks (keyed by a
  digest of the trie's own namespaced chunk-key bytes, so the PR 18
  ``tenant|adapter@version`` isolation holds fleet-wide by construction),
  and tombstones them on eviction. SET/GET are the only store verbs used:
  a tombstone is an overwrite, a dead owner's entries are invalidated by
  any survivor, and the store server needs no new ops.

* :class:`FleetCachePublisher` — the trie listener. At park/insert time
  (on the engine's single-threaded step, while the slot still holds the
  rows) it eagerly exports + encodes the resident's KV into the worker's
  :class:`FleetKvServer` blob store and publishes the directory entries;
  at remove time it withdraws them. Eager encoding is the concurrency
  design: peer fetches are served entirely from the lock-guarded blob
  store by daemon threads — no serve thread ever touches the backend.

* :class:`FleetKvServer` / :class:`FleetCacheClient` — the wire path. The
  server is the PR 17 :class:`~uccl_tpu.serving.kv_tiers.KvTierServer`
  behind a :class:`~uccl_tpu.p2p.channel.ChannelAcceptor`; the client
  lazily dials owners advertised in the store and fetches over
  :class:`~uccl_tpu.serving.kv_tiers.RemoteKVTier` (CRC-verified,
  counted on ``p2p_bytes_total{verb="kv_tier"}``), importing rows
  [0, matched) into the admitted request's OWN slot.

Staleness discipline (tested): the directory is a *hint*, never an
authority. A stale entry (owner evicted the blob, or died) degrades to
the cold miss the admission already counted — ``fleet_cache_stale_total``
marks it, the entry is tombstoned, and the request prefills from 0,
bit-exact. Wrong bytes are impossible: directory keys digest the exact
namespaced token bytes, blob keys are never reused, and the wire path is
CRC-checked. A fetched prefix then self-propagates: when the request
retires, its own trie parks (and re-publishes) the prefix locally.

Counters/gauges (docs/OBSERVABILITY.md): ``fleet_cache_hits_total``,
``fleet_cache_stale_total``, ``fleet_cache_errors_total{reason}``,
``fleet_cache_tokens_imported_total``, ``fleet_dir_invalidations_total``,
gauge ``fleet_dir_resident_entries``.
"""

from __future__ import annotations

import hashlib
import json
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from uccl_tpu import obs
from uccl_tpu.serving.kv_tiers import (
    KvTierServer,
    RemoteKVTier,
    decode_entry,
    encode_entry,
)
from uccl_tpu.serving.prefix_cache import PrefixCache
from uccl_tpu.utils.logging import get_logger

_log = get_logger("P2P")

_HITS = obs.counter(
    "fleet_cache_hits_total",
    "local prefix-cache misses served by a fleet peer: directory hit + "
    "remote fetch + CRC-verified import into the admitted slot",
)
_STALE = obs.counter(
    "fleet_cache_stale_total",
    "directory hits whose owner no longer held the entry at fetch time "
    "(evicted or dead) — degraded to the already-counted cold miss",
)
_ERRORS = obs.counter(
    "fleet_cache_errors_total",
    "fleet cache-plane failures by reason (publish/fetch/peer-dial) — "
    "every one degrades to a local miss, never an engine fault",
)
_TOKENS_IMPORTED = obs.counter(
    "fleet_cache_tokens_imported_total",
    "prompt tokens whose prefill compute was skipped via a cross-worker "
    "fetch (the fleet-tier analogue of prefix_cache_tokens_reused_total)",
)
_INVALIDATIONS = obs.counter(
    "fleet_dir_invalidations_total",
    "directory entries tombstoned because their owner was declared dead "
    "(chaos/heartbeat path) or discovered stale at fetch time",
)
_DIR_RESIDENT = obs.gauge(
    "fleet_dir_resident_entries",
    "directory entries this worker currently publishes (one per "
    "chunk-aligned prefix depth per resident)",
)

_DIR_PREFIX = "fdir/"
_IDX_PREFIX = "fdir_idx/"
_EP_PREFIX = "fleet_ep/"
_TOMBSTONE = b"{}"


def _digest(path: List[bytes]) -> str:
    """Directory key digest of a chunk-key path. The path bytes ARE the
    trie's namespaced chunk keys (``ns + \\x00 + token bytes``), so equal
    digests mean equal tokens in the same tenant/adapter namespace."""
    return hashlib.sha1(b"".join(path)).hexdigest()


class _ChunkShim:
    """Duck-typed ``self`` for :meth:`PrefixCache._chunks`, so directory
    lookups compute byte-identical keys to the tries they index (one
    implementation, zero drift)."""

    __slots__ = ("chunk",)

    def __init__(self, chunk: int):
        self.chunk = chunk


class FleetDirectory:
    """The shared prefix directory, per-worker view.

    Layout over the store (SET/GET only):

    * ``fdir/<sha1(path[:d])>`` -> JSON ``{"o": owner, "k": blob key,
      "t": d*chunk, "x": exact, "nb": blob bytes}`` — one entry per
      published prefix depth; ``{}`` is a tombstone.
    * ``fdir_idx/<worker>`` -> JSON list of the dir keys ``worker`` has
      ever published — the invalidation fan-out for a dead owner. Only
      its owner ever writes it (no cross-writer race).

    Publishing every depth is what makes lookup a longest-prefix-match:
    a requester probes its own usable depths deepest-first and the first
    live entry wins. Last-writer-wins on a shared shallow prefix is fine —
    the directory is a hint and the fetch path tolerates staleness.
    """

    def __init__(self, store, worker: str, chunk: int):
        self.store = store
        self.worker = worker
        self.chunk = int(chunk)
        self._shim = _ChunkShim(self.chunk)
        # dir key -> blob key we last wrote there (our local mirror; a
        # peer may have overwritten since — fetch staleness covers that)
        self._mine: Dict[str, int] = {}
        self._indexed: set = set()  # every dir key ever in our index
        self._lock = threading.Lock()

    # -- publish side ------------------------------------------------------
    def publish(self, path: List[bytes], fleet_key: int, exact: bool,
                nbytes: int) -> List[str]:
        """Register one resident at EVERY prefix depth of ``path``;
        returns the dir keys written (the withdraw handle)."""
        keys = []
        with self._lock:
            for d in range(1, len(path) + 1):
                dk = _DIR_PREFIX + _digest(path[:d])
                val = {"o": self.worker, "k": int(fleet_key),
                       "t": d * self.chunk, "x": bool(exact),
                       "nb": int(nbytes)}
                self.store.set(dk, json.dumps(val).encode())
                self._mine[dk] = int(fleet_key)
                keys.append(dk)
            new_idx = [k for k in keys if k not in self._indexed]
            if new_idx:
                self._indexed.update(new_idx)
                self.store.set(_IDX_PREFIX + self.worker,
                               json.dumps(sorted(self._indexed)).encode())
            _DIR_RESIDENT.set(len(self._mine))
        return keys

    def withdraw(self, dir_keys: List[str], fleet_key: int) -> None:
        """Tombstone the dir keys still pointing at ``fleet_key``. A key
        since re-published for a newer local resident is left alone."""
        with self._lock:
            for dk in dir_keys:
                if self._mine.get(dk) != int(fleet_key):
                    continue
                self.store.set(dk, _TOMBSTONE)
                del self._mine[dk]
            _DIR_RESIDENT.set(len(self._mine))

    # -- lookup side -------------------------------------------------------
    def _keys_of(self, prompt, ns: str) -> List[bytes]:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        usable = (int(prompt.size) - 1) // self.chunk
        if usable < 1:
            return []
        return list(PrefixCache._chunks(self._shim, prompt, usable, ns))

    def lookup(self, prompt, ns: str = "") -> Optional[dict]:
        """Deepest-first longest-prefix-match over the directory. Returns
        ``{"owner", "key", "tokens", "exact", "nbytes", "dir_key"}`` for
        the deepest live entry, or None. Capped at the requester's own
        usable depth, so ``tokens`` is always a resumable boundary."""
        path = self._keys_of(prompt, ns)
        for d in range(len(path), 0, -1):
            dk = _DIR_PREFIX + _digest(path[:d])
            raw = self.store.get(dk)
            if raw is None:
                continue
            try:
                val = json.loads(raw.decode())
            except ValueError:
                continue
            if not val.get("o"):
                continue  # tombstone
            return {"owner": val["o"], "key": int(val["k"]),
                    "tokens": int(val["t"]), "exact": bool(val.get("x", True)),
                    "nbytes": int(val.get("nb", 0)), "dir_key": dk}
        return None

    def tombstone(self, dir_key: str) -> None:
        """Kill one directory entry discovered stale at fetch time (any
        worker may do this — the owner already lost the bytes)."""
        self.store.set(dir_key, _TOMBSTONE)
        _INVALIDATIONS.inc()

    def invalidate_owner(self, dead: str) -> int:
        """Tombstone every directory entry still owned by ``dead`` (the
        chaos/heartbeat path: a survivor sweeps the dead worker's index so
        the fleet stops chasing a peer that cannot answer). Idempotent;
        returns the number of entries killed."""
        raw = self.store.get(_IDX_PREFIX + dead)
        if raw is None:
            return 0
        try:
            keys = json.loads(raw.decode())
        except ValueError:
            return 0
        killed = 0
        for dk in keys:
            cur = self.store.get(dk)
            if cur is None:
                continue
            try:
                val = json.loads(cur.decode())
            except ValueError:
                continue
            if val.get("o") == dead:
                self.store.set(dk, _TOMBSTONE)
                _INVALIDATIONS.inc()
                killed += 1
        return killed


class FleetKvServer(KvTierServer):
    """The worker's published-blob store: a PR 17 tier server fed
    *locally* by the publisher and served *remotely* behind a
    :class:`ChannelAcceptor` (one daemon serve loop per dialing peer,
    looping through idle timeouts — a fleet peer channel is long-lived).
    All storage ops are lock-guarded in the base class, so the publisher
    (engine thread) and the serve loops never race."""

    def __init__(self, capacity_bytes: int, ep=None,
                 idle_timeout_ms: int = 2000):
        super().__init__(capacity_bytes)
        self.idle_timeout_ms = int(idle_timeout_ms)
        self._acceptor = None
        self._closing = False
        if ep is not None:
            from uccl_tpu.p2p.channel import ChannelAcceptor

            self._acceptor = ChannelAcceptor(ep, self._serve_peer)

    def _serve_peer(self, chan) -> None:
        def loop():
            while not self._closing:
                try:
                    self.serve(chan, self.idle_timeout_ms)
                except TimeoutError:
                    continue  # idle peer: keep the channel warm
                except Exception as e:
                    if not self._closing:
                        _ERRORS.inc(reason=type(e).__name__)
                    return

        threading.Thread(target=loop, daemon=True).start()

    def put_local(self, key: int, blob: np.ndarray, meta: dict) -> List[int]:
        """Publisher-side insert (no wire): reserve + store; returns the
        keys LRU-evicted to make room (their directory entries must be
        withdrawn by the caller)."""
        evicted = self._reserve(int(blob.nbytes))
        self._put(int(key), blob, meta)
        return evicted

    def drop_local(self, key: int) -> None:
        self._del(int(key))

    def close(self) -> None:
        self._closing = True
        if self._acceptor is not None:
            self._acceptor.close()


class FleetCachePublisher:
    """The :class:`PrefixCache` listener: mirrors the trie's residency
    into the blob store + directory.

    ``on_insert`` runs on the engine step thread while the parked slot
    still holds its rows — it exports + encodes ONCE (lossless ``raw``
    for device residents, the already-encoded blob for T1 refs) so serve
    threads only ever read the store. T2 refs are not published: their
    bytes already live on a remote tier peer and advertising a
    triple-hop fetch is worse than a cold prefill. Every failure is
    counted and swallowed — publishing is best-effort, admission never
    blocks on the fleet plane."""

    def __init__(self, directory: FleetDirectory, server: FleetKvServer,
                 backend, tiers=None):
        self.directory = directory
        self.server = server
        self.backend = backend
        self.tiers = tiers
        self.chunk = directory.chunk
        self._next_key = 0
        # resident -> (blob key, [dir keys]); blob key -> resident
        self._published: Dict = {}
        self._by_key: Dict[int, object] = {}

    def _encode(self, resident, path) -> Optional[Tuple]:
        if isinstance(resident, (int, np.integer)):
            n = len(path) * self.chunk
            k_rows, v_rows = self.backend.export_slot_kv(int(resident), 0, n)
            blob, meta = encode_entry(k_rows, v_rows)  # lossless raw
            return blob, meta, True
        tier = getattr(resident, "tier", None)
        if tier == "t1" and self.tiers is not None:
            ent = self.tiers.t1.get(resident.key)
            if ent is None:
                return None
            blob, meta, _ = ent  # shared array object: no byte copy
            return blob, meta, bool(getattr(resident, "exact", True))
        return None  # t2 (or unknown): bytes are not local — don't advertise

    # -- PrefixCache listener protocol ------------------------------------
    def on_insert(self, resident, path: List[bytes]) -> None:
        try:
            if resident in self._published:
                return
            enc = self._encode(resident, path)
            if enc is None:
                return
            blob, meta, exact = enc
            if blob.nbytes > self.server.capacity_bytes:
                return
            key = self._next_key
            self._next_key += 1
            for ek in self.server.put_local(key, blob, meta):
                self._withdraw_key(ek)
            dir_keys = self.directory.publish(path, key, exact,
                                              int(blob.nbytes))
            self._published[resident] = (key, dir_keys)
            self._by_key[key] = resident
        except Exception as e:
            _ERRORS.inc(reason="publish")
            _log.warning("fleet: publish failed (%s: %s)",
                         type(e).__name__, e)

    def on_remove(self, resident) -> None:
        try:
            pub = self._published.pop(resident, None)
            if pub is None:
                return
            key, dir_keys = pub
            self._by_key.pop(key, None)
            self.directory.withdraw(dir_keys, key)
            self.server.drop_local(key)
        except Exception as e:
            _ERRORS.inc(reason="withdraw")
            _log.warning("fleet: withdraw failed (%s: %s)",
                         type(e).__name__, e)

    def _withdraw_key(self, key: int) -> None:
        """A blob LRU-evicted by capacity pressure: de-publish it (the
        local trie entry is untouched — only the fleet copy is gone)."""
        resident = self._by_key.pop(key, None)
        if resident is None:
            return
        _, dir_keys = self._published.pop(resident)
        self.directory.withdraw(dir_keys, key)


class FleetCacheClient:
    """The fetch side: consult the directory on a local trie miss and
    pull the entry from the owning peer into the admitted slot.

    Peers are dialed lazily from their ``fleet_ep/<worker>`` store
    advertisement; a peer that fails ``fail_limit`` consecutive times
    latches dead (the PR 17 remote-tier discipline) so a dying worker
    costs a bounded number of timeouts, after which its directory entries
    are swept via :meth:`FleetDirectory.invalidate_owner`."""

    def __init__(self, directory: FleetDirectory, worker: str, ep, store,
                 *, max_entry_bytes: int, n_paths: int = 2,
                 fail_limit: int = 3, timeout_ms: int = 10000):
        self.directory = directory
        self.worker = worker
        self.ep = ep
        self.store = store
        self.max_entry_bytes = int(max_entry_bytes)
        self.n_paths = int(n_paths)
        self.fail_limit = int(fail_limit)
        self.timeout_ms = int(timeout_ms)
        self._remotes: Dict[str, Optional[RemoteKVTier]] = {}
        self._fails: Dict[str, int] = {}

    def _remote_for(self, owner: str) -> Optional[RemoteKVTier]:
        if owner in self._remotes:
            return self._remotes[owner]
        remote = None
        raw = self.store.get(_EP_PREFIX + owner)
        if raw is not None:
            try:
                from uccl_tpu.p2p.channel import Channel

                ip, port = raw.decode().rsplit(":", 1)
                chan = Channel.connect(self.ep, ip, int(port),
                                       n_paths=self.n_paths,
                                       meta=self.worker.encode())
                remote = RemoteKVTier(chan, self.max_entry_bytes,
                                      timeout_ms=self.timeout_ms)
            except Exception as e:
                _ERRORS.inc(reason="dial")
                _log.warning("fleet: dialing %s failed (%s: %s)", owner,
                             type(e).__name__, e)
                # an advertised owner that cannot be dialed is a dead
                # peer from this worker's vantage — same post-mortem
                # moment as a health-detector DEAD transition
                obs.flight_trigger(
                    "peer_dead", key=f"fleet:{owner}", peer=owner,
                    source="fleet_dial", exc=f"{type(e).__name__}: {e}",
                    directory_entries=len(getattr(
                        self.directory, "_entries", ()) or ()))
        self._remotes[owner] = remote
        return remote

    def _peer_failed(self, owner: str, exc: Exception) -> None:
        _ERRORS.inc(reason="fetch")
        n = self._fails.get(owner, 0) + 1
        self._fails[owner] = n
        dead = n >= self.fail_limit
        _log.warning("fleet: fetch from %s failed (%s: %s) — %d/%d%s",
                     owner, type(exc).__name__, exc, n, self.fail_limit,
                     "; peer latched dead" if dead else "")
        if dead:
            remote = self._remotes.get(owner)
            self._remotes[owner] = None  # latch: stop dialing/fetching
            if remote is not None:
                try:
                    remote.close()
                except Exception:
                    pass
            swept = self.directory.invalidate_owner(owner)
            obs.flight_trigger(
                "peer_dead", key=f"fleet:{owner}", peer=owner,
                source="fleet_fetch", fails=n,
                exc=f"{type(exc).__name__}: {exc}",
                entries_invalidated=swept)

    def fetch(self, prompt, ns: str, slot: int, backend) -> Tuple[int, bool]:
        """Serve a local miss from the fleet if possible. Returns
        ``(matched, exact)`` — ``(0, True)`` when the fleet has nothing
        usable (no directory hit, stale owner, dead peer), in which case
        the admission stays the cold miss it already counted."""
        hit = self.directory.lookup(prompt, ns)
        if hit is None or hit["owner"] == self.worker:
            # a self-owned hit means OUR trie just missed what we
            # published — a remove racing the lookup; it is a plain miss
            return 0, True
        owner = hit["owner"]
        remote = self._remote_for(owner)
        if remote is None:
            return 0, True
        with obs.span("fleet.fetch", track="engine", owner=owner,
                      slot=slot, tokens=hit["tokens"]):
            try:
                got = remote.get(hit["key"])
            except Exception as e:
                self._peer_failed(owner, e)
                return 0, True
            self._fails[owner] = 0
            if got is None:
                # the owner LRU-dropped the blob between our directory
                # read and the fetch: the counted cold miss, never wrong
                # bytes — and tombstone so the fleet stops chasing it
                _STALE.inc()
                self.directory.tombstone(hit["dir_key"])
                return 0, True
            blob, meta = got
            try:
                k_rows, v_rows = decode_entry(blob, meta)
            except Exception as e:
                self._peer_failed(owner, e)
                return 0, True
            n = hit["tokens"]
            if k_rows.shape[1] < n:
                _STALE.inc()
                self.directory.tombstone(hit["dir_key"])
                return 0, True
            backend.import_slot_kv(slot, k_rows[:, :n], v_rows[:, :n],
                                   length=n)
        _HITS.inc()
        _TOKENS_IMPORTED.inc(n)
        return n, hit["exact"]

    def close(self) -> None:
        for remote in self._remotes.values():
            if remote is not None:
                try:
                    remote.close()
                except Exception:
                    pass
        self._remotes.clear()


class FleetWorker:
    """One process's whole fleet plane, assembled: directory view +
    published-blob server + fetch client, advertised in the store.

    The engine binds it with :meth:`ServingEngine.attach_fleet`, which
    wires ``publisher`` onto the trie listener hook and consults
    :meth:`fetch` on local misses. ``ip`` defaults to loopback (the
    single-host bench topology); multi-host deployments pass the NIC
    address the endpoint listens on."""

    def __init__(self, name: str, store, ep, *, chunk: int,
                 capacity_bytes: int, max_entry_bytes: int,
                 backend=None, tiers=None, ip: str = "127.0.0.1",
                 n_paths: int = 2, fail_limit: int = 3,
                 timeout_ms: int = 10000):
        self.worker = name
        self.store = store
        self.ep = ep
        self.directory = FleetDirectory(store, name, chunk)
        self.server = FleetKvServer(capacity_bytes, ep)
        store.set(_EP_PREFIX + name, f"{ip}:{ep.port}".encode())
        self.publisher = FleetCachePublisher(self.directory, self.server,
                                             backend, tiers)
        self.client = FleetCacheClient(
            self.directory, name, ep, store,
            max_entry_bytes=max_entry_bytes, n_paths=n_paths,
            fail_limit=fail_limit, timeout_ms=timeout_ms,
        )

    def fetch(self, prompt, ns: str, slot: int, backend) -> Tuple[int, bool]:
        return self.client.fetch(prompt, ns, slot, backend)

    def invalidate_owner(self, dead: str) -> int:
        return self.directory.invalidate_owner(dead)

    def close(self) -> None:
        self.client.close()
        self.server.close()
