"""Multi-tenant LoRA adapter store for the serving engine (ISSUE 18).

Per-tenant low-rank (A, B) adapter pairs on the attention projections
``wq`` and ``wv``, applied in the slot primitives as a **batched per-slot
fused delta**: each slot carries an adapter row id, the compiled program
gathers its (A, B) from stacked device tables and adds
``(x @ A) @ B`` beside the base matmul. Rank is zero-padded to the
store's ``max_rank`` so ONE compiled program serves mixed-rank batches,
and **row 0 is all-zeros** — adapter-free slots ride it as the zero-rank
fast path (their delta is exactly 0.0).

Residency follows the prefix-trie's LRU discipline: the device tables
hold at most ``capacity`` adapters; ``acquire`` of a resident tenant is a
hit, of a published-but-evicted tenant a miss that re-stages it (evicting
the least-recently-used row whose refcount is 0 — rows pinned by live
requests are never evicted). Published host copies are the bounded
archive the misses restage from.

Distribution: adapters arrive as :class:`~uccl_tpu.p2p.weight_push.
WeightSnapshot` versioned snapshots (:meth:`AdapterStore.ingest`) — the
PR 14 push plane is the wire; the snapshot name carries the tenant, its
version becomes the adapter version (the prefix-cache namespace component
that keeps adapter-divergent KV from cross-hitting).

Counters (docs/OBSERVABILITY.md): ``adapter_cache_hits_total``,
``adapter_cache_misses_total``, ``adapter_cache_evictions_total``, gauge
``adapter_cache_resident``.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np

from uccl_tpu import obs

_HITS = obs.counter(
    "adapter_cache_hits_total",
    "adapter acquisitions served from a device-resident table row",
)
_MISSES = obs.counter(
    "adapter_cache_misses_total",
    "adapter acquisitions that had to restage an evicted/new adapter",
)
_EVICTIONS = obs.counter(
    "adapter_cache_evictions_total",
    "resident adapters evicted LRU-first to restage another tenant",
)
_RESIDENT = obs.gauge(
    "adapter_cache_resident",
    "adapters currently staged in the device tables",
)

#: the two projections adapters apply to (query and value — the classic
#: LoRA target set; one fusion point in ``_forward_slots`` serves both
#: stacks, the MoE path wraps it via its ffn hook)
TARGETS = ("wq", "wv")


def make_lora(key, n_layers: int, dim: int, q_out: int, kv_out: int,
              rank: int, scale: float = 0.05):
    """A random LoRA tree for tests/benches: ``{"wq": {"a", "b"}, "wv":
    {"a", "b"}}`` with A ~ N(0, 1/sqrt(dim)) and B ~ N(0, scale) — both
    nonzero so the fused delta is exercised, small so base behavior
    dominates. Shapes: a [L, dim, rank], b [L, rank, out]."""
    import jax

    ks = jax.random.split(key, 4)
    s_in = 1.0 / math.sqrt(dim)

    def rnd(kk, shape, s):
        return np.asarray(jax.random.normal(kk, shape), np.float32) * s

    return {
        "wq": {"a": rnd(ks[0], (n_layers, dim, rank), s_in),
               "b": rnd(ks[1], (n_layers, rank, q_out), scale)},
        "wv": {"a": rnd(ks[2], (n_layers, dim, rank), s_in),
               "b": rnd(ks[3], (n_layers, rank, kv_out), scale)},
    }


def materialize(params, tree):
    """Dense-materialize an adapter into a copy of ``params`` —
    ``wq' = wq + A_q @ B_q``, ``wv' = wv + A_v @ B_v`` — the oracle the
    fused per-slot delta is tested against (fp tolerance: the fused form
    computes ``(x@A)@B``, the materialized form ``x@(W + A@B)``)."""
    import jax.numpy as jnp

    blocks = dict(params["blocks"])
    for t in TARGETS:
        a = jnp.asarray(tree[t]["a"], jnp.float32)
        b = jnp.asarray(tree[t]["b"], jnp.float32)
        blocks[t] = blocks[t] + jnp.einsum("lhr,lro->lho", a, b)
    out = dict(params)
    out["blocks"] = blocks
    return out


class AdapterStore:
    """Bounded, LRU-evicted, refcount-pinned store of per-tenant LoRA
    adapters with rank-padded stacked device tables.

    ``capacity`` is the number of device table rows (besides the zero
    row); published host copies are unbounded by default (they are tiny
    next to KV) but can be capped with ``max_published``.
    """

    def __init__(self, n_layers: int, dim: int, q_out: int, kv_out: int,
                 *, max_rank: int = 8, capacity: int = 4,
                 max_published: Optional[int] = None):
        if max_rank < 1:
            raise ValueError(f"max_rank must be >= 1, got {max_rank}")
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.n_layers, self.dim = n_layers, dim
        self.q_out, self.kv_out = q_out, kv_out
        self.max_rank, self.capacity = max_rank, capacity
        self.max_published = max_published
        # published archive: tenant -> {"version", "rank", "<target>": (a, b)}
        self._published: Dict[str, dict] = {}
        self._pub_seq: Dict[str, int] = {}  # publish-LRU for max_published
        # residency: device row r in [1, capacity] holds one tenant
        self._row_tenant: List[Optional[str]] = [None] * (capacity + 1)
        self._rows: Dict[str, int] = {}  # tenant -> row
        self._refcount = [0] * (capacity + 1)
        self._lru = [0] * (capacity + 1)
        self._seq = 0
        # host staging for the stacked tables, row 0 permanently zero
        t = capacity + 1
        self._host = {
            tgt: (np.zeros((n_layers, t, dim, max_rank), np.float32),
                  np.zeros((n_layers, t, max_rank,
                            q_out if tgt == "wq" else kv_out), np.float32))
            for tgt in TARGETS
        }
        self._tables = None  # device copies, rebuilt lazily on dirty
        self._dirty = True

    # -- publishing (the weight-push consumer) ----------------------------
    def publish(self, tenant: str, tree, *,
                version: Optional[int] = None) -> int:
        """Register (or refresh) ``tenant``'s adapter from a LoRA tree.
        Returns the adapter version (auto-incremented unless pinned). A
        refresh of a RESIDENT tenant restages its table rows in place —
        live slots see the new weights on the next compiled call."""
        rank = None
        clean = {}
        for tgt in TARGETS:
            a = np.asarray(tree[tgt]["a"], np.float32)
            b = np.asarray(tree[tgt]["b"], np.float32)
            out = self.q_out if tgt == "wq" else self.kv_out
            if a.shape[:2] != (self.n_layers, self.dim) or a.ndim != 3:
                raise ValueError(
                    f"adapter {tenant!r} {tgt}.a shape {a.shape} != "
                    f"[{self.n_layers}, {self.dim}, rank]"
                )
            if b.shape != (self.n_layers, a.shape[2], out):
                raise ValueError(
                    f"adapter {tenant!r} {tgt}.b shape {b.shape} != "
                    f"[{self.n_layers}, {a.shape[2]}, {out}]"
                )
            if rank is None:
                rank = a.shape[2]
            elif a.shape[2] != rank:
                raise ValueError(
                    f"adapter {tenant!r} mixes ranks across targets "
                    f"({rank} vs {a.shape[2]})"
                )
            clean[tgt] = (a, b)
        if rank > self.max_rank:
            raise ValueError(
                f"adapter {tenant!r} rank {rank} exceeds the store's "
                f"max_rank {self.max_rank}"
            )
        prev = self._published.get(tenant)
        if version is None:
            version = prev["version"] + 1 if prev else 1
        clean["version"] = int(version)
        clean["rank"] = int(rank)
        self._published[tenant] = clean
        self._seq += 1
        self._pub_seq[tenant] = self._seq
        row = self._rows.get(tenant)
        if row is not None:  # live refresh of a resident adapter
            self._stage(row, clean)
        if (self.max_published is not None
                and len(self._published) > self.max_published):
            # drop the least-recently published NON-resident archive copy
            # (and its publish-order stamp — leaving it would leak one
            # _pub_seq entry per evicted tenant under publish/evict churn)
            victims = [t for t in self._published if t not in self._rows]
            if victims:
                victim = min(victims, key=self._pub_seq.__getitem__)
                del self._published[victim]
                del self._pub_seq[victim]
        return int(version)

    def ingest(self, snapshot) -> int:
        """Consume a :class:`~uccl_tpu.p2p.weight_push.WeightSnapshot`:
        the name's last ``/`` component is the tenant (``adapter/acme``
        → ``acme``), the snapshot version becomes the adapter version."""
        tenant = snapshot.name.rsplit("/", 1)[-1]
        return self.publish(tenant, snapshot.tree(),
                            version=snapshot.version)

    def has(self, tenant: str) -> bool:
        return tenant in self._published

    def can_acquire(self, tenant: Optional[str]) -> bool:
        """True when :meth:`acquire` would succeed without raising: no
        adapter (row 0), an already-resident row, or a published adapter
        with a free or unpinned (evictable) table row to land on.
        Non-mutating — the engine's admission gate, so a request whose
        adapter cannot be pinned right now is deferred in queue instead
        of crashing ``step()`` mid-admission."""
        if tenant is None:
            return True
        if tenant not in self._published:
            return False
        return tenant in self._rows or self.n_available_rows() > 0

    def is_resident(self, tenant: str) -> bool:
        """True when the tenant's adapter currently occupies a table row
        (an acquire would be a refcount hit, never needing a free row)."""
        return tenant in self._rows

    def n_available_rows(self, exclude=()) -> int:
        """Rows a NON-resident acquire could land on right now: free rows
        plus unpinned resident rows (eviction candidates), minus unpinned
        rows whose tenant is in ``exclude``. The engine's batch admission
        gate passes the resident adapters the batch is about to pin as
        ``exclude``, so one batch can never plan more fresh stagings than
        the table can hold once its own resident hits are pinned."""
        n = 0
        for r in range(1, self.capacity + 1):
            t = self._row_tenant[r]
            if t is None:
                n += 1
            elif self._refcount[r] == 0 and t not in exclude:
                n += 1
        return n

    def version(self, tenant: str) -> int:
        return int(self._published[tenant]["version"])

    def tenants(self) -> List[str]:
        return sorted(self._published)

    @property
    def n_resident(self) -> int:
        return len(self._rows)

    # -- residency --------------------------------------------------------
    def _stage(self, row: int, rec: dict) -> None:
        r = rec["rank"]
        for tgt in TARGETS:
            a, b = rec[tgt]
            ha, hb = self._host[tgt]
            ha[:, row] = 0.0
            hb[:, row] = 0.0
            ha[:, row, :, :r] = a
            hb[:, row, :r, :] = b
        self._dirty = True

    def acquire(self, tenant: Optional[str]) -> int:
        """Pin ``tenant``'s adapter into a device table row and return the
        row id (0 for ``tenant=None`` — the zero-rank fast path, never
        pinned). Resident → hit; published-but-evicted → miss + restage
        (LRU-evicting an unpinned row). Raises ``KeyError`` for an
        unpublished tenant and ``RuntimeError`` when every row is pinned
        by live requests."""
        if tenant is None:
            return 0
        rec = self._published.get(tenant)
        if rec is None:
            raise KeyError(f"no published adapter for tenant {tenant!r}")
        row = self._rows.get(tenant)
        self._seq += 1
        if row is not None:
            _HITS.inc()
            self._refcount[row] += 1
            self._lru[row] = self._seq
            return row
        _MISSES.inc()
        free = [r for r in range(1, self.capacity + 1)
                if self._row_tenant[r] is None]
        if free:
            row = free[0]
        else:
            victims = [r for r in range(1, self.capacity + 1)
                       if self._refcount[r] == 0]
            if not victims:
                raise RuntimeError(
                    "adapter store exhausted: every table row is pinned "
                    "by a live request"
                )
            row = min(victims, key=self._lru.__getitem__)
            del self._rows[self._row_tenant[row]]
            _EVICTIONS.inc()
        self._row_tenant[row] = tenant
        self._rows[tenant] = row
        self._refcount[row] = 1
        self._lru[row] = self._seq
        self._stage(row, rec)
        _RESIDENT.set(len(self._rows))
        return row

    def release(self, row: int) -> None:
        """Unpin one acquisition of ``row`` (row 0 is a no-op). The row
        stays resident — a refcount-0 row is evictable, not evicted."""
        if row == 0:
            return
        if self._refcount[row] <= 0:
            raise ValueError(f"release of unpinned adapter row {row}")
        self._refcount[row] -= 1

    # -- the compiled-program face ----------------------------------------
    def device_tables(self) -> dict:
        """``{"wq": (A, B), "wv": (A, B)}`` stacked jnp tables, shapes
        A [L, T, dim, max_rank] / B [L, T, max_rank, out] with T =
        capacity + 1 and row 0 zero. Rebuilt lazily after staging; table
        CONTENT changes never recompile (the tables are jit arguments of
        fixed shape)."""
        if self._dirty or self._tables is None:
            import jax.numpy as jnp

            self._tables = {
                tgt: (jnp.asarray(ha), jnp.asarray(hb))
                for tgt, (ha, hb) in self._host.items()
            }
            self._dirty = False
        return self._tables
