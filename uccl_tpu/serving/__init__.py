"""Continuous-batching serving engine (docs/SERVING.md).

Request scheduler + KV slot manager + serving metrics over the repo's
dense and MoE serving stacks: requests arrive at any time, share one fixed
KV slot pool, and each engine step admits, prefills, decodes and retires —
with every request's tokens bit-identical to the one-shot ``generate``
oracle.
"""

from uccl_tpu.serving.adapters import (  # noqa: F401
    AdapterStore, make_lora, materialize,
)
from uccl_tpu.serving.engine import (  # noqa: F401
    ChunkEvent, DenseBackend, MoEBackend, ServingEngine,
    replicate_backend,
)
from uccl_tpu.serving.sampling import SamplingParams  # noqa: F401
from uccl_tpu.serving.metrics import (  # noqa: F401
    ServingMetrics, percentile, percentiles_ms,
)
from uccl_tpu.serving.health import (  # noqa: F401
    DEAD, HEALTHY, SUSPECT, FailureDetector, abandon_engine,
)
from uccl_tpu.serving.kv_tiers import (  # noqa: F401
    HostKVTier, KvTierServer, RemoteKVTier, TieredKVCache, TierRef,
)
from uccl_tpu.serving.prefix_cache import PrefixCache  # noqa: F401
from uccl_tpu.serving.request import Request, RequestState  # noqa: F401
from uccl_tpu.serving.router import Router, replica_signals  # noqa: F401
from uccl_tpu.serving.scheduler import (  # noqa: F401
    PRIORITY_CLASSES, FIFOScheduler, PriorityScheduler,
    TenantFairScheduler,
)
from uccl_tpu.serving.slots import SlotPool  # noqa: F401
from uccl_tpu.serving.spec import Drafter, NGramDrafter  # noqa: F401

# uccl_tpu.serving.disagg (the prefill/decode worker pair over p2p) is
# imported explicitly by its consumers — it pulls in the p2p runtime.

__all__ = [
    "ChunkEvent", "DenseBackend", "MoEBackend", "ServingEngine",
    "ServingMetrics", "percentile", "percentiles_ms", "PrefixCache",
    "Request", "RequestState", "FIFOScheduler", "PriorityScheduler",
    "TenantFairScheduler", "PRIORITY_CLASSES", "Router",
    "replica_signals", "SlotPool",
    "Drafter", "NGramDrafter", "replicate_backend",
    "SamplingParams", "AdapterStore", "make_lora", "materialize",
    "FailureDetector", "HEALTHY", "SUSPECT", "DEAD", "abandon_engine",
    "TieredKVCache", "HostKVTier", "KvTierServer", "RemoteKVTier",
    "TierRef",
]
