"""Replica router: least-loaded admission over N serving engine replicas.

The heavy-traffic front end (docs/SERVING.md): UCCL's core move is
software-driven multi-path scheduling over dumb transports (PAPER.md §0.1);
the serving analogue sprays requests over a replica set using **live load
signals** instead of round-robin — the same signals the obs layer already
exports, read directly off each replica:

* **free slots** — ``pool.n_free``: immediate admission capacity;
* **token debt** — ``engine.pending_tokens()``: outstanding prefill +
  decode work in step-token units (the per-step spend currency of
  ``step_tokens``), queued AND in-slot — the forward-looking load;
* **recent queue wait** — mean of the last few ``queue_wait_ms`` samples:
  the realized scheduling delay, a lagging confirmation of the debt;
* **adoption backpressure** — for disaggregated prefill fleets
  (``disagg.PrefillWorker.adoption_backpressure()``): requests stuck
  waiting for a decode-side GRANT, so new prompts steer away from a
  prefill worker whose decode peer is saturated.

Selection is lexicographic — ``(debt + bp_tokens·backpressure,
-free_slots, queue_wait_ms, index)``, lowest wins — so the dominant
forward-looking signal decides and the rest break ties deterministically
(the index tail makes equal replicas round-robin-stable rather than
id-0-biased: it rotates with the routed count).

When the chosen replica rejects (bounded queue — the race between the
signal read and the submit), the router **spills over** to the next-best
replica (counted on ``serving_router_spillover_total``); when every
replica rejects, the request is rejected at the router (counted on
``serving_router_rejected_total{reason="saturated"}``) — sustained
overload is visible as a counter, never a hang. Every accepted admission
lands on ``serving_router_requests_total{replica=...}`` plus a ``route``
trace instant carrying the signals the decision was made from, so benches
label arms off real routing decisions (docs/OBSERVABILITY.md).

Replicas are in-process ``ServingEngine``s, or disagg ``PrefillWorker``s
(anything with an ``.engine`` and a ``submit``) — a prefill fleet routed
per-peer. Mixed sets are allowed.

**Fault tolerance** (docs/SERVING.md): with :meth:`Router.enable_health`
the router runs a :class:`~uccl_tpu.serving.health.FailureDetector` over
its replicas (in-process liveness probes — the heartbeat equivalent for
engines that share the process). SUSPECT replicas are excluded from new
routing but keep running (the grace window absorbs stalls without
churn); a DEAD replica's requests are recovered **exactly once**, keyed
by their PR 12 trace_id — queued requests resubmit to survivors under
the SAME trace context (no duplicate mint), active requests restart
from scratch on a survivor (a prefix-cache hit makes the recompute
cheap when available), and requests no survivor can take are counted
``lost``. Every outcome lands on
``serving_recovered_total{outcome=resubmitted|restarted|lost}`` and the
conservation invariant extends to ``submitted == completed + active +
queued + rejected + expired + lost`` across the fleet (the dead
replica's copies exit through its ``lost`` term; the survivors' re-runs
are new submissions there).

**Elastic membership**: :meth:`detach` is the graceful down-scale
primitive — drain admission, finish the replica's active work, hand
parked prefix-cache donors back, then remove it — and :meth:`attach`
the up-scale twin (``ep/elastic.admit_warm_replica`` builds the warm
spare off a pushed weight snapshot and attaches it here).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from uccl_tpu import obs
from uccl_tpu.serving.engine import ServingEngine
from uccl_tpu.serving.health import DEAD, FailureDetector
from uccl_tpu.serving.metrics import ServingMetrics
from uccl_tpu.serving.request import Request, RequestState

_ROUTED = obs.counter(
    "serving_router_requests_total",
    "requests admitted per replica by the least-loaded router",
)
_SPILLOVER = obs.counter(
    "serving_router_spillover_total",
    "admissions that fell through to a lower-ranked replica after the "
    "chosen one rejected (bounded-queue race)",
)
_ROUTER_REJECTS = obs.counter(
    "serving_router_rejected_total",
    "requests rejected at the router: reason=saturated means every "
    "replica's queue was full",
)
_REPLICAS = obs.gauge(
    "serving_router_replicas", "replica count behind the serving router"
)
_DETACHED = obs.counter(
    "serving_router_detached_total",
    "replicas gracefully drained out of the set (the elastic down-scale "
    "primitive: admission drained, active work finished, parked "
    "prefix-cache donors handed back before removal)",
)
_ATTACHED = obs.counter(
    "serving_router_attached_total",
    "replicas added to a live router (warm-spare admission / elastic "
    "up-scale)",
)
_CACHE_STEERED = obs.counter(
    "serving_router_cache_steered_total",
    "admissions whose winning replica was ranked with a non-zero cached "
    "prefix (local trie or fleet-directory longest-prefix match) — the "
    "cache-aware steering signal actually changing placement",
)
# declared in serving/health.py (one family, shared label space)
_RECOVERED_COUNTER = obs.counter("serving_recovered_total")


def replica_signals(replica, *, recent: int = 8) -> Dict[str, float]:
    """The live load signals for one replica, as the router reads them.
    Exposed as a function so tests and benches can audit the exact inputs
    a routing decision saw."""
    eng = engine_of(replica)
    qw = eng.metrics.queue_wait_s[-recent:]
    bp = 0
    hook = getattr(replica, "adoption_backpressure", None)
    if callable(hook):
        bp = int(hook())
    return {
        "free_slots": eng.pool.n_free,
        "queued": eng.sched.qsize,
        "debt_tokens": eng.pending_tokens(),
        "queue_wait_ms": round(sum(qw) / len(qw) * 1e3, 3) if qw else 0.0,
        "backpressure": bp,
    }


def engine_of(replica) -> ServingEngine:
    """The ServingEngine inside a replica (identity for a bare engine,
    ``.engine`` for a disagg PrefillWorker)."""
    return getattr(replica, "engine", replica)


class Router:
    """Least-loaded front end over N serving replicas.

    ``bp_tokens`` prices one unit of adoption backpressure (one request
    stuck awaiting decode capacity) in debt-token units when ranking —
    the default assumes a stuck request is worth about one typical
    request's work.
    """

    def __init__(self, replicas: List, *, bp_tokens: int = 64,
                 detector: Optional[FailureDetector] = None,
                 directory=None):
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.replicas = list(replicas)
        self.bp_tokens = bp_tokens
        # optional fleet prefix-cache directory (serving/fleet.py): ranking
        # then credits the replica OWNING the deepest published prefix of
        # the submitted prompt, so shared system prompts steer toward the
        # worker that already holds their KV (cache-aware steering)
        self.directory = directory
        self.routed = [0] * len(self.replicas)  # per-replica admit counts
        # stable per-replica ids: counter labels and detector peers keep
        # their identity across detach/attach (list indices shift)
        self._pids = list(range(len(self.replicas)))
        self._next_pid = len(self.replicas)
        self._dead: set = set()      # pids already recovered — THE
        # exactly-once guard (one recovery per replica; a request object
        # lives on exactly one replica, so no trace re-runs while a live
        # incarnation exists)
        self._draining: set = set()  # pids mid-detach (no new routes)
        self.recoveries: List[Dict] = []  # audit log (the chaos bench)
        self.detector = detector
        if detector is not None:
            for i, r in enumerate(self.replicas):
                detector.register(self._pids[i], probe=self._probe_for(r))
        _REPLICAS.set(len(self.replicas))

    # -- health --------------------------------------------------------
    @staticmethod
    def _probe_for(replica):
        """In-process liveness probe: alive unless the engine was
        ``kill()``ed — the heartbeat equivalent for replicas sharing the
        router's process (a real remote peer heartbeats over notifs
        instead; see serving/health.py)."""
        eng = engine_of(replica)
        return lambda: not eng.dead

    def enable_health(self, *, suspect_after_s: float = 0.5,
                      dead_after_s: float = 1.5,
                      clock=None) -> FailureDetector:
        """Attach a failure detector over the current replica set (every
        replica registered with an in-process liveness probe). Ticked at
        every :meth:`step`; DEAD replicas are recovered in place."""
        kw = {"suspect_after_s": suspect_after_s,
              "dead_after_s": dead_after_s}
        if clock is not None:
            kw["clock"] = clock
        self.detector = FailureDetector(**kw)
        for i, r in enumerate(self.replicas):
            self.detector.register(self._pids[i], probe=self._probe_for(r))
        return self.detector

    def _routable(self, i: int) -> bool:
        pid = self._pids[i]
        if pid in self._dead or pid in self._draining:
            return False
        if engine_of(self.replicas[i]).dead:
            return False  # killed but not yet detector-confirmed
        if self.detector is not None and not self.detector.is_routable(
                str(pid)):
            return False
        return True

    def _health_tick(self) -> None:
        if self.detector is None:
            return
        for peer, state in self.detector.tick():
            if state != DEAD:
                continue
            try:
                idx = self._pids.index(int(peer))
            except ValueError:
                continue  # already detached
            self._recover(idx)

    def _recover(self, idx: int) -> None:
        """Recover a DEAD replica's requests exactly once: evacuate its
        queue and slots, resubmit each request to the best-ranked HEALTHY
        survivor under its ORIGINAL trace context (queued → resubmitted;
        active → restarted from scratch — the rows died with the
        process), count the unplaceable ones lost. The dead engine's
        copies all exit through its ``lost`` metric so the fleet
        conservation invariant stays exact (module docstring)."""
        from uccl_tpu.obs import TraceContext

        pid = self._pids[idx]
        if pid in self._dead:
            return  # exactly-once per replica
        self._dead.add(pid)
        eng = engine_of(self.replicas[idx])
        queued, active = eng.evacuate()
        stranded = ([(r, "resubmitted") for r in queued]
                    + [(r, "restarted") for r in active])
        for req, kind in stranded:
            outcome = kind
            new_req = None
            # exactly-once is structural: the pid guard above means each
            # replica is recovered once, and a request object lives on
            # exactly one replica — so no trace is ever re-run while a
            # live incarnation exists. A CASCADING failure (the survivor
            # that took this trace dies too) legitimately recovers the
            # same trace_id again: it is a new incarnation of the same
            # request, still under the ORIGINAL context (no new mint).
            ctx = (TraceContext(req.trace_id, req.span_id)
                   if req.trace_id and req.span_id else obs.new_context())
            # a still-QUEUED request keeps its admission deadline (it may
            # even have expired while stranded — the survivor's aging
            # expires it honestly); a restarted ACTIVE request was
            # already admitted once, so re-applying the deadline would
            # break the same contract preemption resume honors. Worker
            # (disagg) replicas never carry deadlines (Router.submit
            # refuses them on such sets) and _submit_to's worker branch
            # ignores the argument.
            ddl = req.deadline_ms if kind == "resubmitted" else None
            ranked, _ = self._ranked()
            for _, i in ranked:
                if i == idx:
                    continue
                new_req = self._submit_to(
                    i, req.prompt, max_new_tokens=req.max_new_tokens,
                    eos_id=req.eos_id, priority=req.priority,
                    tenant=req.tenant, trace=ctx,
                    deadline_ms=ddl,
                )
                if new_req is not None:
                    self.routed[i] += 1
                    _ROUTED.inc(replica=str(self._pids[i]))
                    break
            if new_req is None:
                outcome = "lost"
            req.state = RequestState.LOST
            req.finish_reason = "replica_dead"
            eng.metrics.on_lost(req)
            _RECOVERED_COUNTER.inc(outcome=outcome)
            self.recoveries.append({
                "replica": pid, "rid": req.rid, "outcome": outcome,
                "trace_id": req.trace_id,
            })
            obs.instant("recover", track="router", replica=pid,
                        rid=req.rid, outcome=outcome,
                        trace_id=req.trace_id)

    # -- the routing decision ------------------------------------------
    def _prefix_tokens(self, i: int, prompt, ns: str,
                       dir_hit=None) -> int:
        """Cached-prefix depth (tokens) replica ``i`` could resume this
        prompt from: the deepest of its own trie's longest-prefix match
        (side-effect-free — no counters, no LRU refresh) and the fleet
        directory's deepest entry WHEN this replica owns it. In debt-token
        units by construction: every matched token is prefill work the
        replica does not have to do."""
        eng = engine_of(self.replicas[i])
        best = 0
        cache = eng.prefix_cache
        if cache is not None:
            best = cache._lookup(prompt, ns)[0]
        if dir_hit is not None:
            fleet = getattr(eng, "fleet", None)
            if fleet is not None and dir_hit.get("owner") == fleet.worker:
                best = max(best, int(dir_hit.get("tokens", 0)))
        return best

    def _ranked(self, prompt=None, tenant: str = "default"
                ) -> Tuple[List[Tuple[tuple, int]], Dict[int, Dict]]:
        """ROUTABLE replicas ranked least-loaded first (dead, draining
        and detector-suspect replicas are excluded). With ``prompt`` the
        rank also credits cached prefixes (local trie / fleet directory
        longest-prefix match) against the debt term — cache-aware
        steering. The index tail rotates with the total routed count so
        exactly-equal replicas take turns instead of always electing
        replica 0 (cold-start skew)."""
        n = len(self.replicas)
        rot = sum(self.routed) % n
        ns = "" if tenant == "default" else tenant
        dir_hit = None
        if prompt is not None and self.directory is not None:
            dir_hit = self.directory.lookup(prompt, ns)
        ranked = []
        for i, r in enumerate(self.replicas):
            if not self._routable(i):
                continue
            s = replica_signals(r)
            if prompt is not None:
                s["prefix_tokens"] = self._prefix_tokens(
                    i, prompt, ns, dir_hit)
            key = (
                s["debt_tokens"] + self.bp_tokens * s["backpressure"]
                - s.get("prefix_tokens", 0),
                -s["free_slots"],
                s["queue_wait_ms"],
                (i - rot) % n,
            )
            ranked.append((key, i, s))
        ranked.sort(key=lambda t: t[0])
        return [(k, i) for k, i, _ in ranked], {i: s for _, i, s in ranked}

    def _submit_to(self, i: int, prompt, *, max_new_tokens: int,
                   eos_id, priority: str, trace,
                   tenant: str = "default",
                   deadline_ms: Optional[float] = None
                   ) -> Optional[Request]:
        """One admission attempt against replica ``i`` (engine or disagg
        worker) — shared by routing and recovery so the two cannot
        drift."""
        replica = self.replicas[i]
        eng = engine_of(replica)
        if replica is eng:
            return eng.submit(prompt, max_new_tokens=max_new_tokens,
                              eos_id=eos_id, priority=priority,
                              tenant=tenant,
                              deadline_ms=deadline_ms, trace=trace)
        # disagg prefill worker: the decode budget, the class label and
        # the tenant ride the BEGIN message (the worker's own engine
        # schedules its prefill queue by the same class, and the decode
        # side adopts under the same tenant so fleet-merged per-tenant
        # series stay truthful)
        return replica.submit(prompt, max_new_tokens=max_new_tokens,
                              eos_id=eos_id, priority=priority,
                              tenant=tenant, trace=trace)

    def submit(self, prompt, *, max_new_tokens: int = 16,
               eos_id: Optional[int] = None,
               priority: str = "interactive",
               tenant: str = "default",
               deadline_ms: Optional[float] = None) -> Optional[Request]:
        """Admit one request to the least-loaded replica; on rejection,
        spill to the next-ranked; None when every replica rejected.
        ``deadline_ms`` is refused when the set contains disagg prefill
        workers: their BEGIN already reserved decode-side state, so a
        queue-expired prefill request would strand the peer's grant."""
        if deadline_ms is not None and any(
                r is not engine_of(r) for r in self.replicas):
            raise ValueError(
                "deadline_ms is not supported on disagg prefill "
                "replicas: an expired queued request would strand its "
                "decode-side grant"
            )
        # the router IS the fleet ingress: mint the trace context here so
        # the routing decision and every downstream span (including a
        # disagg peer's, across processes) share one trace_id — a spilled
        # retry is the same request, so the context survives the loop
        ctx = obs.new_context()
        ranked, signals = self._ranked(prompt=prompt, tenant=tenant)
        for rank, (_, i) in enumerate(ranked):
            req = self._submit_to(i, prompt,
                                  max_new_tokens=max_new_tokens,
                                  eos_id=eos_id, priority=priority,
                                  tenant=tenant,
                                  trace=ctx, deadline_ms=deadline_ms)
            if req is None:
                continue  # bounded queue raced the signal read — spill
            self.routed[i] += 1
            _ROUTED.inc(replica=str(self._pids[i]))
            if rank > 0:
                _SPILLOVER.inc()
            if signals[i].get("prefix_tokens", 0) > 0:
                _CACHE_STEERED.inc()
            obs.instant("route", track="router", replica=self._pids[i],
                        rank=rank, rid=req.rid, cls=priority,
                        tenant=tenant,
                        trace_id=ctx.trace_id, **signals[i])
            return req
        _ROUTER_REJECTS.inc(reason="saturated")
        obs.instant("route_reject", track="router",
                    replicas=len(self.replicas))
        return None

    def cancel(self, rid_replica: Tuple[int, int]) -> bool:
        """Cancel a queued request by (replica index, rid)."""
        i, rid = rid_replica
        return engine_of(self.replicas[i]).cancel(rid)

    # -- the drive surface (loadgen.drive-compatible) ------------------
    def _pending_recovery(self) -> bool:
        """A killed-but-not-yet-recovered replica still holding requests
        is outstanding work: ``drain()`` must keep ticking the detector
        until recovery moves them (without health there is nothing to
        wait for — the kill is terminal)."""
        if self.detector is None:
            return False
        return any(engine_of(r).dead and self._pids[i] not in self._dead
                   and engine_of(r).has_work()
                   for i, r in enumerate(self.replicas))

    def has_work(self) -> bool:
        return any(
            not engine_of(r).dead
            and (engine_of(r).has_work()
                 or (hasattr(r, "idle") and not r.idle()))
            for r in self.replicas
        ) or self._pending_recovery()

    def step(self) -> List[Request]:
        """One iteration of every live replica that has work (a dead
        replica is skipped — a dead process does nothing — until the
        health tick recovers it); returns requests finished across the
        set this round."""
        self._health_tick()
        finished: List[Request] = []
        stepped = False
        for r in self.replicas:
            eng = engine_of(r)
            if eng.dead:
                continue
            if r is not eng:
                r.step()  # worker loop: engine step + wire pump
                stepped = True
            elif eng.has_work():
                finished.extend(eng.step())
                stepped = True
        if not stepped and self._pending_recovery():
            # nothing live to run: pace the detector ticks instead of
            # spinning drain()'s step budget away inside the grace window
            time.sleep(0.001)
        return finished

    def drain(self, max_steps: int = 100000) -> List[Request]:
        done: List[Request] = []
        steps = 0
        while self.has_work():
            done.extend(self.step())
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"router drain exceeded {max_steps} steps "
                    f"(queued={self.qsize}, active={self.n_active})"
                )
        return done

    # -- elastic membership --------------------------------------------
    def detach(self, index: int, *, max_steps: int = 100000
               ) -> List[Request]:
        """Gracefully drain replica ``index`` out of the set — the
        elastic DOWN-scale primitive (``ep/elastic.admit_warm_replica``
        is the up-scale twin): admission to it stops immediately, the
        whole set keeps stepping until its queue and slots empty (its
        active work finishes normally — nothing is lost), parked
        prefix-cache donor slots are handed back, and only then is the
        replica removed. Returns every request that finished ACROSS the
        set while draining (a caller mid-load must not lose them).
        Raises if the replica cannot drain in ``max_steps`` or would
        leak a slot."""
        if not (0 <= index < len(self.replicas)):
            raise IndexError(f"no replica {index} (have "
                             f"{len(self.replicas)})")
        if len(self.replicas) == 1:
            raise ValueError("cannot detach the last replica")
        pid = self._pids[index]
        replica = self.replicas[index]
        eng = engine_of(replica)
        self._draining.add(pid)
        try:
            finished: List[Request] = []
            steps = 0

            def busy() -> bool:
                if eng.dead:
                    return False  # died mid-drain: recovery handles it
                if replica is not eng:
                    return eng.has_work() or not replica.idle()
                return eng.has_work()

            while busy():
                finished.extend(self.step())
                steps += 1
                if steps > max_steps:
                    raise RuntimeError(
                        f"detach: replica {pid} still busy after "
                        f"{max_steps} steps (queued={eng.sched.qsize}, "
                        f"active={len(eng._by_slot)})"
                    )
            if eng.dead and pid not in self._dead:
                # died mid-drain: recover NOW instead of waiting out the
                # detector window — detach's contract is "requests are
                # not lost", and the pool must be empty before removal
                self._recover(index)
            if eng.prefix_cache is not None:
                # hand parked donor slots back before removal — a
                # detached replica must leave nothing charged to its pool
                eng.prefix_cache.clear(eng.pool)
            leaked = eng.pool.leaked()
            if leaked:
                raise RuntimeError(
                    f"detach: replica {pid} drained but leaks "
                    f"{leaked} slot(s)"
                )
        finally:
            self._draining.discard(pid)
        self.replicas.pop(index)
        self.routed.pop(index)
        self._pids.pop(index)
        if self.detector is not None:
            self.detector.deregister(pid)
        _REPLICAS.set(len(self.replicas))
        _DETACHED.inc()
        obs.instant("detach", track="router", replica=pid, steps=steps)
        return finished

    def attach(self, replica) -> int:
        """Add a replica to the live set (warm-spare admission / elastic
        up-scale — see ``ep/elastic.admit_warm_replica`` for the
        weight-push-fed construction). Registered with the failure
        detector when health is on. Returns the replica's stable id."""
        pid = self._next_pid
        self._next_pid += 1
        self.replicas.append(replica)
        self.routed.append(0)
        self._pids.append(pid)
        if self.detector is not None:
            self.detector.register(pid, probe=self._probe_for(replica))
        _REPLICAS.set(len(self.replicas))
        _ATTACHED.inc()
        obs.instant("attach", track="router", replica=pid)
        return pid

    # -- aggregate inspection ------------------------------------------
    @property
    def engines(self) -> List[ServingEngine]:
        return [engine_of(r) for r in self.replicas]

    @property
    def qsize(self) -> int:
        return sum(e.sched.qsize for e in self.engines)

    @property
    def n_active(self) -> int:
        return sum(len(e._by_slot) for e in self.engines)

    def leaked(self) -> int:
        return sum(e.pool.leaked() for e in self.engines)

    def snapshot(self) -> dict:
        """Replica-set snapshot: the merged metrics (samples concatenated,
        counts summed — ServingMetrics.merged) plus per-replica snapshots
        and the router's own routed distribution."""
        merged = ServingMetrics.merged([e.metrics for e in self.engines])
        snap = merged.snapshot(
            queued=self.qsize, active=self.n_active,
            n_slots=sum(e.pool.n_slots for e in self.engines),
            occupancy=(sum(e.pool.n_active for e in self.engines)
                       / max(1, sum(e.pool.n_slots for e in self.engines))),
        )
        snap["replicas"] = len(self.replicas)
        snap["routed"] = list(self.routed)
        snap["per_replica"] = [e.snapshot() for e in self.engines]
        snap["dead_replicas"] = len(self._dead)
        snap["leaked"] = self.leaked()
        return snap

    def close(self) -> None:
        for e in self.engines:
            e.close()
