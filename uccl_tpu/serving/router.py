"""Replica router: least-loaded admission over N serving engine replicas.

The heavy-traffic front end (docs/SERVING.md): UCCL's core move is
software-driven multi-path scheduling over dumb transports (PAPER.md §0.1);
the serving analogue sprays requests over a replica set using **live load
signals** instead of round-robin — the same signals the obs layer already
exports, read directly off each replica:

* **free slots** — ``pool.n_free``: immediate admission capacity;
* **token debt** — ``engine.pending_tokens()``: outstanding prefill +
  decode work in step-token units (the per-step spend currency of
  ``step_tokens``), queued AND in-slot — the forward-looking load;
* **recent queue wait** — mean of the last few ``queue_wait_ms`` samples:
  the realized scheduling delay, a lagging confirmation of the debt;
* **adoption backpressure** — for disaggregated prefill fleets
  (``disagg.PrefillWorker.adoption_backpressure()``): requests stuck
  waiting for a decode-side GRANT, so new prompts steer away from a
  prefill worker whose decode peer is saturated.

Selection is lexicographic — ``(debt + bp_tokens·backpressure,
-free_slots, queue_wait_ms, index)``, lowest wins — so the dominant
forward-looking signal decides and the rest break ties deterministically
(the index tail makes equal replicas round-robin-stable rather than
id-0-biased: it rotates with the routed count).

When the chosen replica rejects (bounded queue — the race between the
signal read and the submit), the router **spills over** to the next-best
replica (counted on ``serving_router_spillover_total``); when every
replica rejects, the request is rejected at the router (counted on
``serving_router_rejected_total{reason="saturated"}``) — sustained
overload is visible as a counter, never a hang. Every accepted admission
lands on ``serving_router_requests_total{replica=...}`` plus a ``route``
trace instant carrying the signals the decision was made from, so benches
label arms off real routing decisions (docs/OBSERVABILITY.md).

Replicas are in-process ``ServingEngine``s, or disagg ``PrefillWorker``s
(anything with an ``.engine`` and a ``submit``) — a prefill fleet routed
per-peer. Mixed sets are allowed.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from uccl_tpu import obs
from uccl_tpu.serving.engine import ServingEngine
from uccl_tpu.serving.metrics import ServingMetrics
from uccl_tpu.serving.request import Request

_ROUTED = obs.counter(
    "serving_router_requests_total",
    "requests admitted per replica by the least-loaded router",
)
_SPILLOVER = obs.counter(
    "serving_router_spillover_total",
    "admissions that fell through to a lower-ranked replica after the "
    "chosen one rejected (bounded-queue race)",
)
_ROUTER_REJECTS = obs.counter(
    "serving_router_rejected_total",
    "requests rejected at the router: reason=saturated means every "
    "replica's queue was full",
)
_REPLICAS = obs.gauge(
    "serving_router_replicas", "replica count behind the serving router"
)


def replica_signals(replica, *, recent: int = 8) -> Dict[str, float]:
    """The live load signals for one replica, as the router reads them.
    Exposed as a function so tests and benches can audit the exact inputs
    a routing decision saw."""
    eng = engine_of(replica)
    qw = eng.metrics.queue_wait_s[-recent:]
    bp = 0
    hook = getattr(replica, "adoption_backpressure", None)
    if callable(hook):
        bp = int(hook())
    return {
        "free_slots": eng.pool.n_free,
        "queued": eng.sched.qsize,
        "debt_tokens": eng.pending_tokens(),
        "queue_wait_ms": round(sum(qw) / len(qw) * 1e3, 3) if qw else 0.0,
        "backpressure": bp,
    }


def engine_of(replica) -> ServingEngine:
    """The ServingEngine inside a replica (identity for a bare engine,
    ``.engine`` for a disagg PrefillWorker)."""
    return getattr(replica, "engine", replica)


class Router:
    """Least-loaded front end over N serving replicas.

    ``bp_tokens`` prices one unit of adoption backpressure (one request
    stuck awaiting decode capacity) in debt-token units when ranking —
    the default assumes a stuck request is worth about one typical
    request's work.
    """

    def __init__(self, replicas: List, *, bp_tokens: int = 64):
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.replicas = list(replicas)
        self.bp_tokens = bp_tokens
        self.routed = [0] * len(self.replicas)  # per-replica admit counts
        _REPLICAS.set(len(self.replicas))

    # -- the routing decision ------------------------------------------
    def _ranked(self) -> Tuple[List[Tuple[tuple, int]], Dict[int, Dict]]:
        """Replicas ranked least-loaded first. The index tail rotates with
        the total routed count so exactly-equal replicas take turns
        instead of always electing replica 0 (cold-start skew)."""
        n = len(self.replicas)
        rot = sum(self.routed) % n
        ranked = []
        for i, r in enumerate(self.replicas):
            s = replica_signals(r)
            key = (
                s["debt_tokens"] + self.bp_tokens * s["backpressure"],
                -s["free_slots"],
                s["queue_wait_ms"],
                (i - rot) % n,
            )
            ranked.append((key, i, s))
        ranked.sort(key=lambda t: t[0])
        return [(k, i) for k, i, _ in ranked], {i: s for _, i, s in ranked}

    def submit(self, prompt, *, max_new_tokens: int = 16,
               eos_id: Optional[int] = None,
               priority: str = "interactive",
               deadline_ms: Optional[float] = None) -> Optional[Request]:
        """Admit one request to the least-loaded replica; on rejection,
        spill to the next-ranked; None when every replica rejected.
        ``deadline_ms`` is refused when the set contains disagg prefill
        workers: their BEGIN already reserved decode-side state, so a
        queue-expired prefill request would strand the peer's grant."""
        if deadline_ms is not None and any(
                r is not engine_of(r) for r in self.replicas):
            raise ValueError(
                "deadline_ms is not supported on disagg prefill "
                "replicas: an expired queued request would strand its "
                "decode-side grant"
            )
        # the router IS the fleet ingress: mint the trace context here so
        # the routing decision and every downstream span (including a
        # disagg peer's, across processes) share one trace_id — a spilled
        # retry is the same request, so the context survives the loop
        ctx = obs.new_context()
        ranked, signals = self._ranked()
        for rank, (_, i) in enumerate(ranked):
            replica = self.replicas[i]
            eng = engine_of(replica)
            if replica is eng:
                req = eng.submit(prompt, max_new_tokens=max_new_tokens,
                                 eos_id=eos_id, priority=priority,
                                 deadline_ms=deadline_ms, trace=ctx)
            else:
                # disagg prefill worker: the decode budget and the class
                # label ride the BEGIN message (the worker's own engine
                # schedules its prefill queue by the same class)
                req = replica.submit(prompt,
                                     max_new_tokens=max_new_tokens,
                                     eos_id=eos_id, priority=priority,
                                     trace=ctx)
            if req is None:
                continue  # bounded queue raced the signal read — spill
            self.routed[i] += 1
            _ROUTED.inc(replica=str(i))
            if rank > 0:
                _SPILLOVER.inc()
            obs.instant("route", track="router", replica=i, rank=rank,
                        rid=req.rid, cls=priority,
                        trace_id=ctx.trace_id, **signals[i])
            return req
        _ROUTER_REJECTS.inc(reason="saturated")
        obs.instant("route_reject", track="router",
                    replicas=len(self.replicas))
        return None

    def cancel(self, rid_replica: Tuple[int, int]) -> bool:
        """Cancel a queued request by (replica index, rid)."""
        i, rid = rid_replica
        return engine_of(self.replicas[i]).cancel(rid)

    # -- the drive surface (loadgen.drive-compatible) ------------------
    def has_work(self) -> bool:
        return any(engine_of(r).has_work() or
                   (hasattr(r, "idle") and not r.idle())
                   for r in self.replicas)

    def step(self) -> List[Request]:
        """One iteration of every replica that has work; returns requests
        finished across the set this round."""
        finished: List[Request] = []
        for r in self.replicas:
            eng = engine_of(r)
            if r is not eng:
                r.step()  # worker loop: engine step + wire pump
            elif eng.has_work():
                finished.extend(eng.step())
        return finished

    def drain(self, max_steps: int = 100000) -> List[Request]:
        done: List[Request] = []
        steps = 0
        while self.has_work():
            done.extend(self.step())
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"router drain exceeded {max_steps} steps "
                    f"(queued={self.qsize}, active={self.n_active})"
                )
        return done

    # -- aggregate inspection ------------------------------------------
    @property
    def engines(self) -> List[ServingEngine]:
        return [engine_of(r) for r in self.replicas]

    @property
    def qsize(self) -> int:
        return sum(e.sched.qsize for e in self.engines)

    @property
    def n_active(self) -> int:
        return sum(len(e._by_slot) for e in self.engines)

    def leaked(self) -> int:
        return sum(e.pool.leaked() for e in self.engines)

    def snapshot(self) -> dict:
        """Replica-set snapshot: the merged metrics (samples concatenated,
        counts summed — ServingMetrics.merged) plus per-replica snapshots
        and the router's own routed distribution."""
        merged = ServingMetrics.merged([e.metrics for e in self.engines])
        snap = merged.snapshot(
            queued=self.qsize, active=self.n_active,
            n_slots=sum(e.pool.n_slots for e in self.engines),
            occupancy=(sum(e.pool.n_active for e in self.engines)
                       / max(1, sum(e.pool.n_slots for e in self.engines))),
        )
        snap["replicas"] = len(self.replicas)
        snap["routed"] = list(self.routed)
        snap["per_replica"] = [e.snapshot() for e in self.engines]
        return snap

    def close(self) -> None:
        for e in self.engines:
            e.close()
