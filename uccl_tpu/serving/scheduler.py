"""FIFO admission scheduling with backpressure.

Orca/vLLM-shape policy, smallest useful core: arrivals queue in submission
order; every engine step admits from the queue head while KV slots are free
(so a long-running sequence never starves the queue — it just occupies one
slot); a bounded queue rejects at submit when full (backpressure — the
caller sees it immediately instead of timing out later).
"""

from __future__ import annotations

from collections import deque
from typing import Callable, List, Optional, Tuple

from uccl_tpu.serving.request import Request, RequestState, now
from uccl_tpu.serving.slots import SlotPool


class FIFOScheduler:
    """Bounded FIFO queue + admission loop over a :class:`SlotPool`."""

    def __init__(self, max_queue: Optional[int] = None):
        if max_queue is not None and max_queue < 0:
            raise ValueError(f"max_queue must be >= 0, got {max_queue}")
        self.max_queue = max_queue
        self._queue: deque = deque()
        self._admit_seq = 0

    @property
    def qsize(self) -> int:
        return len(self._queue)

    def peek(self) -> Optional[Request]:
        """The request the next admission would take (None when empty) —
        lets the engine's make_room hook protect the prefix-cache donor
        this request is about to match from being the eviction victim."""
        return self._queue[0] if self._queue else None

    def submit(self, req: Request) -> bool:
        """Queue a request; False = rejected (queue full, backpressure)."""
        if self.max_queue is not None and len(self._queue) >= self.max_queue:
            req.state = RequestState.REJECTED
            return False
        self._queue.append(req)
        return True

    def admit(self, pool: SlotPool, limit: Optional[int] = None,
              make_room: Optional[Callable[[], bool]] = None,
              ) -> List[Tuple[int, Request]]:
        """Move queue-head requests into free slots, in FIFO order, until
        either runs out. ``limit`` caps this call's admissions (the engine's
        per-step token budget: each admission under chunked prefill commits
        one chunk of prefill work per step until its prompt is in KV, so
        admission is where the budget is enforced — None = unbounded).
        ``make_room()`` is consulted only when the pool has no free slot
        and the queue still has work: return True after freeing one (the
        prefix cache's LRU eviction — parked donor slots yield to live
        admissions), False to stop admitting. Returns the newly admitted
        (slot, request) pairs — the engine prefills exactly these."""
        admitted: List[Tuple[int, Request]] = []
        while self._queue and (limit is None or len(admitted) < limit):
            if not pool.n_free and not (make_room is not None
                                        and make_room()):
                break
            req = self._queue.popleft()
            slot = pool.admit(req.rid)
            assert slot is not None  # n_free was checked
            req.slot = slot
            req.state = RequestState.ACTIVE
            req.t_admit = now()
            req.admit_seq = self._admit_seq
            self._admit_seq += 1
            admitted.append((slot, req))
        return admitted
