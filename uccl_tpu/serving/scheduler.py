"""Admission scheduling: FIFO with backpressure, plus SLO priority classes.

Orca/vLLM-shape policy, smallest useful core: arrivals queue in submission
order; every engine step admits from the queue head while KV slots are free
(so a long-running sequence never starves the queue — it just occupies one
slot); a bounded queue rejects at submit when full (backpressure — the
caller sees it immediately instead of timing out later).

Both schedulers support **queue aging + cancellation**: a request submitted
with ``deadline_ms`` that is still queued when the deadline passes leaves
the queue as ``RequestState.EXPIRED`` (the engine counts it on
``serving_rejected_total{reason="deadline"}``), and ``cancel(rid)`` removes
a queued request the same way — a stale queued request no longer occupies
the queue forever (previously it could only be rejected at submit time).

:class:`PriorityScheduler` adds latency classes (Llumnix/SLO-aware shape,
docs/SERVING.md): one FIFO queue per class, admission drains strictly in
class order (``interactive`` before ``batch``), and :meth:`requeue` puts an
engine-preempted request back at the HEAD of its class queue so a paused
victim resumes before any later same-class arrival.
"""

from __future__ import annotations

from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional, Tuple

from uccl_tpu.serving.request import Request, RequestState, now
from uccl_tpu.serving.slots import SlotPool

# class order: admission drains lower-index classes first
PRIORITY_CLASSES = ("interactive", "batch")


class FIFOScheduler:
    """Bounded FIFO queue + admission loop over a :class:`SlotPool`."""

    def __init__(self, max_queue: Optional[int] = None):
        if max_queue is not None and max_queue < 0:
            raise ValueError(f"max_queue must be >= 0, got {max_queue}")
        self.max_queue = max_queue
        self._queue: deque = deque()
        self._admit_seq = 0
        # queued requests carrying a deadline — expire()'s early-out, so
        # deadline-free engines never pay an O(qsize) scan per step
        self._n_deadlined = 0

    # single-queue view — PriorityScheduler overrides to expose its class
    # queues through the same iteration surface
    def _queues(self) -> List[deque]:
        return [self._queue]

    @property
    def qsize(self) -> int:
        return sum(len(q) for q in self._queues())

    def peek(self) -> Optional[Request]:
        """The request the next admission would take (None when empty) —
        lets the engine's make_room hook protect the prefix-cache donor
        this request is about to match from being the eviction victim."""
        for q in self._queues():
            if q:
                return q[0]
        return None

    def queued_requests(self) -> List[Request]:
        """Every queued request, in admission order (the router's token-debt
        signal sums outstanding work over these)."""
        return [r for q in self._queues() for r in q]

    def debug_state(self) -> dict:
        """Flight-bundle face (obs/flight.py): queue shape only, no
        Request bodies — a post-mortem dump must stay small and must not
        carry prompt content."""
        return {"kind": type(self).__name__, "qsize": self.qsize,
                "max_queue": self.max_queue,
                "deadlined": self._n_deadlined}

    def take_all(self) -> List[Request]:
        """Remove and return EVERY queued request, in admission order —
        the dead-replica evacuation (the router resubmits them to
        survivors, or counts them lost). The queues end empty."""
        out: List[Request] = []
        for q in self._queues():
            out.extend(q)
            q.clear()
        self._n_deadlined = 0
        return out

    def submit(self, req: Request) -> bool:
        """Queue a request; False = rejected (queue full, backpressure)."""
        if self.max_queue is not None and self.qsize >= self.max_queue:
            req.state = RequestState.REJECTED
            return False
        self._queue.append(req)
        if req.deadline_ms is not None:
            self._n_deadlined += 1
        return True

    def requeue(self, req: Request) -> None:
        """Put an engine-preempted request back at the queue head: a paused
        victim resumes before any later arrival of its class. Never bounded
        — the request already passed backpressure at submit."""
        self._queue.appendleft(req)

    def defer(self, req: Request) -> None:
        """Undo a just-granted admission: the engine's admission gate
        denied the request AFTER :meth:`admit` popped it (e.g. no adapter
        table row free), so put it back at the queue head with the
        admission stamps reverted — deadline aging and ``cancel`` apply
        exactly as before the attempt. A preemption victim waiting to
        resume (saved-token marker set) returns to PREEMPTED, which stays
        exempt from admission-deadline expiry; anything else is QUEUED
        again. The caller frees the granted slot; a fair scheduler never
        re-bills (``req.billed``)."""
        if req._saved_last_tok is not None:
            req.state = RequestState.PREEMPTED
        else:
            req.state = RequestState.QUEUED
            if req.deadline_ms is not None:
                # _place counted it admitted-in-time; it wasn't admitted
                self._n_deadlined += 1
        req.slot = None
        self.requeue(req)

    def expire(self, t: float) -> List[Request]:
        """Drop every QUEUED request whose admission deadline passed at
        engine-clock ``t`` (state → EXPIRED, finish_reason "deadline").
        Preempted requests waiting to resume are exempt: their deadline was
        an *admission* deadline and they were already admitted once. Free
        when nothing queued carries a deadline (the common case — one
        counter check, no queue scan)."""
        if self._n_deadlined == 0:
            return []
        expired: List[Request] = []
        for q in self._queues():
            for _ in range(len(q)):  # one full rotation keeps queue order
                r = q.popleft()
                if (r.state is RequestState.QUEUED
                        and r.deadline_passed(t)):
                    r.state = RequestState.EXPIRED
                    r.finish_reason = "deadline"
                    self._n_deadlined -= 1
                    expired.append(r)
                else:
                    q.append(r)
        return expired

    def cancel(self, rid: int) -> Optional[Request]:
        """Remove a queued request by id (state → EXPIRED, finish_reason
        "cancel"). Returns the request, or None when ``rid`` is not queued
        (already admitted, finished, or unknown) — only QUEUED requests are
        cancellable here; in-slot requests run to completion."""
        for q in self._queues():
            for r in q:
                if r.rid == rid and r.state is RequestState.QUEUED:
                    q.remove(r)
                    if r.deadline_ms is not None:
                        self._n_deadlined -= 1
                    r.state = RequestState.EXPIRED
                    r.finish_reason = "cancel"
                    return r
        return None

    def admit(self, pool: SlotPool, limit: Optional[int] = None,
              make_room: Optional[Callable[[], bool]] = None,
              ) -> List[Tuple[int, Request]]:
        """Move queue-head requests into free slots, in FIFO order, until
        either runs out. ``limit`` caps this call's admissions (the engine's
        per-step token budget: each admission under chunked prefill commits
        one chunk of prefill work per step until its prompt is in KV, so
        admission is where the budget is enforced — None = unbounded).
        ``make_room()`` is consulted only when the pool has no free slot
        and the queue still has work: return True after freeing one (the
        prefix cache's LRU eviction — parked donor slots yield to live
        admissions — or the engine's priority preemption), False to stop
        admitting. Returns the newly admitted (slot, request) pairs — the
        engine prefills exactly these."""
        admitted: List[Tuple[int, Request]] = []
        while (limit is None or len(admitted) < limit):
            queue = next((q for q in self._queues() if q), None)
            if queue is None:
                break
            if not pool.n_free and not (make_room is not None
                                        and make_room()):
                break
            req = queue.popleft()
            admitted.append((self._place(pool, req), req))
        return admitted

    def _place(self, pool: SlotPool, req: Request) -> int:
        """Shared per-admission bookkeeping (deadline counter, slot grant,
        state + timing stamps) — every scheduler's admit loop funnels
        through this once it has chosen a request and verified capacity."""
        if (req.deadline_ms is not None
                and req.state is RequestState.QUEUED):
            self._n_deadlined -= 1  # made it in before the deadline
        slot = pool.admit(req.rid)
        assert slot is not None  # n_free was checked by the caller
        req.slot = slot
        req.state = RequestState.ACTIVE
        req.t_admit = now()
        req.admit_seq = self._admit_seq
        self._admit_seq += 1
        return slot


class PriorityScheduler(FIFOScheduler):
    """Class-ordered admission: one bounded FIFO queue per priority class.

    ``interactive`` requests are always admitted before ``batch`` requests
    regardless of arrival order; within a class, order is FIFO. The shared
    ``max_queue`` bounds the TOTAL queued count (one backpressure surface —
    a saturated engine rejects both classes, and the router's spillover
    handles the rest). ``requeue`` (the engine's preemption path) restores
    a victim to the head of its own class queue.
    """

    def __init__(self, max_queue: Optional[int] = None):
        super().__init__(max_queue=max_queue)
        self._by_class = {cls: deque() for cls in PRIORITY_CLASSES}

    def _queues(self) -> List[deque]:
        return [self._by_class[cls] for cls in PRIORITY_CLASSES]

    def _class_queue(self, req: Request) -> deque:
        q = self._by_class.get(req.priority)
        if q is None:
            raise ValueError(
                f"unknown priority class {req.priority!r} "
                f"(classes: {PRIORITY_CLASSES})"
            )
        return q

    def submit(self, req: Request) -> bool:
        if self.max_queue is not None and self.qsize >= self.max_queue:
            req.state = RequestState.REJECTED
            return False
        self._class_queue(req).append(req)
        if req.deadline_ms is not None:
            self._n_deadlined += 1
        return True

    def requeue(self, req: Request) -> None:
        self._class_queue(req).appendleft(req)

    def debug_state(self) -> dict:
        st = super().debug_state()
        st["by_class"] = {cls: len(q)
                         for cls, q in self._by_class.items()}
        return st


class TenantFairScheduler(FIFOScheduler):
    """Per-tenant fair admission (ISSUE 18): one FIFO queue per tenant,
    **deficit round-robin** across tenants, plus optional per-tenant
    **token-bucket** rate limits.

    DRR (the classic Shreedhar/Varghese discipline, in request-token
    units): admission visits tenants round-robin; each visit grants the
    tenant one ``quantum`` of deficit, and its queue head is admitted
    while the deficit covers the request's token cost
    (``prompt + max_new_tokens``). A tenant with a thousand queued
    requests therefore gets the same admission *rate* as a tenant with
    one — backlog buys nothing — which is exactly the isolation the
    multi-tenant bench proves: an overloading tenant cannot push a
    victim's SLO attainment down (docs/SERVING.md). An emptied queue
    forfeits its deficit (the DRR rule that stops idle tenants hoarding
    credit).

    The token bucket (``rate`` tokens/sec, capacity ``burst``) is the
    hard per-tenant ceiling ON TOP of DRR's work-conserving share: a
    tenant above its rate holds in queue even when slots are free.
    ``rate=None`` (default) disables it — DRR alone is work-conserving.
    A request whose cost exceeds ``burst`` is REJECTED at submit: the
    bucket refills only up to ``burst``, so such a request could never
    be admitted and would otherwise wedge its tenant's queue head
    forever (livelock). A preempted or engine-deferred request is NOT
    re-charged on requeue (its tokens were billed at first admission).

    Per-tenant fairness and priority classes are mutually exclusive
    surfaces (the engine enforces it): within a tenant, order is FIFO.
    ``clock`` is injectable for deterministic bucket tests.
    """

    def __init__(self, max_queue: Optional[int] = None, *,
                 quantum: int = 64, rate: Optional[float] = None,
                 burst: Optional[float] = None, clock=now):
        super().__init__(max_queue=max_queue)
        if quantum < 1:
            raise ValueError(f"quantum must be >= 1, got {quantum}")
        if rate is not None and rate <= 0:
            raise ValueError(f"rate must be > 0 tokens/sec, got {rate}")
        if burst is not None and burst <= 0:
            raise ValueError(f"burst must be > 0 tokens, got {burst}")
        self.quantum = int(quantum)
        self.rate = rate
        self.burst = float(burst) if burst is not None else (
            4.0 * rate if rate is not None else 0.0
        )
        self._clock = clock
        self._by_tenant: "OrderedDict[str, deque]" = OrderedDict()
        self._deficit: Dict[str, float] = {}
        self._bucket: Dict[str, float] = {}
        self._rr: deque = deque()  # round-robin rotation of tenant names
        self._last_refill: Optional[float] = None

    def _queues(self) -> List[deque]:
        return list(self._by_tenant.values())

    def _tenant_queue(self, tenant: str) -> deque:
        q = self._by_tenant.get(tenant)
        if q is None:
            q = self._by_tenant[tenant] = deque()
            self._deficit[tenant] = 0.0
            self._bucket[tenant] = self.burst
            self._rr.append(tenant)
        return q

    @staticmethod
    def _cost(req: Request) -> int:
        """A request's token cost: the prompt it prefills plus the budget
        it may decode — the unit both the deficit and the bucket meter."""
        return int(req.prompt.size) + int(req.max_new_tokens)

    def submit(self, req: Request) -> bool:
        if self.max_queue is not None and self.qsize >= self.max_queue:
            req.state = RequestState.REJECTED
            return False
        if self.rate is not None and self._cost(req) > self.burst:
            # the bucket never holds more than `burst` tokens, so this
            # request's charge could never be covered: fail fast at
            # submit instead of silently blocking the tenant's FIFO head
            # for every later request (admission livelock)
            req.state = RequestState.REJECTED
            req.finish_reason = "oversized"
            return False
        self._tenant_queue(req.tenant).append(req)
        if req.deadline_ms is not None:
            self._n_deadlined += 1
        return True

    def requeue(self, req: Request) -> None:
        self._tenant_queue(req.tenant).appendleft(req)

    def _refill(self) -> None:
        if self.rate is None:
            return
        t = self._clock()
        if self._last_refill is not None:
            dt = max(0.0, t - self._last_refill)
            for tenant in self._bucket:
                self._bucket[tenant] = min(
                    self.burst, self._bucket[tenant] + self.rate * dt
                )
        self._last_refill = t

    def admit(self, pool: SlotPool, limit: Optional[int] = None,
              make_room: Optional[Callable[[], bool]] = None,
              ) -> List[Tuple[int, Request]]:
        admitted: List[Tuple[int, Request]] = []
        self._refill()
        while limit is None or len(admitted) < limit:
            progress = deficit_short = False
            for _ in range(len(self._rr)):
                if limit is not None and len(admitted) >= limit:
                    break
                tenant = self._rr[0]
                self._rr.rotate(-1)
                q = self._by_tenant[tenant]
                if not q:
                    self._deficit[tenant] = 0.0  # idle forfeits credit
                    continue
                self._deficit[tenant] += self.quantum
                while q and (limit is None or len(admitted) < limit):
                    req = q[0]
                    # a requeued request (preemption resume, engine
                    # adapter-deferral) was billed at first admission
                    charge = 0 if req.billed else self._cost(req)
                    if (self.rate is not None
                            and self._bucket[tenant] < charge):
                        break  # rate-limited: holds even with free slots
                    if self._deficit[tenant] < charge:
                        deficit_short = True  # next round grants more
                        break
                    if not pool.n_free and not (make_room is not None
                                                and make_room()):
                        # The POOL is the blocker, not fairness — park the
                        # wheel back on the denied tenant and retract this
                        # visit's unspent grant (re-granted on resume).
                        # Without the park, every admit call walks a full
                        # rotation and the freed slot always lands on the
                        # front tenant: observed starvation of every other
                        # tenant under a 1-slot pool.
                        self._rr.rotate(1)
                        self._deficit[tenant] = max(
                            0.0, self._deficit[tenant] - self.quantum
                        )
                        return admitted
                    q.popleft()
                    slot = self._place(pool, req)
                    self._deficit[tenant] -= charge
                    if self.rate is not None:
                        self._bucket[tenant] -= charge
                    req.billed = True
                    admitted.append((slot, req))
                    progress = True
                if not q:
                    self._deficit[tenant] = 0.0
            if not progress and not deficit_short:
                break  # every queued tenant is rate-limited
        return admitted

    def debug_state(self) -> dict:
        st = super().debug_state()
        st["by_tenant"] = {t: len(q)
                          for t, q in self._by_tenant.items() if q}
        st["deficit"] = {t: round(v, 2)
                         for t, v in self._deficit.items() if v}
        if self.rate is not None:
            st["bucket"] = {t: round(v, 2)
                            for t, v in self._bucket.items()}
        return st
