#!/usr/bin/env python3
"""Fan the repo out to cluster hosts — the reference's rsync.py analog
(scripts/rsync.py + node_ips/ hostfiles, SURVEY.md §2.5).

Hostfile: one host per line (optionally ``user@host``); '#' comments.

    python scripts/sync.py --hostfile hosts.txt [--dest ~/uccl_tpu] [--jobs 8]
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from concurrent.futures import ThreadPoolExecutor

EXCLUDES = [".git", "__pycache__", ".pytest_cache", "native/build"]


def read_hostfile(path: str):
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                hosts.append(line)
    return hosts


def sync_one(repo: str, host: str, dest: str) -> tuple:
    cmd = ["rsync", "-az", "--delete"]
    for e in EXCLUDES:
        cmd += ["--exclude", e]
    cmd += [repo.rstrip("/") + "/", f"{host}:{dest}/"]
    r = subprocess.run(cmd, capture_output=True, text=True)
    return host, r.returncode, r.stderr.strip()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--hostfile", required=True)
    ap.add_argument("--dest", default="~/uccl_tpu")
    ap.add_argument("--jobs", type=int, default=8)
    opts = ap.parse_args()

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    hosts = read_hostfile(opts.hostfile)
    if not hosts:
        print("hostfile is empty", file=sys.stderr)
        return 1
    rc = 0
    with ThreadPoolExecutor(max_workers=opts.jobs) as pool:
        for host, code, err in pool.map(
            lambda h: sync_one(repo, h, opts.dest), hosts
        ):
            status = "ok" if code == 0 else f"FAILED: {err}"
            print(f"{host}: {status}")
            if code != 0:
                rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
