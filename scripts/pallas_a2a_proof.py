"""Compile/smoke proof for the Pallas EP all-to-all (wire="pallas").

Two modes:

* default (TPU session): an 8-way all-to-all kernel cannot EXECUTE on one
  chip, but it can be LOWERED for the TPU backend through the full
  Pallas→Mosaic pipeline using an abstract 8-device mesh — that exercises
  kernel tracing, VMEM layout/tiling, the full-peer barrier, credit
  semaphore plumbing and the remote-copy lowering, i.e. everything short of
  the final Mosaic→LLO compile that needs the real topology. Covered
  programs: the normal (sorted) EP dispatch AND combine and the LL
  dense-chunk dispatch AND combine, each on the pallas wire, at f32 and
  bf16 payloads plus the fp8+scales wire format. ``--chunks N`` adds the
  chunk-pipelined arms (per-chunk kernels on rotated collective ids — the
  double-buffered dispatch/combine schedule). Run from
  scripts/onchip_ladder.sh, step 1c.

* ``--interpret`` (any host, CI smoke tier): EXECUTES the kernels under the
  TPU interpreter on a small virtual CPU mesh and checks them against the
  lax wire — the fast fail-first gate for kernel regressions on CPU
  runners (scripts/qa.sh and the GitHub workflow run it with --chunks 2
  under a hard timeout). Small shapes on purpose: the whole smoke must
  finish in seconds-to-a-minute, not re-prove the full oracle suite
  (tests/test_pallas_a2a.py does that).

Prints one line per case; exits nonzero on any failure (or, in lowering
mode, if any lowered module lacks the ``tpu_custom_call`` the
device-initiated path must contain).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

W, T, H, E, K = 8, 128, 512, 16, 2
CAP = max(1, int(1.25 * T * K / E))


def _parse_args(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--chunks", type=int, default=0,
        help="also prove the chunk-pipelined arms at this depth (0 = "
             "unchunked only)",
    )
    ap.add_argument(
        "--interpret", action="store_true",
        help="execute under the TPU interpreter on a virtual CPU mesh and "
             "check vs the lax wire (CI smoke tier; no TPU needed)",
    )
    ap.add_argument(
        "--wire-dtype", default=None, choices=["fp8", "int8"],
        help="also prove the block-quantized wire (docs/QUANT_WIRE.md): "
             "quantized ring allreduce + EP roundtrip arms — interpret "
             "mode checks pallas == lax bit-identity on the quantized "
             "path, the documented error bound vs full precision, and "
             "exact zeros on zero input",
    )
    ap.add_argument(
        "--metrics-out", default=None,
        help="dump the Prometheus counter registry here on exit (the "
             "quantized smoke's ep_bytes_total{...,wire_dtype} series — "
             "validated by scripts/check_obs.py --quant)",
    )
    return ap.parse_args(argv)


def _setup_interpret_env():
    """Must run BEFORE jax is imported: the smoke needs virtual devices."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()


def _lowering_proof(chunks: int, wire_dtype=None) -> int:
    import jax
    import jax.numpy as jnp
    from jax.sharding import AbstractMesh, PartitionSpec as P

    from uccl_tpu.collective import pallas_ccl
    from uccl_tpu.ep import ll as ep_ll
    from uccl_tpu.ep import ops as ep_ops
    from uccl_tpu.utils.jaxcompat import shard_map

    if jax.default_backend() != "tpu":
        sys.exit("pallas_a2a_proof: needs a TPU backend (tunnel session); "
                 "use --interpret for the CPU smoke tier")
    mesh = AbstractMesh((W,), ("x",))
    per_pair, r_max = ep_ll.ll_bounds(T, K, E // W, W, None, None)
    i32, f32 = jnp.int32, jnp.float32

    def S(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype)

    def _dispatch(nc):
        def f(x, idx):
            plan = ep_ops.plan_slots(idx, E, CAP)
            return ep_ops.dispatch_sorted(x, plan, E, CAP, "x",
                                          wire="pallas", n_chunks=nc)

        return f

    def _dispatch_fp8(x, idx):
        plan = ep_ops.plan_slots(idx, E, CAP)
        return ep_ops.dispatch_sorted(x, plan, E, CAP, "x", wire="pallas",
                                      wire_fp8=True)

    def _combine(nc):
        def f(y, slot, wts):
            return ep_ops.combine_sorted(y, slot, wts, "x", wire="pallas",
                                         n_chunks=nc)

        return f

    def _ll_dispatch(nc):
        def f(x, idx, wts):
            r = ep_ll.ll_dispatch(x, idx, wts, E, "x", wire="pallas",
                                  wire_fp8=True, n_chunks=nc)
            return r.recv_x, r.group_sizes

        return f

    def _ll_combine(nc):
        def f(y, slot, wts, send_mat, recv_mat, regroup, src_off):
            state = ep_ll.LLState(slot, wts, send_mat, recv_mat, regroup,
                                  src_off, "pallas", nc)
            return ep_ll.ll_combine(y, state, "x", wire_fp8=True)

        return f

    cases = []
    for dtype in (jnp.float32, jnp.bfloat16):
        name = jnp.dtype(dtype).name
        cases += [
            (f"dispatch_{name}", _dispatch(1),
             (S((T, H), dtype), S((T, K), i32)),
             (P(), P()), P()),
            (f"combine_{name}", _combine(1),
             (S((E // W, W * CAP, H), dtype), S((T, K), i32),
              S((T, K), f32)),
             (P(), P(), P()), P()),
        ]
    cases += [
        ("dispatch_fp8_wire", _dispatch_fp8,
         (S((T, H), jnp.bfloat16), S((T, K), i32)), (P(), P()), P()),
        ("ll_dispatch_fp8", _ll_dispatch(1),
         (S((T, H), jnp.bfloat16), S((T, K), i32), S((T, K), f32)),
         (P(), P(), P()), (P(), P())),
        ("ll_combine_fp8", _ll_combine(1),
         (S((r_max, H), jnp.bfloat16), S((T, K), i32), S((T, K), f32),
          S((W, E // W), i32), S((W, E // W), i32), S((r_max,), i32),
          S((W,), i32)),
         (P(),) * 7, P()),
    ]
    if wire_dtype:
        # quantized-wire lowerings: the EP dispatch with the generic
        # wire_dtype knob and the quantized ring allreduce kernel (RS-q +
        # quantize-once AG in one pallas_call)
        def _dispatch_q(x, idx):
            plan = ep_ops.plan_slots(idx, E, CAP)
            return ep_ops.dispatch_sorted(x, plan, E, CAP, "x",
                                          wire="pallas",
                                          wire_dtype=wire_dtype)

        cases += [
            (f"dispatch_{wire_dtype}_wire", _dispatch_q,
             (S((T, H), jnp.bfloat16), S((T, K), i32)), (P(), P()), P()),
            (f"ring_ar_{wire_dtype}",
             lambda x: pallas_ccl.ring_all_reduce(x, "x",
                                                  wire_dtype=wire_dtype),
             (S((T, H), jnp.bfloat16),), (P(),), P()),
        ]
    if chunks > 1:
        cases += [
            (f"dispatch_chunked{chunks}", _dispatch(chunks),
             (S((T, H), jnp.float32), S((T, K), i32)), (P(), P()), P()),
            (f"combine_chunked{chunks}", _combine(chunks),
             (S((E // W, W * CAP, H), jnp.float32), S((T, K), i32),
              S((T, K), f32)),
             (P(), P(), P()), P()),
            (f"ll_dispatch_chunked{chunks}", _ll_dispatch(chunks),
             (S((T, H), jnp.bfloat16), S((T, K), i32), S((T, K), f32)),
             (P(), P(), P()), (P(), P())),
            (f"ll_combine_chunked{chunks}", _ll_combine(chunks),
             (S((r_max, H), jnp.bfloat16), S((T, K), i32), S((T, K), f32),
              S((W, E // W), i32), S((W, E // W), i32), S((r_max,), i32),
              S((W,), i32)),
             (P(),) * 7, P()),
        ]

    failed = 0
    for name, fn, shapes, in_specs, out_spec in cases:
        mapped = shard_map(fn, mesh, in_specs, out_spec, check_vma=False)
        try:
            txt = jax.jit(mapped).lower(*shapes).as_text()
            ok = "tpu_custom_call" in txt or "mosaic" in txt.lower()
            print(f"pallas_a2a_proof {name}: "
                  f"{'LOWERED' if ok else 'no-custom-call?'} "
                  f"({len(txt)} chars of StableHLO)")
            failed += 0 if ok else 1
        except Exception as e:  # noqa: BLE001 - report-and-continue proof
            print(f"pallas_a2a_proof {name}: FAILED {e!r}")
            failed += 1
    return failed


def _interpret_smoke(chunks: int) -> int:
    """Execute small kernel cases under the TPU interpreter and compare to
    the lax wire — worlds 4 (even, real chunked kernels within the interp
    budget) and 5 (odd: pad path + antipodal step)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    import uccl_tpu.utils.jaxcompat  # noqa: F401 (installs polyfills)
    from uccl_tpu.ep import ll as ep_ll
    from uccl_tpu.ep import ops as ep_ops
    from uccl_tpu.ep import pallas_a2a
    from uccl_tpu.utils.jaxcompat import shard_map

    devs = jax.devices()
    rng = np.random.default_rng(0)
    depths = sorted({1, max(1, chunks)})
    failed = 0

    def run(mesh, fn, *args, out_specs=None):
        in_specs = tuple(P("x") for _ in args)
        out_specs = P("x") if out_specs is None else out_specs
        return jax.jit(
            shard_map(fn, mesh, in_specs, out_specs, check_vma=False)
        )(*args)

    def case(name, ok):
        nonlocal failed
        print(f"pallas_a2a_proof[interpret] {name}: "
              f"{'OK' if ok else 'MISMATCH'}")
        failed += 0 if ok else 1

    for n in (4, 5):
        mesh = Mesh(np.array(devs[:n]), ("x",))
        x = jnp.asarray(rng.normal(size=(n, n, 5, 9)), jnp.float32)
        want = np.asarray(run(
            mesh,
            lambda v: jax.lax.all_to_all(v[0], "x", 0, 0, tiled=True)[None],
            x,
        ))
        for nc in depths:
            got = np.asarray(run(
                mesh,
                lambda v, nc=nc: pallas_a2a.all_to_all(
                    v[0], "x", n_chunks=nc, chunk_axis=2
                )[None],
                x,
            ))
            case(f"kernel_w{n}_c{nc}", bool((got == want).all()))

        # one sorted dispatch+combine roundtrip and one LL fp8 roundtrip
        t, h, e, k = 8, 16, 2 * n, 2
        cap = max(1, int(1.25 * t * k / e))
        xs = rng.standard_normal((n, t, h)).astype(np.float32)
        idx = rng.integers(0, e, (n, t, k)).astype(np.int32)
        wts = rng.uniform(0.1, 1.0, (n, t, k)).astype(np.float32)

        def sorted_path(wire, nc):
            def f(xv, iv, wv):
                plan = ep_ops.plan_slots(iv[0], e, cap)
                recv = ep_ops.dispatch_sorted(
                    xv[0], plan, e, cap, "x", wire=wire, n_chunks=nc
                )
                return ep_ops.combine_sorted(
                    recv * 2.0, plan, wv[0], "x", wire=wire, n_chunks=nc
                )[None]

            return np.asarray(run(
                mesh, f, *map(jnp.asarray, (xs, idx, wts))
            ))

        ref = sorted_path("lax", 1)
        for nc in depths:
            case(f"sorted_w{n}_c{nc}",
                 bool((sorted_path("pallas", nc) == ref).all()))

        def ll_path(wire, nc):
            def f(xv, iv, wv):
                r = ep_ll.ll_dispatch(
                    xv[0], iv[0], wv[0], e, "x", wire=wire, wire_fp8=True,
                    n_chunks=nc,
                )
                return r.recv_x[None]

            return np.asarray(run(
                mesh, f, *map(jnp.asarray, (xs, idx, wts))
            ))

        ll_ref = ll_path("dense", 1)
        for nc in depths:
            case(f"ll_fp8_w{n}_c{nc}",
                 bool((ll_path("pallas", nc) == ll_ref).all()))
    return failed


def _interpret_quant_smoke(chunks: int, wire_dtype: str) -> int:
    """Quantized-wire smoke (--wire-dtype): the pallas ring allreduce and
    the sorted EP roundtrip at worlds 4 and 5, asserting (1) the quantized
    pallas path is bit-identical to the quantized lax path (same shared
    codec either wire), (2) error vs full precision sits inside the
    documented per-hop bound (docs/QUANT_WIRE.md), (3) an all-zero payload
    round-trips to EXACT zeros (the codec's scale-guard contract)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    import uccl_tpu.utils.jaxcompat  # noqa: F401 (installs polyfills)
    from uccl_tpu.collective import pallas_ccl
    from uccl_tpu.ep import ops as ep_ops
    from uccl_tpu.utils.jaxcompat import shard_map

    devs = jax.devices()
    rng = np.random.default_rng(0)
    depths = sorted({1, max(1, chunks)})
    failed = 0
    # two quantize round trips (dispatch + combine, or RS hops + AG) of
    # rel error: half-ulp/QMAX per trip, with headroom for summation
    rel_bound = {"fp8": 0.12, "int8": 0.02}[wire_dtype]

    def case(name, ok):
        nonlocal failed
        print(f"pallas_a2a_proof[interpret,{wire_dtype}] {name}: "
              f"{'OK' if ok else 'MISMATCH'}")
        failed += 0 if ok else 1

    for n in (4, 5):
        mesh = Mesh(np.array(devs[:n]), ("x",))

        def run(fn, *args, n_in=None):
            n_in = len(args) if n_in is None else n_in
            return np.asarray(jax.jit(shard_map(
                fn, mesh, tuple(P("x") for _ in range(n_in)), P("x"),
                check_vma=False,
            ))(*args))

        # -- quantized ring allreduce ---------------------------------
        x = jnp.asarray(rng.normal(size=(n, 3, 200)), jnp.float32)

        def ar(v, wd=None):
            return pallas_ccl.ring_all_reduce(
                v[0], "x", wire_dtype=wd)[None]

        want = run(lambda v: ar(v), x)
        got = run(lambda v: ar(v, wire_dtype), x)
        err = np.abs(got - want).max() / (np.abs(want).max() + 1e-12)
        case(f"ring_ar_w{n}_err({err:.2e})", bool(err < rel_bound))
        zero = run(lambda v: ar(v, wire_dtype),
                   jnp.zeros((n, 3, 200), jnp.float32))
        case(f"ring_ar_w{n}_zero_exact", bool((zero == 0.0).all()))

        # -- quantized sorted EP roundtrip ----------------------------
        t, h, e, k = 8, 64, 2 * n, 2
        cap = max(1, int(1.25 * t * k / e))
        xs = jnp.asarray(rng.standard_normal((n, t, h)), jnp.float32)
        idx = jnp.asarray(rng.integers(0, e, (n, t, k)), jnp.int32)
        wts = jnp.asarray(rng.uniform(0.1, 1.0, (n, t, k)), jnp.float32)

        def sorted_path(wire, nc, wd):
            def f(xv, iv, wv):
                plan = ep_ops.plan_slots(iv[0], e, cap)
                recv = ep_ops.dispatch_sorted(
                    xv[0], plan, e, cap, "x", wire=wire, n_chunks=nc,
                    wire_dtype=wd,
                )
                return ep_ops.combine_sorted(
                    recv, plan, wv[0], "x", wire=wire, n_chunks=nc,
                    wire_dtype=wd,
                )[None]

            return run(f, xs, idx, wts)

        ref = sorted_path("lax", 1, None)
        lax_q = sorted_path("lax", 1, wire_dtype)
        err = np.abs(lax_q - ref).max() / (np.abs(ref).max() + 1e-12)
        case(f"sorted_w{n}_err({err:.2e})", bool(err < rel_bound))
        for nc in depths:
            case(f"sorted_w{n}_c{nc}_pallas_eq_lax",
                 bool((sorted_path("pallas", nc, wire_dtype)
                       == lax_q).all()))
    return failed


def main():
    args = _parse_args()
    if args.interpret:
        _setup_interpret_env()
        if args.wire_dtype:
            failed = _interpret_quant_smoke(args.chunks, args.wire_dtype)
        else:
            failed = _interpret_smoke(args.chunks)
    else:
        failed = _lowering_proof(args.chunks, args.wire_dtype)
    if args.metrics_out:
        from uccl_tpu import obs

        obs.write_metrics(args.metrics_out)
        print(f"pallas_a2a_proof: metrics -> {args.metrics_out}")
    sys.exit(1 if failed else 0)


if __name__ == "__main__":
    main()
