"""Single-chip compile proof for the Pallas EP all-to-all (wire="pallas").

An 8-way all-to-all kernel cannot EXECUTE on one chip, but it can be LOWERED
for the TPU backend through the full Pallas→Mosaic pipeline using an abstract
8-device mesh — that exercises kernel tracing, VMEM layout/tiling, the
full-peer barrier, credit semaphore plumbing and the remote-copy lowering,
i.e. everything short of the final Mosaic→LLO compile that needs the real
topology. Covered programs: the normal (sorted) EP dispatch AND combine and
the LL dense-chunk dispatch AND combine, each on the pallas wire, at f32 and
bf16 payloads plus the fp8+scales wire format.

(On CPU backends pallas refuses non-interpret lowering, so this is a
TPU-session artifact; run it from scripts/onchip_ladder.sh, step 1c.)

Prints one line per case; exits nonzero on any failure or if any lowered
module lacks the ``tpu_custom_call`` the device-initiated path must contain.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax.sharding import AbstractMesh, PartitionSpec as P

from uccl_tpu.ep import ll as ep_ll
from uccl_tpu.ep import ops as ep_ops
from uccl_tpu.utils.jaxcompat import shard_map

W, T, H, E, K = 8, 128, 512, 16, 2
CAP = max(1, int(1.25 * T * K / E))


def _dispatch(x, idx):
    tfs, _slot, _kept = ep_ops.sorted_from_topk(idx, E, CAP)
    return ep_ops.dispatch_sorted(x, tfs, E, CAP, "x", wire="pallas")


def _dispatch_fp8(x, idx):
    tfs, _slot, _kept = ep_ops.sorted_from_topk(idx, E, CAP)
    return ep_ops.dispatch_sorted(x, tfs, E, CAP, "x", wire="pallas",
                                  wire_fp8=True)


def _combine(y, slot, wts):
    return ep_ops.combine_sorted(y, slot, wts, "x", wire="pallas")


def _ll_dispatch(x, idx, wts):
    r = ep_ll.ll_dispatch(x, idx, wts, E, "x", wire="pallas", wire_fp8=True)
    return r.recv_x, r.group_sizes


def _ll_combine(y, slot, wts, send_mat, recv_mat, regroup, src_off):
    state = ep_ll.LLState(slot, wts, send_mat, recv_mat, regroup, src_off,
                          "pallas")
    return ep_ll.ll_combine(y, state, "x", wire_fp8=True)


def main():
    if jax.default_backend() != "tpu":
        sys.exit("pallas_a2a_proof: needs a TPU backend (tunnel session)")
    mesh = AbstractMesh((W,), ("x",))
    per_pair, r_max = ep_ll.ll_bounds(T, K, E // W, W, None, None)
    i32, f32 = jnp.int32, jnp.float32

    def S(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype)

    cases = []
    for dtype in (jnp.float32, jnp.bfloat16):
        name = jnp.dtype(dtype).name
        cases += [
            (f"dispatch_{name}", _dispatch,
             (S((T, H), dtype), S((T, K), i32)),
             (P(), P()), P()),
            (f"combine_{name}", _combine,
             (S((E // W, W * CAP, H), dtype), S((T, K), i32),
              S((T, K), f32)),
             (P(), P(), P()), P()),
        ]
    cases += [
        ("dispatch_fp8_wire", _dispatch_fp8,
         (S((T, H), jnp.bfloat16), S((T, K), i32)), (P(), P()), P()),
        ("ll_dispatch_fp8", _ll_dispatch,
         (S((T, H), jnp.bfloat16), S((T, K), i32), S((T, K), f32)),
         (P(), P(), P()), (P(), P())),
        ("ll_combine_fp8", _ll_combine,
         (S((r_max, H), jnp.bfloat16), S((T, K), i32), S((T, K), f32),
          S((W, E // W), i32), S((W, E // W), i32), S((r_max,), i32),
          S((W,), i32)),
         (P(),) * 7, P()),
    ]

    failed = 0
    for name, fn, shapes, in_specs, out_spec in cases:
        mapped = shard_map(fn, mesh, in_specs, out_spec, check_vma=False)
        try:
            txt = jax.jit(mapped).lower(*shapes).as_text()
            ok = "tpu_custom_call" in txt or "mosaic" in txt.lower()
            print(f"pallas_a2a_proof {name}: "
                  f"{'LOWERED' if ok else 'no-custom-call?'} "
                  f"({len(txt)} chars of StableHLO)")
            failed += 0 if ok else 1
        except Exception as e:  # noqa: BLE001 - report-and-continue proof
            print(f"pallas_a2a_proof {name}: FAILED {e!r}")
            failed += 1
    sys.exit(1 if failed else 0)


if __name__ == "__main__":
    main()
