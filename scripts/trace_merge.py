#!/usr/bin/env python
"""Merge N per-process Chrome traces into one clock-aligned fleet trace.

Each ``--trace-out`` dump carries its process's clock metadata
(``otherData.clock``: ``wall_epoch_us`` — the wall time of its monotonic
ts 0 — and ``offset_us``, the process's estimated wall offset from the
fleet's reference clock, set by the disagg HELLO clock exchange). This
tool places every file on one timeline::

    aligned_ts = ts + (wall_epoch_us - offset_us) - min_base

gives each file its own pid (named from its ``process_name`` metadata),
keeps flow-event ids untouched (they derive from trace_ids, so s/f pairs
bind ACROSS files), and validates the result with named failures:

* every ``B`` has its ``E`` on the same pid/tid; ``X`` durations >= 0;
* every flow-finish (``f``) resolves a flow-start (``s``) with its id;
* causal order per trace_id after alignment: ``submit`` (the BEGIN mint)
  <= ``grant`` <= ``adopt`` <= ``finish`` wherever those events exist —
  i.e. no GRANT precedes its BEGIN once the clocks are aligned.

Exit is non-zero on any violation, so qa.sh/ci.yml can gate on it. The
summary counts *cross-process* requests: trace_ids whose events span >= 2
pids with a resolved flow pair (what ``check_obs --fleet`` asserts >= 1).

Usage: python scripts/trace_merge.py --out MERGED.json TRACE.json...
(stdlib-only — runnable before any dependency is installed).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import Counter, defaultdict
from typing import Dict, List

# the cross-process causal chain (BEGIN <= GRANT <= FINAL in stream
# terms), in required timeline order; absent stages are skipped (a
# non-disagg trace has no grant/adopt). "finish" stays OUT: the prefill
# fleet's local 1-token request legitimately finishes before the decode
# side adopts, so only the stream's own stages are globally ordered.
CAUSAL_ORDER = ("submit", "grant", "adopt")


def fail(msg: str) -> None:
    print(f"trace_merge: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def warn(msg: str) -> None:
    print(f"trace_merge: WARNING — {msg}", file=sys.stderr)


def load_trace(path: str, strict: bool = False) -> Dict:
    """Load one per-process trace. A trace without the clock-handshake
    record (``otherData.clock.wall_epoch_us``) is *unanchored*: under
    ``--strict`` that is fatal, otherwise it is merged UNADJUSTED (its
    timestamps keep their own epoch) with a warning — a partial fleet
    view beats crashing out of the whole merge when one worker died
    before its clock exchange."""
    with open(path) as f:
        trace = json.load(f)
    if not isinstance(trace.get("traceEvents"), list):
        fail(f"{path}: no traceEvents list")
    clock = trace.get("otherData", {}).get("clock")
    anchored = isinstance(clock, dict) and "wall_epoch_us" in clock
    if not anchored:
        if strict:
            fail(f"{path}: no otherData.clock.wall_epoch_us — cannot "
                 f"align an unanchored trace (--strict)")
        warn(f"{path}: no otherData.clock.wall_epoch_us — merging "
             f"UNADJUSTED (its timeline may not align with the anchored "
             f"files; cross-file causal checks are skipped)")
    trace["_anchored"] = anchored
    return trace


def process_name_of(trace: Dict, path: str) -> str:
    for ev in trace["traceEvents"]:
        if ev.get("name") == "process_name" and ev.get("ph") == "M":
            return str(ev.get("args", {}).get("name", ""))
    return os.path.splitext(os.path.basename(path))[0]


def merge_traces(paths: List[str], strict: bool = False) -> Dict:
    """Load, align and concatenate; returns the merged trace dict
    (validation is separate — :func:`validate_merged`). Unanchored files
    (no clock handshake) merge with shift 0 — their own timeline —
    unless ``strict`` makes that fatal."""
    traces = [load_trace(p, strict=strict) for p in paths]
    # per-file alignment base: wall anchor corrected by the process's
    # estimated offset from the reference clock (0 when never synced);
    # None for an unanchored file — it cannot participate in alignment
    bases = []
    for p, t in zip(paths, traces):
        if not t["_anchored"]:
            bases.append(None)
            continue
        clock = t["otherData"]["clock"]
        bases.append(float(clock["wall_epoch_us"])
                     - float(clock.get("offset_us", 0.0)))
    anchored_bases = [b for b in bases if b is not None]
    t0 = min(anchored_bases) if anchored_bases else 0.0
    out: List[Dict] = []
    meta = {"merged_from": [], "producer": "uccl_tpu trace_merge",
            # the wall epoch (us) of the merged timeline's ts 0 — what
            # `doctor --trace` uses to place flight bundles on this
            # timeline; 0.0 when every input was unanchored
            "merged_wall_epoch_us": t0}
    for i, (path, trace, base) in enumerate(zip(paths, traces, bases)):
        pid = i + 1
        shift = (base - t0) if base is not None else 0.0
        name = process_name_of(trace, path)
        meta["merged_from"].append({
            "path": path, "pid": pid, "process_name": name,
            "shift_us": round(shift, 3),
            "anchored": trace["_anchored"],
            "clock": trace["otherData"].get("clock"),
            "dropped_events": trace["otherData"].get("dropped_events", 0),
        })
        for ev in trace["traceEvents"]:
            ev = dict(ev)
            ev["pid"] = pid
            if ev.get("ph") != "M" and "ts" in ev:
                ev["ts"] = round(ev["ts"] + shift, 3)
            out.append(ev)
    out.sort(key=lambda ev: (ev.get("ts", -1.0), ev["pid"]))
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "otherData": meta}


def validate_merged(merged: Dict) -> Dict:
    """Named-failure validation of a merged trace; returns summary stats
    (events, trace_ids, cross-process request count)."""
    evs = merged["traceEvents"]
    # pids merged without a clock anchor sit on their own timeline —
    # cross-clock causal order is meaningless for chains touching them
    unanchored_pids = {
        m["pid"] for m in merged["otherData"].get("merged_from", ())
        if not m.get("anchored", True)
    }
    b, e = Counter(), Counter()
    flows: Dict[str, Dict] = defaultdict(lambda: {"s": [], "f": []})
    by_trace: Dict[str, List[Dict]] = defaultdict(list)
    for ev in evs:
        ph = ev.get("ph")
        if ph == "X" and ev.get("dur", 0) < 0:
            fail(f"X event {ev['name']!r} with negative dur after merge")
        if ph == "B":
            b[(ev["pid"], ev["tid"])] += 1
        elif ph == "E":
            e[(ev["pid"], ev["tid"])] += 1
        elif ph in ("s", "f"):
            flows[str(ev.get("id"))][ph].append(ev)
        tid = (ev.get("args") or {}).get("trace_id")
        if tid:
            by_trace[tid].append(ev)
    if b != e:
        fail(f"unbalanced B/E after merge ({dict(b)} vs {dict(e)})")
    for fid, sf in flows.items():
        if sf["f"] and not sf["s"]:
            fail(f"flow id {fid}: finish without a start — the s/f pair "
                 f"did not resolve across the merged files")
    # causal order per trace_id on the ALIGNED timeline (skipped for
    # chains that touch an unanchored pid — their ts were never aligned)
    skipped_causal = 0
    for tid, tevs in by_trace.items():
        if unanchored_pids and any(
                ev["pid"] in unanchored_pids for ev in tevs):
            skipped_causal += 1
            continue
        stages = {}
        for ev in tevs:
            n = ev["name"]
            if n in CAUSAL_ORDER and n not in stages:
                stages[n] = ev
        chain = [stages[n] for n in CAUSAL_ORDER if n in stages]
        for a, bnext in zip(chain, chain[1:]):
            if a["ts"] > bnext["ts"]:
                fail(f"trace {tid}: {bnext['name']!r} "
                     f"(pid {bnext['pid']}, ts {bnext['ts']}) precedes "
                     f"{a['name']!r} (pid {a['pid']}, ts {a['ts']}) after "
                     f"clock alignment — causal order violated")
    cross = 0
    for tid, tevs in by_trace.items():
        pids = {ev["pid"] for ev in tevs}
        if len(pids) < 2:
            continue
        # the flow pair derived from this trace_id (obs.flow_id rule),
        # resolved with its start and finish on DIFFERENT processes
        try:
            fid = str(int(tid[:15], 16))
        except ValueError:
            continue
        sf = flows.get(fid)
        if (sf and sf["s"] and sf["f"]
                and {ev["pid"] for ev in sf["s"]}
                != {ev["pid"] for ev in sf["f"]}):
            cross += 1
    stats = {"events": len(evs), "trace_ids": len(by_trace),
             "cross_process_requests": cross}
    if unanchored_pids:
        stats["unanchored_files"] = len(unanchored_pids)
        stats["causal_checks_skipped"] = skipped_causal
        warn(f"{len(unanchored_pids)} unanchored file(s) merged "
             f"unadjusted; causal order skipped for {skipped_causal} "
             f"trace id(s)")
    return stats


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Merge per-process Chrome traces into one "
                    "clock-aligned fleet trace (validated).",
    )
    ap.add_argument("inputs", nargs="+", help="per-process trace JSONs")
    ap.add_argument("--out", required=True, help="merged trace path")
    ap.add_argument("--strict", action="store_true",
                    help="fail (exit 1) on a trace missing the clock "
                         "handshake instead of merging it unadjusted "
                         "with a warning")
    args = ap.parse_args(argv)
    if len(args.inputs) < 2:
        fail("need >= 2 traces to merge")
    merged = merge_traces(args.inputs, strict=args.strict)
    stats = validate_merged(merged)
    merged["otherData"]["stats"] = stats
    with open(args.out, "w") as f:
        json.dump(merged, f)
    print(f"trace_merge: OK — {len(args.inputs)} files, "
          f"{stats['events']} events, {stats['trace_ids']} trace id(s), "
          f"{stats['cross_process_requests']} cross-process request(s) "
          f"-> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
